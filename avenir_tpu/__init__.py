"""avenir_tpu — a TPU-native data-mining framework.

A ground-up JAX/XLA re-design of the capabilities of the reference system
(zhanglei/avenir, a Hadoop-MapReduce + Storm batch/streaming data-mining
toolkit): Naive Bayes, mutual-information / correlation feature analysis,
decision trees, k-nearest-neighbor, Markov / hidden-Markov sequence models,
logistic regression, Fisher discriminant, multi-armed bandits (batch and
online), and class-balancing samplers.

Architecture (vs the reference's layers, see SURVEY.md):

  L0' JAX/XLA + TPU runtime      (replaces Hadoop MR / Storm / Redis / HDFS)
  L1' core data layer            (replaces chombo: schema, CSV ingest, config)
  L2' jittable model math        (same inventory as the reference's plain-Java kernels)
  L3' estimator API fit/predict  (replaces one-Tool-class-per-algorithm MR jobs)
  L4' in-process pipeline driver (replaces knn.sh / tutorial runbooks)
      + host streaming loop      (replaces the Storm topology + Redis queues)

The reference's mapper/combiner/reducer triple collapses into
``vmap(record_kernel)`` + one-hot-einsum/``psum`` aggregation; the MR shuffle
becomes XLA collectives over ICI; multi-stage HDFS pipelines become function
composition over in-memory arrays.
"""

__version__ = "0.1.0"

from avenir_tpu.core.schema import FeatureField, FeatureSchema
from avenir_tpu.core.config import JobConfig

__all__ = [
    "FeatureField",
    "FeatureSchema",
    "JobConfig",
    "__version__",
]
