"""CLI — the ``hadoop jar avenir-1.0.jar <ToolClass> -Dconf.path=<props>
<in> <out>`` contract as ``python -m avenir_tpu <JobName> -Dconf.path=<props>
<in> <out>``.

Accepts the reference's fully-qualified class names or simple names, ``-D``
property overrides (applied over the properties file, as Hadoop's
GenericOptionsParser does), and prints the job counters on completion the way
the Hadoop job client did.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Tuple


def parse_args(argv: List[str]) -> Tuple[str, Dict[str, str], List[str]]:
    if not argv:
        raise SystemExit(
            "usage: python -m avenir_tpu <JobName> [-Dkey=value ...] <input> <output>\n"
            "       python -m avenir_tpu --list")
    job_name = argv[0]
    overrides: Dict[str, str] = {}
    positional: List[str] = []
    for arg in argv[1:]:
        if arg == "--resume":
            # sugar for -Dstream.resume=true (restore the latest
            # stream.checkpoint.dir snapshot and continue from its cursor)
            overrides["stream.resume"] = "true"
            continue
        if arg.startswith("-D"):
            body = arg[2:]
            if "=" not in body:
                raise SystemExit(f"bad -D option (need -Dkey=value): {arg!r}")
            k, v = body.split("=", 1)
            overrides[k.strip()] = v.strip()
        else:
            positional.append(arg)
    return job_name, overrides, positional


def main(argv: List[str]) -> int:
    import os
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        # the image's sitecustomize pins the jax_platforms *config* to the TPU
        # tunnel, which beats the env var — honor an explicit CPU request
        import jax
        jax.config.update("jax_platforms", "cpu")
    # CrossGraft: a worker spawned by the fleet launcher (python -m
    # avenir_tpu.launch) carries its rank in the environment — join the
    # fleet BEFORE any jax work, through the hardened bounded coordinator
    # join (a bad coordinator raises a typed LaunchError, never hangs)
    if os.environ.get("AVENIR_NUM_PROCESSES"):
        from avenir_tpu.launch import join_from_env
        join_from_env()
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.jobs import REGISTRY, get_job

    if argv and argv[0] in ("--list", "list"):
        for name in sorted(k for k in REGISTRY if "." not in k):
            print(name)
        return 0
    job_name, overrides, positional = parse_args(argv)
    conf_path = overrides.pop("conf.path", None)
    conf = JobConfig.from_file(conf_path) if conf_path else JobConfig()
    for k, v in overrides.items():
        conf.set(k, v)
    # launcher-assigned journal shard suffix: adopted unless the conf
    # (file or -D) names its own — the per-process trace.writer.suffix
    # contract the fleet launcher's teardown merge relies on
    if os.environ.get("AVENIR_WRITER_SUFFIX") and \
            not conf.get("trace.writer.suffix"):
        conf.set("trace.writer.suffix", os.environ["AVENIR_WRITER_SUFFIX"])
    if len(positional) != 2:
        raise SystemExit(f"expected <input> <output>, got {positional}")
    job = get_job(job_name)
    # persistent XLA compilation cache: a one-shot CLI job's wall time is
    # dominated by first compiles (~tens of seconds on TPU), while the count
    # kernels themselves run in milliseconds — repeat invocations of the
    # same job shapes skip the compile entirely. Placed here so --list and
    # usage errors touch nothing; disable with AVENIR_COMPILATION_CACHE=
    # (empty) or point it at a custom directory.
    cache_dir = os.environ.get(
        "AVENIR_COMPILATION_CACHE",
        os.path.join("~", ".cache", "avenir_tpu", "xla"))
    if cache_dir:
        try:
            import jax
            # partition by backend: against a remote-compile tunnel even
            # CPU-backend kernels are compiled with the SERVICE host's CPU
            # features, and loading those executables on the local CPU can
            # SIGILL — keeping per-backend subdirectories means purely-local
            # runs never load remotely-compiled artifacts
            cache_dir = os.path.join(
                os.path.abspath(os.path.expanduser(cache_dir)),
                jax.default_backend())
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        except Exception:
            pass                       # cache is an optimization, never fatal
    counters = job.run(conf, positional[0], positional[1])
    # (the final counter snapshot is journaled by Job.run itself under
    # the job's name — round 15 moved it there so multi-process workers
    # and Python-API callers snapshot too, not just this CLI)
    for group, vals in sorted(counters.as_dict().items()):
        print(group)
        for k, v in sorted(vals.items()):
            print(f"\t{k}={v}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))


def cli() -> None:
    """console-script entry point (pyproject.toml [project.scripts])."""
    raise SystemExit(main(sys.argv[1:]))
