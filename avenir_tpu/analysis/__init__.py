"""graftlint — AST-based hazard analysis for the avenir_tpu codebase.

Every advisor round found the same *classes* of bug by hand: a
process-divergent value flowing into a collective (ADVICE.md round 5,
``jobs/regress.py``), checkpoint state that doesn't fingerprint its
configuration (``models/correlation.py``), fixed-width format keys that
silently mis-sort past their width (``jobs/chombo.py``), config keys that
exist in code but not in ``docs/jobs.md``, and per-chunk host syncs that
turn compiled loops into RTT walls (the round-5 tree-induction wall).
These are exactly the invariants a compiler-first stack should check
mechanically — DrJAX gets its MapReduce correctness from making sharded
structure visible to the compiler; this package makes the *process
structure* visible to a static pass, so the invariants hold at authoring
time instead of at 2am in a multi-process run.

Usage::

    python -m avenir_tpu.analysis [paths...]        # lint (default tree)
    python -m avenir_tpu.analysis --json ...        # machine-readable
    python -m avenir_tpu.analysis --write-baseline  # grandfather findings
    python -m avenir_tpu.analysis --write-registry  # regen config registry

Per-line suppression: ``# graftlint: disable=GL005`` (same line, or alone
on the line above) with a comment saying why.  Grandfathered findings live
in ``avenir_tpu/analysis/baseline.json`` with a ``why`` per entry.

Pure stdlib — importing this package must never pull in jax (the lint gate
runs in CI before any device work).
"""

from avenir_tpu.analysis.engine import Finding, run_paths  # noqa: F401
from avenir_tpu.analysis.rules import RULES  # noqa: F401

__all__ = ["Finding", "run_paths", "RULES"]
