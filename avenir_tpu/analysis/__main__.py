"""graftlint CLI — ``python -m avenir_tpu.analysis [paths...]``.

Emits ``file:line: RULE message`` per finding (or a JSON array with
``--json``) and exits non-zero when any non-baselined finding remains.
Run from the repo root (paths in the baseline and registries are
root-relative).  Stdlib-only: never imports jax.

Incremental mode: ``--changed`` scopes the re-analysis to the files git
reports as modified and reuses the warm facts cache
(``.graftlint-cache.json``) for everything else — the cross-file rules
still see the whole tree, so a warm run is well under a second.
``--stats`` prints files/rules/cache-hits/wall.  ``--check-registry``
fails when either generated registry (config keys, counter groups/span
sites) is stale — the pre-commit hook runs both.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

from avenir_tpu.analysis import engine, registry_gen

DEFAULT_PATHS = ("avenir_tpu", "benchmarks", "bench.py")
DEFAULT_DOC_PATHS = ("docs", "README.md")
CACHE_PATH = ".graftlint-cache.json"


def _git_changed(root: str) -> Optional[Set[str]]:
    """Root-relative paths with uncommitted changes (worktree or index),
    or None when git is unavailable — callers fall back to a full run."""
    try:
        proc = subprocess.run(
            ["git", "-C", root, "status", "--porcelain", "-uall"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None
    changed: Set[str] = set()
    for line in proc.stdout.splitlines():
        p = line[3:].strip()
        if " -> " in p:
            p = p.split(" -> ")[-1]
        if p.startswith('"') and p.endswith('"'):
            p = p[1:-1]
        changed.add(p)
    return changed


def _check_registries(paths: List[str], doc_paths: List[str]) -> int:
    """Exit status 1 when a generated registry no longer matches what a
    fresh scan produces (the staleness gate pre-commit runs)."""
    stale = []
    want_cfg = {
        key: registry_gen.scan_documented_keys(doc_paths).get(key)
        for key in registry_gen.scan_code_keys(paths)
    }
    try:
        from avenir_tpu.analysis.config_registry import CONFIG_KEYS
        have_cfg = dict(CONFIG_KEYS)
    except ImportError:
        have_cfg = None
    if have_cfg != {k: (v.replace(os.sep, "/") if v else None)
                    for k, v in want_cfg.items()}:
        stale.append("config_registry.py")
    groups, spans = registry_gen.scan_counter_span_sites(paths)
    documented = registry_gen.scan_doc_tokens(doc_paths)
    want_groups = {g: documented.get(g) for g in sorted(groups)}
    want_spans = {s: documented.get(s) for s in sorted(spans)}
    try:
        from avenir_tpu.analysis.counter_registry import (COUNTER_GROUPS,
                                                          SPAN_SITES)
        if dict(COUNTER_GROUPS) != want_groups or \
                dict(SPAN_SITES) != want_spans:
            stale.append("counter_registry.py")
    except ImportError:
        stale.append("counter_registry.py")
    if stale:
        print(f"stale registr{'y' if len(stale) == 1 else 'ies'}: "
              f"{', '.join(stale)} — regenerate with "
              f"`python -m avenir_tpu.analysis --write-registry`",
              file=sys.stderr)
        return 1
    print("registries up to date")
    return 0


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.analysis",
        description="graftlint — whole-program AST hazard analysis "
                    "(GL001–GL012)")
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)} when present)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON array")
    ap.add_argument("--baseline", default=engine.BASELINE_PATH,
                    help="baseline file (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show every finding)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print grandfathered findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather all current findings (then fill in "
                         "each entry's 'why')")
    ap.add_argument("--write-registry", action="store_true",
                    help="regenerate analysis/config_registry.py and "
                         "analysis/counter_registry.py from the code + "
                         "docs trees")
    ap.add_argument("--check-registry", action="store_true",
                    help="fail when a generated registry is stale "
                         "(pre-commit gate)")
    ap.add_argument("--changed", action="store_true",
                    help="incremental: re-analyze only git-modified files, "
                         "reuse the facts cache for the rest (cross-file "
                         "rules still see the whole tree)")
    ap.add_argument("--stats", action="store_true",
                    help="print files/rules/cache-hits/wall to stderr")
    ap.add_argument("--no-cache", action="store_true",
                    help=f"skip the facts cache ({CACHE_PATH})")
    args = ap.parse_args(argv)

    paths = args.paths or [p for p in DEFAULT_PATHS if os.path.exists(p)]
    if not paths:
        ap.error("no paths given and none of the defaults exist "
                 f"({', '.join(DEFAULT_PATHS)}) — run from the repo root")
    doc_paths = [p for p in DEFAULT_DOC_PATHS if os.path.exists(p)]

    if args.write_registry:
        registry = registry_gen.write_registry(paths, doc_paths)
        undoc = sorted(k for k, v in registry.items() if v is None)
        print(f"wrote {registry_gen.REGISTRY_PATH}: "
              f"{len(registry)} keys, {len(undoc)} undocumented"
              + (f" ({', '.join(undoc)})" if undoc else ""))
        groups, spans = registry_gen.write_counter_registry(paths,
                                                            doc_paths)
        undoc2 = sorted(k for k, v in {**groups, **spans}.items()
                        if v is None)
        print(f"wrote {registry_gen.COUNTER_REGISTRY_PATH}: "
              f"{len(groups)} groups, {len(spans)} spans, "
              f"{len(undoc2)} undocumented"
              + (f" ({', '.join(undoc2)})" if undoc2 else ""))
        return 0

    if args.check_registry:
        return _check_registries(paths, doc_paths)

    baseline = None if args.no_baseline else args.baseline
    changed = _git_changed(os.getcwd()) if args.changed else None
    stats: dict = {}
    findings = engine.run_paths(
        paths, baseline_path=baseline,
        cache_path=None if args.no_cache else CACHE_PATH,
        changed=changed, stats=stats)

    if args.write_baseline:
        existing = engine.load_baseline(
            args.baseline if os.path.exists(args.baseline) else None)
        engine.write_baseline(args.baseline, findings, existing=existing)
        n_new = sum(1 for f in findings if not f.baselined)
        print(f"wrote {args.baseline}: {n_new} new entr"
              f"{'y' if n_new == 1 else 'ies'} (existing whys preserved) — "
              f"fill in each new 'why' before committing")
        return 0

    live = [f for f in findings if not f.baselined]
    shown = findings if args.show_baselined else live
    if args.json:
        print(json.dumps([f.as_dict() for f in shown], indent=2))
    else:
        for f in shown:
            print(f.format())
        n_base = sum(1 for f in findings if f.baselined)
        print(f"graftlint: {len(live)} finding(s), {n_base} baselined",
              file=sys.stderr)
    if args.stats:
        print(f"graftlint stats: {stats.get('files', 0)} files, "
              f"{stats.get('rules', 0)} rules, "
              f"{stats.get('cache_hits', 0)} cache hits, "
              f"{stats.get('wall_s', 0.0)}s", file=sys.stderr)
    return 1 if live else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
