"""graftlint CLI — ``python -m avenir_tpu.analysis [paths...]``.

Emits ``file:line: RULE message`` per finding (or a JSON array with
``--json``) and exits non-zero when any non-baselined finding remains.
Run from the repo root (paths in the baseline and registry are
root-relative).  Stdlib-only: never imports jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from avenir_tpu.analysis import engine, registry_gen

DEFAULT_PATHS = ("avenir_tpu", "benchmarks", "bench.py")
DEFAULT_DOC_PATHS = ("docs", "README.md")


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.analysis",
        description="graftlint — AST hazard analysis (GL001–GL005)")
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)} when present)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON array")
    ap.add_argument("--baseline", default=engine.BASELINE_PATH,
                    help="baseline file (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show every finding)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print grandfathered findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather all current findings (then fill in "
                         "each entry's 'why')")
    ap.add_argument("--write-registry", action="store_true",
                    help="regenerate analysis/config_registry.py from the "
                         "code + docs trees")
    args = ap.parse_args(argv)

    paths = args.paths or [p for p in DEFAULT_PATHS if os.path.exists(p)]
    if not paths:
        ap.error("no paths given and none of the defaults exist "
                 f"({', '.join(DEFAULT_PATHS)}) — run from the repo root")

    if args.write_registry:
        registry = registry_gen.write_registry(
            paths, [p for p in DEFAULT_DOC_PATHS if os.path.exists(p)])
        undoc = sorted(k for k, v in registry.items() if v is None)
        print(f"wrote {registry_gen.REGISTRY_PATH}: "
              f"{len(registry)} keys, {len(undoc)} undocumented"
              + (f" ({', '.join(undoc)})" if undoc else ""))
        return 0

    baseline = None if args.no_baseline else args.baseline
    findings = engine.run_paths(paths, baseline_path=baseline)

    if args.write_baseline:
        existing = engine.load_baseline(
            args.baseline if os.path.exists(args.baseline) else None)
        engine.write_baseline(args.baseline, findings, existing=existing)
        n_new = sum(1 for f in findings if not f.baselined)
        print(f"wrote {args.baseline}: {n_new} new entr"
              f"{'y' if n_new == 1 else 'ies'} (existing whys preserved) — "
              f"fill in each new 'why' before committing")
        return 0

    live = [f for f in findings if not f.baselined]
    shown = findings if args.show_baselined else live
    if args.json:
        print(json.dumps([f.as_dict() for f in shown], indent=2))
    else:
        for f in shown:
            print(f.format())
        n_base = sum(1 for f in findings if f.baselined)
        print(f"graftlint: {len(live)} finding(s), {n_base} baselined",
              file=sys.stderr)
    return 1 if live else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
