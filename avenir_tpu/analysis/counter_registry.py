"""Generated counter-group / span-site registry — DO NOT EDIT
BY HAND.

Regenerate with `python -m avenir_tpu.analysis --write-registry`
after adding a counter group or span name.  Maps every
resolvable Counters group and tracer span literal in the code
tree to the doc file that documents it; None = undocumented
(GL008 fails the build on it).  F-string names are normalized
to wildcards ("Serving.*"), matching docs written as
"Serving.<model>".
"""

COUNTER_GROUPS = {
    'Aggregate': 'docs/observability.md',
    'Fleet': 'docs/architecture.md',
    'Groups': 'docs/observability.md',
    'Iterations': 'docs/observability.md',
    'Model': 'docs/observability.md',
    'Pool': 'docs/analysis.md',
    'Projection': 'docs/observability.md',
    'Records': 'docs/analysis.md',
    'Round': 'docs/observability.md',
    'Serving.*': 'docs/analysis.md',
    'Shard': 'docs/architecture.md',
    'SharedScan': 'docs/architecture.md',
    'Splits': 'docs/observability.md',
    'Stream': 'docs/analysis.md',
    'Task': 'docs/jobs.md',
    'Tenant.*': 'docs/multitenancy.md',
    'Tree': 'docs/observability.md',
    'TreePhase': 'docs/jobs.md',
    'Validation': 'docs/observability.md',
    'Words': 'docs/observability.md',
}

SPAN_SITES = {
    'bench.nb_mi': 'docs/observability.md',
    'bench.pass': 'docs/observability.md',
    'chunk': 'docs/observability.md',
    'feeder.stage': 'docs/observability.md',
    'job.*': 'docs/observability.md',
    'pipeline.run': 'docs/observability.md',
    'probe': 'docs/jobs.md',
    'scan': 'docs/observability.md',
    'scan.chunk': 'docs/observability.md',
    'scan.fused': 'docs/observability.md',
    'serve.request': 'docs/architecture.md',
    'stage.*': 'docs/observability.md',
}
