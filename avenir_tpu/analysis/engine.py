"""graftlint engine — file walking, suppression comments, baseline filtering.

The engine is rule-agnostic: it parses each ``.py`` file once, hands the
tree to every registered rule (``avenir_tpu/analysis/rules.py``), then
applies the two escape hatches in order:

1. **suppression comments** — ``# graftlint: disable=GL001[,GL002]`` on the
   finding's line (or alone on the line directly above it) drops the
   finding at the source; the comment is expected to say why.
   ``# graftlint: disable-file=GL004`` anywhere in a file's first 20 lines
   disables a rule for the whole file.
2. **baseline** — ``baseline.json`` grandfathers known findings by
   ``(rule, path, message)`` (line numbers are deliberately excluded so
   unrelated edits don't churn the baseline); each entry carries a ``why``.

Everything here is stdlib-only: the lint gate must run (and fail fast)
without importing jax or touching a device.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9, ]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*graftlint:\s*disable-file=([A-Z0-9, ]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation.  ``path`` is root-relative POSIX (stable across
    machines — the baseline and CI compare these)."""

    rule: str
    path: str
    line: int
    message: str
    baselined: bool = False

    @property
    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line number excluded so edits above a
        grandfathered finding don't invalidate its entry."""
        return (self.rule, self.path, self.message)

    def format(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{tag}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "baselined": self.baselined}


def _parse_rule_list(text: str) -> Set[str]:
    return {r.strip() for r in text.split(",") if r.strip()}


def suppressions(src: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """(line → suppressed rules, file-wide suppressed rules).

    A ``disable=`` comment applies to its own line; when the line holds
    nothing but the comment it applies to the next line instead (the
    conventional place for a suppression with a why-comment above the
    flagged statement).  Findings anchor at the statement's first line, so
    multi-line calls take the comment on (or above) that first line.
    """
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    lines = src.splitlines()
    for i, line in enumerate(lines, start=1):
        if i <= 20:
            mf = _SUPPRESS_FILE_RE.search(line)
            if mf:
                file_wide |= _parse_rule_list(mf.group(1))
                continue
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = _parse_rule_list(m.group(1))
        target = i + 1 if line.strip().startswith("#") else i
        per_line.setdefault(target, set()).update(rules)
    return per_line, file_wide


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)
        elif p.endswith(".py"):
            yield p


def load_baseline(path: Optional[str]) -> List[dict]:
    if path is None or not os.path.exists(path):
        return []
    with open(path) as fh:
        data = json.load(fh)
    entries = data.get("findings", [])
    for e in entries:
        if not e.get("why"):
            raise ValueError(
                f"baseline entry {e.get('rule')}:{e.get('path')} has no "
                f"'why' — every grandfathered finding must say why it is "
                f"acceptable (or be fixed instead)")
    return entries


def write_baseline(path: str, findings: Sequence[Finding],
                   existing: Sequence[dict] = ()) -> None:
    """Grandfather the current findings: existing entries that still match
    a finding keep their curated ``why`` (an entry whose finding was fixed
    is dropped — the whole-tree test enforces that staleness anyway); new
    non-baselined findings get stub ``why`` fields the author must fill in
    (load_baseline rejects empty ones)."""
    live_keys = {f.key for f in findings}
    kept = [e for e in existing
            if (e["rule"], e["path"], e["message"]) in live_keys]
    kept_keys = {(e["rule"], e["path"], e["message"]) for e in kept}
    fresh = [{"rule": f.rule, "path": f.path, "message": f.message,
              "why": "FILL ME IN — why is this finding acceptable?"}
             for f in sorted(findings, key=lambda f: (f.path, f.line))
             if f.key not in kept_keys]
    entries = sorted(kept + fresh, key=lambda e: (e["path"], e["rule"]))
    with open(path, "w") as fh:
        json.dump({"findings": entries}, fh, indent=2)
        fh.write("\n")


def lint_file(path: str, relpath: str, rules=None,
              config_keys: Optional[dict] = None) -> List[Finding]:
    """All findings for one file, suppression comments already applied."""
    from avenir_tpu.analysis.rules import RULES, RuleContext

    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("GL000", relpath, e.lineno or 1,
                        f"file does not parse: {e.msg}")]
    per_line, file_wide = suppressions(src)
    ctx = RuleContext(src=src, relpath=relpath, config_keys=config_keys)
    out: List[Finding] = []
    for rule_id, rule_fn in (rules or RULES).items():
        if rule_id in file_wide:
            continue
        for line, message in rule_fn(tree, ctx):
            if rule_id in per_line.get(line, ()):
                continue
            out.append(Finding(rule_id, relpath, line, message))
    return out


def run_paths(paths: Sequence[str], root: Optional[str] = None,
              baseline_path: Optional[str] = BASELINE_PATH,
              rules=None, config_keys: Optional[dict] = None) -> List[Finding]:
    """Lint ``paths`` (files or directories); returns findings sorted by
    (path, line) with baselined ones flagged, not dropped — callers decide
    whether to show them (CI fails only on non-baselined findings)."""
    root = os.path.abspath(root or os.getcwd())
    baseline = {(e["rule"], e["path"], e["message"])
                for e in load_baseline(baseline_path)}
    findings: List[Finding] = []
    for path in _iter_py_files([os.fspath(p) for p in paths]):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root) if ap.startswith(root + os.sep) \
            else ap
        rel = rel.replace(os.sep, "/")
        findings.extend(lint_file(ap, rel, rules=rules,
                                  config_keys=config_keys))
    # dedupe (two identical format specs on one line report once), then
    # flag baselined entries
    findings = [
        Finding(f.rule, f.path, f.line, f.message,
                baselined=f.key in baseline)
        for f in dict.fromkeys(findings)
    ]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
