"""graftlint engine — whole-program pass, caching, suppressions, baseline.

Since round 21 the engine runs **two phases**:

1. **per-file** — each ``.py`` file is parsed once; the local rules
   (GL001–GL005, GL009–GL012 in rules.py) run on its tree and a
   JSON-serializable *facts* record is extracted (symbol table, import
   targets, call edges, lock regions, emit/counter/span sites —
   program.py).  Both outputs are content-hash-cached per file
   (``--changed`` additionally trusts git to skip re-reading unchanged
   files), so warm re-runs cost milliseconds.
2. **project** — a :class:`~avenir_tpu.analysis.program.ProjectContext`
   aggregates every file's facts (symbol index, import graph, transitive
   I/O closure) and the cross-file rules run over it: GL006 (I/O
   reachable under a held lock), GL007 (event-schema drift, both
   directions), GL008 (counter/span registry drift).  This phase is
   always fresh — it is cheap dict work.

The two escape hatches apply to both phases, in order:

1. **suppression comments** — ``# graftlint: disable=GL001[,GL002]`` on the
   finding's line (or alone on the line directly above it) drops the
   finding at the source; the comment is expected to say why.
   ``# graftlint: disable-file=GL004`` anywhere in a file's first 20 lines
   disables a rule for the whole file.
2. **baseline** — ``baseline.json`` grandfathers known findings by
   ``(rule, path, message)`` (line numbers are deliberately excluded so
   unrelated edits don't churn the baseline); each entry carries a ``why``.

Everything here is stdlib-only: the lint gate must run (and fail fast)
without importing jax or touching a device.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9, ]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*graftlint:\s*disable-file=([A-Z0-9, ]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation.  ``path`` is root-relative POSIX (stable across
    machines — the baseline and CI compare these)."""

    rule: str
    path: str
    line: int
    message: str
    baselined: bool = False

    @property
    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line number excluded so edits above a
        grandfathered finding don't invalidate its entry."""
        return (self.rule, self.path, self.message)

    def format(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{tag}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "baselined": self.baselined}


def _parse_rule_list(text: str) -> Set[str]:
    return {r.strip() for r in text.split(",") if r.strip()}


def suppressions(src: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """(line → suppressed rules, file-wide suppressed rules).

    A ``disable=`` comment applies to its own line; when the line holds
    nothing but the comment it applies to the next line instead (the
    conventional place for a suppression with a why-comment above the
    flagged statement).  Findings anchor at the statement's first line, so
    multi-line calls take the comment on (or above) that first line.
    """
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    lines = src.splitlines()
    for i, line in enumerate(lines, start=1):
        if i <= 20:
            mf = _SUPPRESS_FILE_RE.search(line)
            if mf:
                file_wide |= _parse_rule_list(mf.group(1))
                continue
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = _parse_rule_list(m.group(1))
        target = i + 1 if line.strip().startswith("#") else i
        per_line.setdefault(target, set()).update(rules)
    return per_line, file_wide


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)
        elif p.endswith(".py"):
            yield p


def load_baseline(path: Optional[str]) -> List[dict]:
    if path is None or not os.path.exists(path):
        return []
    with open(path) as fh:
        data = json.load(fh)
    entries = data.get("findings", [])
    for e in entries:
        if not e.get("why"):
            raise ValueError(
                f"baseline entry {e.get('rule')}:{e.get('path')} has no "
                f"'why' — every grandfathered finding must say why it is "
                f"acceptable (or be fixed instead)")
    return entries


def write_baseline(path: str, findings: Sequence[Finding],
                   existing: Sequence[dict] = ()) -> None:
    """Grandfather the current findings: existing entries that still match
    a finding keep their curated ``why`` (an entry whose finding was fixed
    is dropped — the whole-tree test enforces that staleness anyway); new
    non-baselined findings get stub ``why`` fields the author must fill in
    (load_baseline rejects empty ones)."""
    live_keys = {f.key for f in findings}
    kept = [e for e in existing
            if (e["rule"], e["path"], e["message"]) in live_keys]
    kept_keys = {(e["rule"], e["path"], e["message"]) for e in kept}
    fresh = [{"rule": f.rule, "path": f.path, "message": f.message,
              "why": "FILL ME IN — why is this finding acceptable?"}
             for f in sorted(findings, key=lambda f: (f.path, f.line))
             if f.key not in kept_keys]
    entries = sorted(kept + fresh, key=lambda e: (e["path"], e["rule"]))
    with open(path, "w") as fh:
        json.dump({"findings": entries}, fh, indent=2)
        fh.write("\n")


# ---------------------------------------------------------------------------
# the per-file phase (cacheable)
# ---------------------------------------------------------------------------

def _file_record(src: str, path: str, relpath: str, local_rules: dict,
                 config_keys: Optional[dict],
                 event_once: Optional[frozenset]) -> dict:
    """Everything the project phase needs from one file: local findings
    (suppressions already applied), program facts, and the suppression
    maps (project findings are filtered against them later).  Pure
    function of (src, rule set) — safe to cache by content hash."""
    from avenir_tpu.analysis.program import extract_facts
    from avenir_tpu.analysis.rules import RuleContext

    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return {"findings": [["GL000", e.lineno or 1,
                              f"file does not parse: {e.msg}"]],
                "facts": None, "suppress": {"lines": {}, "file": []}}
    per_line, file_wide = suppressions(src)
    ctx = RuleContext(src=src, relpath=relpath, config_keys=config_keys,
                      event_once=event_once)
    findings: List[list] = []
    for rule_id, rule_fn in local_rules.items():
        if rule_id in file_wide:
            continue
        for line, message in rule_fn(tree, ctx):
            if rule_id in per_line.get(line, ()):
                continue
            findings.append([rule_id, line, message])
    return {
        "findings": findings,
        "facts": extract_facts(tree, src, relpath),
        "suppress": {
            "lines": {str(k): sorted(v) for k, v in per_line.items()},
            "file": sorted(file_wide),
        },
    }


def lint_file(path: str, relpath: str, rules=None,
              config_keys: Optional[dict] = None) -> List[Finding]:
    """Local findings for one file, suppression comments applied (the
    pre-round-21 single-file entry point, kept for direct callers; the
    cross-file rules need :func:`run_paths`)."""
    from avenir_tpu.analysis.program import PROJECT_RULES
    from avenir_tpu.analysis.rules import RULES

    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    local = {rid: fn for rid, fn in (rules or RULES).items()
             if rid not in PROJECT_RULES}
    rec = _file_record(src, path, relpath, local, config_keys, None)
    return [Finding(rule, relpath, line, message)
            for rule, line, message in rec["findings"]]


# ---------------------------------------------------------------------------
# the facts cache
# ---------------------------------------------------------------------------

def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def cache_salt(config_keys: Optional[dict] = None,
               event_once: Optional[frozenset] = None) -> str:
    """Hash of the analyzer's own sources + the golden event schema (+ any
    caller-supplied registries): editing a rule or the schema invalidates
    every cached record."""
    from avenir_tpu.analysis.program import EVENT_SCHEMA_PATH

    h = hashlib.sha256()
    analysis_dir = os.path.dirname(__file__)
    sources = sorted(
        os.path.join(analysis_dir, n) for n in os.listdir(analysis_dir)
        if n.endswith(".py"))
    sources.append(EVENT_SCHEMA_PATH)
    for p in sources:
        if os.path.exists(p):
            with open(p, "rb") as fh:
                h.update(fh.read())
    h.update(repr(sorted(config_keys.items())).encode()
             if config_keys is not None else b"-")
    h.update(repr(sorted(event_once)).encode()
             if event_once is not None else b"-")
    return h.hexdigest()


def _load_cache(cache_path: Optional[str], salt: str) -> Dict[str, dict]:
    if cache_path is None or not os.path.exists(cache_path):
        return {}
    try:
        with open(cache_path) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return {}
    if data.get("salt") != salt:
        return {}
    return data.get("files", {})


def _write_cache(cache_path: Optional[str], salt: str,
                 files: Dict[str, dict]) -> None:
    if cache_path is None:
        return
    tmp = cache_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"salt": salt, "files": files}, fh)
    os.replace(tmp, cache_path)


# ---------------------------------------------------------------------------
# the whole-program run
# ---------------------------------------------------------------------------

def run_paths(paths: Sequence[str], root: Optional[str] = None,
              baseline_path: Optional[str] = BASELINE_PATH,
              rules=None, config_keys: Optional[dict] = None,
              event_schema=None, counter_registry: Optional[dict] = None,
              cache_path: Optional[str] = None,
              changed: Optional[Set[str]] = None,
              stats: Optional[dict] = None) -> List[Finding]:
    """Lint ``paths`` (files or directories); returns findings sorted by
    (path, line) with baselined ones flagged, not dropped — callers decide
    whether to show them (CI fails only on non-baselined findings).

    - ``rules``: restrict to these rule ids (a dict — local entries map to
      their check functions, project ids select the built-in project
      rules).  None = everything.
    - ``event_schema``/``counter_registry``: registry overrides for GL007/
      GL008 (tests); None loads the real ones.
    - ``cache_path``: JSON facts cache (content-hash keyed, salted with
      the analyzer sources); None disables caching.
    - ``changed``: root-relative paths whose content may differ from the
      cache — any OTHER cached file is reused without re-reading
      (``--changed``'s git-scoped warm path).
    - ``stats``: dict that receives {files, cache_hits, rules, wall_s}.
    """
    from avenir_tpu.analysis import program
    from avenir_tpu.analysis.rules import RULES

    t0 = time.monotonic()
    root = os.path.abspath(root or os.getcwd())
    baseline = {(e["rule"], e["path"], e["message"])
                for e in load_baseline(baseline_path)}

    local_rules = {rid: fn for rid, fn in (rules or RULES).items()
                   if rid not in program.PROJECT_RULES}
    project_rules = {rid: program.PROJECT_RULES[rid]
                     for rid in (rules or program.PROJECT_RULES)
                     if rid in program.PROJECT_RULES}

    if event_schema is None:
        event_schema = program.load_event_schema()
    if counter_registry is None:
        counter_registry = program.load_counter_registry()
    event_once = (frozenset(event_schema.once)
                  if event_schema is not None else frozenset())

    salt = cache_salt(config_keys, event_once)
    cache = _load_cache(cache_path, salt)
    records: Dict[str, dict] = {}
    hits = 0
    for path in _iter_py_files([os.fspath(p) for p in paths]):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root) if ap.startswith(root + os.sep) \
            else ap
        rel = rel.replace(os.sep, "/")
        entry = cache.get(rel)
        if entry is not None and changed is not None and \
                rel not in changed:
            records[rel] = entry["rec"]        # trust git: skip the read
            hits += 1
            continue
        with open(ap, encoding="utf-8") as fh:
            src = fh.read()
        sha = _sha(src.encode("utf-8"))
        if entry is not None and entry["sha"] == sha:
            records[rel] = entry["rec"]
            hits += 1
            continue
        rec = _file_record(src, ap, rel, local_rules, config_keys,
                           event_once)
        cache[rel] = {"sha": sha, "rec": rec}
        records[rel] = rec
    _write_cache(cache_path, salt, cache)

    findings: List[Finding] = []
    for rel, rec in records.items():
        for rule, line, message in rec["findings"]:
            findings.append(Finding(rule, rel, line, message))

    # project phase — always fresh over the aggregated facts
    if project_rules:
        ctx = program.ProjectContext(
            files={rel: rec["facts"] for rel, rec in records.items()
                   if rec["facts"] is not None},
            root=root, event_schema=event_schema,
            counter_registry=counter_registry)
        for rule_id, rule_fn in project_rules.items():
            for rel, line, message in rule_fn(ctx):
                sup = records.get(rel, {}).get(
                    "suppress", {"lines": {}, "file": []})
                if rule_id in sup["file"] or \
                        rule_id in sup["lines"].get(str(line), ()):
                    continue
                findings.append(Finding(rule_id, rel, line, message))

    # dedupe (two identical format specs on one line report once), then
    # flag baselined entries
    findings = [
        Finding(f.rule, f.path, f.line, f.message,
                baselined=f.key in baseline)
        for f in dict.fromkeys(findings)
    ]
    if stats is not None:
        stats.update({
            "files": len(records), "cache_hits": hits,
            "rules": len(local_rules) + len(project_rules),
            "wall_s": round(time.monotonic() - t0, 3),
        })
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
