"""graftlint whole-program pass — per-file facts, project context, and the
cross-file rules GL006–GL008.

The engine runs two phases (engine.py):

1. **per-file** — parse once, run the local rules (rules.py), and extract
   a JSON-serializable *facts* record: symbol table, import targets, call
   edges, lock regions with the calls they enclose, journal-emit sites,
   counter/span sites.  Facts are content-hash-cached, so a warm re-run
   never re-parses unchanged files.
2. **project** — build a :class:`ProjectContext` over every file's facts
   (symbol index, import graph, transitive I/O closure) and run the
   project rules below.  This phase is always fresh and cheap (pure dict
   work over the aggregated facts).

Everything is stdlib-only; the golden event schema is loaded standalone
(``importlib``) so linting never imports the telemetry package (or jax).
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from avenir_tpu.analysis.rules import _dotted, _unparse

_ANALYSIS_DIR = os.path.dirname(__file__)
EVENT_SCHEMA_PATH = os.path.normpath(
    os.path.join(_ANALYSIS_DIR, os.pardir, "telemetry", "schema.py"))

# dotted-name tails whose call is journal/file I/O when the receiver looks
# like the tracer/journal/span plumbing (``tel.tracer().event(...)``,
# ``self.journal.emit(...)``, ``_TRACER.gauge(...)``)
_EMIT_TAILS = {"event", "event_once", "gauge", "counters", "emit",
               "emit_span", "_journal_emit"}
_EMIT_RECEIVER_HINTS = ("tracer", "journal", "tel.", "span")

# threading lock constructors — a ``with`` over a name assigned from one
# of these opens a lock region (FileLock deliberately NOT here: file I/O
# under a FileLock is the locking discipline, not the hazard)
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}

_EVENT_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")


# ---------------------------------------------------------------------------
# per-file facts extraction
# ---------------------------------------------------------------------------

def _is_test_file(relpath: str) -> bool:
    base = os.path.basename(relpath)
    return ("tests/" in relpath.replace(os.sep, "/")
            or base.startswith("test_") or base == "conftest.py")


def _sink(call: ast.Call) -> Optional[str]:
    """Non-None when this call IS file/journal I/O: ``open()``, a FileLock
    acquire, or a tracer/journal emit."""
    func = call.func
    dotted = _dotted(func) or ""
    tail = dotted.split(".")[-1] if dotted else (
        func.attr if isinstance(func, ast.Attribute) else "")
    if dotted == "open":
        return "open()"
    if tail == "FileLock":
        return "FileLock()"
    if tail in _EMIT_TAILS:
        recv = _unparse(func.value).lower() \
            if isinstance(func, ast.Attribute) else ""
        if tail == "_journal_emit" and recv in ("self", "cls"):
            return f"journal {tail}()"
        if any(h in recv for h in _EMIT_RECEIVER_HINTS):
            return f"journal {tail}()"
    return None


def _emit_site(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, event-name) for a tracer/span ``.event("literal")`` /
    ``.event_once("literal")`` call; None for dynamic names or non-emit
    calls.  Raw ``Journal.emit`` is excluded: the Journal is
    schema-agnostic plumbing (tests journal fixture events through it)."""
    func = call.func
    if not isinstance(func, ast.Attribute) or \
            func.attr not in ("event", "event_once", "_journal_emit"):
        return None
    recv = _unparse(func.value).lower()
    # "self"/"cls" receivers cover the Tracer's own internal emits
    # (self.event("counters", ...), self._journal_emit("span.open", ...))
    if recv not in ("self", "cls") and \
            not any(h in recv for h in _EMIT_RECEIVER_HINTS):
        return None
    if call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str):
        return func.attr, call.args[0].value
    return None


def _call_ref(call: ast.Call) -> Optional[dict]:
    """A resolvable reference to the callee, or None (calls on call
    results, subscripts, deep attribute chains)."""
    dotted = _dotted(call.func)
    if not dotted:
        return None
    parts = dotted.split(".")
    if len(parts) == 1:
        return {"k": "name", "n": parts[0]}
    if parts[0] in ("self", "cls") and len(parts) == 2:
        return {"k": "self", "n": parts[1]}
    if len(parts) == 2:
        return {"k": "dotted", "t": dotted}
    return None


def _fstring_pattern(node: ast.AST) -> Optional[str]:
    """'Serving.*' for ``f"Serving.{model}"``; the literal itself for a
    plain string; None otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                parts.append("*")
            else:
                return None
        pat = "".join(parts)
        return re.sub(r"\*+", "*", pat)
    return None


class _FactsVisitor(ast.NodeVisitor):
    """One walk producing the whole facts record for a file."""

    def __init__(self, src: str, relpath: str):
        self.relpath = relpath
        self.facts: dict = {
            "defs": {}, "classes": {}, "imports": {},
            "calls": [], "io_direct": [], "lock_regions": [],
            "emits": [], "deferred_events": [],
            "counter_sites": [], "span_sites": [], "thread_targets": [],
        }
        # stacks
        self._cls: List[str] = []
        self._fn: List[str] = []
        self._locks: List[dict] = []
        # name → last literal/f-string assignment per function (def-use
        # for counter groups passed through a variable)
        self._str_assigns: List[Dict[str, str]] = [{}]
        # module-level constants: NAME = ("Group", "name") tuples
        self._module_tuples: Dict[str, str] = {}
        # names assigned from threading lock constructors
        self._lock_names: Set[str] = set()

    # -- scopes -------------------------------------------------------------
    def _qual(self) -> Optional[str]:
        if not self._fn:
            return None
        return ".".join(self._fn)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.facts["classes"][node.name] = {
            "line": node.lineno,
            "methods": [n.name for n in node.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))],
        }
        self._cls.append(node.name)
        self._fn.append(node.name)
        self.generic_visit(node)
        self._fn.pop()
        self._cls.pop()

    def _visit_fn(self, node) -> None:
        self._fn.append(node.name)
        self.facts["defs"][".".join(self._fn)] = node.lineno
        self._str_assigns.append({})
        self.generic_visit(node)
        self._str_assigns.pop()
        self._fn.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- imports ------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            self.facts["imports"][local] = {"mod": alias.name, "attr": None}

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        if node.level:
            pkg = os.path.dirname(self.relpath).replace(os.sep, "/")
            parts = pkg.split("/")
            if node.level > 1:
                parts = parts[:len(parts) - (node.level - 1)]
            mod = ".".join(parts + ([mod] if mod else []))
        for alias in node.names:
            local = alias.asname or alias.name
            self.facts["imports"][local] = {"mod": mod, "attr": alias.name}

    # -- assignments (def-use for groups, lock names, module tuples) --------
    def visit_Assign(self, node: ast.Assign) -> None:
        value_txt = _unparse(node.value)
        pat = _fstring_pattern(node.value)
        for tgt in node.targets:
            name = _dotted(tgt)
            if name is None:
                continue
            tail = name.split(".")[-1]
            if isinstance(node.value, ast.Call):
                ctor = (_dotted(node.value.func) or "").split(".")[-1]
                if ctor in _LOCK_CTORS and "FileLock" not in value_txt:
                    self._lock_names.add(tail)
            if pat is not None:
                self._str_assigns[-1][tail] = pat
            if not self._fn and isinstance(node.value, ast.Tuple) and \
                    node.value.elts and \
                    isinstance(node.value.elts[0], ast.Constant) and \
                    isinstance(node.value.elts[0].value, str):
                self._module_tuples[tail] = node.value.elts[0].value
        self.generic_visit(node)

    # -- lock regions -------------------------------------------------------
    def _is_lock_expr(self, expr: ast.AST) -> bool:
        name = _dotted(expr)
        if name is None:
            return False
        return name.split(".")[-1] in self._lock_names

    def visit_With(self, node: ast.With) -> None:
        lock_items = [it for it in node.items
                      if self._is_lock_expr(it.context_expr)]
        if lock_items:
            region = {"fn": self._qual(), "lock_line": node.lineno,
                      "lock": _unparse(lock_items[0].context_expr),
                      "calls": []}
            self.facts["lock_regions"].append(region)
            self._locks.append(region)
            self.generic_visit(node)
            self._locks.pop()
        else:
            self.generic_visit(node)

    # -- calls --------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        qual = self._qual()
        sink = _sink(node)
        ref = _call_ref(node)
        if sink is not None:
            self.facts["io_direct"].append(
                {"fn": qual, "line": node.lineno, "what": sink})
        elif ref is not None:
            self.facts["calls"].append(
                {"fn": qual, "line": node.lineno, "ref": ref})
        if self._locks and self._locks[-1]["fn"] == qual:
            self._locks[-1]["calls"].append(
                {"line": node.lineno, "sink": sink, "ref": ref,
                 "text": _unparse(node.func)})
        emit = _emit_site(node)
        if emit is not None:
            self.facts["emits"].append(
                {"line": node.lineno, "kind": emit[0], "name": emit[1]})
        self._counter_or_span_site(node)
        self._thread_target(node)
        self.generic_visit(node)

    # -- counter / span sites ----------------------------------------------
    def _counter_or_span_site(self, node: ast.Call) -> None:
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        recv = _unparse(func.value).lower()
        if func.attr in ("increment", "set") and "counter" in recv:
            group = None
            if node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Starred):
                    const = self._module_tuples.get(
                        (_dotted(arg.value) or "").split(".")[-1])
                    group = const
                else:
                    group = _fstring_pattern(arg)
                    if group is None and isinstance(arg, ast.Name):
                        for scope in reversed(self._str_assigns):
                            if arg.id in scope:
                                group = scope[arg.id]
                                break
            if group is not None:
                self.facts["counter_sites"].append(
                    {"line": node.lineno, "group": group})
        elif func.attr in ("span", "emit_span") and \
                any(h in recv for h in _EMIT_RECEIVER_HINTS):
            if node.args:
                name = _fstring_pattern(node.args[0])
                if name is not None:
                    self.facts["span_sites"].append(
                        {"line": node.lineno, "name": name})

    # -- thread targets (facts for GL009, resolved locally) -----------------
    def _thread_target(self, node: ast.Call) -> None:
        dotted = _dotted(node.func) or ""
        if dotted.split(".")[-1] != "Thread":
            return
        for kw in node.keywords:
            if kw.arg == "target":
                ref = _call_ref(ast.Call(func=kw.value, args=[],
                                         keywords=[]))
                self.facts["thread_targets"].append(
                    {"line": node.lineno, "ref": ref,
                     "text": _unparse(kw.value)})


def extract_facts(tree: ast.AST, src: str, relpath: str) -> dict:
    visitor = _FactsVisitor(src, relpath)
    # prescan: lock-name assignments can appear after their use sites
    # (methods defined above __init__) — collect them first
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = (_dotted(node.value.func) or "").split(".")[-1]
            if ctor in _LOCK_CTORS:
                for tgt in node.targets:
                    name = _dotted(tgt)
                    if name:
                        visitor._lock_names.add(name.split(".")[-1])
    # deferred-fire tuples: ("tenant.throttled", {...}) appended under a
    # lock and emitted after release (tenancy/arbiter.py) — these count as
    # live emit sites for GL007's liveness direction (never for the
    # unknown-name direction: arbitrary dotted tuples would false-flag)
    deferred = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Tuple) and node.elts and \
                isinstance(node.elts[0], ast.Constant) and \
                isinstance(node.elts[0].value, str) and \
                _EVENT_NAME_RE.match(node.elts[0].value):
            deferred.add(node.elts[0].value)
    visitor.visit(tree)
    visitor.facts["deferred_events"] = sorted(deferred)
    return visitor.facts


# ---------------------------------------------------------------------------
# registries the project rules check against
# ---------------------------------------------------------------------------

@dataclass
class EventSchema:
    """The golden journal-event schema, loaded standalone from
    ``telemetry/schema.py`` (no package import — never pulls in jax)."""

    names: Dict[str, int]                  # event → line in the schema file
    once: Set[str]
    relpath: str
    explicit: bool = False                 # passed by the caller (tests)


def load_event_schema(path: Optional[str] = None,
                      explicit: bool = False) -> Optional[EventSchema]:
    path = path or EVENT_SCHEMA_PATH
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location("_graftlint_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    src_lines = open(path, encoding="utf-8").read().splitlines()
    names: Dict[str, int] = {}
    for ev in mod.GOLDEN_EVENT_KEYS:
        line = next((i for i, ln in enumerate(src_lines, 1)
                     if f'"{ev}"' in ln), 1)
        names[ev] = line
    return EventSchema(names=names, once=set(getattr(mod, "EVENT_ONCE", ())),
                       relpath=path, explicit=explicit)


def load_counter_registry() -> Optional[dict]:
    try:
        from avenir_tpu.analysis.counter_registry import (COUNTER_GROUPS,
                                                          SPAN_SITES)
        return {"groups": COUNTER_GROUPS, "spans": SPAN_SITES}
    except ImportError:                        # registry not generated yet
        return None


# ---------------------------------------------------------------------------
# project context
# ---------------------------------------------------------------------------

@dataclass
class ProjectContext:
    """Aggregated facts for every linted file: symbol index, import graph,
    and the transitive file/journal-I/O closure GL006 walks."""

    files: Dict[str, dict]                 # relpath → facts
    root: str = ""
    event_schema: Optional[EventSchema] = None
    counter_registry: Optional[dict] = None
    modmap: Dict[str, str] = field(default_factory=dict)
    io_reach: Set[Tuple[str, str]] = field(default_factory=set)

    def __post_init__(self) -> None:
        for rel in self.files:
            mod = rel[:-3] if rel.endswith(".py") else rel
            if mod.endswith("/__init__"):
                mod = mod[:-len("/__init__")]
            self.modmap[mod.replace("/", ".")] = rel
        self._build_io_closure()

    # -- symbol resolution --------------------------------------------------
    def _target_in_module(self, rel: str, name: str) \
            -> Optional[Tuple[str, str]]:
        facts = self.files.get(rel)
        if facts is None:
            return None
        if name in facts["classes"]:
            if "__init__" in facts["classes"][name]["methods"]:
                return (rel, f"{name}.__init__")
            return (rel, name)
        if name in facts["defs"]:
            return (rel, name)
        return None

    def resolve(self, rel: str, fn_qual: Optional[str],
                ref: Optional[dict]) -> Optional[Tuple[str, str]]:
        """(file, qual) the reference points at, or None (unresolvable —
        attribute chains on arbitrary objects never produce findings)."""
        if ref is None:
            return None
        facts = self.files[rel]
        if ref["k"] == "self":
            cls = (fn_qual or "").split(".")[0]
            if cls in facts["classes"] and \
                    ref["n"] in facts["classes"][cls]["methods"]:
                return (rel, f"{cls}.{ref['n']}")
            return None
        if ref["k"] == "name":
            local = self._target_in_module(rel, ref["n"])
            if local is not None:
                return local
            imp = facts["imports"].get(ref["n"])
            if imp is not None and imp["attr"] is not None:
                target_rel = self.modmap.get(imp["mod"])
                if target_rel is not None:
                    return self._target_in_module(target_rel, imp["attr"])
            return None
        if ref["k"] == "dotted":
            first, attr = ref["t"].split(".", 1)
            imp = facts["imports"].get(first)
            if imp is not None and imp["attr"] is None:
                target_rel = self.modmap.get(imp["mod"])
                if target_rel is not None:
                    return self._target_in_module(target_rel, attr)
            return None
        return None

    # -- transitive I/O closure ---------------------------------------------
    def _build_io_closure(self) -> None:
        reach: Set[Tuple[str, str]] = set()
        for rel, facts in self.files.items():
            for rec in facts["io_direct"]:
                if rec["fn"] is not None:
                    reach.add((rel, rec["fn"]))
        edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for rel, facts in self.files.items():
            for rec in facts["calls"]:
                if rec["fn"] is None:
                    continue
                tgt = self.resolve(rel, rec["fn"], rec["ref"])
                if tgt is not None:
                    edges.setdefault(tgt, set()).add((rel, rec["fn"]))
        frontier = list(reach)
        while frontier:
            tgt = frontier.pop()
            for caller in edges.get(tgt, ()):
                if caller not in reach:
                    reach.add(caller)
                    frontier.append(caller)
        self.io_reach = reach


# ---------------------------------------------------------------------------
# project rules — (relpath, line, message) triples
# ---------------------------------------------------------------------------

ProjectResult = List[Tuple[str, int, str]]


def check_gl006(ctx: ProjectContext) -> ProjectResult:
    """File/journal I/O (journal emit, FileLock acquire, ``open``)
    reachable inside a held ``threading.Lock``/``RLock``/``Condition``
    region.  The PR 14 review class (fixed twice): a journal write under
    the arbiter/door lock serializes every other tenant's grant behind
    one shed storm's file I/O.  Defer the emit past the release
    (tenancy/arbiter.py's ``fires`` list) instead."""
    out: ProjectResult = []
    for rel, facts in ctx.files.items():
        for region in facts["lock_regions"]:
            for call in region["calls"]:
                if call["sink"] is not None:
                    out.append((rel, call["line"], (
                        f"{call['sink']} inside a held lock region "
                        f"({region['lock']} at line "
                        f"{region['lock_line']}) — journal/file I/O under "
                        f"a threading lock serializes every other holder "
                        f"behind the write; defer the emit past the "
                        f"release (tenancy/arbiter.py fires-list pattern)")))
                    continue
                tgt = ctx.resolve(rel, region["fn"], call["ref"])
                if tgt is not None and tgt in ctx.io_reach:
                    out.append((rel, call["line"], (
                        f"call {call['text']}() reaches file/journal I/O "
                        f"({tgt[0]}::{tgt[1]}) inside a held lock region "
                        f"({region['lock']} at line "
                        f"{region['lock_line']}) — defer the I/O past the "
                        f"release (tenancy/arbiter.py fires-list pattern)")))
    return out


def check_gl007(ctx: ProjectContext) -> ProjectResult:
    """Journal-event-name drift, both directions (the GL004 registry
    pattern pointed at events): every tracer ``.event("x.y")`` literal
    must exist in ``telemetry/schema.py``'s golden schema, and every
    schema event must still have a live emit site (literal call or a
    deferred-fire tuple).  The drift class the golden-schema gate kept
    catching one review round late."""
    schema = ctx.event_schema
    if schema is None:
        return []
    out: ProjectResult = []
    emitted: Set[str] = set()
    for rel, facts in ctx.files.items():
        emitted.update(facts["deferred_events"])
        for emit in facts["emits"]:
            emitted.add(emit["name"])
            if emit["name"] not in schema.names:
                out.append((rel, emit["line"], (
                    f"journal event {emit['name']!r} is not in the golden "
                    f"event schema (telemetry/schema.py GOLDEN_EVENT_KEYS) "
                    f"— add it with its exact key set (and document it in "
                    f"docs/observability.md), or fix the name")))
    # the liveness direction only makes sense over the full tree (or when
    # a test hands us a schema explicitly): linting a subdirectory must
    # not declare every un-emitted event dead
    schema_rel = os.path.relpath(schema.relpath, ctx.root or os.getcwd())
    schema_rel = schema_rel.replace(os.sep, "/")
    if schema.explicit or schema_rel in ctx.files:
        for ev, line in schema.names.items():
            if ev not in emitted:
                out.append((schema_rel, line, (
                    f"schema event {ev!r} has no live emit site in the "
                    f"linted tree — remove it from GOLDEN_EVENT_KEYS or "
                    f"restore its producer")))
    return out


def check_gl008(ctx: ProjectContext) -> ProjectResult:
    """Counter-group / span-name drift against the generated registry
    (``analysis/counter_registry.py`` — same discipline as GL004's config
    registry).  F-string groups like ``f"Serving.{model}"`` normalize to
    ``Serving.*`` and match docs written as ``Serving.<model>``.  Test
    files are exempt (fixture groups are deliberate)."""
    registry = ctx.counter_registry
    if registry is None:
        return []
    out: ProjectResult = []
    for rel, facts in ctx.files.items():
        if _is_test_file(rel):
            continue
        for site in facts["counter_sites"]:
            doc = registry["groups"].get(site["group"], KeyError)
            if doc is KeyError:
                out.append((rel, site["line"], (
                    f"counter group {site['group']!r} is not in "
                    f"analysis/counter_registry.py — regenerate with "
                    f"`python -m avenir_tpu.analysis --write-registry`")))
            elif doc is None:
                out.append((rel, site["line"], (
                    f"counter group {site['group']!r} is undocumented — "
                    f"no docs/*.md mentions it; add it to "
                    f"docs/observability.md and regenerate the registry")))
        for site in facts["span_sites"]:
            doc = registry["spans"].get(site["name"], KeyError)
            if doc is KeyError:
                out.append((rel, site["line"], (
                    f"span name {site['name']!r} is not in "
                    f"analysis/counter_registry.py — regenerate with "
                    f"`python -m avenir_tpu.analysis --write-registry`")))
            elif doc is None:
                out.append((rel, site["line"], (
                    f"span name {site['name']!r} is undocumented — no "
                    f"docs/*.md span table mentions it; add it to "
                    f"docs/observability.md and regenerate the registry")))
    return out


PROJECT_RULES = {
    "GL006": check_gl006,
    "GL007": check_gl007,
    "GL008": check_gl008,
}
