"""Registry generators — the ground truth GL004 and GL008 lint against.

Scans the code tree for every ``conf.get*("literal")`` read (the same AST
extractor GL004 lints with, so the two can never disagree) and the docs
tree for every backtick-documented dotted key, then writes
``avenir_tpu/analysis/config_registry.py`` mapping each code key to the
doc file that mentions it (or ``None`` when undocumented — which GL004
then fails).  Round 20 added the same discipline for counter groups and
span names: ``counter_registry.py`` is generated from the facts
extractor GL008 lints with (f-string groups normalize to ``Serving.*``,
docs written as ``Serving.<model>`` match).  Regenerate after adding a
config key, counter group, or span::

    python -m avenir_tpu.analysis --write-registry
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

REGISTRY_PATH = os.path.join(os.path.dirname(__file__), "config_registry.py")
COUNTER_REGISTRY_PATH = os.path.join(os.path.dirname(__file__),
                                     "counter_registry.py")

# a documented key is a backtick span shaped like a dotted properties key:
# lowercase dotted segments (`stream.chunk.rows`), optionally written as
# `-Dkey=value` or `key=value`; single-segment keys (`seed`) only count
# when they appear in a `key` (value) doc position — handled by allowing
# bare [a-z]+ spans too, filtered against the code keys (false positives
# in docs are harmless: only keys the CODE reads enter the registry).
_FENCE_RE = re.compile(r"^```.*?^```\s*$", re.MULTILINE | re.DOTALL)
_BACKTICK_RE = re.compile(r"`([^`\n]+)`")
_KEY_RE = re.compile(r"^[a-z][a-z0-9]*(\.[a-z][a-z0-9]*)*$")


def scan_code_keys(paths: Sequence[str]) -> Dict[str, List[Tuple[str, int]]]:
    """key → [(file, line), ...] for every conf.get*("literal") in .py files
    under ``paths``."""
    from avenir_tpu.analysis.engine import _iter_py_files
    from avenir_tpu.analysis.rules import iter_conf_key_calls

    out: Dict[str, List[Tuple[str, int]]] = {}
    for path in _iter_py_files([os.fspath(p) for p in paths]):
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue                      # GL000 reports it; skip here
        for line, key in iter_conf_key_calls(tree):
            out.setdefault(key, []).append((path, line))
    return out


def scan_documented_keys(doc_paths: Sequence[str]) -> Dict[str, str]:
    """key → doc file for every dotted key mentioned in backticks across
    the given markdown files/dirs."""
    files: List[str] = []
    for p in doc_paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith("."))
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(filenames)
                             if n.endswith(".md"))
        elif p.endswith(".md") and os.path.exists(p):
            files.append(p)
    out: Dict[str, str] = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            # fenced code blocks would desync the inline-backtick pairing
            # (a ``` fence is an odd run of backticks), so drop them first
            text = _FENCE_RE.sub("", fh.read())
        for span in _BACKTICK_RE.findall(text):
            token = span.strip()
            if token.startswith("-D"):
                token = token[2:]
            token = token.split("=", 1)[0].strip()
            if _KEY_RE.match(token):
                out.setdefault(token, f.replace(os.sep, "/"))
    return out


def write_registry(code_paths: Sequence[str], doc_paths: Sequence[str],
                   root: Optional[str] = None,
                   out_path: str = REGISTRY_PATH) -> Dict[str, Optional[str]]:
    root = os.path.abspath(root or os.getcwd())
    code_keys = scan_code_keys(code_paths)
    documented = scan_documented_keys(doc_paths)

    def rel(p: str) -> str:
        ap = os.path.abspath(p)
        return (os.path.relpath(ap, root) if ap.startswith(root + os.sep)
                else ap).replace(os.sep, "/")

    registry: Dict[str, Optional[str]] = {
        key: (rel(documented[key]) if key in documented else None)
        for key in sorted(code_keys)
    }
    lines = [
        '"""Generated config-key registry — DO NOT EDIT BY HAND.',
        "",
        "Regenerate with `python -m avenir_tpu.analysis --write-registry`",
        "after adding or documenting a config key.  Maps every",
        'conf.get*("…") literal in the code tree to the doc file that',
        "documents it; None = undocumented (GL004 fails the build on it).",
        '"""',
        "",
        "CONFIG_KEYS = {",
    ]
    for key, doc in registry.items():
        lines.append(f"    {key!r}: {doc!r},")
    lines.append("}")
    with open(out_path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return registry


# ---------------------------------------------------------------------------
# counter-group / span-site registry (GL008 ground truth)
# ---------------------------------------------------------------------------

def scan_counter_span_sites(paths: Sequence[str]) \
        -> Tuple[Dict[str, List[Tuple[str, int]]],
                 Dict[str, List[Tuple[str, int]]]]:
    """(group → sites, span-name → sites) for every resolvable
    ``counters.increment/set`` group and tracer ``span``/``emit_span``
    literal under ``paths`` — the same facts extractor GL008 lints with,
    so the registry and the rule can never disagree.  Test files are
    excluded (fixture groups are deliberate)."""
    from avenir_tpu.analysis.engine import _iter_py_files
    from avenir_tpu.analysis.program import _is_test_file, extract_facts

    groups: Dict[str, List[Tuple[str, int]]] = {}
    spans: Dict[str, List[Tuple[str, int]]] = {}
    for path in _iter_py_files([os.fspath(p) for p in paths]):
        if _is_test_file(path.replace(os.sep, "/")):
            continue
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue                      # GL000 reports it; skip here
        facts = extract_facts(tree, src, path)
        for site in facts["counter_sites"]:
            groups.setdefault(site["group"], []).append((path,
                                                        site["line"]))
        for site in facts["span_sites"]:
            spans.setdefault(site["name"], []).append((path, site["line"]))
    return groups, spans


def scan_doc_tokens(doc_paths: Sequence[str]) -> Dict[str, str]:
    """token → doc file for every backtick span across the markdown
    tree, with ``<placeholder>`` segments normalized to ``*`` so
    ``Serving.<model>`` in docs matches the ``Serving.*`` pattern the
    code's f-string group normalizes to."""
    files: List[str] = []
    for p in doc_paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith("."))
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(filenames)
                             if n.endswith(".md"))
        elif p.endswith(".md") and os.path.exists(p):
            files.append(p)
    out: Dict[str, str] = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            text = _FENCE_RE.sub("", fh.read())
        for span in _BACKTICK_RE.findall(text):
            token = span.strip()
            token = re.sub(r"<[^<>]+>", "*", token)
            token = re.sub(r"\*+", "*", token)
            if token:
                out.setdefault(token, f.replace(os.sep, "/"))
    return out


def write_counter_registry(code_paths: Sequence[str],
                           doc_paths: Sequence[str],
                           root: Optional[str] = None,
                           out_path: str = COUNTER_REGISTRY_PATH) \
        -> Tuple[Dict[str, Optional[str]], Dict[str, Optional[str]]]:
    root = os.path.abspath(root or os.getcwd())
    groups, spans = scan_counter_span_sites(code_paths)
    documented = scan_doc_tokens(doc_paths)

    def rel(p: str) -> str:
        ap = os.path.abspath(p)
        return (os.path.relpath(ap, root) if ap.startswith(root + os.sep)
                else ap).replace(os.sep, "/")

    group_reg: Dict[str, Optional[str]] = {
        g: (rel(documented[g]) if g in documented else None)
        for g in sorted(groups)
    }
    span_reg: Dict[str, Optional[str]] = {
        s: (rel(documented[s]) if s in documented else None)
        for s in sorted(spans)
    }
    lines = [
        '"""Generated counter-group / span-site registry — DO NOT EDIT',
        "BY HAND.",
        "",
        "Regenerate with `python -m avenir_tpu.analysis --write-registry`",
        "after adding a counter group or span name.  Maps every",
        "resolvable Counters group and tracer span literal in the code",
        "tree to the doc file that documents it; None = undocumented",
        "(GL008 fails the build on it).  F-string names are normalized",
        'to wildcards ("Serving.*"), matching docs written as',
        '"Serving.<model>".',
        '"""',
        "",
        "COUNTER_GROUPS = {",
    ]
    for key, doc in group_reg.items():
        lines.append(f"    {key!r}: {doc!r},")
    lines.append("}")
    lines.append("")
    lines.append("SPAN_SITES = {")
    for key, doc in span_reg.items():
        lines.append(f"    {key!r}: {doc!r},")
    lines.append("}")
    with open(out_path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return group_reg, span_reg
