"""graftlint rules GL001–GL005 — each encodes a bug class an advisor round
found by hand in THIS repo (see docs/analysis.md for the history and
ADVICE.md citations).

All rules are pure-AST (stdlib ``ast`` only) and deliberately scoped to the
patterns this codebase actually uses, trading generality for a near-zero
false-positive rate: a lint gate that cries wolf gets suppressed wholesale
and protects nothing.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

RuleResult = List[Tuple[int, str]]          # (line, message)


@dataclass
class RuleContext:
    src: str
    relpath: str
    # GL004: key → doc location (None = undocumented); None = load default
    config_keys: Optional[dict] = None
    # GL011: events documented once-per-run (telemetry/schema.py
    # EVENT_ONCE); None = load default from the schema file
    event_once: Optional[frozenset] = None


# ---------------------------------------------------------------------------
# shared AST plumbing
# ---------------------------------------------------------------------------

def _attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._gl_parent = node          # type: ignore[attr-defined]


def _ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "_gl_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_gl_parent", None)


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.device_get' for Attribute/Name chains; None for anything else
    (calls on call results, subscripts, ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for anc in _ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _in_loop(node: ast.AST, stop_at: Optional[ast.AST] = None) -> bool:
    for anc in _ancestors(node):
        if anc is stop_at:
            return False
        if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
            return True
    return False


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:                         # pragma: no cover
        return ""


# ---------------------------------------------------------------------------
# GL001 — collective divergence
# ---------------------------------------------------------------------------

# the multi-process merge seams (parallel/mesh.py, jax multihost utils): a
# value that differs across processes must never be computed on the path
# into one of these without either a writer guard (process 0 computes, the
# collective itself broadcasts) or the error-through-the-collective pattern
_GL001_SINKS = ("all_process_sum_state", "process_allgather",
                "broadcast_one_to_all")

# process-divergent value producers: unlocked file reads, env, clocks, RNG,
# and per-process checkpoint restores
_GL001_SOURCE_CALLS = {"open", "load_state"}
_GL001_SOURCE_DOTTED_PREFIXES = (
    "os.environ", "os.getenv", "time.time", "time.monotonic",
    "time.perf_counter", "random.", "np.random.", "numpy.random.",
)
_GL001_SOURCE_METHOD_SUFFIXES = (".restore",)

_GL001_GUARDS = ("is_output_writer", "process_index", "process_count",
                 "nprocs")


def _gl001_is_source(call: ast.Call) -> Optional[str]:
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    if dotted in _GL001_SOURCE_CALLS:
        return dotted
    for prefix in _GL001_SOURCE_DOTTED_PREFIXES:
        if dotted == prefix.rstrip(".") or dotted.startswith(prefix):
            return dotted
    for suffix in _GL001_SOURCE_METHOD_SUFFIXES:
        if dotted.endswith(suffix):
            return dotted
    return None


def _gl001_guarded(node: ast.AST, fn: ast.AST) -> bool:
    for anc in _ancestors(node):
        if anc is fn:
            return False
        if isinstance(anc, ast.If) and any(
                g in _unparse(anc.test) for g in _GL001_GUARDS):
            return True
    return False


def check_gl001(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """Process-divergent value (unlocked read / env / clock / RNG /
    per-process restore) computed in a function that enters a cross-process
    collective, without a writer guard.  The regress.py round-5 bug class:
    peers read the LR coefficient file independently of the writer's locked
    read, then entered the gradient collective with different resume
    weights (ADVICE.md r5 #1)."""
    _attach_parents(tree)
    out: RuleResult = []
    for fn in _functions(tree):
        has_sink = any(
            isinstance(n, ast.Call)
            and (_dotted(n.func) or "").split(".")[-1] in _GL001_SINKS
            for n in ast.walk(fn))
        if not has_sink:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _enclosing_function(node) is not fn:
                continue                     # belongs to a nested function
            src_name = _gl001_is_source(node)
            if src_name is None or _gl001_guarded(node, fn):
                continue
            out.append((node.lineno, (
                f"process-divergent value from {src_name}() computed in a "
                f"function that enters a cross-process collective "
                f"({'/'.join(_GL001_SINKS[:2])}) without a writer guard — "
                f"route it through process 0 + the broadcast handshake "
                f"(jobs/regress.py::_broadcast_resume pattern)")))
    return out


# ---------------------------------------------------------------------------
# GL002 — unfingerprinted checkpoint/accumulator keys
# ---------------------------------------------------------------------------

_GL002_IDENTITY_HINTS = ("run", "fingerprint", "fp", "key", "id", "meta",
                         "schema")


def check_gl002(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """Checkpoint/accumulator state that doesn't fingerprint the
    configuration that produced it.  The correlation.py round-5 bug class:
    einsum-path keys named only c0, c256, ... restored cleanly after the
    attribute lists changed, silently summing incompatible pair counts
    (ADVICE.md r5 #3 — fixed in PR 1 by the `_einsum_key_prefix`
    fingerprint).

    Pattern A: a dict literal passed to a checkpoint ``save`` whose keys
    carry no identity/fingerprint component (``run``/``id``/...).
    Pattern B: an f-string accumulator key whose literal part is a bare
    1–3 letter tag and whose placeholders are plain loop indices — no
    fingerprint variable qualifies the key family.
    """
    _attach_parents(tree)
    out: RuleResult = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func) or ""
        tail = dotted.split(".")[-1]
        receiver = dotted.rsplit(".", 1)[0] if "." in dotted else ""
        # -- pattern A: snapshot dict without an identity key -------------
        if tail in ("save", "save_state") and (
                "save_state" in dotted or "mgr" in receiver
                or "manager" in receiver or "checkpoint" in receiver):
            for arg in node.args:
                if not isinstance(arg, ast.Dict):
                    continue
                keys = [k.value for k in arg.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
                if keys and not any(
                        h in k for k in keys for h in _GL002_IDENTITY_HINTS):
                    out.append((arg.lineno, (
                        f"checkpoint snapshot dict {{{', '.join(keys)}}} "
                        f"carries no run/config identity key — a stale "
                        f"snapshot from another configuration restores "
                        f"silently (models/correlation.py r5 bug class); "
                        f"add a fingerprint entry and validate on restore")))
        # -- pattern B: bare-index accumulator key family -----------------
        if tail == "add" and "acc" in dotted.split(".")[0].lower() and \
                node.args and isinstance(node.args[0], ast.JoinedStr):
            key = node.args[0]
            first = key.values[0] if key.values else None
            if (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and re.fullmatch(r"[a-z]{1,3}", first.value)
                    and all(isinstance(v, (ast.Constant, ast.FormattedValue))
                            for v in key.values)):
                out.append((key.lineno, (
                    f"accumulator key {_unparse(key)!r} is a bare "
                    f"tag+index with no configuration fingerprint "
                    f"component — a checkpoint restored under a different "
                    f"configuration produces the same key names and sums "
                    f"incompatible partials; qualify the key family like "
                    f"models/correlation.py::_einsum_key_prefix")))
    return out


# ---------------------------------------------------------------------------
# GL003 — fixed-width format keys without a bound assert
# ---------------------------------------------------------------------------

_WIDTH_RE = re.compile(r"^0(\d+)d$")


def _gl003_has_bound_check(scope: ast.AST, width: int) -> bool:
    """True when the enclosing scope compares something against 10**width
    (either spelling) — the loud-failure guard that keeps lexicographic
    order == numeric order inside the key width."""
    bound = 10 ** width
    for node in ast.walk(scope):
        if isinstance(node, ast.Constant) and node.value == bound:
            return True
        if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Pow)
                and isinstance(node.left, ast.Constant)
                and node.left.value == 10
                and isinstance(node.right, ast.Constant)
                and node.right.value == width):
            return True
    return False


def check_gl003(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """``{x:0Nd}`` fixed-width keys with no adjacent 10**N bound check.
    The chombo.py round-5 bug class: ``c{idx:08d}`` snapshot keys silently
    mis-ordered the ascending-key finalize fold past 10^8 chunks
    (ADVICE.md r5 #4 — the fixed path now asserts ``idx < 10**12``).
    Sorted folds, directory names, and generated ids all merge or list
    lexicographically, so a value past the width reorders silently."""
    _attach_parents(tree)
    out: RuleResult = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FormattedValue) or \
                node.format_spec is None:
            continue
        spec = "".join(
            v.value for v in node.format_spec.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str))
        m = _WIDTH_RE.match(spec)
        if not m:
            continue
        width = int(m.group(1))
        scope = _enclosing_function(node) or tree
        if _gl003_has_bound_check(scope, width):
            continue
        out.append((node.lineno, (
            f"fixed-width key format ':{spec}' has no adjacent 10**{width} "
            f"bound check — values past the width silently break "
            f"lexicographic==numeric ordering (jobs/chombo.py r5 bug "
            f"class); assert/raise against 10**{width} in the same "
            f"function, or widen the field")))
    return out


# ---------------------------------------------------------------------------
# GL004 — config keys outside the generated registry / undocumented
# ---------------------------------------------------------------------------

_CONF_GETTERS = {"get", "get_int", "get_float", "get_bool", "get_list",
                 "get_int_list", "get_float_list"}


def iter_conf_key_calls(tree: ast.AST) -> Iterator[Tuple[int, str]]:
    """(line, key) for every ``conf.get*("literal")`` call — shared by the
    GL004 check and the registry generator so they can never disagree on
    what counts as a config-key read."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _CONF_GETTERS):
            continue
        dotted = _dotted(node.func) or ""
        receiver = dotted.rsplit(".", 1)[0].split(".")[-1].lower()
        if "conf" not in receiver and "cfg" not in receiver:
            continue                    # dict.get(...) etc, not a JobConfig
        if node.args and isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            yield node.args[0].lineno, node.args[0].value


def _default_config_keys() -> dict:
    try:
        from avenir_tpu.analysis.config_registry import CONFIG_KEYS
        return CONFIG_KEYS
    except ImportError:                      # registry not generated yet
        return {}


def check_gl004(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """Every ``conf.get*("…")`` literal must exist in the generated
    ``analysis/config_registry.py`` AND be documented in docs/.  The drift
    this catches: keys like ``class.condtion.weighted`` (the reference's
    own typo, kept for compat) living in code with no doc trail, so config
    written against docs/jobs.md silently does nothing."""
    registry = ctx.config_keys if ctx.config_keys is not None \
        else _default_config_keys()
    out: RuleResult = []
    for line, key in iter_conf_key_calls(tree):
        if key not in registry:
            out.append((line, (
                f"unknown config key {key!r} — not in "
                f"analysis/config_registry.py; regenerate with "
                f"`python -m avenir_tpu.analysis --write-registry` and "
                f"document the key in docs/jobs.md")))
        elif registry[key] is None:
            out.append((line, (
                f"config key {key!r} is undocumented — no docs/*.md "
                f"mentions it; add it to docs/jobs.md and regenerate the "
                f"registry")))
    return out


# ---------------------------------------------------------------------------
# GL005 — host sync inside a hot loop
# ---------------------------------------------------------------------------

_GL005_SYNC_DOTTED = {"jax.device_get", "jax.block_until_ready"}
_GL005_FETCHERS = {"float", "int", "np.asarray", "np.array",
                   "numpy.asarray", "numpy.array"}
_GL005_DEVICE_PREFIXES = ("jnp.", "jax.lax.", "jax.nn.", "lax.")


def _gl005_on_host(node: ast.AST) -> bool:
    for anc in _ancestors(node):
        if isinstance(anc, ast.With) and any(
                "on_host" in _unparse(item.context_expr)
                for item in anc.items):
            return True
    return False


def check_gl005(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """``.item()`` / ``jax.device_get`` / ``float(traced)`` /
    ``np.asarray(traced)`` inside a ``for``/``while`` loop: each iteration
    pays a full host↔device round trip, serializing the pipeline — the
    round-5 tree-induction wall (~100 ms RTT × depth capped induction at
    0.21× sklearn until PR 1 moved selection on-device).  Values are
    "traced" when assigned in the same function from a jnp./jax.lax. call;
    ``with …on_host():`` blocks are exempt (explicit host-compute
    escape hatch, ops/info.py)."""
    _attach_parents(tree)
    out: RuleResult = []
    for fn in _functions(tree):
        tainted = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                dotted = _dotted(node.value.func) or ""
                if any(dotted.startswith(p)
                       for p in _GL005_DEVICE_PREFIXES):
                    for tgt in node.targets:
                        for t in ast.walk(tgt):
                            if isinstance(t, ast.Name):
                                tainted.add(t.id)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _enclosing_function(node) is not fn:
                continue
            if not _in_loop(node, stop_at=fn) or _gl005_on_host(node):
                continue
            dotted = _dotted(node.func) or ""
            hit = None
            if dotted in _GL005_SYNC_DOTTED:
                hit = dotted
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                hit = ".item()"
            elif dotted in _GL005_FETCHERS and node.args:
                arg = node.args[0]
                base = arg
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                arg_dotted = _dotted(arg.func) if isinstance(arg, ast.Call) \
                    else None
                if (isinstance(base, ast.Name) and base.id in tainted) or \
                        (arg_dotted and any(
                            arg_dotted.startswith(p)
                            for p in _GL005_DEVICE_PREFIXES)):
                    hit = f"{dotted}(<traced>)"
            if hit:
                out.append((node.lineno, (
                    f"host sync {hit} inside a loop — every iteration pays "
                    f"a device round trip (the r05 tree-induction RTT "
                    f"wall); batch the fetch outside the loop or keep the "
                    f"reduction on device (models/tree.py::"
                    f"_device_select_splits pattern)")))
    return out


# ---------------------------------------------------------------------------
# GL009 — thread targets without exception routing
# ---------------------------------------------------------------------------

_GL009_BROAD = {"Exception", "BaseException"}


def _gl009_routes_exceptions(fn: ast.AST) -> bool:
    """True when the function body contains a broad try/except — the
    minimum routing discipline for code that runs on its own thread (the
    handler is expected to push the error into a queue / handshake list /
    typed shed, which review checks; this rule only catches the
    nothing-at-all class)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if handler.type is None:
                return True
            names = [handler.type] if not isinstance(handler.type,
                                                     ast.Tuple) \
                else list(handler.type.elts)
            for n in names:
                if isinstance(n, ast.Name) and n.id in _GL009_BROAD:
                    return True
    return False


def check_gl009(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """``threading.Thread(target=f)`` where ``f`` (resolved in this file)
    has no broad except anywhere in its body: an exception kills the
    thread silently and the joiner hangs or loses the failure.  The PR 6
    ``_handshake_errors`` class — worker threads must route failures into
    a handshake/queue/typed-shed path the spawner drains.  Test files are
    exempt (like GL008): a fixture thread that raises fails the test
    through its joined-state assertions, and pytest owns the report."""
    from avenir_tpu.analysis.program import _is_test_file
    if _is_test_file(ctx.relpath):
        return []
    _attach_parents(tree)
    # symbol table: module functions + methods, by simple name
    defs: Dict[str, ast.AST] = {}
    methods: Dict[Tuple[str, str], ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
            for anc in _ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    methods[(anc.name, node.name)] = node
                    break
    out: RuleResult = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if (_dotted(node.func) or "").split(".")[-1] != "Thread":
            continue
        target = next((kw.value for kw in node.keywords
                       if kw.arg == "target"), None)
        if target is None:
            continue
        dotted = _dotted(target)
        fn = None
        if dotted is None:
            continue                         # lambda / call result: skip
        parts = dotted.split(".")
        if len(parts) == 1:
            fn = defs.get(parts[0])
        elif parts[0] in ("self", "cls") and len(parts) == 2:
            for anc in _ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    fn = methods.get((anc.name, parts[1]))
                    break
        if fn is None:
            continue                         # cross-object target: skip
        if not _gl009_routes_exceptions(fn):
            out.append((node.lineno, (
                f"thread target {dotted}() has no broad except — an "
                f"uncaught exception kills the thread silently and the "
                f"joiner hangs or loses the failure; route errors into a "
                f"handshake/queue/typed-shed path the spawner drains "
                f"(jobs/base.py::_handshake_errors pattern)")))
    return out


# ---------------------------------------------------------------------------
# GL010 — bare ValueError/RuntimeError on conf-contract paths
# ---------------------------------------------------------------------------

_GL010_BARE = {"ValueError", "RuntimeError"}
_GL010_KEY_RE = re.compile(r"[a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+")


def _gl010_message_literals(exc: ast.Call) -> str:
    """The constant text of the exception message (plain string or the
    literal parts of an f-string)."""
    if not exc.args:
        return ""
    arg = exc.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        return "".join(v.value for v in arg.values
                       if isinstance(v, ast.Constant)
                       and isinstance(v.value, str))
    return ""


def check_gl010(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """``raise ValueError/RuntimeError`` on a conf-contract path — the
    config error contract (core/config.py::ConfigError, PR 7's
    ``shard.devices`` fix) demands the typed error so callers and the CLI
    can distinguish bad configuration from internal failures.  Fires when
    the message names a registered config key, or when the raise is
    guarded by an ``if`` over a value read from ``conf.get*()`` in the
    same function."""
    registry = ctx.config_keys if ctx.config_keys is not None \
        else _default_config_keys()
    _attach_parents(tree)
    out: RuleResult = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Raise) or \
                not isinstance(node.exc, ast.Call) or \
                not isinstance(node.exc.func, ast.Name) or \
                node.exc.func.id not in _GL010_BARE:
            continue
        kind = node.exc.func.id
        message = _gl010_message_literals(node.exc)
        named_keys = [t for t in _GL010_KEY_RE.findall(message)
                      if t in registry]
        conf_guarded = False
        fn = _enclosing_function(node)
        if fn is not None and not named_keys:
            tainted = set()
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign) and \
                        isinstance(n.value, ast.Call) and \
                        isinstance(n.value.func, ast.Attribute) and \
                        n.value.func.attr in _CONF_GETTERS:
                    dotted = _dotted(n.value.func) or ""
                    receiver = dotted.rsplit(".", 1)[0].split(".")[-1]
                    if "conf" in receiver.lower() or \
                            "cfg" in receiver.lower():
                        for tgt in n.targets:
                            for t in ast.walk(tgt):
                                if isinstance(t, ast.Name):
                                    tainted.add(t.id)
            for anc in _ancestors(node):
                if anc is fn:
                    break
                if isinstance(anc, ast.If) and any(
                        isinstance(t, ast.Name) and t.id in tainted
                        for t in ast.walk(anc.test)):
                    conf_guarded = True
                    break
        if named_keys or conf_guarded:
            what = (f"names config key {named_keys[0]!r}" if named_keys
                    else "is guarded by a conf.get*() value")
            out.append((node.lineno, (
                f"bare {kind} on a conf-contract path ({what}) — raise "
                f"ConfigError (core/config.py) instead so callers and "
                f"the CLI can tell bad configuration from internal "
                f"failures (the PR 7 shard.devices class); ConfigError "
                f"subclasses ValueError, so existing callers keep "
                f"working")))
    return out


# ---------------------------------------------------------------------------
# GL011 — once-per-run events emitted without the latch
# ---------------------------------------------------------------------------

def _default_event_once() -> frozenset:
    from avenir_tpu.analysis.program import load_event_schema
    schema = load_event_schema()
    return frozenset(schema.once) if schema is not None else frozenset()


def check_gl011(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """A once-per-run event (telemetry/schema.py EVENT_ONCE) emitted via
    plain ``.event()`` instead of ``event_once``/a latch: restarts,
    retries, and per-chunk paths spam duplicates of records every
    consumer treats as unique (the shard.topology/fleet.join/
    tenant.admitted contract)."""
    once = ctx.event_once if ctx.event_once is not None \
        else _default_event_once()
    if not once:
        return []
    from avenir_tpu.analysis.program import _emit_site
    out: RuleResult = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        site = _emit_site(node)
        if site is not None and site[0] == "event" and site[1] in once:
            out.append((node.lineno, (
                f"once-per-run event {site[1]!r} emitted with plain "
                f".event() — use tracer.event_once(..., key=...) (or an "
                f"equivalent latch) so restarts and per-chunk paths "
                f"can't journal duplicates")))
    return out


# ---------------------------------------------------------------------------
# GL012 — silently swallowed broad excepts
# ---------------------------------------------------------------------------

def check_gl012(tree: ast.AST, ctx: RuleContext) -> RuleResult:
    """``except Exception:`` (or bare ``except:``) whose body is nothing
    but ``pass``/``continue``/``break`` — the failure leaves no trace:
    no re-raise, no counter, no journal event.  Exempt when the ``try``
    body imports (optional-dependency probes are the one legitimate
    silent catch).  The review class behind PR 14's swallowed journal
    errors: a silent except turns a real failure into a debugging
    session."""
    _attach_parents(tree)
    out: RuleResult = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        probes_import = any(isinstance(n, (ast.Import, ast.ImportFrom))
                            for stmt in node.body
                            for n in ast.walk(stmt))
        if probes_import:
            continue
        for handler in node.handlers:
            broad = handler.type is None or (
                isinstance(handler.type, ast.Name)
                and handler.type.id in ("Exception", "BaseException"))
            if not broad:
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue, ast.Break))
                   for s in handler.body):
                out.append((handler.lineno, (
                    f"except "
                    f"{'Exception' if handler.type is not None else ''}"
                    f" swallows silently — no re-raise, counter, or "
                    f"journal event survives the failure; record it "
                    f"(Counters / tracer.event) or re-raise, and if the "
                    f"silence is designed, say why on a graftlint "
                    f"disable comment")))
    return out


# ---------------------------------------------------------------------------

RULES: Dict[str, Callable[[ast.AST, RuleContext], RuleResult]] = {
    "GL001": check_gl001,
    "GL002": check_gl002,
    "GL003": check_gl003,
    "GL004": check_gl004,
    "GL005": check_gl005,
    "GL009": check_gl009,
    "GL010": check_gl010,
    "GL011": check_gl011,
    "GL012": check_gl012,
}
