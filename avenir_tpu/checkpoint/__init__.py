"""ElasticGraft — the elastic-restore plane (round 16).

``checkpoint/reshard.py`` is the redistribution transform that makes
checkpointed accumulator state layout-portable across topology change
(kill on 8 devices, resume on 4, byte-identical); ``utils/checkpoint.py``
remains the durable snapshot store it operates on.
"""

from avenir_tpu.checkpoint.reshard import (  # noqa: F401
    MESH_TAG,
    ReshardError,
    journal_reshard,
    rekey_state,
    reshard_state_tree,
    snapshot_suffix,
    spec_suffix,
    split_mesh_key,
    state_suffix,
)
