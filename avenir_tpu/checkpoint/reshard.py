"""ElasticGraft reshard — topology-portable checkpoint redistribution.

PR 7's mesh-qualified accumulator keys (``parallel/shard.py::
ShardSpec.g_suffix``, ``:mesh:<axis><n>``) make a resharded restore fail
LOUDLY — the right first step, but production TPU quota is preemptible,
and a fleet that shrinks 8→4 devices must resume, not die.  This module
is the redistribution transform (the portable collective array-
redistribution recipe, arXiv 2112.01075, applied to *host* accumulator
state): a saved state tree is re-keyed and redistributed for a new
topology, exactly, or refused with a typed :class:`ReshardError` naming
the offending key.

Why re-keying is exact: every mesh-qualified entry is a 64-bit HOST
total that the in-kernel psum already reduced over the source mesh —
int64 count sums (and the order-exact float64 moment sums the tests
construct) are mesh-shape-invariant, so an 8-way fold's totals ARE the
4-way fold's totals byte-for-byte.  The same argument covers
CrossGraft's PROCESS-qualified suffixes (``:mesh:proc2xdata4``): the
global fold's hierarchical psum already reduced over both axes before
the host total existed, so a kill-on-2-procs → resume-on-1-proc restore
re-keys the identical bytes (tests/test_reshard.py cross-process case).  The mesh suffix exists to prevent
*silent* cross-topology summing, not because the numbers differ; the
transform moves state across that gate deliberately and journals the
crossing (``checkpoint.reshard``).

What stays refused (genuinely non-portable):

- a ``g:`` key whose mesh suffix matches neither the declared source
  topology nor the target (mixed/unknown-topology state);
- two entries that would collide under one target key;
- a ``g:`` key whose base LAYOUT differs from the target fold's (the
  kernel plan is a pure function of (F, B, C) — a base mismatch means
  the schema changed, which no redistribution can reconcile);
- chunked-einsum count state (``fc``/``pcc<off>`` keys) restored onto a
  gram-keyed routing: the pair-chunked tensors cannot be promoted back
  into one G matrix (pairs outside the union were never aggregated).

The routing-aware half — *demoting* a gram onto a target that folds
under chunked einsum keys — lives with the owner of the routing,
:meth:`avenir_tpu.pipeline.scan.ChunkFolder.adopt_state`; this module
holds the generic key algebra so every seam (``WindowCheckpointer``,
``StreamCheckpointer``, ``CheckpointManager.restore(reshard_to=...)``)
transforms state the same way.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

MESH_TAG = ":mesh:"


class ReshardError(ValueError):
    """State that cannot be redistributed to the target topology; the
    message names the offending key."""


def spec_suffix(spec) -> str:
    """The mesh-qualifier suffix of a topology operand: a
    ``ShardSpec``-like object (``g_suffix``), an explicit suffix string
    (``":mesh:data4"`` or ``""``), or None (unsharded)."""
    if spec is None:
        return ""
    if isinstance(spec, str):
        if spec and not spec.startswith(MESH_TAG):
            raise ReshardError(
                f"target suffix {spec!r} is not a {MESH_TAG}<axis><n> "
                f"mesh qualifier")
        return spec
    return spec.g_suffix


def split_mesh_key(key: str) -> Tuple[str, str]:
    """``"g:cls:f4:b5:c2:mesh:data8"`` → ``("g:cls:f4:b5:c2",
    ":mesh:data8")``; an unqualified key keeps an empty suffix."""
    pos = key.find(MESH_TAG)
    if pos < 0:
        return key, ""
    return key[:pos], key[pos:]


def state_suffix(state: Dict[str, Any]) -> Optional[str]:
    """The ONE mesh suffix an accumulator-state mapping was folded under:
    ``":mesh:<axis><n>"`` for a fused-shard fold, ``""`` for an
    unqualified gram, None when the mapping holds no gram key at all (no
    topology evidence — an empty pane, a moments-only fold).  Raises
    :class:`ReshardError` on mixed-topology state — two suffixes in one
    mapping means some totals would survive a re-key that others refuse,
    which is exactly the silent-partial-fold hazard."""
    seen: Dict[str, str] = {}
    for key in state:
        if isinstance(key, str) and key.startswith("g:"):
            _, sfx = split_mesh_key(key)
            seen[sfx] = key
    if len(seen) > 1:
        raise ReshardError(
            f"mixed-topology accumulator state: gram keys "
            f"{sorted(seen.values())} carry different mesh qualifiers — "
            f"state folded under two topologies cannot be redistributed")
    return next(iter(seen), None)


def snapshot_suffix(state: Dict[str, Any]) -> Optional[str]:
    """The writing topology of a WHOLE checkpoint snapshot: the recorded
    ``"shard"`` field when present (round-16 snapshots), else inferred
    from the gram keys of every accumulator mapping it holds
    (``ring[i]["state"]`` pane states, ``"acc"`` totals) — panes with no
    gram evidence (empty panes) don't vote.  None = no evidence anywhere;
    :class:`ReshardError` when two panes disagree."""
    recorded = state.get("shard")
    if isinstance(recorded, str):
        return recorded
    votes = set()
    for rec in state.get("ring") or []:
        if isinstance(rec, dict):
            sfx = state_suffix(rec.get("state") or {})
            if sfx is not None:
                votes.add(sfx)
    if isinstance(state.get("acc"), dict):
        sfx = state_suffix(state["acc"])
        if sfx is not None:
            votes.add(sfx)
    if len(votes) > 1:
        raise ReshardError(
            f"snapshot holds accumulator state under {len(votes)} "
            f"different topologies ({sorted(votes)}) — mixed-topology "
            f"snapshots cannot be redistributed")
    return next(iter(votes), None)


def rekey_state(state: Dict[str, Any], target,
                source=None) -> Tuple[Dict[str, Any], List[str]]:
    """Re-key every mesh-qualified ``g:`` entry of one accumulator-state
    mapping for the target topology; values pass through UNTOUCHED (the
    64-bit totals are mesh-shape-invariant — see module docstring).

    ``target``/``source`` are :func:`spec_suffix` operands; a None source
    means "accept whatever one suffix the state carries" (inferred via
    :func:`state_suffix`).  Returns ``(new_state, rekeyed_keys)``.
    Raises :class:`ReshardError` on a suffix that matches neither source
    nor target, or a post-transform collision.
    """
    dst = spec_suffix(target)
    if source is not None:
        src = spec_suffix(source)
    else:
        inferred = state_suffix(state)
        src = dst if inferred is None else inferred
    out: Dict[str, Any] = {}
    rekeyed: List[str] = []
    for key, val in state.items():
        new_key = key
        if isinstance(key, str) and key.startswith("g:"):
            base, sfx = split_mesh_key(key)
            if sfx not in (src, dst):
                raise ReshardError(
                    f"gram state {key!r} was folded under topology "
                    f"{sfx or 'unsharded'!r}, not the declared source "
                    f"{src or 'unsharded'!r} — refusing to redistribute "
                    f"state of unknown provenance")
            new_key = base + dst
            if new_key != key:
                rekeyed.append(key)
        if new_key in out:
            raise ReshardError(
                f"redistributing {key!r} onto {new_key!r} collides with "
                f"another entry of the same state — the source mapping "
                f"already holds both topologies' totals")
        out[new_key] = val
    return out, rekeyed


def _is_acc_state(node: Any) -> bool:
    return isinstance(node, dict) and any(
        isinstance(k, str) and k.startswith("g:") for k in node)


def reshard_state_tree(tree: Any, target,
                       source=None) -> Tuple[Any, List[str]]:
    """Walk an arbitrary checkpoint state tree and re-key every
    accumulator-state mapping (any dict holding a ``g:`` key) for the
    target topology — the generic transform behind
    ``CheckpointManager.restore(reshard_to=...)``.  Covers the shapes the
    repo persists today: ``WindowCheckpointer`` pane rings (``ring[i]
    ["state"]``), ``StreamCheckpointer`` totals (``"acc"``), and LR
    history/gradient folds (no ``g:`` keys — pass through untouched, as
    do cursors and pane/row counters, which count rows, not devices).
    A top-level ``"shard"`` entry (the recorded writing topology) is
    rewritten to the target suffix.  Returns ``(new_tree, rekeyed_keys)``.
    """
    rekeyed: List[str] = []

    def walk(node: Any) -> Any:
        if _is_acc_state(node):
            out, moved = rekey_state(node, target, source)
            rekeyed.extend(moved)
            return out
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        if isinstance(node, tuple):
            return tuple(walk(v) for v in node)
        return node

    out = walk(tree)
    # only the TOP-LEVEL "shard" entry is the snapshot's recorded writing
    # topology; nested dicts (component extras) may use the name freely
    if isinstance(out, dict) and isinstance(out.get("shard"), str):
        out["shard"] = spec_suffix(target)
    return out, rekeyed


def journal_reshard(src: str, dst: str, keys: int, directory: str = "",
                    run: str = "") -> None:
    """Journal one ``checkpoint.reshard`` crossing (golden-schema'd,
    tests/test_telemetry.py): the topology a snapshot was written under,
    the topology it was redistributed onto, and how many accumulator
    entries moved — so GraftFleet's merged trace explains every
    preemption drill end to end."""
    from avenir_tpu.telemetry import spans as tel

    tel.tracer().event("checkpoint.reshard",
                       dir=directory, run=run,
                       src=src or "unsharded", dst=dst or "unsharded",
                       keys=keys)


def describe(suffix: str) -> str:
    """Human-readable topology name for error messages/logs."""
    return suffix or "unsharded"


def suffix_procs(suffix: str) -> int:
    """The process count a mesh qualifier encodes: ``:mesh:proc2xdata4``
    → 2 (CrossGraft's global fold), ``:mesh:data8`` / ``""`` → 1.  The
    transform itself is suffix-OPAQUE (64-bit host totals are
    mesh-shape-invariant, so re-keying a process-qualified entry moves
    the same bytes — a kill-on-2-procs → resume-on-1-proc restore is
    byte-identical by the same argument as 8→4 devices); this parser
    exists for diagnostics and the journal, not for the algebra."""
    import re

    if not suffix:
        return 1
    m = re.match(rf"{re.escape(MESH_TAG)}([a-z]+)(\d+)x", suffix)
    return int(m.group(2)) if m else 1
