from avenir_tpu.core.schema import FeatureField, FeatureSchema
from avenir_tpu.core.config import JobConfig
from avenir_tpu.core.encoding import DatasetEncoder, EncodedDataset

__all__ = ["FeatureField", "FeatureSchema", "JobConfig", "DatasetEncoder", "EncodedDataset"]
