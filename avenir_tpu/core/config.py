"""Job configuration — Java-properties-compatible key/value config.

The reference drives every job from a ``.properties`` file passed as
``-Dconf.path=...`` and loaded into the Hadoop ``Configuration``
(chombo ``Utility.setConfiguration``, called in every job ``run()``, e.g.
bayesian/BayesianDistribution.java:68). Keys are dotted names with optional
system prefixes; values are strings with typed getters and defaults (chombo
``ConfigUtility``).

This module keeps that two-artifact contract (properties + JSON feature
schema) so a reference user's config carries over: the same property names are
honored by the estimators (``field.delim.regex``, ``top.match.count``,
``kernel.function.type``, ...).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional


class ConfigError(ValueError):
    """A deterministic configuration/schema error — the same inputs will
    fail the same way, so retry layers must fail fast instead of retrying
    (see utils/retry.py RetryPolicy.from_conf)."""


class JobConfig:
    """Parsed properties file with typed getters.

    ``prefix`` mirrors the reference's behavior of accepting keys both bare
    and namespaced (``avenir.some.key`` == ``some.key``).
    """

    def __init__(self, props: Optional[Dict[str, str]] = None, prefix: str = "avenir"):
        self.props: Dict[str, str] = dict(props or {})
        self.prefix = prefix

    # -- construction --------------------------------------------------------
    @classmethod
    def from_file(cls, path: str, prefix: str = "avenir") -> "JobConfig":
        with open(path, "r") as fh:
            return cls.from_lines(fh, prefix=prefix)

    @classmethod
    def from_lines(cls, lines: Iterable[str], prefix: str = "avenir") -> "JobConfig":
        props: Dict[str, str] = {}
        for raw in lines:
            line = raw.strip()
            if not line or line.startswith("#") or line.startswith("!"):
                continue
            # Java Properties rule: split at the FIRST '=' or ':' in the line
            cut = min((i for i in (line.find("="), line.find(":")) if i >= 0), default=-1)
            if cut >= 0:
                props[line[:cut].strip()] = line[cut + 1:].strip()
        return cls(props, prefix=prefix)

    # -- lookup --------------------------------------------------------------
    def _lookup(self, key: str) -> Optional[str]:
        if key in self.props:
            return self.props[key]
        pref = f"{self.prefix}.{key}"
        if pref in self.props:
            return self.props[pref]
        if key.startswith(f"{self.prefix}.") and key[len(self.prefix) + 1:] in self.props:
            return self.props[key[len(self.prefix) + 1:]]
        return None

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        val = self._lookup(key)
        return default if val is None else val

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        val = self._lookup(key)
        return default if val is None or val == "" else int(val)

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        val = self._lookup(key)
        return default if val is None or val == "" else float(val)

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self._lookup(key)
        if val is None or val == "":
            return default
        return val.strip().lower() in ("true", "1", "yes", "on")

    def get_list(self, key: str, default: Optional[List[str]] = None, delim: str = ",") -> Optional[List[str]]:
        val = self._lookup(key)
        if val is None or val == "":
            return default
        return [v.strip() for v in val.split(delim)]

    def get_int_list(self, key: str, default: Optional[List[int]] = None, delim: str = ",") -> Optional[List[int]]:
        vals = self.get_list(key, None, delim)
        return default if vals is None else [int(v) for v in vals]

    def get_float_list(self, key: str, default: Optional[List[float]] = None, delim: str = ",") -> Optional[List[float]]:
        vals = self.get_list(key, None, delim)
        return default if vals is None else [float(v) for v in vals]

    def set(self, key: str, value: Any) -> "JobConfig":
        self.props[key] = str(value)
        return self

    def __contains__(self, key: str) -> bool:
        return self._lookup(key) is not None

    def __repr__(self) -> str:
        return f"JobConfig({len(self.props)} props, prefix={self.prefix!r})"

    # -- common keys ---------------------------------------------------------
    @property
    def field_delim(self) -> str:
        return self.get("field.delim", ",")

    @property
    def field_delim_regex(self) -> str:
        return self.get("field.delim.regex", ",")

    @property
    def debug_on(self) -> bool:
        return self.get_bool("debug.on", False)
