"""CSV record I/O — chunked readers/writers for the CSV-in/CSV-out contract.

The reference's I/O contract is CSV text lines in, CSV text lines out, with
record semantics supplied by the JSON feature schema. This module reads CSV
into column-major numpy string arrays in bounded-size chunks (the analog of
HDFS-block-sized mapper inputs) so datasets stream through fixed-shape device
batches.

A native C++ fast path (``avenir_tpu.runtime.native``) parses+encodes in one
pass when the compiled library is available; this module is the portable
fallback and the vocabulary/tooling layer shared by both paths.
"""

from __future__ import annotations

import io
from typing import Iterator, List, Optional, Sequence, TextIO, Union

import numpy as np


def iter_csv_chunks(
    source: Union[str, TextIO],
    chunk_rows: int = 1_000_000,
    delim: str = ",",
    skip_blank: bool = True,
) -> Iterator[np.ndarray]:
    """Yield 2-D object arrays of string fields, ``chunk_rows`` rows at a time.

    ``source`` is a file path or an open text handle. Rows shorter than the
    first row raise — ragged records are a data error, as in the reference
    (mappers would throw ``ArrayIndexOutOfBounds``).
    """
    own = isinstance(source, str)
    fh: TextIO = open(source, "r") if own else source
    try:
        width: Optional[int] = None
        rows: List[List[str]] = []
        for line in fh:
            line = line.rstrip("\n").rstrip("\r")
            if skip_blank and not line:
                continue
            parts = line.split(delim)
            if width is None:
                width = len(parts)
            elif len(parts) != width:
                raise ValueError(f"ragged CSV record: expected {width} fields, got {len(parts)}: {line!r}")
            rows.append(parts)
            if len(rows) >= chunk_rows:
                yield np.array(rows, dtype=object)
                rows = []
        if rows:
            yield np.array(rows, dtype=object)
    finally:
        if own:
            fh.close()


def read_csv(source: Union[str, TextIO], delim: str = ",") -> np.ndarray:
    """Read an entire CSV source into one 2-D object array of strings."""
    chunks = list(iter_csv_chunks(source, chunk_rows=1 << 30, delim=delim))
    if not chunks:
        return np.empty((0, 0), dtype=object)
    return np.concatenate(chunks, axis=0) if len(chunks) > 1 else chunks[0]


def read_csv_string(text: str, delim: str = ",") -> np.ndarray:
    return read_csv(io.StringIO(text), delim=delim)


def write_csv(path_or_handle: Union[str, TextIO], rows: Sequence[Sequence], delim: str = ",") -> None:
    own = isinstance(path_or_handle, str)
    fh: TextIO = open(path_or_handle, "w") if own else path_or_handle
    try:
        for row in rows:
            fh.write(delim.join("" if v is None else str(v) for v in row))
            fh.write("\n")
    finally:
        if own:
            fh.close()
