"""Record encoding — CSV string fields → fixed-shape integer/float arrays.

This is the rebuild's single most reused kernel. The reference re-implements
the same per-record binning in every mapper (categorical bin = the value
string, numeric bin = ``int(value / bucketWidth)`` — reference
bayesian/BayesianDistribution.java:149-160, explore/MutualInformation.java:150-190);
here it is done once, producing dense int codes that every downstream
aggregation consumes as one-hot tensors on the MXU.

Key differences from the reference, forced by TPU/XLA static shapes:

- The reference's hashmap keyed by value-string gives it an *open* vocabulary
  for free. TPU kernels need a *closed* vocabulary, so :meth:`DatasetEncoder.fit`
  builds one (schema ``cardinality`` when present, observed values otherwise)
  and every categorical feature reserves one out-of-vocabulary bin at index
  ``n_bins - 1`` so transform never fails on unseen values.
- Numeric binned features get a ``bin_offset`` so codes are 0-based even for
  negative values (the reference's Java int division truncates toward zero;
  we use floor and carry the offset, which only relabels bins — all
  count-based statistics are invariant to bin labels).

Encoded output is column-major:

- ``codes``  int32 [N, Fb] — bin index per *binned* feature (categorical or
  bucketWidth numeric), in schema ordinal order;
- ``cont``   float32 [N, Fc] — raw value per *continuous* (Gaussian) feature;
- ``labels`` int32 [N] — class-value index (when a class attribute exists);
- ``ids``    object [N] — untouched id strings for output joining.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from avenir_tpu.core.schema import FeatureField, FeatureSchema
from avenir_tpu.core.csv_io import iter_csv_chunks

OOV = "__OOV__"


class NoDataError(ValueError):
    """Raised when a fit stream yields zero chunks.

    A dedicated type (not a message substring) because
    ``jobs.base.distributed_fit`` must distinguish "this process owned zero
    chunks of a non-empty job" from any other ValueError — matching on
    exception text couples that control flow to wording."""


@dataclass
class EncodedDataset:
    """A fully-encoded batch (or whole dataset) ready for device transfer."""

    codes: np.ndarray                       # int32 [N, Fb]
    cont: np.ndarray                        # float32 [N, Fc]
    labels: Optional[np.ndarray] = None     # int32 [N]
    ids: Optional[np.ndarray] = None        # object [N]
    n_bins: np.ndarray = dc_field(default_factory=lambda: np.zeros(0, np.int32))  # [Fb]
    class_values: List[str] = dc_field(default_factory=list)
    binned_ordinals: List[int] = dc_field(default_factory=list)
    cont_ordinals: List[int] = dc_field(default_factory=list)
    # true (pre-ballast) row count for a padded batch; None = num_rows is
    # already the truth.  Row accounting must read this, never count pad.
    valid_rows: Optional[int] = None

    @property
    def num_rows(self) -> int:
        return int(self.codes.shape[0]) if self.codes.size or self.codes.shape[0] else int(self.cont.shape[0])

    @property
    def num_binned(self) -> int:
        return int(self.codes.shape[1])

    @property
    def num_cont(self) -> int:
        return int(self.cont.shape[1])

    @property
    def num_classes(self) -> int:
        return len(self.class_values)

    @property
    def max_bins(self) -> int:
        return int(self.n_bins.max()) if self.n_bins.size else 0

    def bin_mask(self) -> np.ndarray:
        """bool [Fb, B] — True where a bin index is valid for the feature."""
        b = self.max_bins
        return np.arange(b)[None, :] < self.n_bins[:, None]

    def slice(self, start: int, stop: int) -> "EncodedDataset":
        return EncodedDataset(
            codes=self.codes[start:stop],
            cont=self.cont[start:stop],
            labels=None if self.labels is None else self.labels[start:stop],
            ids=None if self.ids is None else self.ids[start:stop],
            n_bins=self.n_bins,
            class_values=self.class_values,
            binned_ordinals=self.binned_ordinals,
            cont_ordinals=self.cont_ordinals,
        )


def pad_rows(n_target: int, *arrays: Optional[np.ndarray], fill: int = -1):
    """Pad axis 0 of each array up to ``n_target`` rows — THE ballast-fill
    home (round 12): integer arrays pad with ``fill`` (default −1, which is
    count-neutral under one-hot: a −1 code/label produces an all-zero row,
    so pad rows drop out of EVERY count table), float arrays pad with 0
    (moment kernels pair them with −1 labels, so they are also neutral).
    ``parallel/mesh.pad_batch``, the stream panes and the serving batcher's
    bucket pad all route through here so the fill contract cannot diverge
    per call site.  None entries pass through; a single array comes back
    bare."""
    out = []
    for a in arrays:
        if a is None:
            out.append(None)
            continue
        pad = n_target - a.shape[0]
        if pad < 0:
            raise ValueError(f"n_target {n_target} < batch {a.shape[0]}")
        if pad == 0:
            out.append(a)
            continue
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        val = fill if np.issubdtype(a.dtype, np.integer) else 0
        out.append(np.pad(a, widths, constant_values=val))
    return out if len(out) > 1 else out[0]


def pad_ballast(ds: "EncodedDataset", n_target: int,
                fill: int = -1) -> "EncodedDataset":
    """EncodedDataset-level ballast pad: rows [num_rows, n_target) are shape
    ballast only.  With the default ``fill=-1`` the pad rows carry label −1
    (ALWAYS −1, regardless of ``fill``) and code −1 — the drop-invalid
    contract both the gram kernel and the einsum paths share, so padding
    changes no statistic while keeping the compiled-shape set finite (mesh
    shard staging, stream panes).  Scoring callers that mask by slicing
    (``serving/registry._pad_ds`` — a pad row's score is computed but never
    read) pass ``fill=0`` so their pad rows stay in-vocabulary."""
    if ds.num_rows == n_target:
        return ds
    codes, cont = pad_rows(n_target, ds.codes, ds.cont, fill=fill)
    labels = (None if ds.labels is None
              else pad_rows(n_target, ds.labels, fill=-1))
    return EncodedDataset(
        codes=codes, cont=cont, labels=labels, ids=None,
        n_bins=ds.n_bins, class_values=ds.class_values,
        binned_ordinals=ds.binned_ordinals, cont_ordinals=ds.cont_ordinals,
        valid_rows=(ds.valid_rows if ds.valid_rows is not None
                    else ds.num_rows))


def peek_chunks(data):
    """(meta, lazy chunk iterable) for the Union[EncodedDataset,
    Iterable[EncodedDataset]] fit contract: peek the first chunk for shape
    metadata without materializing the stream; raises on empty input."""
    import itertools

    it = iter([data] if isinstance(data, EncodedDataset) else data)
    meta = next(it, None)
    if meta is None:
        raise NoDataError("no data")
    return meta, itertools.chain([meta], it)


class DatasetEncoder:
    """Schema-driven encoder with a fitted closed vocabulary.

    Usage::

        enc = DatasetEncoder(schema)
        ds = enc.fit_transform(rows)          # rows: object array [N, ncols]
        more = enc.transform(other_rows)      # same vocab/binning
    """

    def __init__(self, schema: FeatureSchema):
        self.schema = schema
        self.binned_fields: List[FeatureField] = schema.binned_feature_fields
        self.cont_fields: List[FeatureField] = schema.continuous_feature_fields
        self.class_field: Optional[FeatureField] = schema.class_field
        self.id_field: Optional[FeatureField] = schema.id_field
        # per-binned-feature state
        self.vocab: Dict[int, Dict[str, int]] = {}       # ordinal -> value -> code (categorical)
        self.bin_offset: Dict[int, int] = {}             # ordinal -> min bin (numeric binned)
        self.n_bins: Dict[int, int] = {}                 # ordinal -> bin count (incl. OOV slot for categorical)
        self.class_values: List[str] = []
        self.class_map: Dict[str, int] = {}
        self._inv_vocab_cache: Dict[int, Dict[int, str]] = {}
        self._fitted = False
        # pre-seed from schema where the schema fully specifies the vocabulary
        for f in self.binned_fields:
            if f.is_categorical and f.cardinality:
                self.vocab[f.ordinal] = {v: i for i, v in enumerate(f.cardinality)}
                self.n_bins[f.ordinal] = len(f.cardinality) + 1  # + OOV
            elif not f.is_categorical and f.min is not None and f.max is not None:
                assert f.bucket_width
                lo = int(np.floor(f.min / f.bucket_width))
                hi = int(np.floor(f.max / f.bucket_width))
                self.bin_offset[f.ordinal] = lo
                self.n_bins[f.ordinal] = hi - lo + 1
        if self.class_field is not None and self.class_field.cardinality:
            self.class_values = list(self.class_field.cardinality)
            self.class_map = {v: i for i, v in enumerate(self.class_values)}

    def max_ordinal(self, with_labels: bool = True) -> int:
        """Largest CSV column ordinal any consumed field reads — callers
        validating a row width must ensure ``ncols > max_ordinal``."""
        ords = [f.ordinal for f in self.binned_fields + self.cont_fields]
        if self.id_field is not None:
            ords.append(self.id_field.ordinal)
        if with_labels and self.class_field is not None:
            ords.append(self.class_field.ordinal)
        return max(ords, default=-1)

    def schema_complete(self, with_labels: bool = True) -> bool:
        """True when the schema fully specified every vocabulary/bin range
        (and class values, if ``with_labels``) — i.e. :meth:`transform`
        works without a data-fitting pass, the contract the reference's
        mappers rely on and the one the native fast path requires."""
        for f in self.binned_fields:
            if f.ordinal not in self.vocab and f.ordinal not in self.bin_offset:
                return False
        if with_labels and self.class_field is not None and not self.class_values:
            return False
        return True

    # -- fitting -------------------------------------------------------------
    def fit(self, rows: np.ndarray) -> "DatasetEncoder":
        """Learn vocabularies / bin ranges not fully specified by the schema."""
        for f in self.binned_fields:
            col = rows[:, f.ordinal]
            if f.is_categorical:
                if f.ordinal not in self.vocab:
                    values = sorted(set(col.tolist()))
                    self.vocab[f.ordinal] = {v: i for i, v in enumerate(values)}
                    self.n_bins[f.ordinal] = len(values) + 1  # + OOV
            else:
                if f.ordinal not in self.bin_offset:
                    vals = col.astype(np.float64)
                    bins = np.floor(vals / f.bucket_width).astype(np.int64)
                    lo, hi = int(bins.min()), int(bins.max())
                    self.bin_offset[f.ordinal] = lo
                    self.n_bins[f.ordinal] = hi - lo + 1
        if self.class_field is not None and not self.class_values:
            col = rows[:, self.class_field.ordinal]
            self.class_values = sorted(set(col.tolist()))
            self.class_map = {v: i for i, v in enumerate(self.class_values)}
        self._fitted = True
        return self

    # -- transform -----------------------------------------------------------
    def transform(self, rows: np.ndarray, with_labels: bool = True) -> EncodedDataset:
        if not self._fitted:
            # schema may have fully specified everything; verify
            missing = [f.name for f in self.binned_fields
                       if f.ordinal not in self.vocab and f.ordinal not in self.bin_offset]
            if missing or (self.class_field is not None and with_labels and not self.class_values):
                from avenir_tpu.core.config import ConfigError
                raise ConfigError(
                    f"encoder not fitted and schema incomplete for fields: {missing}")
        n = rows.shape[0]
        codes = np.zeros((n, len(self.binned_fields)), dtype=np.int32)
        for j, f in enumerate(self.binned_fields):
            col = rows[:, f.ordinal]
            if f.is_categorical:
                vmap = self.vocab[f.ordinal]
                oov = self.n_bins[f.ordinal] - 1
                codes[:, j] = np.array([vmap.get(v, oov) for v in col.tolist()], dtype=np.int32)
            else:
                vals = col.astype(np.float64)
                bins = np.floor(vals / f.bucket_width).astype(np.int64) - self.bin_offset[f.ordinal]
                codes[:, j] = np.clip(bins, 0, self.n_bins[f.ordinal] - 1).astype(np.int32)
        cont = np.zeros((n, len(self.cont_fields)), dtype=np.float32)
        for j, f in enumerate(self.cont_fields):
            cont[:, j] = rows[:, f.ordinal].astype(np.float64).astype(np.float32)
        labels = None
        if self.class_field is not None and with_labels and rows.shape[1] > self.class_field.ordinal:
            col = rows[:, self.class_field.ordinal]
            try:
                labels = np.array([self.class_map[v] for v in col.tolist()], dtype=np.int32)
            except KeyError as e:
                raise ValueError(f"unknown class value {e} (known: {self.class_values})") from None
        ids = rows[:, self.id_field.ordinal] if self.id_field is not None else None
        return EncodedDataset(
            codes=codes, cont=cont, labels=labels, ids=ids,
            n_bins=np.array([self.n_bins[f.ordinal] for f in self.binned_fields], dtype=np.int32),
            class_values=list(self.class_values),
            binned_ordinals=[f.ordinal for f in self.binned_fields],
            cont_ordinals=[f.ordinal for f in self.cont_fields],
        )

    def fit_transform(self, rows: np.ndarray, with_labels: bool = True) -> EncodedDataset:
        return self.fit(rows).transform(rows, with_labels=with_labels)

    # -- streaming -----------------------------------------------------------
    def iter_encoded(
        self, source, chunk_rows: int = 1_000_000, delim: str = ",", with_labels: bool = True,
    ) -> Iterator[EncodedDataset]:
        """Stream CSV chunks through :meth:`transform` (fit must have run)."""
        for chunk in iter_csv_chunks(source, chunk_rows=chunk_rows, delim=delim):
            yield self.transform(chunk, with_labels=with_labels)

    # -- decoding ------------------------------------------------------------
    # -- state capture (ship the fitted encoding with a saved model) ---------
    def state_dict(self) -> Dict:
        """JSON-safe fitted state: vocabularies, bin offsets/counts, class
        values. Saved next to models whose parameters are keyed by raw bin
        codes (e.g. the decision tree's ``seg_of_bin`` tables), so scoring
        re-creates the exact train-time code space instead of re-fitting on
        the scoring input."""
        return {
            "vocab": {str(k): v for k, v in self.vocab.items()},
            "bin_offset": {str(k): v for k, v in self.bin_offset.items()},
            "n_bins": {str(k): v for k, v in self.n_bins.items()},
            "class_values": list(self.class_values),
        }

    def load_state_dict(self, state: Dict) -> "DatasetEncoder":
        self.vocab = {int(k): dict(v) for k, v in state["vocab"].items()}
        self.bin_offset = {int(k): int(v) for k, v in state["bin_offset"].items()}
        self.n_bins = {int(k): int(v) for k, v in state["n_bins"].items()}
        self.class_values = list(state["class_values"])
        self.class_map = {v: i for i, v in enumerate(self.class_values)}
        self._inv_vocab_cache = {}
        self._fitted = True
        return self

    def _inverse_vocab(self, ordinal: int) -> Dict[int, str]:
        if ordinal not in self._inv_vocab_cache:
            self._inv_vocab_cache[ordinal] = {i: v for v, i in self.vocab[ordinal].items()}
        return self._inv_vocab_cache[ordinal]

    def bin_label(self, binned_index: int, code: int) -> str:
        """Human/serde label of a bin code, matching the reference's emitted bin
        labels (value string for categorical, integer bin id for numeric)."""
        f = self.binned_fields[binned_index]
        if f.is_categorical:
            return self._inverse_vocab(f.ordinal).get(code, OOV)
        return str(code + self.bin_offset[f.ordinal])

    def bin_code(self, binned_index: int, label: str) -> int:
        f = self.binned_fields[binned_index]
        if f.is_categorical:
            return self.vocab[f.ordinal].get(label, self.n_bins[f.ordinal] - 1)
        return int(label) - self.bin_offset[f.ordinal]

    def class_label(self, idx: int) -> str:
        return self.class_values[idx]
