"""JSON feature schema — the dataset-semantics contract.

Parses the same JSON schema files the reference consumes (e.g.
``resource/churn.json``, ``resource/hosp_readmit.json``): a ``fields`` list
where each field carries ``name``, ``ordinal``, ``dataType``, and optional
``id`` / ``feature`` / ``classAttr`` flags, ``cardinality`` (categorical
vocabulary), ``bucketWidth`` (numeric binning), ``min`` / ``max``, and
``maxSplit`` (decision-tree split bound).

Field semantics mirror the subset of chombo ``FeatureSchema`` /
``FeatureField`` the reference actually uses (reference uses:
bayesian/BayesianDistribution.java:140-175, explore/ClassPartitionGenerator.java:235-272).
The class attribute is the field flagged ``classAttr`` or, failing that, the
unique field that is neither an id nor a feature (the convention in the
reference's shipped schemas, e.g. ``status`` in churn.json).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Sequence

CATEGORICAL = "categorical"
INT = "int"
LONG = "long"
DOUBLE = "double"
STRING = "string"

_NUMERIC_TYPES = (INT, LONG, DOUBLE)


@dataclass
class FeatureField:
    """One column of the CSV record, as described by the JSON schema."""

    name: str
    ordinal: int
    data_type: str = STRING
    is_id: bool = False
    is_feature: bool = False
    is_class_attr: bool = False
    cardinality: Optional[List[str]] = None
    bucket_width: Optional[float] = None
    min: Optional[float] = None
    max: Optional[float] = None
    max_split: Optional[int] = None
    extra: Dict[str, Any] = dc_field(default_factory=dict)

    @property
    def is_categorical(self) -> bool:
        return self.data_type == CATEGORICAL

    @property
    def is_numeric(self) -> bool:
        return self.data_type in _NUMERIC_TYPES

    @property
    def is_integer(self) -> bool:
        return self.data_type in (INT, LONG)

    @property
    def is_binned(self) -> bool:
        """True if values map to a discrete bin index.

        Categorical fields bin by vocabulary position; numeric fields bin by
        ``floor(value / bucketWidth)`` when ``bucketWidth`` is defined — the
        same binning rule the reference applies per record
        (bayesian/BayesianDistribution.java:149-160). Numeric fields without a
        bucket width are modeled as continuous (Gaussian).
        """
        return self.is_categorical or (self.is_numeric and self.bucket_width is not None)

    @property
    def is_continuous(self) -> bool:
        return self.is_numeric and self.bucket_width is None

    def cardinality_index(self, value: str) -> int:
        """Vocabulary position of a categorical value (-1 if unknown)."""
        if self.cardinality is None:
            return -1
        try:
            return self.cardinality.index(value)
        except ValueError:
            return -1

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "FeatureField":
        known = {
            "name", "ordinal", "dataType", "id", "feature", "classAttr",
            "cardinality", "bucketWidth", "min", "max", "maxSplit",
        }
        card = obj.get("cardinality")
        if card is not None:
            card = [str(v) for v in card]
        return cls(
            name=str(obj.get("name", "")),
            ordinal=int(obj["ordinal"]),
            data_type=str(obj.get("dataType", STRING)),
            is_id=bool(obj.get("id", False)),
            is_feature=bool(obj.get("feature", False)),
            is_class_attr=bool(obj.get("classAttr", False)),
            cardinality=card,
            bucket_width=(float(obj["bucketWidth"]) if "bucketWidth" in obj else None),
            min=(float(obj["min"]) if "min" in obj else None),
            max=(float(obj["max"]) if "max" in obj else None),
            max_split=(int(obj["maxSplit"]) if "maxSplit" in obj else None),
            extra={k: v for k, v in obj.items() if k not in known},
        )

    def to_json(self) -> Dict[str, Any]:
        obj: Dict[str, Any] = {"name": self.name, "ordinal": self.ordinal, "dataType": self.data_type}
        if self.is_id:
            obj["id"] = True
        if self.is_feature:
            obj["feature"] = True
        if self.is_class_attr:
            obj["classAttr"] = True
        if self.cardinality is not None:
            obj["cardinality"] = list(self.cardinality)
        if self.bucket_width is not None:
            obj["bucketWidth"] = self.bucket_width
        if self.min is not None:
            obj["min"] = self.min
        if self.max is not None:
            obj["max"] = self.max
        if self.max_split is not None:
            obj["maxSplit"] = self.max_split
        obj.update(self.extra)
        return obj


class FeatureSchema:
    """Ordered collection of :class:`FeatureField` with role accessors."""

    def __init__(self, fields: Sequence[FeatureField]):
        self.fields: List[FeatureField] = sorted(fields, key=lambda f: f.ordinal)
        self._by_ordinal = {f.ordinal: f for f in self.fields}
        self._by_name = {f.name: f for f in self.fields}

    # -- construction --------------------------------------------------------
    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "FeatureSchema":
        return cls([FeatureField.from_json(f) for f in obj.get("fields", [])])

    @classmethod
    def from_file(cls, path: str) -> "FeatureSchema":
        with open(path, "r") as fh:
            return cls.from_json(json.load(fh))

    @classmethod
    def from_string(cls, text: str) -> "FeatureSchema":
        return cls.from_json(json.loads(text))

    def to_json(self) -> Dict[str, Any]:
        return {"fields": [f.to_json() for f in self.fields]}

    def to_file(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=2)

    # -- accessors -----------------------------------------------------------
    def field_by_ordinal(self, ordinal: int) -> FeatureField:
        return self._by_ordinal[ordinal]

    def field_by_name(self, name: str) -> FeatureField:
        return self._by_name[name]

    @property
    def id_field(self) -> Optional[FeatureField]:
        for f in self.fields:
            if f.is_id:
                return f
        return None

    @property
    def class_field(self) -> Optional[FeatureField]:
        for f in self.fields:
            if f.is_class_attr:
                return f
        rest = [f for f in self.fields if not f.is_id and not f.is_feature]
        if len(rest) == 1:
            return rest[0]
        return None

    @property
    def feature_fields(self) -> List[FeatureField]:
        return [f for f in self.fields if f.is_feature]

    @property
    def binned_feature_fields(self) -> List[FeatureField]:
        return [f for f in self.feature_fields if f.is_binned]

    @property
    def continuous_feature_fields(self) -> List[FeatureField]:
        return [f for f in self.feature_fields if f.is_continuous]

    @property
    def feature_ordinals(self) -> List[int]:
        return [f.ordinal for f in self.feature_fields]

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __repr__(self) -> str:
        roles = []
        for f in self.fields:
            tag = "id" if f.is_id else ("class" if f is self.class_field else ("feat" if f.is_feature else "-"))
            roles.append(f"{f.name}[{f.ordinal}]:{f.data_type}:{tag}")
        return f"FeatureSchema({', '.join(roles)})"
