"""Synthetic data generators with planted structure.

numpy ports of the reference's Python/Ruby generators (resource/*.py,
resource/*.rb) — each encodes a ground-truth mechanism the corresponding
algorithm is expected to recover, which is how the reference is validated
(SURVEY.md §4). Here they drive automated end-to-end tests.
"""

from avenir_tpu.datagen.churn import generate_churn, CHURN_SCHEMA_JSON
from avenir_tpu.datagen.disease import generate_disease, DISEASE_SCHEMA_JSON

__all__ = ["generate_churn", "CHURN_SCHEMA_JSON",
           "generate_disease", "DISEASE_SCHEMA_JSON"]
