"""Customer-transaction generators for the Markov-chain marketing runbook.

Ports the last three reference synthesizers (SURVEY §4 "port the
generators"): ``buy_xaction.rb`` (history-dependent purchase stream),
``xaction_seq.rb`` (transactions → per-customer state-symbol sequences, the
input of ``MarkovStateTransitionModel``), and ``mark_plan.rb`` (transactions
+ transition-count model → next-contact marketing plan). The planted
structure is the reference's own: purchase amount depends on recency and
size of the previous purchase (buy_xaction.rb:34-44), so the derived
(daysDiff, amountDiff) state sequences carry real transition signal for the
Markov jobs to learn.

Vectorized numpy per day (the reference loops per transaction); output rows
and state alphabet match the reference byte-for-byte in layout.
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence

import numpy as np

# dd ∈ {S,M,L} (days since previous) × ad ∈ {L,E,G} (prev vs current amount)
# — the 9-state alphabet shared by xaction_seq.rb and mark_plan.rb
STATES = ["SL", "SE", "SG", "ML", "ME", "MG", "LL", "LE", "LG"]

_EPOCH = datetime.date(2013, 1, 1)


def _id(rng: np.random.Generator, n: int = 10) -> str:
    return "".join(rng.choice(list("0123456789"), size=n))


def generate_buy_xactions(cust_count: int, days_count: int,
                          visitor_percent: float = 0.1,
                          seed: int = 0) -> List[str]:
    """``custID,xid,date,amount`` rows (buy_xaction.rb layout).

    Per day, ~visitor_percent of customers (±15%) transact; a customer's
    amount depends on days since and size of their previous purchase —
    recent small purchases are followed by ~50, old ones by ~180
    (buy_xaction.rb:34-44) — planting the Markov structure the
    state-sequence jobs recover."""
    rng = np.random.default_rng(seed)
    cust_ids = [_id(rng) for _ in range(cust_count)]
    last_day: Dict[int, int] = {}
    last_amt: Dict[int, int] = {}
    rows: List[str] = []
    xid = 1_400_000_000
    for day in range(days_count):
        n = int(visitor_percent * cust_count * (85 + rng.integers(30)) / 100)
        picks = rng.integers(0, cust_count, size=n)
        date = _EPOCH + datetime.timedelta(days=day)
        for c in picks:
            c = int(c)
            if c in last_day:
                nd = day - last_day[c]
                la = last_amt[c]
                if nd < 30:
                    amount = (50 + int(rng.integers(20)) - 10 if la < 40
                              else 30 + int(rng.integers(10)) - 5)
                elif nd < 60:
                    amount = (100 + int(rng.integers(40)) - 20 if la < 80
                              else 60 + int(rng.integers(20)) - 10)
                else:
                    amount = (180 + int(rng.integers(60)) - 30 if la < 150
                              else 120 + int(rng.integers(40)) - 20)
            else:
                amount = 40 + int(rng.integers(180))
            last_day[c] = day
            last_amt[c] = amount
            xid += 1
            rows.append(f"{cust_ids[c]},{xid},{date.isoformat()},{amount}")
    return rows


def _state(days_diff: int, prev_amt: int, amt: int,
           short_days: int, long_days: int) -> str:
    dd = "S" if days_diff < short_days else ("M" if days_diff < long_days
                                             else "L")
    if prev_amt < 0.9 * amt:
        ad = "L"
    elif prev_amt < 1.1 * amt:
        ad = "E"
    else:
        ad = "G"
    return dd + ad


def _group_by_customer(xaction_rows: Sequence[str]):
    by_cust: Dict[str, List[List[str]]] = {}
    for line in xaction_rows:
        items = line.split(",")
        by_cust.setdefault(items[0], []).append(items[2:])
    return by_cust


def xactions_to_sequences(xaction_rows: Sequence[str],
                          short_days: int = 15,
                          long_days: int = 60) -> List[str]:
    """``custID,state,state,...`` rows (xaction_seq.rb) — the training input
    of the MarkovStateTransitionModel job. Customers with fewer than two
    transitions are dropped, like the reference (seq.size > 1)."""
    out: List[str] = []
    for cid, xs in _group_by_customer(xaction_rows).items():
        seq: List[str] = []
        for prev, cur in zip(xs, xs[1:]):
            days = (datetime.date.fromisoformat(cur[0]) -
                    datetime.date.fromisoformat(prev[0])).days
            seq.append(_state(days, int(prev[1]), int(cur[1]),
                              short_days, long_days))
        if len(seq) > 1:
            out.append(cid + "," + ",".join(seq))
    return out


def marketing_plan(xaction_rows: Sequence[str],
                   model_rows: Sequence[Sequence[int]],
                   states: Optional[List[str]] = None) -> List[str]:
    """``custID, next_contact_date`` rows (mark_plan.rb): each customer's
    LAST observed state row of the transition-count model picks (argmax)
    the expected next state; S/M/L next states map to +15/+45/+90 days
    after the last transaction. Note the reference uses 30/60-day
    thresholds here (mark_plan.rb:55-61), not xaction_seq's 15/60."""
    states = states or STATES
    model = [list(map(int, r)) for r in model_rows]
    out: List[str] = []
    for cid, xs in _group_by_customer(xaction_rows).items():
        seq: List[str] = []
        last_date = _EPOCH
        for prev, cur in zip(xs, xs[1:]):
            d_cur = datetime.date.fromisoformat(cur[0])
            last_date = d_cur
            days = (d_cur - datetime.date.fromisoformat(prev[0])).days
            seq.append(_state(days, int(prev[1]), int(cur[1]), 30, 60))
        if not seq:
            continue
        row = model[states.index(seq[-1])]
        next_state = states[int(np.argmax(row))]
        delta = {"S": 15, "M": 45, "L": 90}[next_state[0]]
        nd = last_date + datetime.timedelta(days=delta)
        out.append(f"{cid}, {nd.isoformat()}")
    return out
