"""Customer-churn generator — planted-structure port of resource/usage.rb.

Mechanism (usage.rb:20-82): categorical usage/payment features drawn from
fixed weighted distributions; churn probability starts at 25% and is scaled
by per-level multipliers (overage minutes ×1.8, high data ×1.6, high CS calls
×1.6, poor payment ×1.3, old account ×1.2...); ``status`` is ``closed`` with
that probability. A correct Naive Bayes / Cramér / MI implementation must
recover these drivers (minUsed, dataUsed, csCalls strongest).
"""

from __future__ import annotations

import json
from typing import Tuple

import numpy as np

CHURN_SCHEMA_JSON = {
    "fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "minUsed", "ordinal": 1, "dataType": "categorical",
         "cardinality": ["low", "med", "high", "overage"], "feature": True},
        {"name": "dataUsed", "ordinal": 2, "dataType": "categorical",
         "cardinality": ["low", "med", "high"], "feature": True},
        {"name": "CSCalls", "ordinal": 3, "dataType": "categorical",
         "cardinality": ["low", "med", "high"], "feature": True},
        {"name": "payment", "ordinal": 4, "dataType": "categorical",
         "cardinality": ["poor", "average", "good"], "feature": True},
        {"name": "acctAge", "ordinal": 5, "dataType": "categorical",
         "cardinality": ["1", "2", "3", "4", "5"], "feature": True},
        {"name": "status", "ordinal": 6, "dataType": "categorical",
         "cardinality": ["open", "closed"]},
    ]
}

_MIN_LEVELS = (["low", "med", "high", "overage"], [2, 5, 3, 2])
_DATA_LEVELS = (["low", "med", "high"], [4, 6, 2])
_CS_LEVELS = (["low", "med", "high"], [6, 3, 1])
_PAY_LEVELS = (["poor", "average", "good"], [2, 5, 4])

_MIN_MULT = {"low": 1.2, "med": 1.0, "high": 1.4, "overage": 1.8}
_DATA_MULT = {"low": 1.1, "med": 1.3, "high": 1.6}
_CS_MULT = {"low": 1.0, "med": 1.2, "high": 1.6}
_PAY_MULT = {"poor": 1.3, "average": 1.0, "good": 1.0}
_AGE_MULT = {1: 1.0, 2: 1.0, 3: 1.05, 4: 1.2, 5: 1.3}


def _draw(rng: np.random.Generator, n: int, levels_weights) -> np.ndarray:
    levels, weights = levels_weights
    p = np.asarray(weights, np.float64)
    return rng.choice(np.array(levels, object), size=n, p=p / p.sum())


def generate_churn(n: int, seed: int = 42) -> np.ndarray:
    """Object array [n, 7] of CSV fields matching CHURN_SCHEMA_JSON."""
    rng = np.random.default_rng(seed)
    min_used = _draw(rng, n, _MIN_LEVELS)
    data_used = _draw(rng, n, _DATA_LEVELS)
    cs_calls = _draw(rng, n, _CS_LEVELS)
    payment = _draw(rng, n, _PAY_LEVELS)
    acct_age = rng.integers(1, 5, size=n)  # 1..4 as in usage.rb rand(4)+1

    pr = np.full(n, 25.0)
    pr *= np.vectorize(_MIN_MULT.get)(min_used)
    pr *= np.vectorize(_DATA_MULT.get)(data_used)
    pr *= np.vectorize(_CS_MULT.get)(cs_calls)
    pr *= np.vectorize(_PAY_MULT.get)(payment)
    pr *= np.vectorize(_AGE_MULT.get)(acct_age)
    pr = np.minimum(pr, 99.0)
    closed = rng.uniform(0, 100, size=n) < pr

    rows = np.empty((n, 7), dtype=object)
    # ids are zero-padded so lexicographic order == generation order for
    # any downstream sort/group; n past the width would break that (GL003)
    assert n < 10 ** 10, "customer ids overflow the 10-digit width"
    rows[:, 0] = [f"C{int(i):010d}" for i in range(n)]
    rows[:, 1] = min_used
    rows[:, 2] = data_used
    rows[:, 3] = cs_calls
    rows[:, 4] = payment
    rows[:, 5] = acct_age.astype(str).astype(object)
    rows[:, 6] = np.where(closed, "closed", "open").astype(object)
    return rows


def churn_schema_string() -> str:
    return json.dumps(CHURN_SCHEMA_JSON)
