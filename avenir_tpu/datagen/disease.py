"""Disease-risk patient generator — planted-structure port of
resource/disease.rb (the rule-mining tutorial's data,
resource/tutorial_diesase_rule_mining.txt).

Mechanism (disease.rb): weighted categorical draws — race EUA:10 AFA:3
LAA:1 ASA:1, diet LF:2 REG:8 HF:4, family history NFH:5 FH:1, domestic
life S:2 DP:4 — age uniform 20-79, weight uniform 120-239. Disease
probability starts at 15% and multiplies by age band (<40 ×1.0, <50
×1.05, <60 ×1.15, <70 ×1.4, else ×1.5), race (AFA ×1.2, ASA ×0.9, LAA
×0.95), diet (HF ×1.15), family history (FH ×1.2), and single domestic
life (×1.2), capped at 99%. Age is the strongest planted driver — the
rule-mining (candidate-split) job should rank an age split highest.

Schema mirrors resource/patient.json (age binned bucketWidth 5 with
min/max/maxSplit; weight continuous; open-vocabulary categoricals there —
declared here for streaming use).
"""

from __future__ import annotations

import numpy as np

DISEASE_SCHEMA_JSON = {
    "fields": [
        {"name": "patientID", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "age", "ordinal": 1, "dataType": "int", "feature": True,
         "min": 20, "max": 80, "maxSplit": 3, "bucketWidth": 5},
        {"name": "race", "ordinal": 2, "dataType": "categorical", "feature": True,
         "cardinality": ["EUA", "AFA", "LAA", "ASA"]},
        {"name": "weight", "ordinal": 3, "dataType": "int", "feature": True,
         "min": 120, "max": 240, "maxSplit": 3, "bucketWidth": 20},
        {"name": "diet", "ordinal": 4, "dataType": "categorical", "feature": True,
         "cardinality": ["LF", "REG", "HF"]},
        {"name": "familyHistory", "ordinal": 5, "dataType": "categorical",
         "feature": True, "cardinality": ["NFH", "FH"]},
        {"name": "domesticLife", "ordinal": 6, "dataType": "categorical",
         "feature": True, "cardinality": ["S", "DP"]},
        {"name": "disease", "ordinal": 7, "dataType": "categorical",
         "cardinality": ["No", "Yes"]},
    ]
}

_RACE_MULT = {"AFA": 1.2, "ASA": 0.9, "LAA": 0.95, "EUA": 1.0}
_DIET_MULT = {"HF": 1.15, "LF": 1.0, "REG": 1.0}


def _weighted(rng, values_weights):
    values = [v for v, _ in values_weights]
    w = np.array([float(x) for _, x in values_weights])
    return lambda n: rng.choice(values, size=n, p=w / w.sum())


def generate_disease(n: int, seed: int = 0) -> np.ndarray:
    """[n, 8] object array of rows in disease.rb's column order."""
    rng = np.random.default_rng(seed)
    age = rng.integers(20, 80, size=n)
    race = _weighted(rng, [("EUA", 10), ("AFA", 3), ("LAA", 1), ("ASA", 1)])(n)
    weight = rng.integers(120, 240, size=n)
    diet = _weighted(rng, [("LF", 2), ("REG", 8), ("HF", 4)])(n)
    fam = _weighted(rng, [("NFH", 5), ("FH", 1)])(n)
    dom = _weighted(rng, [("S", 2), ("DP", 4)])(n)

    pr = np.full(n, 15.0)
    age_mult = np.select(
        [age < 40, age < 50, age < 60, age < 70],
        [1.0, 1.05, 1.15, 1.4], default=1.5)
    pr *= age_mult
    pr *= np.vectorize(_RACE_MULT.get)(race)
    pr *= np.vectorize(_DIET_MULT.get)(diet)
    pr *= np.where(fam == "FH", 1.2, 1.0)
    pr *= np.where(dom == "S", 1.2, 1.0)
    pr = np.minimum(pr, 99.0)
    status = np.where(rng.integers(0, 100, size=n) < pr, "Yes", "No")

    rows = np.empty((n, 8), dtype=object)
    # zero-padded ids: lexicographic == generation order (graftlint GL003)
    assert n < 10 ** 11, "patient ids overflow the 11-digit width"
    rows[:, 0] = [f"P{i:011d}" for i in range(n)]
    rows[:, 1] = [str(v) for v in age]
    rows[:, 2] = race
    rows[:, 3] = [str(v) for v in weight]
    rows[:, 4] = diet
    rows[:, 5] = fam
    rows[:, 6] = dom
    rows[:, 7] = status
    return rows
