"""E-learning activity generator — planted-structure port of
resource/elearn.py.

Mechanism (elearn.py:13-105): 9 truncated-Gaussian activity signals; failure
probability starts at 10% and gains additive bumps for low activity — low
testScore up to +34, low assignmentScore up to +28, low contentTime up to
+10, etc.; ``status`` is F with that probability. A correct kNN classifier
must beat the majority baseline by exploiting locality in the signal space.
"""

from __future__ import annotations

import numpy as np

ELEARN_SCHEMA_JSON = {
    "fields": [
        {"name": "userID", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "contentTime", "ordinal": 1, "dataType": "int", "feature": True},
        {"name": "discussTime", "ordinal": 2, "dataType": "int", "feature": True},
        {"name": "organizerTime", "ordinal": 3, "dataType": "int", "feature": True},
        {"name": "emailCount", "ordinal": 4, "dataType": "int", "feature": True},
        {"name": "testScore", "ordinal": 5, "dataType": "int", "feature": True},
        {"name": "assignmentScore", "ordinal": 6, "dataType": "int", "feature": True},
        {"name": "chatMsgCount", "ordinal": 7, "dataType": "int", "feature": True},
        {"name": "searchTime", "ordinal": 8, "dataType": "int", "feature": True},
        {"name": "bookMarkCount", "ordinal": 9, "dataType": "int", "feature": True},
        {"name": "status", "ordinal": 10, "dataType": "categorical",
         "cardinality": ["P", "F"]},
    ]
}


def generate_elearn(n: int, seed: int = 42) -> np.ndarray:
    rng = np.random.default_rng(seed)

    def gauss(mu, sd, lo=0, hi=None):
        v = rng.normal(mu, sd, size=n)
        v = np.maximum(v, lo)
        if hi is not None:
            v = np.clip(v, lo, hi)
        return v.astype(np.int64)

    content = gauss(300, 100)
    discuss = gauss(80, 40)
    organizer = gauss(40, 20)
    email = gauss(10, 6)
    test = np.clip(rng.normal(50, 30, size=n), 10, 100).astype(np.int64)
    assign = np.clip(rng.normal(60, 40, size=n), 10, 100).astype(np.int64)
    chat = gauss(100, 60)
    search = gauss(60, 40)
    bookmark = gauss(12, 8)

    prob = np.full(n, 10.0)
    prob += np.select([content < 100, content < 150], [10, 6], 0)
    prob += np.select([discuss < 30, discuss < 50], [8, 4], 0)
    prob += np.where(discuss < 10, 5, 0)      # elearn.py's organizer bump keys on discussTime
    prob += np.where(email < 3, 6, 0)
    prob += np.select([test < 30, test < 40, test < 50], [34, 20, 14], 0)
    prob += np.select([assign < 35, assign < 50, assign < 60], [28, 18, 10], 0)
    prob += np.where(chat < 20, 4, 0)
    prob += np.select([search < 15, search < 30], [7, 3], 0)
    prob += np.where(bookmark < 4, 8, 0)
    fail = rng.integers(0, 101, size=n) < prob

    cols = [content, discuss, organizer, email, test, assign, chat, search, bookmark]
    rows = np.empty((n, 11), dtype=object)
    rows[:, 0] = [str(1000000 + int(i)) for i in rng.integers(0, 1000000, size=n)]
    for j, c in enumerate(cols):
        rows[:, j + 1] = c.astype(str).astype(object)
    rows[:, 10] = np.where(fail, "F", "P").astype(object)
    return rows
