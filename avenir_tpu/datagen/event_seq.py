"""Transaction state-sequence generator — planted-structure port of
resource/xaction_state.rb + event_seq.rb.

Mechanism (xaction_state.rb:20-45): each adjacent transaction pair maps to a
state = (days-between bucket: S<30, M<60, L) × (amount-ratio bucket:
L growing, E even, G shrinking) — 9 states. Here the sequences are drawn
directly from a planted first-order transition matrix (row-stochastic, with a
dominant self/next structure), so a correct Markov-chain trainer must recover
the matrix and a Viterbi/HMM stack can be validated against known dynamics.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

STATES: List[str] = [d + a for d in "SML" for a in "LEG"]


def planted_transition_matrix(seed: int = 7, concentration: float = 8.0) -> np.ndarray:
    """[9, 9] row-stochastic matrix with planted structure: heavy mass on a
    per-row preferred successor (customers are habit-driven), Dirichlet noise
    elsewhere."""
    rng = np.random.default_rng(seed)
    s = len(STATES)
    base = rng.dirichlet(np.ones(s), size=s)
    pref = rng.permutation(s)
    for i in range(s):
        base[i] = (base[i] + concentration * np.eye(s)[pref[i]])
        base[i] /= base[i].sum()
    return base


def generate_xaction_sequences(
    n_customers: int = 500, min_len: int = 10, max_len: int = 40,
    seed: int = 42, trans: np.ndarray = None,
) -> Tuple[List[List[str]], np.ndarray]:
    """(sequences, transition matrix). Row format for the sequence file is
    ``custID, state, state, ...`` (the xaction_state.rb output shape)."""
    rng = np.random.default_rng(seed)
    if trans is None:
        trans = planted_transition_matrix(seed)
    s = len(STATES)
    init = np.full(s, 1.0 / s)
    seqs: List[List[str]] = []
    for _ in range(n_customers):
        length = int(rng.integers(min_len, max_len + 1))
        state = rng.choice(s, p=init)
        seq = [STATES[state]]
        for _ in range(length - 1):
            state = rng.choice(s, p=trans[state])
            seq.append(STATES[state])
        seqs.append(seq)
    return seqs, trans


def sequences_to_rows(seqs: List[List[str]]) -> List[List[str]]:
    return [[f"C{i:07d}"] + seq for i, seq in enumerate(seqs)]
