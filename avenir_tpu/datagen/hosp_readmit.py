"""Hospital-readmission generator — planted-structure port of
resource/hosp_readmit.rb.

Mechanism (hosp_readmit.rb:20-98): weighted draws for 3 numeric + 7
categorical features; readmission probability starts at 20% and gains
additive bumps — age>80 +10, living alone +9, low follow-up +8, smoker +6,
unemployed +6, high alcohol +5, heavy+short +5, retired +4, poor diet +4 —
with employment/diet correlated to age/employment. MI feature ranking must
surface the strong drivers (age, familyStatus, followUp, smoking).
"""

from __future__ import annotations

import numpy as np

HOSP_SCHEMA_JSON = {
    "fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "age", "ordinal": 1, "dataType": "int", "feature": True,
         "bucketWidth": 10, "min": 10, "max": 90},
        {"name": "weight", "ordinal": 2, "dataType": "int", "feature": True,
         "bucketWidth": 10, "min": 130, "max": 250},
        {"name": "height", "ordinal": 3, "dataType": "int", "feature": True,
         "bucketWidth": 5, "min": 50, "max": 75},
        {"name": "employmentStatus", "ordinal": 4, "dataType": "categorical", "feature": True,
         "cardinality": ["employed", "unemployed", "retired"]},
        {"name": "familyStatus", "ordinal": 5, "dataType": "categorical", "feature": True,
         "cardinality": ["alone", "with partner"]},
        {"name": "diet", "ordinal": 6, "dataType": "categorical", "feature": True,
         "cardinality": ["average", "poor", "good"]},
        {"name": "exercise", "ordinal": 7, "dataType": "categorical", "feature": True,
         "cardinality": ["average", "low", "high"]},
        {"name": "followUp", "ordinal": 8, "dataType": "categorical", "feature": True,
         "cardinality": ["average", "low", "high"]},
        {"name": "smoking", "ordinal": 9, "dataType": "categorical", "feature": True,
         "cardinality": ["non smoker", "smoker"]},
        {"name": "alcohol", "ordinal": 10, "dataType": "categorical", "feature": True,
         "cardinality": ["average", "low", "high"]},
        {"name": "readmitted", "ordinal": 11, "dataType": "categorical",
         "cardinality": ["N", "Y"]},
    ]
}


def _range_draw(rng, n, ranges_weights):
    """Weighted draw of ranges then uniform int within range."""
    ranges = [r for r, _ in ranges_weights]
    w = np.array([w for _, w in ranges_weights], np.float64)
    pick = rng.choice(len(ranges), size=n, p=w / w.sum())
    lo = np.array([r[0] for r in ranges])[pick]
    hi = np.array([r[1] for r in ranges])[pick]
    return rng.integers(lo, hi + 1)


def _cat_draw(rng, n, values_weights):
    vals = np.array([v for v, _ in values_weights], object)
    w = np.array([w for _, w in values_weights], np.float64)
    return rng.choice(vals, size=n, p=w / w.sum())


def generate_hosp_readmit(n: int, seed: int = 42) -> np.ndarray:
    rng = np.random.default_rng(seed)
    age = _range_draw(rng, n, [((10, 20), 2), ((21, 30), 3), ((31, 40), 6), ((41, 50), 10),
                               ((51, 60), 14), ((61, 70), 19), ((71, 80), 25), ((81, 90), 21)])
    wt = _range_draw(rng, n, [((130, 140), 9), ((141, 150), 13), ((151, 160), 16),
                              ((161, 170), 20), ((171, 180), 23), ((181, 190), 20),
                              ((191, 200), 17), ((201, 211), 14), ((211, 220), 10),
                              ((221, 230), 7), ((231, 240), 5), ((241, 250), 3)])
    ht = _range_draw(rng, n, [((50, 55), 9), ((56, 60), 12), ((61, 65), 16),
                              ((66, 70), 23), ((71, 75), 14)])
    emp = _cat_draw(rng, n, [("employed", 10), ("unemployed", 1), ("retired", 3)])
    emp = np.where((age > 68) & (rng.uniform(size=n) < 0.8), "retired", emp).astype(object)
    fam = _cat_draw(rng, n, [("alone", 10), ("with partner", 15)])
    diet = _cat_draw(rng, n, [("average", 10), ("poor", 4), ("good", 2)])
    diet = np.where((emp == "unemployed") & (rng.uniform(size=n) < 0.7), "poor", diet).astype(object)
    ex = _cat_draw(rng, n, [("average", 10), ("low", 12), ("high", 4)])
    follow = _cat_draw(rng, n, [("average", 10), ("low", 14), ("high", 3)])
    smoke = _cat_draw(rng, n, [("non smoker", 10), ("smoker", 3)])
    alco = _cat_draw(rng, n, [("average", 10), ("low", 16), ("high", 4)])

    prob = np.full(n, 20.0)
    prob += np.select([age > 80, age > 70, age > 60], [10, 5, 3], 0)
    prob += np.select([(wt > 200) & (ht < 70), (wt > 180) & (ht < 60)], [5, 3], 0)
    prob += np.select([emp == "unemployed", emp == "retired"], [6, 4], 0)
    prob += np.where(fam == "alone", 9, 0)
    prob += np.select([diet == "poor", diet == "average"], [4, 2], 0)
    prob += np.select([ex == "low", ex == "average"], [3, 1], 0)
    prob += np.where(follow == "low", 8, 0)   # the rb's 'avearge' typo branch never fires
    prob += np.where(smoke == "smoker", 6, 0)
    prob += np.select([alco == "high", alco == "average"], [5, 2], 0)
    readmit = rng.uniform(0, 100, size=n) < prob

    rows = np.empty((n, 12), dtype=object)
    # zero-padded ids: lexicographic == generation order (graftlint GL003)
    assert n < 10 ** 10, "patient ids overflow the 10-digit width"
    rows[:, 0] = [f"P{int(i):010d}" for i in range(n)]
    rows[:, 1] = age.astype(str).astype(object)
    rows[:, 2] = wt.astype(str).astype(object)
    rows[:, 3] = ht.astype(str).astype(object)
    rows[:, 4] = emp
    rows[:, 5] = fam
    rows[:, 6] = diet
    rows[:, 7] = ex
    rows[:, 8] = follow
    rows[:, 9] = smoke
    rows[:, 10] = alco
    rows[:, 11] = np.where(readmit, "Y", "N").astype(object)
    return rows
