"""Lead-generation simulator — planted-structure port of resource/lead_gen.py.

Mechanism (lead_gen.py:12-15): three landing pages with Gaussian
click-through distributions — page1 (30, 12), page2 (60, 30), page3 (80, 10)
— so page3 is the best arm. The reference runs this as a live closed loop
against the Storm topology through Redis queues; here the same loop drives
:class:`avenir_tpu.pipeline.streaming.ReinforcementLearnerServer` through
in-process queues, asserting the learner converges to page3.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

CTR_DISTR: Dict[str, Tuple[float, float]] = {
    "page1": (30.0, 12.0),
    "page2": (60.0, 30.0),
    "page3": (80.0, 10.0),
}
BEST_ACTION = "page3"


class LeadGenSimulator:
    """Event source + reward oracle, one object closing the loop.

    Implements the EventSource/RewardReader protocols of the serving loop:
    each ``next_event`` is a session visit; each action selection gets a
    CTR draw from that page's Gaussian banked as its reward.
    """

    def __init__(self, n_events: int, seed: int = 0,
                 ctr: Optional[Dict[str, Tuple[float, float]]] = None):
        self.rng = np.random.default_rng(seed)
        self.remaining = n_events
        self.round = 0
        self.ctr = dict(ctr or CTR_DISTR)
        self._pending_rewards: List[Tuple[str, float]] = []
        self.selections: Dict[str, int] = {a: 0 for a in self.ctr}

    @property
    def actions(self) -> List[str]:
        return list(self.ctr)

    # -- EventSource ---------------------------------------------------------
    def next_event(self) -> Optional[Tuple[str, int]]:
        if self.remaining <= 0:
            return None
        self.remaining -= 1
        self.round += 1
        return str(uuid.uuid5(uuid.NAMESPACE_OID, str(self.round))), self.round

    # -- RewardReader --------------------------------------------------------
    def read_rewards(self) -> List[Tuple[str, float]]:
        out, self._pending_rewards = self._pending_rewards, []
        return out

    # -- ActionWriter --------------------------------------------------------
    def write(self, event_id: str, actions: List[str]) -> None:
        for a in actions:
            mu, sd = self.ctr[a]
            click_rate = float(np.clip(self.rng.normal(mu, sd), 0.0, 100.0))
            self._pending_rewards.append((a, click_rate))
            self.selections[a] += 1

    def best_selected(self) -> str:
        return max(self.selections, key=self.selections.get)
