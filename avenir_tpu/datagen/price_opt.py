"""Price-optimization generator — planted-structure port of
resource/price_opt.py.

Mechanism (price_opt.py:6-27): each product draws ``num_price`` in 6–11
(``randrange(6, 12)``-style exclusive top, price_opt.py:11) and gets
``num_price − 1`` (i.e. 5–10) candidate price points on an arithmetic grid —
mirroring the reference generator's own 1-based loop (price_opt.py:17) —
with a concave revenue curve: revenue climbs by
``rev_delta`` per step up to a halfway point, then falls, so exactly one
price is revenue-optimal. A correct bandit must converge its per-product
selection to that price (the price_optimize_tutorial round loop).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np


@dataclass
class Product:
    product_id: str
    prices: List[int]
    mean_revenue: List[float]
    noise_sd: float

    @property
    def optimal_price(self) -> int:
        return self.prices[int(np.argmax(self.mean_revenue))]


@dataclass
class PriceOptSimulator:
    """Closed-loop revenue oracle: products with concave revenue curves."""

    products: Dict[str, Product] = field(default_factory=dict)
    rng: np.random.Generator = field(default_factory=lambda: np.random.default_rng(0))

    def reward(self, product_id: str, price: str) -> float:
        """Noisy revenue draw for selecting ``price`` on ``product_id``."""
        p = self.products[product_id]
        i = p.prices.index(int(price))
        return float(max(self.rng.normal(p.mean_revenue[i], p.noise_sd), 0.0))

    def initial_rows(self) -> List[List[str]]:
        """(group, item, count, reward) rows — the bandit-job input with no
        pulls yet (the tutorial's bootstrap state)."""
        return [[pid, str(price), "0", "0"]
                for pid, p in self.products.items() for price in p.prices]


def generate_price_opt(n_products: int = 20, seed: int = 42) -> PriceOptSimulator:
    rng = np.random.default_rng(seed)
    sim = PriceOptSimulator(rng=np.random.default_rng(seed + 1))
    for _ in range(n_products):
        pid = str(rng.integers(1_000_000, 8_000_000))
        num_price = int(rng.integers(6, 12))
        price_delta = int(rng.integers(2, 4))
        price = int(rng.integers(10, 80))
        rev = float(rng.integers(10_000, 30_000))
        rev_delta = float(rng.integers(500, 1500))
        halfway = num_price // 2 + int(rng.integers(-2, 2))
        prices, revs = [], []
        for step in range(1, num_price):
            prices.append(price)
            revs.append(rev)
            price += price_delta
            if step < halfway:
                rev += rev_delta + float(rng.integers(-20, 20))
            else:
                rev -= rev_delta + float(rng.integers(-20, 20))
        sim.products[pid] = Product(pid, prices, revs, noise_sd=200.0)
    return sim
