"""Abandoned-cart retargeting generator — planted-structure port of
resource/retarget.py.

Mechanism (retarget.py:9-22): 9 campaign types (send-hour 1/2/3 × cross-sell /
social / none) with a fixed conversion-probability table (1C 75% ... 3N 15%);
cart amount is independent noise. A correct decision tree must split on
campaignType first and ignore amount.
"""

from __future__ import annotations

import numpy as np

RETARGET_SCHEMA_JSON = {
    "fields": [
        {"name": "custID", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "campaignType", "ordinal": 1, "dataType": "categorical", "feature": True,
         "maxSplit": 2,
         "cardinality": ["1C", "1S", "1N", "2C", "2S", "2N", "3C", "3S", "3N"]},
        {"name": "amount", "ordinal": 2, "dataType": "int", "feature": True,
         "bucketWidth": 50},
        {"name": "succeeded", "ordinal": 3, "dataType": "categorical",
         "cardinality": ["N", "Y"]},
    ]
}

CONVERSION = {"1C": 75, "1S": 60, "1N": 50, "2C": 60, "2S": 40, "2N": 30,
              "3C": 20, "3S": 20, "3N": 15}


def generate_retarget(n: int, seed: int = 42) -> np.ndarray:
    rng = np.random.default_rng(seed)
    types = np.array(list(CONVERSION), object)
    t = rng.choice(types, size=n)
    conv_prob = np.vectorize(CONVERSION.get)(t)
    conv = rng.integers(1, 101, size=n) < conv_prob
    amount = 20 + rng.integers(0, 301, size=n)
    rows = np.empty((n, 4), dtype=object)
    rows[:, 0] = [str(1000000 + int(i)) for i in rng.integers(0, 999999, size=n)]
    rows[:, 1] = t
    rows[:, 2] = amount.astype(str).astype(object)
    rows[:, 3] = np.where(conv, "Y", "N").astype(object)
    return rows
