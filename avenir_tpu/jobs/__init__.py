"""Job registry — reference Tool class names → TPU-native jobs.

Jobs are addressable by the reference's fully-qualified class name
(``org.avenir.bayesian.BayesianDistribution``) or the simple name, so the
reference's runbooks translate verb-for-verb.
"""

from __future__ import annotations

from typing import Dict, Type

from avenir_tpu.jobs.base import Job
from avenir_tpu.jobs.bayesian import BayesianDistribution, BayesianPredictor
from avenir_tpu.jobs.chombo import NumericalAttrStats, Projection, RunningAggregator
from avenir_tpu.jobs.explore import (
    BaggingSampler,
    CramerCorrelation,
    HeterogeneityReductionCorrelation,
    MutualInformation,
    UnderSamplingBalancer,
)
from avenir_tpu.jobs.knn import (
    FeatureCondProbJoiner,
    NearestNeighbor,
    SameTypeSimilarity,
)
from avenir_tpu.jobs.markov import (
    HiddenMarkovModelBuilder,
    MarkovStateTransitionModel,
    ViterbiStatePredictor,
)
from avenir_tpu.jobs.regress import FisherDiscriminant, LogisticRegressionJob
from avenir_tpu.jobs.reinforce import (
    AuerDeterministic,
    GreedyRandomBandit,
    RandomFirstGreedyBandit,
    SoftMaxBandit,
)
from avenir_tpu.jobs.text import WordCounter
from avenir_tpu.jobs.tree import (
    ClassPartitionGenerator,
    DataPartitioner,
    DecisionTreeBuilder,
    SplitGenerator,
)
from avenir_tpu.serving.replay import ScoringPlane

# reference package of each job's counterpart (for fully-qualified lookup)
_PACKAGES: Dict[str, str] = {
    "BayesianDistribution": "bayesian",
    "BayesianPredictor": "bayesian",
    "MutualInformation": "explore",
    "CramerCorrelation": "explore",
    "HeterogeneityReductionCorrelation": "explore",
    "BaggingSampler": "explore",
    "UnderSamplingBalancer": "explore",
    "ClassPartitionGenerator": "explore",
    "SplitGenerator": "tree",
    "DataPartitioner": "tree",
    "DecisionTreeBuilder": "tree",
    "NearestNeighbor": "knn",
    "FeatureCondProbJoiner": "knn",
    "SameTypeSimilarity": "knn",
    "MarkovStateTransitionModel": "markov",
    "HiddenMarkovModelBuilder": "markov",
    "ViterbiStatePredictor": "markov",
    "LogisticRegressionJob": "regress",
    "FisherDiscriminant": "discriminant",
    "GreedyRandomBandit": "reinforce",
    "AuerDeterministic": "reinforce",
    "SoftMaxBandit": "reinforce",
    "RandomFirstGreedyBandit": "reinforce",
    "WordCounter": "text",
}

# chombo sibling-library jobs the runbooks call between avenir jobs — kept
# addressable by their org.chombo.mr names (SURVEY.md §2.11)
_CHOMBO_JOBS = {"RunningAggregator", "Projection", "NumericalAttrStats"}

JOB_CLASSES = [
    BayesianDistribution, BayesianPredictor,
    MutualInformation, CramerCorrelation, HeterogeneityReductionCorrelation,
    BaggingSampler, UnderSamplingBalancer,
    ClassPartitionGenerator, SplitGenerator, DataPartitioner, DecisionTreeBuilder,
    NearestNeighbor, FeatureCondProbJoiner, SameTypeSimilarity,
    MarkovStateTransitionModel, HiddenMarkovModelBuilder, ViterbiStatePredictor,
    LogisticRegressionJob, FisherDiscriminant,
    GreedyRandomBandit, AuerDeterministic, SoftMaxBandit, RandomFirstGreedyBandit,
    WordCounter,
    RunningAggregator, Projection, NumericalAttrStats,
    # the serving plane's replay stage (no reference analog: the reference
    # has no online scoring surface at all — SURVEY §2)
    ScoringPlane,
]

REGISTRY: Dict[str, Type[Job]] = {}
for _cls in JOB_CLASSES:
    REGISTRY[_cls.name] = _cls
    pkg = _PACKAGES.get(_cls.name)
    if pkg:
        REGISTRY[f"org.avenir.{pkg}.{_cls.name}"] = _cls
    if _cls.name in _CHOMBO_JOBS:
        REGISTRY[f"org.chombo.mr.{_cls.name}"] = _cls


def get_job(name: str) -> Job:
    try:
        return REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown job {name!r}; known: "
            f"{sorted(k for k in REGISTRY if '.' not in k)}") from None


# the continuous-analytics plane's replay stage (no reference analog: the
# reference's statistics are whole-file batch scans — SURVEY §0).  A bare
# MODULE import, placed last: stream/job.py registers itself into
# REGISTRY/JOB_CLASSES at the end of its own body, which is the only
# wiring that survives every entry point of the import cycle — jobs-first
# (this line triggers the registration), stream-first (stream/job.py is
# mid-import above us on the stack, so this line binds the partial module
# without touching its names, and the registration runs when its body
# completes).  A ``from ... import StreamAnalytics`` here would crash any
# stream-first import.
import avenir_tpu.stream.job  # noqa: E402,F401
