"""Job layer — the reference's ``hadoop jar <ToolClass> -Dconf.path=p in out``
contract, minus the cluster.

Every reference algorithm ships as a Hadoop ``Tool`` with a ``run()`` wiring
mappers/reducers and a CSV-in/CSV-out + properties + JSON-schema driver
contract (e.g. bayesian/BayesianDistribution.java:58-84). Here a job is a
plain object with ``run(conf, input_path, output_path) -> Counters``: input is
a CSV file or a directory of part files, output is written as
``<out>/part-00000`` (the MR output-directory convention scripts like
resource/knn.sh already expect), and the properties file / feature schema keep
their reference key names (``feature.schema.file.path``,
``field.delim.regex``, ...).

The execution substrate is the in-process TPU engine: instead of a mapper
fleet + shuffle + reducer, each job streams encoded chunks through jitted
aggregation kernels (see avenir_tpu.ops.agg) and writes its output lines from
host memory.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from avenir_tpu.core.config import JobConfig
from avenir_tpu.core.csv_io import iter_csv_chunks, read_csv
from avenir_tpu.core.encoding import DatasetEncoder, EncodedDataset
from avenir_tpu.core.schema import FeatureSchema
from avenir_tpu.utils.metrics import Counters

PART_FILE = "part-00000"


def input_files(path: str) -> List[str]:
    """Resolve a job input path (file, or dir of part files) to file list.

    Directory reads skip hidden files and ``_SUCCESS`` markers, mirroring
    Hadoop's FileInputFormat conventions.
    """
    if os.path.isdir(path):
        names = sorted(
            n for n in os.listdir(path)
            if not n.startswith(".") and not n.startswith("_")
        )
        return [os.path.join(path, n) for n in names]
    return [path]


def read_input(path: str, delim: str = ",") -> np.ndarray:
    """All input rows as one [N, ncols] object array of strings."""
    chunks = [read_csv(f, delim=delim) for f in input_files(path)]
    chunks = [c for c in chunks if c.size]
    if not chunks:
        return np.empty((0, 0), dtype=object)
    return np.concatenate(chunks, axis=0) if len(chunks) > 1 else chunks[0]


def iter_input_chunks(path: str, chunk_rows: int = 1_000_000,
                      delim: str = ",") -> Iterator[np.ndarray]:
    for f in input_files(path):
        yield from iter_csv_chunks(f, chunk_rows=chunk_rows, delim=delim)


def write_output(path: str, lines: Sequence[str], part: str = PART_FILE) -> str:
    """Write job output lines under ``<path>/<part>`` (MR layout); returns the
    part-file path. A path that already names a file (has an extension and a
    non-dir parent semantic) is honored as a plain file for single-artifact
    outputs like the LR coefficient file."""
    if path.endswith(os.sep) or not os.path.splitext(path)[1]:
        os.makedirs(path, exist_ok=True)
        target = os.path.join(path, part)
    else:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        target = path
    with open(target, "w") as fh:
        for line in lines:
            fh.write(line)
            fh.write("\n")
    return target


def read_lines(path: str) -> List[str]:
    out: List[str] = []
    for f in input_files(path):
        with open(f) as fh:
            out.extend(line.rstrip("\r\n") for line in fh if line.strip())
    return out


class Job:
    """Base: subclasses set ``name`` (the reference Tool class simple name)
    and implement :meth:`execute`."""

    name: str = ""

    def run(self, conf: JobConfig, input_path: str, output_path: str) -> Counters:
        counters = Counters()
        self.execute(conf, input_path, output_path, counters)
        return counters

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        raise NotImplementedError

    # -- shared plumbing -----------------------------------------------------
    @staticmethod
    def auto_mesh(conf: JobConfig):
        """Data-parallel mesh over all local devices, or None single-device.

        When more than one accelerator is attached, jobs shard each chunk's
        batch axis over a 1-D ``data`` mesh and let XLA insert the count
        all-reduce over ICI — the reference's mapper-fleet + combiner +
        shuffle, with zero per-job code. ``data.parallel.auto=false``
        disables it (single-device execution regardless of topology).

        Single-process only: the sharding path places globally-addressed
        arrays (``device_put_sharded_batch``), so multi-host (DCN) runs —
        where each process addresses only its local devices — must build
        their mesh and per-process arrays explicitly
        (``parallel/mesh.py::{make_hybrid_mesh, process_local_batch}``)."""
        if not conf.get_bool("data.parallel.auto", True):
            return None
        import jax

        if jax.process_count() > 1 or jax.device_count() < 2:
            return None
        from avenir_tpu.parallel.mesh import make_mesh

        return make_mesh(("data",))

    @staticmethod
    def load_schema(conf: JobConfig) -> FeatureSchema:
        path = conf.get("feature.schema.file.path")
        if not path:
            raise ValueError("feature.schema.file.path not set")
        return FeatureSchema.from_file(path)

    @staticmethod
    def encoder_for(conf: JobConfig) -> DatasetEncoder:
        return DatasetEncoder(Job.load_schema(conf))

    @staticmethod
    def encode_input(conf: JobConfig, input_path: str,
                     with_labels: bool = True,
                     encoder: Optional[DatasetEncoder] = None,
                     need_rows: bool = True):
        """(encoder, encoded dataset, raw rows) for whole-input jobs.

        ``need_rows=False`` (train/analyze jobs that never echo the raw
        fields) unlocks the native C++ encode path: CSV bytes go straight
        through ``runtime.native.encode_bytes`` (~3× the Python
        parse+transform) when the library is built, the schema is complete
        (vocabularies/bins/class values pre-declared — the same condition
        streaming train needs), and the delimiter is a single char; raw
        ``rows`` come back as None on that path. Identical encode semantics
        either way (tests/test_native.py parity suite)."""
        delim = conf.field_delim_regex
        enc = encoder or Job.encoder_for(conf)
        if not need_rows and len(delim) == 1:
            ds = Job._encode_input_native(input_path, enc, delim, with_labels)
            if ds is not None:
                return enc, ds, None
        rows = read_input(input_path, delim=delim)
        ds = enc.fit_transform(rows, with_labels=with_labels) if not enc._fitted \
            else enc.transform(rows, with_labels=with_labels)
        return enc, ds, rows

    @staticmethod
    def encode_input_with_lines(conf: JobConfig, input_path: str,
                                with_labels: bool = True,
                                encoder: Optional[DatasetEncoder] = None):
        """(encoder, encoded dataset, raw input lines) for scoring jobs that
        echo each input line into their output (line ``i`` corresponds to
        dataset row ``i``; blank lines are skipped on both sides). Uses the
        native encode path under the same conditions as
        ``encode_input(need_rows=False)``; the Python fallback reconstructs
        lines from the parsed fields (identical text for well-formed CSV)."""
        delim = conf.field_delim_regex
        enc = encoder or Job.encoder_for(conf)
        # echoing raw lines is only equivalent to rejoining parsed fields
        # when the input and output delimiters agree (they are independent
        # reference properties); otherwise the Python path rejoins uniformly
        if len(delim) == 1 and delim == conf.field_delim:
            got = Job._encode_input_native(input_path, enc, delim,
                                           with_labels, want_lines=True)
            if got is not None:
                ds, lines = got
                if lines is not None and len(lines) == ds.num_rows:
                    return enc, ds, lines
                # alignment/decode surprise: fall through to Python
        enc2, ds, rows = Job.encode_input(conf, input_path,
                                          with_labels=with_labels, encoder=enc)
        return enc2, ds, [conf.field_delim.join(str(v) for v in row)
                          for row in rows]

    @staticmethod
    def _sniff_ncols(path: str, delim: str, block: int = 1 << 16) -> int:
        """Field count of the first non-blank line of ``path``, reading in
        bounded blocks (never the whole file). 0 when the file has no
        non-blank line."""
        buf = b""
        with open(path, "rb") as fh:
            while True:
                chunk = fh.read(block)
                buf += chunk
                pos = 0
                while True:
                    nl = buf.find(b"\n", pos)
                    if nl < 0:
                        break
                    ln = buf[pos:nl]
                    if ln.strip():
                        return ln.rstrip(b"\r").count(delim.encode()) + 1
                    pos = nl + 1
                buf = buf[pos:]
                if not chunk:                  # EOF: trailing partial line
                    return (buf.rstrip(b"\r").count(delim.encode()) + 1
                            if buf.strip() else 0)

    @staticmethod
    def _encode_input_native(input_path: str, enc: DatasetEncoder,
                             delim: str, with_labels: bool,
                             want_lines: bool = False):
        """EncodedDataset via the C++ data plane, or None if unavailable.

        With ``want_lines`` returns ``(dataset, lines)`` where ``lines`` are
        the raw non-blank input lines derived from the SAME bytes the
        encoder parsed (one read per file), or ``lines=None`` when the
        bytes don't decode as UTF-8 (caller falls back to the Python path,
        which reads with the locale encoding)."""
        from avenir_tpu.runtime import native

        if not native.is_available() or \
                not (enc._fitted or enc.schema_complete(with_labels)):
            return None
        # Pre-pass: sniff ncols for EVERY part file (bounded reads — no part
        # is loaded whole) before encoding any. Parts of a multi-file input
        # directory may differ in width, and a narrow part anywhere must
        # divert the whole directory to the Python path (graceful
        # degradation) — discovering that after encoding earlier parts would
        # throw their work away.
        files = []
        for f in input_files(input_path):
            ncols = Job._sniff_ncols(f, delim)
            if ncols == 0:
                continue                       # empty/blank file: skip
            if ncols <= enc.max_ordinal(with_labels):
                # narrower file than the schema consumes: the Python
                # path degrades gracefully (e.g. labels=None when the
                # class column is absent); never index C++ out of range
                return None
            files.append((f, ncols))
        parts = []
        lines: Optional[List[str]] = [] if want_lines else None
        for f, ncols in files:
            with open(f, "rb") as fh:
                data = fh.read()
            parts.append(native.encode_bytes(data, enc, ncols=ncols,
                                             delim=delim,
                                             with_labels=with_labels))
            if lines is not None:
                try:
                    lines.extend(ln.decode().rstrip("\r")
                                 for ln in data.split(b"\n") if ln.strip())
                except UnicodeDecodeError:
                    lines = None
        if not parts:
            return None                      # empty input: python path decides
        if len(parts) == 1:
            ds = parts[0]
        else:
            first = parts[0]
            cat = lambda key: (None if getattr(first, key) is None else
                               np.concatenate([getattr(p, key) for p in parts]))
            ds = EncodedDataset(
                codes=cat("codes"), cont=cat("cont"), labels=cat("labels"),
                ids=cat("ids"), n_bins=first.n_bins,
                class_values=first.class_values,
                binned_ordinals=first.binned_ordinals,
                cont_ordinals=first.cont_ordinals)
        return (ds, lines) if want_lines else ds

    def encoded_data_source(self, conf: JobConfig, input_path: str,
                            counters: Counters, with_labels: bool = True,
                            mesh=None):
        """(encoder, data, rows_fn) for count-aggregation jobs whose model
        ``fit`` accepts either one EncodedDataset or a chunk iterable.

        With ``stream.chunk.rows`` set, ``data`` is the lazy retried chunk
        stream (:meth:`iter_encoded_retrying`) so arbitrarily large inputs
        never materialize whole; otherwise it is the whole encoded input
        (native path when eligible). ``rows_fn()`` reports rows processed —
        call it only after ``fit`` has consumed the stream.

        The chunk stream is pulled through a :class:`DeviceFeeder`
        (``stream.prefetch.depth`` buffers, default 2; 0 disables): a worker
        thread runs the read+parse+encode of chunk N+1 and stages its arrays
        on device (sharded over ``mesh`` when given — the same placement the
        model's fit would apply) while the compiled step consumes chunk N —
        the I/O/compute overlap Hadoop's mapper JVMs gave the reference for
        free."""
        if conf.get("stream.chunk.rows"):
            enc = self.encoder_for(conf)
            box = {"n": 0}

            def chunks():
                for d in self.iter_encoded_retrying(
                        conf, input_path, enc, counters,
                        with_labels=with_labels):
                    box["n"] += d.num_rows
                    yield d

            data = chunks()
            depth = conf.get_int("stream.prefetch.depth", 2)
            if depth > 0:
                from avenir_tpu.runtime.feeder import DeviceFeeder

                def stage(ds):
                    from avenir_tpu.parallel.mesh import maybe_shard_batch
                    codes, labels, cont = maybe_shard_batch(
                        mesh, ds.codes, ds.labels, ds.cont)
                    return EncodedDataset(
                        codes=codes, cont=cont, labels=labels, ids=ds.ids,
                        n_bins=ds.n_bins, class_values=ds.class_values,
                        binned_ordinals=ds.binned_ordinals,
                        cont_ordinals=ds.cont_ordinals)

                data = DeviceFeeder(data, depth=depth, stage=stage)
            return enc, data, lambda: box["n"]
        enc, ds, _rows = self.encode_input(conf, input_path,
                                           with_labels=with_labels,
                                           need_rows=False)
        return enc, ds, lambda: ds.num_rows

    @staticmethod
    def iter_encoded_retrying(conf: JobConfig, input_path: str,
                              encoder: DatasetEncoder,
                              counters: Counters,
                              with_labels: bool = True) -> Iterator[EncodedDataset]:
        """Stream encoded chunks with per-chunk retry — the streaming train
        path, gated by ``stream.chunk.rows``.

        The retried task is the whole read+parse+encode of one chunk,
        addressed by (file, byte offset) exactly as a Hadoop map task is
        addressed by its input split: on retry the task re-opens the file,
        re-seeks, and re-reads, so transient I/O faults are covered along
        with encode faults (policy from ``mapred.map.max.attempts``; the
        read loop is owned here rather than delegated to
        ``iter_input_chunks`` precisely because retries need seekable
        addressing, which a generator cannot replay).

        Requires a schema-complete encoder (vocabularies via
        ``cardinality``, numeric ranges via ``min``/``max``), exactly the
        contract the reference's mappers rely on — with an open vocabulary
        the single-pass stream cannot assign stable codes, and
        ``DatasetEncoder.transform`` raises ConfigError (non-retryable)."""
        from avenir_tpu.core.csv_io import read_csv_string
        from avenir_tpu.runtime import native
        from avenir_tpu.utils.retry import RetryPolicy, run_with_retry

        policy = RetryPolicy.from_conf(conf)
        chunk_rows = conf.get_int("stream.chunk.rows", 1_000_000)
        delim = conf.field_delim_regex
        # an incomplete schema must still fail fast with ConfigError via the
        # python transform, so the native path also gates on completeness
        use_native = (native.is_available() and len(delim) == 1 and
                      (encoder._fitted or encoder.schema_complete(with_labels)))
        i = 0
        for f in input_files(input_path):
            offset = 0
            while True:
                def task(path=f, off=offset):
                    with open(path, "rb") as fh:
                        fh.seek(off)
                        raw: List[bytes] = []
                        while len(raw) < chunk_rows:
                            ln = fh.readline()
                            if not ln:
                                break
                            if ln.strip():
                                raw.append(ln)
                        end = fh.tell()
                    if not raw:
                        return end, None
                    ncols = raw[0].rstrip(b"\r\n").count(delim.encode()) + 1
                    if use_native and ncols > encoder.max_ordinal(with_labels):
                        return end, native.encode_bytes(
                            b"".join(raw), encoder, ncols=ncols, delim=delim,
                            with_labels=with_labels)
                    rows = read_csv_string(b"".join(raw).decode(), delim=delim)
                    return end, encoder.transform(rows, with_labels=with_labels)

                offset, ds = run_with_retry(
                    task, policy=policy, counters=counters, task=f"chunk[{i}]")
                if ds is None:
                    break
                i += 1
                yield ds
