"""Job layer — the reference's ``hadoop jar <ToolClass> -Dconf.path=p in out``
contract, minus the cluster.

Every reference algorithm ships as a Hadoop ``Tool`` with a ``run()`` wiring
mappers/reducers and a CSV-in/CSV-out + properties + JSON-schema driver
contract (e.g. bayesian/BayesianDistribution.java:58-84). Here a job is a
plain object with ``run(conf, input_path, output_path) -> Counters``: input is
a CSV file or a directory of part files, output is written as
``<out>/part-00000`` (the MR output-directory convention scripts like
resource/knn.sh already expect), and the properties file / feature schema keep
their reference key names (``feature.schema.file.path``,
``field.delim.regex``, ...).

The execution substrate is the in-process TPU engine: instead of a mapper
fleet + shuffle + reducer, each job streams encoded chunks through jitted
aggregation kernels (see avenir_tpu.ops.agg) and writes its output lines from
host memory.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.core.csv_io import iter_csv_chunks, read_csv
from avenir_tpu.core.encoding import DatasetEncoder, EncodedDataset
from avenir_tpu.core.schema import FeatureSchema
from avenir_tpu.utils.metrics import Counters

PART_FILE = "part-00000"


def input_files(path: str) -> List[str]:
    """Resolve a job input path (file, or dir of part files) to file list.

    Directory reads skip hidden files and ``_SUCCESS`` markers, mirroring
    Hadoop's FileInputFormat conventions.
    """
    if os.path.isdir(path):
        names = sorted(
            n for n in os.listdir(path)
            if not n.startswith(".") and not n.startswith("_")
        )
        return [os.path.join(path, n) for n in names]
    return [path]


def read_input(path: str, delim: str = ",") -> np.ndarray:
    """All input rows as one [N, ncols] object array of strings."""
    chunks = [read_csv(f, delim=delim) for f in input_files(path)]
    chunks = [c for c in chunks if c.size]
    if not chunks:
        return np.empty((0, 0), dtype=object)
    return np.concatenate(chunks, axis=0) if len(chunks) > 1 else chunks[0]


def iter_input_chunks(path: str, chunk_rows: int = 1_000_000,
                      delim: str = ",") -> Iterator[np.ndarray]:
    for f in input_files(path):
        yield from iter_csv_chunks(f, chunk_rows=chunk_rows, delim=delim)


def output_target(path: str, part: str = PART_FILE) -> str:
    """Resolve a job output path to its writable target (creating parent
    dirs): ``<path>/<part>`` for the MR directory layout, or ``path``
    itself when it already names a plain file (has an extension) — the
    single definition behind :func:`write_output` and the streaming jobs
    that write their part file incrementally."""
    if path.endswith(os.sep) or not os.path.splitext(path)[1]:
        os.makedirs(path, exist_ok=True)
        return os.path.join(path, part)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    return path


def write_output(path: str, lines: Sequence[str], part: str = PART_FILE) -> str:
    """Write job output lines under ``<path>/<part>`` (MR layout); returns the
    part-file path. A path that already names a file (has an extension and a
    non-dir parent semantic) is honored as a plain file for single-artifact
    outputs like the LR coefficient file."""
    target = output_target(path, part)
    with open(target, "w") as fh:
        for line in lines:
            fh.write(line)
            fh.write("\n")
    return target


def read_lines(path: str) -> List[str]:
    out: List[str] = []
    for f in input_files(path):
        with open(f) as fh:
            out.extend(line.rstrip("\r\n") for line in fh if line.strip())
    return out


class Job:
    """Base: subclasses set ``name`` (the reference Tool class simple name)
    and implement :meth:`execute`."""

    name: str = ""

    def run(self, conf: JobConfig, input_path: str, output_path: str) -> Counters:
        from avenir_tpu import tenancy
        from avenir_tpu.telemetry import spans as tel

        tracer = tel.configure(conf)
        # GraftPool (round 18): a standalone job is a tenant workload too
        # — arm the arbiter from tenant.* contracts (no-op without them)
        # and run under the conf's tenant label, so its chunk folds draw
        # arbitrated dispatch slots and its journal events attribute.
        # The scope itself is free when tenant.id is unset (None labels
        # are dropped).
        tenancy.configure(conf)
        counters = Counters()
        # the conf fingerprint ties the span to the exact configuration
        # that ran — the same identity checkpoint snapshots carry (GL002),
        # so a journal and a checkpoint dir cross-reference.  Built only
        # when tracing is on: the fingerprint sorts+hashes every property,
        # which an untraced run must not pay per job.
        attrs = None
        if tracer.enabled:
            attrs = {"conf": StreamCheckpointer.run_id_from_conf(conf),
                     "input": input_path, "output": output_path}
        # GraftBox: the job body is the launcher worker's heartbeat seam
        # — a guarded region plus the progress beats from the chunk/pane
        # folds inside it, so a worker wedged anywhere in execute() trips
        # hang.detected and captures a bundle (one attribute check when
        # blackbox.watchdog.sec is unset)
        from avenir_tpu.telemetry import blackbox

        with tel.label_scope(tenant=conf.get("tenant.id")), \
                tracer.span(f"job.{self.name or type(self).__name__}",
                            attrs=attrs), \
                blackbox.watchdog_guard(
                    f"job.{self.name or type(self).__name__}"):
            self.execute(conf, input_path, output_path, counters)
        # GraftFleet (round 15): journal this job's final counter
        # snapshot under the job name — in a multi-process run EVERY
        # process's shard then carries its own totals (per-process
        # attribution in the merged fleet view, and the data the SLO
        # evaluator's counter metrics read), and a standalone Python-API
        # run becomes scrapeable post-hoc (`telemetry metrics`) without
        # going through the CLI wrapper.  Only when this job is the
        # OUTERMOST traced unit: nested under an enclosing span (a
        # pipeline stage), the driver already journals the stage
        # snapshot, and a second identically-valued series would both
        # double the CLI's counter-delta report and double-count in the
        # SLO evaluator's per-writer totals.
        if tracer.enabled and tracer.current() is None:
            # the snapshot keeps the tenant label (it is emitted after
            # the job span closed, outside the scope above) so a
            # per-tenant SLO filter still sees this job's totals
            with tel.label_scope(tenant=conf.get("tenant.id")):
                tracer.counters(self.name or type(self).__name__, counters)
        # GraftProf: flush cumulative program wall totals at the job
        # boundary — a one-shot CLI run exits without ever calling
        # Tracer.disable, and totals below the periodic flush threshold
        # would otherwise die with the process (no-op when profiling is
        # off or nothing new was sampled)
        from avenir_tpu.telemetry import profile as _profile

        _profile.profiler().flush()
        return counters

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        raise NotImplementedError

    # -- shared plumbing -----------------------------------------------------
    @staticmethod
    def auto_mesh(conf: JobConfig):
        """Data-parallel mesh over all local devices, or None single-device.

        When more than one accelerator is attached, jobs shard each chunk's
        batch axis over a 1-D ``data`` mesh and let XLA insert the count
        all-reduce over ICI — the reference's mapper-fleet + combiner +
        shuffle, with zero per-job code. ``data.parallel.auto=false``
        disables it (single-device execution regardless of topology).

        Single-process only: the sharding path places globally-addressed
        arrays (``device_put_sharded_batch``), so multi-host (DCN) runs —
        where each process addresses only its local devices — must build
        their mesh and per-process arrays explicitly
        (``parallel/mesh.py::{make_hybrid_mesh, process_local_batch}``)."""
        if not conf.get_bool("data.parallel.auto", True):
            return None
        import jax

        if jax.process_count() > 1 or jax.device_count() < 2:
            return None
        from avenir_tpu.parallel.mesh import make_mesh

        return make_mesh(("data",))

    @staticmethod
    def load_schema(conf: JobConfig) -> FeatureSchema:
        path = conf.get("feature.schema.file.path")
        if not path:
            raise ConfigError("feature.schema.file.path not set")
        return FeatureSchema.from_file(path)

    @staticmethod
    def encoder_for(conf: JobConfig) -> DatasetEncoder:
        return DatasetEncoder(Job.load_schema(conf))

    @staticmethod
    def encode_input(conf: JobConfig, input_path: str,
                     with_labels: bool = True,
                     encoder: Optional[DatasetEncoder] = None,
                     need_rows: bool = True):
        """(encoder, encoded dataset, raw rows) for whole-input jobs.

        ``need_rows=False`` (train/analyze jobs that never echo the raw
        fields) unlocks the native C++ encode path: CSV bytes go straight
        through ``runtime.native.encode_bytes`` (~3× the Python
        parse+transform) when the library is built, the schema is complete
        (vocabularies/bins/class values pre-declared — the same condition
        streaming train needs), and the delimiter is a single char; raw
        ``rows`` come back as None on that path. Identical encode semantics
        either way (tests/test_native.py parity suite)."""
        delim = conf.field_delim_regex
        enc = encoder or Job.encoder_for(conf)
        if not need_rows and len(delim) == 1:
            ds = Job._encode_input_native(input_path, enc, delim, with_labels)
            if ds is not None:
                return enc, ds, None
        rows = read_input(input_path, delim=delim)
        ds = enc.fit_transform(rows, with_labels=with_labels) if not enc._fitted \
            else enc.transform(rows, with_labels=with_labels)
        return enc, ds, rows

    @staticmethod
    def encode_input_with_lines(conf: JobConfig, input_path: str,
                                with_labels: bool = True,
                                encoder: Optional[DatasetEncoder] = None):
        """(encoder, encoded dataset, raw input lines) for scoring jobs that
        echo each input line into their output (line ``i`` corresponds to
        dataset row ``i``; blank lines are skipped on both sides). Uses the
        native encode path under the same conditions as
        ``encode_input(need_rows=False)``; the Python fallback reconstructs
        lines from the parsed fields (identical text for well-formed CSV)."""
        delim = conf.field_delim_regex
        enc = encoder or Job.encoder_for(conf)
        # echoing raw lines is only equivalent to rejoining parsed fields
        # when the input and output delimiters agree (they are independent
        # reference properties); otherwise the Python path rejoins uniformly
        if len(delim) == 1 and delim == conf.field_delim:
            got = Job._encode_input_native(input_path, enc, delim,
                                           with_labels, want_lines=True)
            if got is not None:
                ds, lines = got
                if lines is not None and len(lines) == ds.num_rows:
                    return enc, ds, lines
                # alignment/decode surprise: fall through to Python
        enc2, ds, rows = Job.encode_input(conf, input_path,
                                          with_labels=with_labels, encoder=enc)
        return enc2, ds, [conf.field_delim.join(str(v) for v in row)
                          for row in rows]

    @staticmethod
    def _sniff_ncols(path: str, delim: str, block: int = 1 << 16) -> int:
        """Field count of the first non-blank line of ``path``, reading in
        bounded blocks (never the whole file). 0 when the file has no
        non-blank line.

        Delimiters are counted per block as the first line streams by, so
        a single-line multi-GB file costs O(L) work and O(block) memory —
        the previous form accumulated the line in one buffer and re-scanned
        it from offset 0 on every block (O(L²); round-2 advisory)."""
        d = delim.encode()
        # single-byte delimiters (the normal case) can never straddle a
        # block boundary, so the count accumulates per block and the line
        # itself is never retained; multi-byte delimiters keep the line
        # buffered (counted once at line end) with the newline search
        # resuming where the last block left off — O(L) either way.
        streaming = len(d) == 1 and d != b"\r"
        count = -1                     # -1: still skipping blank lines
        tail = b""                     # carried bytes (1 on streaming path)
        scan0 = 0                      # newline-search resume offset
        with open(path, "rb") as fh:
            while True:
                chunk = fh.read(block)
                data = tail + chunk
                tail = b""
                at_eof = not chunk
                while count < 0:
                    nl = data.find(b"\n")
                    if nl < 0:
                        if data.strip():
                            count = 0          # first line starts here
                        elif at_eof:
                            return 0
                        break
                    if data[:nl].strip():      # whole first line in hand
                        return data[:nl].rstrip(b"\r").count(d) + 1
                    data = data[nl + 1:]       # blank line: skip
                if count < 0:
                    tail = data
                    continue
                nl = data.find(b"\n", scan0)
                if nl >= 0:
                    return count + data[:nl].rstrip(b"\r").count(d) + 1
                if at_eof:
                    return count + data.rstrip(b"\r").count(d) + 1
                if streaming:
                    # keep one byte so a final \r\n still strips correctly
                    body, tail = data[:-1], data[-1:]
                    count += body.count(d)
                    scan0 = 0
                else:
                    tail = data
                    scan0 = len(data)

    @staticmethod
    def _encode_input_native(input_path: str, enc: DatasetEncoder,
                             delim: str, with_labels: bool,
                             want_lines: bool = False):
        """EncodedDataset via the C++ data plane, or None if unavailable.

        With ``want_lines`` returns ``(dataset, lines)`` where ``lines`` are
        the raw non-blank input lines derived from the SAME bytes the
        encoder parsed (one read per file), or ``lines=None`` when the
        bytes don't decode as UTF-8 (caller falls back to the Python path,
        which reads with the locale encoding)."""
        from avenir_tpu.runtime import native

        if not native.is_available() or \
                not (enc._fitted or enc.schema_complete(with_labels)):
            return None
        # Pre-pass: sniff ncols for EVERY part file (bounded reads — no part
        # is loaded whole) before encoding any. Parts of a multi-file input
        # directory may differ in width, and a narrow part anywhere must
        # divert the whole directory to the Python path (graceful
        # degradation) — discovering that after encoding earlier parts would
        # throw their work away.
        files = []
        for f in input_files(input_path):
            ncols = Job._sniff_ncols(f, delim)
            if ncols == 0:
                continue                       # empty/blank file: skip
            if ncols <= enc.max_ordinal(with_labels):
                # narrower file than the schema consumes: the Python
                # path degrades gracefully (e.g. labels=None when the
                # class column is absent); never index C++ out of range
                return None
            files.append((f, ncols))
        parts = []
        lines: Optional[List[str]] = [] if want_lines else None
        for f, ncols in files:
            with open(f, "rb") as fh:
                data = fh.read()
            parts.append(native.encode_bytes(data, enc, ncols=ncols,
                                             delim=delim,
                                             with_labels=with_labels))
            if lines is not None:
                try:
                    lines.extend(ln.decode().rstrip("\r")
                                 for ln in data.split(b"\n") if ln.strip())
                except UnicodeDecodeError:
                    lines = None
        if not parts:
            return None                      # empty input: python path decides
        if len(parts) == 1:
            ds = parts[0]
        else:
            first = parts[0]
            cat = lambda key: (None if getattr(first, key) is None else
                               np.concatenate([getattr(p, key) for p in parts]))
            ds = EncodedDataset(
                codes=cat("codes"), cont=cat("cont"), labels=cat("labels"),
                ids=cat("ids"), n_bins=first.n_bins,
                class_values=first.class_values,
                binned_ordinals=first.binned_ordinals,
                cont_ordinals=first.cont_ordinals)
        return (ds, lines) if want_lines else ds

    # -- multi-process execution (the Hadoop N-machine analog) ---------------
    @staticmethod
    def process_grid():
        """(process_index, process_count) under ``jax.distributed``
        initialization; (0, 1) in a plain single-process run."""
        import jax

        try:
            return jax.process_index(), jax.process_count()
        except Exception:                              # pragma: no cover
            return 0, 1

    @classmethod
    def is_output_writer(cls) -> bool:
        """Single-writer output protocol: process 0 writes the part file
        (Hadoop's reducer wrote through the OutputCommitter; here the
        merged totals are replicated, so one designated writer suffices)."""
        return cls.process_grid()[0] == 0

    @classmethod
    def distributed_plan(cls, conf: JobConfig, checkpointer):
        """(owner, accumulator, distributed) for a streaming count job.

        Under ``jax.distributed`` with ``stream.chunk.rows`` set, chunks
        are assigned round-robin by index (``idx % nprocs == pid`` — the
        analog of Hadoop handing each of N machines its input splits,
        ``BayesianDistribution.java:82``), each process accumulates its own
        partials, and :meth:`distributed_stream` merges the totals once at
        end of stream.

        Checkpointing COMPOSES with this mode (round-5): the checkpointer
        is already process-scoped (``StreamCheckpointer.from_conf`` homes
        each process's snapshots under ``proc-<pid>-of-<nprocs>/``), so
        each process durably snapshots its OWN partial totals + cursor
        over its OWN owned-chunk stream; a killed multi-process run
        relaunched with ``--resume`` restores every process's partials and
        re-streams only unconsumed owned chunks — Hadoop's task-level
        re-execution on a cluster (resource/knn.properties:5-6), not
        whole-job re-run."""
        pid, nprocs = cls.process_grid()
        if nprocs <= 1 or not conf.get("stream.chunk.rows"):
            return None, (checkpointer.accumulator if checkpointer else None), False
        owner = lambda idx: idx % nprocs == pid
        if checkpointer is not None:
            return owner, checkpointer.accumulator, True
        from avenir_tpu.ops import agg

        return owner, agg.Accumulator(), True

    @staticmethod
    def distributed_stream(chunks, accumulator, rows_fn, merged: dict):
        """Pass chunks through; at exhaustion, replace the accumulator's
        totals with the across-process sum (``all_process_sum_state``) and
        store the global row count in ``merged["rows"]`` — every model
        ``fit`` reads its totals only after consuming the stream, so the
        merge lands exactly between the last local chunk and finalization,
        with zero per-model code.  The row count rides in the same single
        packed gather, so every process — including one that owned no
        chunks at all — executes exactly one identical collective."""
        for ds in chunks:
            yield ds
        from avenir_tpu.parallel.mesh import all_process_sum_state

        state = accumulator.state()
        state["__rows__"] = np.asarray(rows_fn(), np.int64)
        total = all_process_sum_state(state)
        merged["rows"] = int(total.pop("__rows__"))
        accumulator.load(total)

    @classmethod
    def distributed_fit(cls, fit, data, acc, merged: dict):
        """Run a model ``fit`` over the distributed stream, tolerating a
        process that owned zero chunks (more processes than chunks): its
        stream is empty, so ``fit`` raises ``NoDataError`` — but only AFTER the
        end-of-stream merge collective ran, so its totals were (vacuously)
        contributed and its peers never stall.  Such a process returns
        None; it is never the output writer (process 0 always owns chunk
        0).  A globally-empty input re-raises on every process, matching
        single-process behavior."""
        from avenir_tpu.core.encoding import NoDataError
        try:
            return fit(data)
        except NoDataError:
            if merged.get("rows", 0) > 0 and not cls.is_output_writer():
                return None
            raise

    def encoded_data_source(self, conf: JobConfig, input_path: str,
                            counters: Counters, with_labels: bool = True,
                            mesh=None, checkpointer=None, owner=None,
                            shard=None):
        """(encoder, data, rows_fn) for count-aggregation jobs whose model
        ``fit`` accepts either one EncodedDataset or a chunk iterable.

        With ``stream.chunk.rows`` set, ``data`` is the lazy retried chunk
        stream (:meth:`iter_encoded_retrying`) so arbitrarily large inputs
        never materialize whole; otherwise it is the whole encoded input
        (native path when eligible). ``rows_fn()`` reports rows processed —
        call it only after ``fit`` has consumed the stream.

        The chunk stream is pulled through a :class:`DeviceFeeder`
        (``stream.prefetch.depth`` buffers, default 2; 0 disables): a worker
        thread runs the read+parse+encode of chunk N+1 and stages its arrays
        on device (sharded over ``mesh`` when given — the same placement the
        model's fit would apply) while the compiled step consumes chunk N —
        the I/O/compute overlap Hadoop's mapper JVMs gave the reference for
        free.

        With a :class:`StreamCheckpointer` the stream resumes from the
        persisted cursor and snapshots (count totals, cursor, rows) every N
        consumed chunks. The cursor travels WITH each chunk through the
        prefetch queue, so a checkpoint always describes exactly the chunks
        the model has accumulated — the feeder's read-ahead can never let
        the cursor outrun the counts (which on crash would silently drop
        the in-flight chunks from the resumed totals)."""
        if conf.get("stream.chunk.rows"):
            enc = self.encoder_for(conf)
            ckpt = checkpointer
            base_rows = ckpt.base_rows if ckpt else 0
            box = {"n": base_rows}

            pairs = self.iter_encoded_retrying(
                conf, input_path, enc, counters, with_labels=with_labels,
                start=ckpt.start if ckpt else None, emit_cursor=True,
                owner=owner)
            depth = conf.get_int("stream.prefetch.depth", 2)
            if depth > 0:
                from avenir_tpu.runtime.feeder import (DeviceFeeder,
                                                       sharded_pair_stage)

                if shard is not None:
                    # ShardGraft staging: ballast-pad to the pow-2 shard
                    # target and place round-robin over the mesh data axis
                    # on the prefetch worker (upload overlaps compute)
                    stage = sharded_pair_stage(shard)
                else:
                    def stage(item):
                        from avenir_tpu.parallel.mesh import maybe_shard_batch
                        ds, cur = item
                        codes, labels, cont = maybe_shard_batch(
                            mesh, ds.codes, ds.labels, ds.cont)
                        return EncodedDataset(
                            codes=codes, cont=cont, labels=labels, ids=ds.ids,
                            n_bins=ds.n_bins, class_values=ds.class_values,
                            binned_ordinals=ds.binned_ordinals,
                            cont_ordinals=ds.cont_ordinals), cur

                pairs = DeviceFeeder(pairs, depth=depth, stage=stage)

            def consume():
                if ckpt is None:
                    # plain streaming: straight pass-through (no lookahead —
                    # it would pin one staged chunk beyond the prefetch
                    # depth for no benefit)
                    for ds, cur in pairs:
                        box["n"] = base_rows + cur["rows"]
                        yield ds
                    return
                # one-pair lookahead: a checkpoint for chunk k is written
                # only when chunk k+1 exists, so a persisted cursor never
                # points at end-of-stream (a resume therefore always has at
                # least one chunk to re-read, which keeps the models'
                # peek-first-chunk metadata contract intact)
                it = iter(pairs)
                prev = next(it, None)
                while prev is not None:
                    ds, cur = prev
                    box["n"] = base_rows + cur["rows"]
                    yield ds
                    nxt = next(it, None)
                    ckpt.chunk_done(cur, last=nxt is None)
                    prev = nxt

            return enc, Job._chunk_telemetry(consume(), counters), \
                lambda: box["n"]
        enc, ds, _rows = self.encode_input(conf, input_path,
                                           with_labels=with_labels,
                                           need_rows=False)
        return enc, ds, lambda: ds.num_rows

    @staticmethod
    def _chunk_telemetry(chunks, counters: Counters):
        """Per-chunk telemetry around a streamed chunk source: a
        retroactive ``chunk`` span covering the consumer's work on each
        chunk (model accumulate + device dispatch — emitted between
        yields, parented to the job span the consumer holds), and the
        generalized compile-key diff so the ``Telemetry::recompiles``
        counter measures shape churn in BATCH streams exactly like the
        serving batcher measures it online (a steady stream recompiles
        once at most, for the ragged tail chunk).

        GraftProf (round 14): under ``profile.on`` each chunk's dispatch
        shape is also a registered program — the span gains a
        ``program=<id>`` attr, the consumer-side wall accumulates against
        it, and device memory is sampled at the chunk boundary (the
        monitor's key feed is the one compile-key source; the registry
        rides it, so program count == primed + recompiled keys by
        construction)."""
        import time as _time

        from avenir_tpu.telemetry import profile as _profile
        from avenir_tpu.telemetry import spans as tel

        def gen():
            tracer = tel.tracer()
            prof = _profile.profiler()
            monitor = tel.CompileKeyMonitor(counters, scope="stream",
                                            auto_prime=True)
            parent = tracer.current()
            for k, ds in enumerate(chunks):
                key = tel.CompileKeyMonitor.shape_key(
                    ds.codes, ds.labels, ds.cont)
                monitor.observe([key])
                attrs = {"chunk": k, "rows": ds.num_rows}
                if prof.enabled:
                    attrs["program"] = _profile.program_id("stream", key)
                t0 = _time.perf_counter()
                yield ds
                dur_s = _time.perf_counter() - t0
                if prof.enabled:
                    prof.sample(key, "stream", dur_s)
                    prof.sample_device_memory("chunk")
                tracer.emit_span("chunk", dur_s, parent=parent, attrs=attrs)

        return gen()

    @staticmethod
    def _iter_chunks_retrying(conf: JobConfig, input_path: str,
                              counters: Counters, decode,
                              owner=None, start: Optional[dict] = None):
        """The ONE chunk-scan/retry engine behind both streaming readers.

        Scans each input file by (byte offset, global chunk index); the
        retried task re-opens, re-seeks, re-reads AND re-decodes one chunk
        (``decode(raw_lines, path)`` runs inside the task so decode faults
        are retried with the read, policy from ``mapred.map.max.attempts``).
        ``owner`` is the multi-process chunk-assignment predicate —
        non-owned chunks are scanned to locate boundaries but never decoded
        or yielded.  ``start`` resumes from a persisted cursor
        (``{"file", "offset", "chunk"}``).  Yields
        ``(file, offset_after, chunk_index_after, payload)`` for owned,
        non-empty chunks."""
        from avenir_tpu.utils.retry import RetryPolicy, run_with_retry

        policy = RetryPolicy.from_conf(conf)
        chunk_rows = conf.get_int("stream.chunk.rows", 1_000_000)
        i = int(start["chunk"]) if start else 0
        all_files = list(input_files(input_path))
        if start:
            if start["file"] not in all_files:
                raise ConfigError(
                    f"resume cursor names {start['file']!r}, which is not "
                    f"among the input files — the input changed since the "
                    f"checkpoint was written")
            all_files = all_files[all_files.index(start["file"]):]
        for fi, f in enumerate(all_files):
            offset = int(start["offset"]) if start and fi == 0 else 0
            while True:
                def task(path=f, off=offset, idx=i):
                    mine = owner is None or owner(idx)
                    with open(path, "rb") as fh:
                        fh.seek(off)
                        raw: List[bytes] = []
                        nraw = 0
                        while nraw < chunk_rows:
                            ln = fh.readline()
                            if not ln:
                                break
                            if ln.strip():
                                nraw += 1
                                if mine:
                                    raw.append(ln)
                        end = fh.tell()
                    if not nraw:
                        return end, None
                    if not mine:
                        return end, Job._SKIP
                    return end, decode(raw, path)

                offset, payload = run_with_retry(
                    task, policy=policy, counters=counters, task=f"chunk[{i}]")
                if payload is None:
                    break
                i += 1
                if payload is Job._SKIP:
                    continue
                yield f, offset, i, payload

    @staticmethod
    def iter_line_chunks_retrying(conf: JobConfig, input_path: str,
                                  counters: Counters, owner=None,
                                  emit_index: bool = False):
        """Stream raw non-blank lines in ``stream.chunk.rows``-sized chunks
        with per-chunk retry — the ragged-input analog of
        :meth:`iter_encoded_retrying` for jobs whose records are not
        rectangular CSV (sequence files, raw text), over the same
        :meth:`_iter_chunks_retrying` engine.  Yields ``list[str]`` (lines
        with the newline stripped), or ``(global_chunk_index, list[str])``
        with ``emit_index`` — jobs whose merge keys are per-chunk need the
        index."""
        decode = lambda raw, path: [ln.decode().rstrip("\r\n") for ln in raw]
        for _f, _off, idx, lines in Job._iter_chunks_retrying(
                conf, input_path, counters, decode, owner=owner):
            yield (idx - 1, lines) if emit_index else lines

    _SKIP = object()                     # non-owned chunk marker

    @staticmethod
    def stream_checkpointer(conf: JobConfig):
        """The job's StreamCheckpointer, or None when not configured."""
        return StreamCheckpointer.from_conf(conf)


    @staticmethod
    def iter_encoded_retrying(conf: JobConfig, input_path: str,
                              encoder: DatasetEncoder,
                              counters: Counters,
                              with_labels: bool = True,
                              start: Optional[dict] = None,
                              emit_cursor: bool = False,
                              owner=None):
        """Stream encoded chunks with per-chunk retry — the streaming train
        path, gated by ``stream.chunk.rows``.

        The retried task is the whole read+parse+encode of one chunk,
        addressed by (file, byte offset) exactly as a Hadoop map task is
        addressed by its input split: on retry the task re-opens the file,
        re-seeks, re-reads and re-encodes, so transient I/O faults are
        covered along with encode faults (policy from
        ``mapred.map.max.attempts``).  The scan/retry engine is the shared
        :meth:`_iter_chunks_retrying`; this wrapper owns only the
        CSV-encode decode step and the cursor bookkeeping.

        ``start`` resumes mid-stream from a cursor a previous run persisted
        (``{"file", "offset", "chunk"}`` — the position AFTER the last
        accumulated chunk); ``emit_cursor`` yields ``(chunk, cursor)`` pairs
        where the cursor additionally carries the cumulative ``rows``
        yielded since ``start`` — the checkpoint/resume seam for streaming
        aggregation jobs (StreamCheckpointer).

        Requires a schema-complete encoder (vocabularies via
        ``cardinality``, numeric ranges via ``min``/``max``), exactly the
        contract the reference's mappers rely on — with an open vocabulary
        the single-pass stream cannot assign stable codes, and
        ``DatasetEncoder.transform`` raises ConfigError (non-retryable).

        ``owner``: optional ``fn(chunk_index) -> bool`` chunk-assignment
        predicate for multi-process runs — non-owned chunks are scanned
        (to locate boundaries) but never parsed, encoded, or yielded; the
        Hadoop analog is the JobTracker handing each mapper its input
        splits."""
        from avenir_tpu.core.csv_io import read_csv_string
        from avenir_tpu.runtime import native

        delim = conf.field_delim_regex
        # an incomplete schema must still fail fast with ConfigError via the
        # python transform, so the native path also gates on completeness
        use_native = (native.is_available() and len(delim) == 1 and
                      (encoder._fitted or encoder.schema_complete(with_labels)))

        def decode(raw, path):
            ncols = raw[0].rstrip(b"\r\n").count(delim.encode()) + 1
            if use_native and ncols > encoder.max_ordinal(with_labels):
                return native.encode_bytes(
                    b"".join(raw), encoder, ncols=ncols, delim=delim,
                    with_labels=with_labels)
            rows = read_csv_string(b"".join(raw).decode(), delim=delim)
            return encoder.transform(rows, with_labels=with_labels)

        rows_out = 0
        for f, offset, i, ds in Job._iter_chunks_retrying(
                conf, input_path, counters, decode, owner=owner, start=start):
            if emit_cursor:
                rows_out += ds.num_rows
                yield ds, {"file": f, "offset": offset, "chunk": i,
                           "rows": rows_out}
            else:
                yield ds


class StreamCheckpointer:
    """Mid-stream durability for streaming count-aggregation jobs.

    Hadoop gave the reference per-task durability for free: map outputs are
    materialized, so a crashed job re-runs only failed tasks. The streaming
    jobs here accumulate count tensors in memory across the whole input, so
    without this a crash at chunk N restarts from zero. Configured via:

    - ``stream.checkpoint.dir``: snapshot directory (enables the feature)
    - ``stream.checkpoint.interval.chunks``: snapshot every N consumed
      chunks (default 8)
    - ``stream.resume``: restore the latest snapshot and continue from its
      cursor (also the CLI's ``--resume`` flag)
    - ``stream.fault.crash.after.chunks``: fault injection — raise after N
      consumed chunks (kill-and-resume testing, incl. the 100M-row proof)
    - ``stream.run.id``: optional explicit run identity; defaults to a
      fingerprint of the job's stable properties (volatile relaunch flags
      — ``stream.resume``, ``stream.fault.*`` — excluded), so a crashed
      run's relaunch carries the same identity

    The snapshot is {accumulator totals, cursor(file, offset, chunk),
    rows}; counts are integer (or order-stable float64) host totals, so a
    resumed run's model files are byte-identical to an uninterrupted one.
    On successful job completion :meth:`finish` removes the directory —
    stale snapshots must never leak into a later, unrelated run.  In
    multi-process mode each process subdirectory is tagged with the run id
    (``RUN_TAG``), and the end-of-run sweep removes ONLY subdirectories of
    the same run (e.g. a crashed relaunch of this job at a different
    process count) — a concurrent job sharing the root under a different
    run id keeps its live snapshots (round-5 advisor finding).  Two
    concurrent runs with identical properties AND a shared root remain
    indistinguishable; a checkpoint root is exclusive to one run identity."""

    def __init__(self, directory: str, interval_chunks: int = 8,
                 resume: bool = False, crash_after_chunks: int = 0,
                 parent_dir: Optional[str] = None, run_id: str = "",
                 defer_errors: bool = False, reshard: bool = False):
        from avenir_tpu.ops import agg
        from avenir_tpu.utils.checkpoint import CheckpointManager

        self.directory = directory
        self.parent_dir = parent_dir         # multi-process: shared root
        self.run_id = run_id
        self.interval = max(int(interval_chunks), 1)
        self.crash_after = int(crash_after_chunks)
        self.accumulator = agg.Accumulator()
        self.base_rows = 0
        self.start: Optional[dict] = None      # cursor to resume from
        self._consumed = 0                     # chunks consumed THIS run
        # construction/restore failures: raised here single-process, but in
        # a distributed run held until _handshake_errors so the failure
        # travels THROUGH a collective every process enters (a process that
        # raised early would strand its peers in their next collective —
        # the round-5 LR-resume hazard class, jobs/regress.py)
        self.error: Optional[str] = None
        self.mgr = None
        try:
            if parent_dir is not None and run_id:
                # tag the process subdirectory with this run's identity so
                # the sweep in finish() can tell our stale subdirs from a
                # live concurrent job's (the id is conf-derived, hence
                # stable across crash + relaunch — including at a different
                # process count).  A subdirectory already tagged by a
                # DIFFERENT run must refuse loudly (round-8 graftlint
                # GL001/GL002 audit) BEFORE CheckpointManager touches it:
                # its _recover() sweeps temp dirs and promotes .bak
                # snapshots under a no-concurrent-writer assumption, which
                # against a live foreign run is exactly the pollution the
                # refusal exists to prevent.
                os.makedirs(directory, exist_ok=True)
                prior = self._read_tag(directory)
                if prior is not None and prior != run_id:
                    self.error = (
                        f"checkpoint subdirectory {directory!r} is tagged "
                        f"with run id {prior!r}, not this run's {run_id!r} "
                        f"— a checkpoint root is exclusive to one run "
                        f"identity; clear the directory or point "
                        f"stream.checkpoint.dir elsewhere")
                else:
                    with open(os.path.join(directory, "RUN_TAG"), "w") as fh:
                        fh.write(run_id)
            if self.error is None:
                self.mgr = CheckpointManager(directory, keep=2)
            if resume and self.error is None:
                state = None
                try:
                    state = self.mgr.restore()
                except Exception as e:
                    self.error = (f"checkpoint restore from {directory!r} "
                                  f"failed: {type(e).__name__}: {e}")
                if state is not None:
                    # snapshots fingerprint the run identity that wrote
                    # them (graftlint GL002): a stale snapshot from another
                    # configuration must fail loudly, never merge silently
                    snap_run = str(state.get("run", ""))
                    if snap_run and self.run_id and snap_run != self.run_id:
                        self.error = (
                            f"snapshot in {directory!r} was written by run "
                            f"{snap_run!r}, not this run {self.run_id!r} — "
                            f"the configuration changed since the "
                            f"checkpoint; clear the directory and re-run")
                        state = None
                if state is not None:
                    # ElasticGraft (round 16): the standalone streaming
                    # folds run unsharded, so a mesh-qualified snapshot
                    # (written by a sharded seam sharing the directory)
                    # is a topology crossing — redistribute under the
                    # shard.reshard.on.restore gate, refuse loudly
                    # otherwise, never fold silently.  Suffix-less
                    # ROUTING crossings (kernel↔einsum key families) are
                    # deliberately NOT gated here: the model fit paths
                    # that consume this accumulator dictate their route
                    # from the restored keys themselves — converting a
                    # gram exactly or continuing on the einsum family —
                    # and reject foreign layouts loudly
                    # (models/mutual_info.py::fit resume gate)
                    from avenir_tpu.checkpoint import reshard as _reshard

                    try:
                        snap_sfx = _reshard.snapshot_suffix(state)
                    except _reshard.ReshardError as e:
                        snap_sfx = None
                        self.error = str(e)
                        state = None
                    if state is not None and snap_sfx:
                        if reshard:
                            state, moved = _reshard.reshard_state_tree(
                                state, "")
                            _reshard.journal_reshard(
                                snap_sfx, "", len(moved),
                                directory=self.directory, run=self.run_id)
                        else:
                            self.error = (
                                f"snapshot in {directory!r} was folded "
                                f"under mesh topology {snap_sfx!r} but "
                                f"this job folds unsharded — set "
                                f"shard.reshard.on.restore=true to "
                                f"redistribute it, or clear the "
                                f"directory and re-run")
                            state = None
                if state is not None:
                    self.accumulator.load(state["acc"])
                    self.base_rows = int(state["rows"])
                    self.start = {k: state["cursor"][k]
                                  for k in ("file", "offset", "chunk")}
                    from avenir_tpu.telemetry import spans as tel

                    tel.tracer().event(
                        "checkpoint.restore", dir=self.directory,
                        run=self.run_id, rows=self.base_rows,
                        chunk=int(self.start["chunk"]))
        except Exception as e:
            # ANY construction failure (tag write, makedirs, manager
            # recovery, malformed snapshot) must be deferrable: a process
            # raising here before the handshake would strand its peers in
            # the collective
            self.error = (f"checkpointer construction in {directory!r} "
                          f"failed: {type(e).__name__}: {e}")
        if self.error and not defer_errors:
            raise ConfigError(self.error)

    @staticmethod
    def run_id_from_conf(conf: JobConfig) -> str:
        """The run's identity tag: ``stream.run.id`` when set, else a
        fingerprint of the stable properties.  Volatile relaunch flags and
        operational knobs (``stream.resume``, ``stream.fault.*``,
        ``stream.checkpoint.*``, ``stream.prefetch.*``) are excluded so a
        crashed run and its resume relaunch share the identity even when
        the relaunch drops the fault-injection/interval knobs — the
        finish() sweep may then reclaim the crashed run's subdirectories
        at ANY process count, and the snapshot run-fingerprint gate
        (round 8) accepts the relaunch, while a different job's live
        snapshots (different semantic properties → different id) are
        rejected loudly.  ``stream.chunk.rows`` stays IN the fingerprint:
        it defines the chunk boundaries a persisted cursor means."""
        explicit = conf.get("stream.run.id")
        if explicit:
            return explicit
        import hashlib

        # the topology/drill shard.* keys and fault.* joined the volatile
        # set in round 16 (ElasticGraft): the mesh topology is execution
        # LAYOUT, not semantics — results are proven byte-identical
        # across it, the mesh-qualified g: keys + the snapshot's recorded
        # "shard" suffix carry topology identity now, and the
        # shard.reshard.on.restore gate governs crossing it.  Keeping
        # shard.devices in the fingerprint would make every
        # preempted-and-shrunk relaunch a "different run", unreachable by
        # the elastic restore by construction; fault.*/shard.skew.* are
        # relaunch scaffolding like stream.fault.*.  Deliberately NOT
        # excluded: shard.allreduce.quantized — it changes NUMERICS (the
        # lossy int8 collective), so a relaunch flipping it is a
        # different run whose totals must never merge with exact ones
        # (the same reason pipeline/scan.py lists it in _COMPAT_KEYS)
        volatile = ("stream.resume", "stream.fault.", "stream.checkpoint.",
                    "stream.prefetch.", "shard.devices", "shard.data.axis",
                    "shard.proc.", "shard.reshard.", "shard.skew.", "fault.")
        stable = sorted(
            (k, v) for k, v in conf.props.items()
            if not any(k == v0.rstrip(".") or k.startswith(v0)
                       for v0 in volatile))
        return hashlib.blake2s(repr(stable).encode(),
                               digest_size=6).hexdigest()

    @classmethod
    def from_conf(cls, conf: JobConfig) -> Optional["StreamCheckpointer"]:
        directory = conf.get("stream.checkpoint.dir")
        if not directory or not conf.get("stream.chunk.rows"):
            return None
        # multi-process: snapshots are PROCESS-SCOPED — each process owns a
        # deterministic slice of the chunk stream (idx % nprocs == pid), so
        # its cursor + partial totals are private state.  The subdirectory
        # name pins the topology: a relaunch with a different nprocs finds
        # no snapshot and restarts cleanly from zero (correct, never
        # double-counted) instead of resuming a cursor whose ownership
        # pattern no longer matches.
        pid, nprocs = Job.process_grid()
        if nprocs >= 10 ** 3:
            # the proc subdirectory name is 3-digit zero-padded; a wider
            # count would still format (python widens) but break the
            # fixed-width == lexicographic contract the sweep regex and
            # any sorted listing rely on (graftlint GL003)
            raise ConfigError(
                f"{nprocs} processes exceeds the proc-NNN-of-NNN 3-digit "
                f"checkpoint-subdirectory width")
        parent = None
        if nprocs > 1:
            parent = directory
            directory = os.path.join(directory,
                                     f"proc-{pid:03d}-of-{nprocs:03d}")
        ckpt = cls(directory,
                   conf.get_int("stream.checkpoint.interval.chunks", 8),
                   conf.get_bool("stream.resume", False),
                   conf.get_int("stream.fault.crash.after.chunks", 0),
                   parent_dir=parent,
                   run_id=cls.run_id_from_conf(conf),
                   defer_errors=nprocs > 1,
                   reshard=conf.get_bool("shard.reshard.on.restore", False))
        if nprocs > 1:
            ckpt._handshake_errors(pid)
        return ckpt

    def _handshake_errors(self, pid: int) -> None:
        """Distributed construction/restore handshake (round-8 graftlint
        GL001 audit): every process enters exactly ONE collective carrying
        its construction error (or nothing), so a tag conflict or corrupt
        snapshot on ANY process raises on ALL of them — instead of one
        process dying early and stranding its peers in the end-of-stream
        merge.  The same error-through-the-collective pattern as the LR
        resume broadcast (jobs/regress.py::_broadcast_resume)."""
        from avenir_tpu.parallel.mesh import all_process_sum_state

        assert pid < 10 ** 3          # from_conf bounds nprocs (GL003)
        state = {}
        if self.error:
            state[f"ckpt_err_p{pid:03d}"] = np.frombuffer(
                self.error.encode(), np.uint8).copy()
        folded = all_process_sum_state(state)
        errs = sorted(k for k in folded if k.startswith("ckpt_err_p"))
        if errs:
            peers = ", ".join(k[len("ckpt_err_p"):] for k in errs)
            raise ConfigError(
                f"checkpointer construction failed on process(es) {peers}: "
                + folded[errs[0]].tobytes().decode(errors="replace"))

    def chunk_done(self, cursor: dict, last: bool) -> None:
        """Called by the stream after the model has accumulated the chunk
        ``cursor`` describes; snapshots on the interval (never for the
        final chunk — the job completes and finish() deletes the state)."""
        self._consumed += 1
        total_rows = self.base_rows + int(cursor["rows"])
        if not last and self._consumed % self.interval == 0:
            # "run" fingerprints the writing configuration (graftlint
            # GL002): restore rejects a snapshot whose run id differs
            self.mgr.save(int(cursor["chunk"]),
                          {"acc": self.accumulator.state(),
                           "cursor": {"file": cursor["file"],
                                      "offset": int(cursor["offset"]),
                                      "chunk": int(cursor["chunk"])},
                           "rows": total_rows,
                           "run": self.run_id})
            from avenir_tpu.telemetry import spans as tel

            tel.tracer().event("checkpoint.save", dir=self.directory,
                               run=self.run_id, rows=total_rows,
                               chunk=int(cursor["chunk"]))
        if self.crash_after and self._consumed >= self.crash_after:
            raise RuntimeError(
                f"stream.fault.crash.after.chunks={self.crash_after}: "
                f"injected crash after chunk {cursor['chunk']}")

    @staticmethod
    def _read_tag(directory: str) -> Optional[str]:
        try:
            with open(os.path.join(directory, "RUN_TAG")) as fh:
                return fh.read().strip()
        except OSError:
            return None

    def finish(self) -> None:
        """Remove this run's snapshots after a successful run.  Deletes only
        manager-owned ``step_*``/temp entries — never unrelated files a user
        may keep in the same (possibly shared) directory — and the directory
        itself only once it is empty.  In a multi-process run each process
        clears its own ``proc-*`` subdirectory; a successful finish also
        sweeps snapshot subdirectories left by crashed runs OF THE SAME RUN
        ID at other process counts (a stale cursor from an old topology
        restored much later against changed input would silently contribute
        mixed totals).  Subdirectories tagged with a DIFFERENT run id — a
        concurrent job sharing the root — or with no tag at all are left
        intact: destroying a live run's durability is strictly worse than
        leaving a stale directory behind (round-5 advisor finding)."""
        import re

        from avenir_tpu.utils.checkpoint import CheckpointManager

        self._remove_tag(self.directory)
        self.mgr.clear()
        root = self.parent_dir or self.directory
        try:
            names = os.listdir(root)
        except FileNotFoundError:
            return
        for name in names:
            sub = os.path.join(root, name)
            if re.fullmatch(r"proc-\d+-of-\d+", name) and \
                    self.run_id and self._read_tag(sub) == self.run_id:
                self._remove_tag(sub)
                CheckpointManager(sub, keep=2).clear()
        try:
            os.rmdir(root)                   # only succeeds when empty
        except OSError:
            pass

    @staticmethod
    def _remove_tag(directory: str) -> None:
        try:
            os.remove(os.path.join(directory, "RUN_TAG"))
        except OSError:
            pass
