"""Naive-Bayes jobs — BayesianDistribution (train) and BayesianPredictor
(score), driving avenir_tpu.models.naive_bayes through the reference's job
contract (bayesian/BayesianDistribution.java, bayesian/BayesianPredictor.java).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.jobs.base import Job, read_lines, write_output
from avenir_tpu.models import naive_bayes as nb
from avenir_tpu.utils.metrics import Counters


class BayesianDistribution(Job):
    """Train: CSV in → model-file CSV rows out (the reference's model layout,
    BayesianPredictor.java:186-224)."""

    name = "BayesianDistribution"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        if not conf.get_bool("tabular.input", True):
            self._execute_text(conf, input_path, output_path, counters)
            return
        nbayes = nb.NaiveBayes(laplace=conf.get_float("laplace.smoothing", 1.0),
                               mesh=self.auto_mesh(conf))
        # stream.chunk.rows switches to the chunked read+encode stream under
        # the task-retry policy (needs a schema-complete encoder);
        # stream.checkpoint.dir additionally persists (counts, cursor) every
        # N chunks so a killed run resumes with --resume / stream.resume
        ckpt = self.stream_checkpointer(conf)
        # under jax.distributed (N processes), chunks are round-robin
        # assigned, per-process partial counts are merged once at end of
        # stream, and process 0 writes — Hadoop's N-machine execution of
        # this same job (BayesianDistribution.java:82)
        owner, acc, distributed = self.distributed_plan(conf, ckpt)
        enc, data, rows_fn = self.encoded_data_source(conf, input_path, counters,
                                                      mesh=nbayes.mesh,
                                                      checkpointer=ckpt,
                                                      owner=owner)
        merged: dict = {}
        if distributed:
            data = self.distributed_stream(data, acc, rows_fn, merged)
            model = self.distributed_fit(
                lambda d: nbayes.fit(d, accumulator=acc), data, acc, merged)
        else:
            model = nbayes.fit(data, accumulator=acc)
        rows = merged["rows"] if distributed else rows_fn()
        lines = (nb.model_to_lines(model, enc, delim=conf.field_delim)
                 if model is not None else [])
        if self.is_output_writer():
            write_output(output_path, lines)
        if ckpt:
            ckpt.finish()
        counters.set("Records", "Processed", rows)
        counters.set("Model", "Rows", len(lines))

    def _execute_text(self, conf: JobConfig, input_path: str, output_path: str,
                      counters: Counters) -> None:
        """``tabular.input=false``: rows are ``text<delim>classVal``; each
        analyzer token becomes a bag-of-words feature under ordinal 1 —
        multinomial NB counts in the same model-row layout
        (BayesianDistribution.java:125-131,185-196; tokenization flags shared
        with WordCounter)."""
        from avenir_tpu.jobs.base import input_files
        from avenir_tpu.text.analyzer import tokenize

        delim = conf.field_delim_regex
        stop = conf.get_bool("remove.stop.words", True)
        stem = conf.get_bool("stem.words", False)
        vocab: dict = {}
        token_codes: List[int] = []
        token_class: List[int] = []
        class_values: List[str] = []
        cmap: dict = {}
        doc_counts: List[int] = []
        n_rows = 0
        for f in input_files(input_path):
            with open(f) as fh:
                for line in fh:
                    line = line.rstrip("\n")
                    if not line.strip():
                        continue
                    items = line.split(delim)
                    text, cv = items[0], items[1]
                    if cv not in cmap:
                        cmap[cv] = len(class_values)
                        class_values.append(cv)
                        doc_counts.append(0)
                    ci = cmap[cv]
                    doc_counts[ci] += 1
                    n_rows += 1
                    for tok in tokenize(text, stopwords=stop, stem=stem):
                        token_codes.append(vocab.setdefault(tok, len(vocab)))
                        token_class.append(ci)
        # [C, V] class×token co-occurrence: a flat bincount — the one-hot
        # einsum form would materialize an O(tokens × vocab) operand and
        # agg's chunk guard caps it at 2^24 tokens; counting scales to any
        # corpus
        c, v = len(class_values), len(vocab)
        if token_codes:
            flat = (np.asarray(token_class, np.int64) * v
                    + np.asarray(token_codes, np.int64))
            cv_counts = np.bincount(flat, minlength=c * v).reshape(c, v)
        else:
            cv_counts = np.zeros((max(c, 1), 0), np.int64)
        d = conf.field_delim
        lines: List[str] = []
        tokens = list(vocab)
        for ti, tok in enumerate(tokens):
            col = cv_counts[:, ti]
            for ci, cval in enumerate(class_values):
                if col[ci]:
                    lines.append(d.join([cval, "1", tok, str(int(col[ci]))]))
            lines.append(d.join(["", "1", tok, str(int(col.sum()))]))
        for ci, cval in enumerate(class_values):
            lines.append(d.join([cval, "", "", str(doc_counts[ci])]))
        write_output(output_path, lines)
        counters.set("Records", "Processed", n_rows)
        counters.set("Model", "Vocabulary", len(vocab))
        counters.set("Model", "Rows", len(lines))


def _cost_matrix(conf: JobConfig, class_values: List[str]) -> Optional[np.ndarray]:
    """Misclassification costs from the reference's property pair
    (``bp.predict.class`` names, ``bp.predict.class.cost`` values ×100 —
    BayesianPredictor.java:375-391) or a dense ``misclassification.cost``."""
    names = conf.get_list("bp.predict.class")
    costs = conf.get_float_list("bp.predict.class.cost")
    if names and costs:
        # cost of predicting class v when wrong; scale-invariant under argmin
        per_class = dict(zip(names, costs))
        c = len(class_values)
        mat = np.zeros((c, c))
        for pi, pv in enumerate(class_values):
            for ai in range(c):
                if ai != pi:
                    mat[ai, pi] = per_class.get(pv, 1.0)
        return mat
    flat = conf.get_float_list("misclassification.cost")
    if flat:
        c = len(class_values)
        return np.asarray(flat, np.float64).reshape(c, c)
    return None


class BayesianPredictor(Job):
    """Score: CSV in + model file → rows with predicted class appended.

    Honored properties (reference names): ``bayesian.model.file.path``,
    ``prediction.mode`` (validation → confusion-matrix counters),
    ``class.prob.diff.threshold`` (ambiguity flag,
    BayesianPredictor.java:319-326), ``use.cost.based.classifier`` +
    cost properties (:375-391), ``positive.class.value``,
    ``output.feature.prob.only`` (per-record class posterior rows consumed by
    the kNN class-conditional weighting path, :276-286).
    """

    name = "BayesianPredictor"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim
        model_path = conf.get("bayesian.model.file.path")
        if not model_path:
            raise ConfigError("bayesian.model.file.path not set")
        if not conf.get_bool("tabular.input", True):
            self._predict_text(conf, input_path, output_path, counters)
            return
        validate = conf.get("prediction.mode", "prediction") == "validation"
        prob_only = conf.get_bool("output.feature.prob.only")
        if prob_only:                      # no echo: skip line collection
            enc, ds, _rows = self.encode_input(
                conf, input_path, with_labels=validate, need_rows=False)
            in_lines = None
        else:
            enc, ds, in_lines = self.encode_input_with_lines(
                conf, input_path, with_labels=validate)
        model = nb.model_from_lines(read_lines(model_path), enc, delim=delim)

        threshold = conf.get_float("class.prob.diff.threshold")
        if threshold is not None and threshold > 1.0:
            threshold /= 100.0          # reference thresholds are % ints
        cost = (_cost_matrix(conf, model.class_values)
                if conf.get_bool("use.cost.based.classifier") else None)
        result = nb.NaiveBayes().predict(
            model, ds, cost=cost, ambiguity_threshold=threshold,
            validate=validate, pos_class=conf.get("positive.class.value"))

        out: List[str] = []
        if prob_only:
            # (id or row-index, classVal, posterior) rows for the kNN joiner
            ids = ds.ids if ds.ids is not None else np.arange(ds.num_rows)
            for i in range(ds.num_rows):
                for ci, cv in enumerate(model.class_values):
                    out.append(delim.join(
                        [str(ids[i]), cv, f"{result.probs[i, ci]:.6f}"]))
        else:
            amb = result.ambiguous
            for i, line in enumerate(in_lines):
                items = [line, model.class_values[int(result.predicted[i])]]
                if amb is not None and bool(amb[i]):
                    items.append("ambiguous")
                out.append(delim.join(items))
        write_output(output_path, out)
        counters.set("Records", "Processed", ds.num_rows)
        if result.counters is not None:
            counters.merge(result.counters)

    def _predict_text(self, conf: JobConfig, input_path: str, output_path: str,
                      counters: Counters) -> None:
        """``tabular.input=false``: multinomial-NB scoring of ``text[,class]``
        rows against a text-mode model (the reference trains text
        distributions but ships no text predictor — this completes the
        pipeline; validation uses the second column as the actual class)."""
        import math

        from avenir_tpu.jobs.base import input_files
        from avenir_tpu.text.analyzer import tokenize
        from avenir_tpu.utils.metrics import ConfusionMatrix

        delim = conf.field_delim_regex
        stop = conf.get_bool("remove.stop.words", True)
        stem = conf.get_bool("stem.words", False)
        laplace = conf.get_float("laplace.smoothing", 1.0)
        validate = conf.get("prediction.mode", "prediction") == "validation"

        # model rows: (classVal, 1, token, count) posteriors; (classVal,,,n) priors
        token_counts: dict = {}
        class_counts: dict = {}
        for line in read_lines(conf.get("bayesian.model.file.path")):
            items = line.split(delim)
            if len(items) >= 4 and items[0] and items[1] == "1":
                token_counts.setdefault(items[0], {})[items[2]] = float(items[3])
            elif len(items) >= 4 and items[0] and not items[1] and not items[2]:
                class_counts[items[0]] = float(items[3])
        class_values = sorted(class_counts)
        if not class_values:
            raise ValueError("text model has no class-prior rows")
        vocab_size = len({t for d in token_counts.values() for t in d})
        total_docs = sum(class_counts.values())
        class_token_totals = {cv: sum(token_counts.get(cv, {}).values())
                              for cv in class_values}

        d = conf.field_delim
        out: List[str] = []
        cm = ConfusionMatrix(class_values,
                             pos_class=conf.get("positive.class.value")) \
            if validate else None
        n_rows = 0
        unknown_actual = 0
        for f in input_files(input_path):
            with open(f) as fh:
                for line in fh:
                    line = line.rstrip("\n")
                    if not line.strip():
                        continue
                    items = line.split(delim)
                    toks = tokenize(items[0], stopwords=stop, stem=stem)
                    best, best_score = None, -math.inf
                    for cv in class_values:
                        score = math.log(class_counts[cv] / total_docs)
                        denom = class_token_totals[cv] + laplace * max(vocab_size, 1)
                        tc = token_counts.get(cv, {})
                        for t in toks:
                            score += math.log((tc.get(t, 0.0) + laplace) / denom)
                        if score > best_score:
                            best, best_score = cv, score
                    out.append(d.join(items + [best]))
                    n_rows += 1
                    if cm is not None and len(items) > 1:
                        if items[1] in class_values:
                            cm.add(class_values.index(items[1]),
                                   class_values.index(best))
                        else:
                            # actual class absent from the model: count it
                            # instead of aborting the whole run mid-stream
                            unknown_actual += 1
        write_output(output_path, out)
        counters.set("Records", "Processed", n_rows)
        if cm is not None:
            cm.publish(counters)
            if unknown_actual:
                counters.set("Validation", "UnknownActualClass", unknown_actual)
