"""Naive-Bayes jobs — BayesianDistribution (train) and BayesianPredictor
(score), driving avenir_tpu.models.naive_bayes through the reference's job
contract (bayesian/BayesianDistribution.java, bayesian/BayesianPredictor.java).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from avenir_tpu.core.config import JobConfig
from avenir_tpu.jobs.base import Job, read_lines, write_output
from avenir_tpu.models import naive_bayes as nb
from avenir_tpu.utils.metrics import Counters


class BayesianDistribution(Job):
    """Train: CSV in → model-file CSV rows out (the reference's model layout,
    BayesianPredictor.java:186-224)."""

    name = "BayesianDistribution"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        enc, ds, _rows = self.encode_input(conf, input_path)
        model = nb.NaiveBayes(laplace=conf.get_float("laplace.smoothing", 1.0)).fit(ds)
        lines = nb.model_to_lines(model, enc, delim=conf.field_delim)
        write_output(output_path, lines)
        counters.set("Records", "Processed", ds.num_rows)
        counters.set("Model", "Rows", len(lines))


def _cost_matrix(conf: JobConfig, class_values: List[str]) -> Optional[np.ndarray]:
    """Misclassification costs from the reference's property pair
    (``bp.predict.class`` names, ``bp.predict.class.cost`` values ×100 —
    BayesianPredictor.java:375-391) or a dense ``misclassification.cost``."""
    names = conf.get_list("bp.predict.class")
    costs = conf.get_float_list("bp.predict.class.cost")
    if names and costs:
        # cost of predicting class v when wrong; scale-invariant under argmin
        per_class = dict(zip(names, costs))
        c = len(class_values)
        mat = np.zeros((c, c))
        for pi, pv in enumerate(class_values):
            for ai in range(c):
                if ai != pi:
                    mat[ai, pi] = per_class.get(pv, 1.0)
        return mat
    flat = conf.get_float_list("misclassification.cost")
    if flat:
        c = len(class_values)
        return np.asarray(flat, np.float64).reshape(c, c)
    return None


class BayesianPredictor(Job):
    """Score: CSV in + model file → rows with predicted class appended.

    Honored properties (reference names): ``bayesian.model.file.path``,
    ``prediction.mode`` (validation → confusion-matrix counters),
    ``class.prob.diff.threshold`` (ambiguity flag,
    BayesianPredictor.java:319-326), ``use.cost.based.classifier`` +
    cost properties (:375-391), ``positive.class.value``,
    ``output.feature.prob.only`` (per-record class posterior rows consumed by
    the kNN class-conditional weighting path, :276-286).
    """

    name = "BayesianPredictor"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim
        model_path = conf.get("bayesian.model.file.path")
        if not model_path:
            raise ValueError("bayesian.model.file.path not set")
        validate = conf.get("prediction.mode", "prediction") == "validation"
        enc, ds, rows = self.encode_input(conf, input_path, with_labels=validate)
        model = nb.model_from_lines(read_lines(model_path), enc, delim=delim)

        threshold = conf.get_float("class.prob.diff.threshold")
        if threshold is not None and threshold > 1.0:
            threshold /= 100.0          # reference thresholds are % ints
        cost = (_cost_matrix(conf, model.class_values)
                if conf.get_bool("use.cost.based.classifier") else None)
        result = nb.NaiveBayes().predict(
            model, ds, cost=cost, ambiguity_threshold=threshold,
            validate=validate, pos_class=conf.get("positive.class.value"))

        out: List[str] = []
        if conf.get_bool("output.feature.prob.only"):
            # (id or row-index, classVal, posterior) rows for the kNN joiner
            ids = ds.ids if ds.ids is not None else np.arange(ds.num_rows)
            for i in range(ds.num_rows):
                for ci, cv in enumerate(model.class_values):
                    out.append(delim.join(
                        [str(ids[i]), cv, f"{result.probs[i, ci]:.6f}"]))
        else:
            amb = result.ambiguous
            for i, row in enumerate(rows):
                items = list(row) + [model.class_values[int(result.predicted[i])]]
                if amb is not None and bool(amb[i]):
                    items.append("ambiguous")
                out.append(delim.join(str(v) for v in items))
        write_output(output_path, out)
        counters.set("Records", "Processed", ds.num_rows)
        if result.counters is not None:
            counters.merge(result.counters)
