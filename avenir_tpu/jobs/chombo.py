"""Subsumed chombo MR jobs — the external sibling-library surface that the
reference's runbooks invoke directly between avenir jobs (SURVEY.md §2.11).

The reference's pipelines are not closed under avenir's own Tool classes:
the price-optimization bandit loop calls ``org.chombo.mr.RunningAggregator``
to fold each round's reward measurements into the running
(group, item, count, sum, avg) state (resource/price_optimize_tutorial.txt:
44-78, config keys ``incremental.file.prefix`` / ``quantity.attr`` at :88-90),
and the email-marketing Markov runbook calls ``org.chombo.mr.Projection`` to
turn transaction rows into per-customer field sequences
(resource/tutorial_opt_email_marketing.txt:19-42). The rebuild keeps both
addressable by their chombo class names so those runbooks translate
verb-for-verb.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Tuple

from avenir_tpu.core.config import JobConfig
from avenir_tpu.core.csv_io import read_csv
from avenir_tpu.jobs.base import Job, input_files, write_output
from avenir_tpu.utils.metrics import Counters


def _fmt(x: float, precision: int = 6) -> str:
    """Compact numeric formatting: ints stay ints, floats keep ``precision``
    sig figs; non-finite values print as-is (nan/inf/-inf)."""
    if math.isfinite(x) and x == int(x):
        return str(int(x))
    return f"{x:.{precision}g}"


def _fmt_full(x: float) -> str:
    """Full-precision formatting for accumulated moments: 6 sig figs would
    throw away exactly the digits the f64 accumulation preserves (e.g. a
    mean of 1e7 + 0.0118)."""
    return _fmt(x, precision=15)


class RunningAggregator(Job):
    """org.chombo.mr.RunningAggregator — merge incremental measurement files
    into running per-(group, item) aggregates.

    Input dir layout (the tutorial's contract): the current aggregate rows
    ``group,item,count,sum,avg`` plus incremental files whose basename starts
    with ``incremental.file.prefix`` (default ``inc``) carrying one new
    measurement per row at column ``quantity.attr``. Output rows are the
    updated ``group,item,count,sum,avg`` — which feed the next bandit round
    with ``count.ordinal=2`` / ``reward.ordinal=4``
    (resource/price_optimize_tutorial.txt:70-90).
    """

    name = "RunningAggregator"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        prefix = conf.get("incremental.file.prefix", "inc")
        qattr = conf.get_int("quantity.attr", 2)

        agg: Dict[Tuple[str, str], List[float]] = {}   # insertion-ordered
        n_inc = 0
        for f in input_files(input_path):
            incremental = os.path.basename(f).startswith(prefix)
            for r in read_csv(f, delim=delim):
                cell = agg.setdefault((str(r[0]), str(r[1])), [0.0, 0.0])
                if incremental:
                    cell[0] += 1.0
                    cell[1] += float(r[qattr])
                    n_inc += 1
                else:
                    cell[0] += float(r[2])
                    cell[1] += float(r[3])

        d = conf.field_delim
        lines = []
        for (g, item), (cnt, tot) in agg.items():
            avg = tot / cnt if cnt > 0 else 0.0
            lines.append(d.join([g, item, _fmt(cnt), _fmt(tot), _fmt(avg)]))
        write_output(output_path, lines)
        counters.set("Aggregate", "Keys", len(agg))
        counters.set("Aggregate", "IncrementalRows", n_inc)


class Projection(Job):
    """org.chombo.mr.Projection (group-by mode) — group rows by a key field,
    order within the group, and emit the projected fields flattened:
    ``key,fA(r1),fB(r1),fA(r2),fB(r2),...``.

    The email-marketing runbook projects (date, amount) per customer ordered
    by date; its downstream state encoder (resource/xaction_state.rb:8-50)
    consumes exactly that layout. Config: ``projection.key.field`` (default
    0), ``projection.field.ordinals`` (comma list; default all non-key
    columns), ``projection.sort.field`` (optional ordinal; lexicographic, so
    ISO dates order correctly).
    """

    name = "Projection"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        key_ord = conf.get_int("projection.key.field", 0)
        field_ords = conf.get_int_list("projection.field.ordinals", None)
        sort_ord = conf.get_int("projection.sort.field")

        groups: Dict[str, List[Tuple[str, List[str]]]] = {}   # insertion-ordered
        n_rows = 0
        for f in input_files(input_path):
            rows = read_csv(f, delim=delim)
            if not rows.size:
                continue
            ords = field_ords if field_ords is not None else [
                i for i in range(rows.shape[1]) if i != key_ord]
            for r in rows:
                row = [str(v) for v in r]
                sort_key = row[sort_ord] if sort_ord is not None else ""
                groups.setdefault(row[key_ord], []).append(
                    (sort_key, [row[i] for i in ords]))
                n_rows += 1

        d = conf.field_delim
        lines = []
        for key, grp in groups.items():
            if sort_ord is not None:
                grp = sorted(grp, key=lambda kv: kv[0])
            flat: List[str] = [key]
            for _, vals in grp:
                flat.extend(vals)
            lines.append(d.join(flat))
        write_output(output_path, lines)
        counters.set("Projection", "Groups", len(groups))
        counters.set("Projection", "Rows", n_rows)


class NumericalAttrStats(Job):
    """org.chombo.mr.NumericalAttrStats — per-(attr [, conditioning value])
    count / sum / sumSq / mean / variance / stdDev / min / max over numeric
    columns.

    The reference reuses this chombo job's mapper+combiner as the first
    stage of FisherDiscriminant (discriminant/FisherDiscriminant.java:56-58)
    and runbooks call it standalone for data profiling. Numeric attrs come
    from ``attr.list`` or default to every numeric schema feature; an
    optional ``cond.attr.ord`` (the class ordinal in the Fisher usage)
    partitions the stats. Moment accumulation runs on device via
    ops/agg.class_moments — exactly the per-class (count, Σx, Σx²) shuffle
    the reference's combiner performs map-side.
    """

    name = "NumericalAttrStats"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        import numpy as np

        from avenir_tpu.jobs.base import read_input
        from avenir_tpu.ops import agg

        if conf.get("stream.chunk.rows"):
            self._execute_streaming(conf, input_path, output_path, counters)
            return
        delim = conf.field_delim_regex
        rows = read_input(input_path, delim=delim)
        attr_ords = conf.get_int_list("attr.list", None)
        if attr_ords is None:
            try:
                schema = self.load_schema(conf)
                attr_ords = [f.ordinal for f in schema.feature_fields
                             if f.is_numeric]
            except ValueError:
                attr_ords = list(range(rows.shape[1] if rows.size else 0))
        cond_ord = conf.get_int("cond.attr.ord")

        if not rows.size or not attr_ords:
            write_output(output_path, [])
            return
        vals64 = rows[:, attr_ords].astype(np.float64)
        if cond_ord is not None:
            cond_vals = [str(v) for v in rows[:, cond_ord]]
            uniq = sorted(set(cond_vals))
            cmap = {v: i for i, v in enumerate(uniq)}
            labels = np.asarray([cmap[v] for v in cond_vals], np.int32)
        else:
            uniq = [""]
            labels = np.zeros(len(rows), np.int32)
        # Shift each value by its f64 per-(group, column) mean before the f32
        # device pass: the E[x²]−E[x]² form on raw f32 sums cancels
        # catastrophically when |mean| >> std (the reference chombo job
        # accumulates in double). The shift must be per GROUP, not global —
        # with conditioned groups whose means are far apart, a global shift
        # still leaves each group's values large in f32. Raw sum/sumSq lines
        # are reconstructed in f64 below.
        # The shift is the mean of the FINITE values only: an inf row must
        # stay inf after shifting (inf - inf would turn it into nan and
        # change what the output prints).
        shift = np.zeros((len(uniq), len(attr_ords)))
        for ci in range(len(uniq)):
            sel = vals64[labels == ci]
            fin = np.isfinite(sel)
            n_fin = fin.sum(axis=0)
            shift[ci] = np.where(
                n_fin > 0,
                np.where(fin, sel, 0.0).sum(axis=0) / np.maximum(n_fin, 1),
                0.0)
        vals = (vals64 - shift[labels]).astype(np.float32)
        from avenir_tpu.parallel.mesh import maybe_shard_batch
        vals_b, labels_b = maybe_shard_batch(self.auto_mesh(conf), vals, labels)
        cnt, s1, s2 = (np.asarray(a) for a in agg.class_moments(
            vals_b, labels_b, len(uniq)))

        d = conf.field_delim
        lines: List[str] = []
        cnt = cnt.astype(np.float64)
        s1 = s1.astype(np.float64)
        s2 = s2.astype(np.float64)
        for ai, aord in enumerate(attr_ords):
            col = vals64[:, ai]
            for ci, cval in enumerate(uniq):
                n = cnt[ci]
                if not n:
                    continue
                m = float(shift[ci, ai])
                # shifted-space mean/var (stable), raw sum/sumSq rebuilt in f64
                mean_s = s1[ci, ai] / n
                var = max(s2[ci, ai] / n - mean_s * mean_s, 0.0)
                raw_sum = s1[ci, ai] + n * m
                raw_sumsq = s2[ci, ai] + 2.0 * m * s1[ci, ai] + n * m * m
                sub = col[labels == ci]
                fields = [str(aord)] + ([cval] if cond_ord is not None else [])
                fields += [_fmt(float(n)), _fmt_full(float(raw_sum)),
                           _fmt_full(float(raw_sumsq)),
                           _fmt_full(float(mean_s + m)),
                           _fmt_full(float(var)),
                           _fmt_full(float(np.sqrt(var))),
                           _fmt_full(float(sub.min())),
                           _fmt_full(float(sub.max()))]
                lines.append(d.join(fields))
        write_output(output_path, lines)
        counters.set("Records", "Processed", len(rows))

    # -- streaming / multi-process path --------------------------------------
    def _execute_streaming(self, conf: JobConfig, input_path: str,
                           output_path: str, counters: Counters) -> None:
        """``stream.chunk.rows`` path: chunked raw-line stream (owner-
        assigned under jax.distributed — the reference ran this chombo Tool
        across N machines like every MR job), one moment snapshot PER
        (chunk, group) merged at end of stream, finalized in global chunk
        order.

        Byte-identical for any process count BY CONSTRUCTION: each chunk's
        snapshot is computed identically by whichever process owns it
        (shift = the chunk's own per-group finite mean keeps the f32 device
        moments stable), snapshots ride unique keys through the union merge
        (never summed), and finalization translates every snapshot to the
        group's lowest-chunk anchor shift and folds in ascending chunk
        index — the f64 addition sequence does not depend on nprocs.

        State-growth contract (round-5 advisor finding): the per-(chunk,
        group) snapshots are merge keys, so host state — and, under
        ``jax.distributed``, the single end-of-stream allgather payload —
        grows as O(chunks × groups) × 6·A·8 bytes.  Small
        ``stream.chunk.rows`` against a huge input, or a high-cardinality
        ``cond.attr.ord``, can push that into gigabytes (and toward the
        2^31-byte packed-gather limit of ``all_process_sum_state``);
        ``stream.stats.max.state.mb`` (default 1024) bounds it LOUDLY —
        raise the chunk size (fewer snapshots), drop the conditioning
        column's cardinality, or lift the cap explicitly.  Chunk keys are
        zero-padded to 12 digits so the ascending-key finalize fold stays
        ordered; the index is asserted below the format width (the old
        8-digit format silently mis-ordered past 10^8 chunks)."""
        import numpy as np

        from avenir_tpu.core.config import ConfigError
        from avenir_tpu.ops import agg
        from avenir_tpu.parallel.mesh import maybe_shard_batch

        if conf.get("stream.checkpoint.dir"):
            raise ConfigError(
                "stream.checkpoint.dir is not supported on the "
                "NumericalAttrStats streaming path (per-chunk snapshots are "
                "merge keys, not a resumable cursor) — configuring it must "
                "fail loudly rather than silently run without durability")
        delim = conf.field_delim_regex
        attr_ords = conf.get_int_list("attr.list", None)
        if attr_ords is None:
            try:
                schema = self.load_schema(conf)
                attr_ords = [f.ordinal for f in schema.feature_fields
                             if f.is_numeric]
            except ValueError:
                raise ConfigError(
                    "streaming NumericalAttrStats needs attr.list or "
                    "feature.schema.file.path (column count is unknown "
                    "before the first chunk)")
        cond_ord = conf.get_int("cond.attr.ord")
        owner, _acc, distributed = self.distributed_plan(conf, None)
        mesh = self.auto_mesh(conf)
        a = len(attr_ords)
        max_state_bytes = conf.get_int("stream.stats.max.state.mb", 1024) << 20
        state_bytes = 0
        overflow = None            # guard tripped: raise AFTER the collective
        state: dict = {}
        nrows = 0
        for idx, lines in self.iter_line_chunks_retrying(
                conf, input_path, counters, owner=owner, emit_index=True):
            if idx >= 10 ** 12:
                raise ConfigError(
                    f"chunk index {idx} exceeds the 12-digit snapshot-key "
                    f"width; raise stream.chunk.rows (keys past the width "
                    f"would silently mis-order the finalize fold)")
            rows = np.array([ln.split(delim) for ln in lines], dtype=object)
            nrows += len(rows)
            vals64 = rows[:, attr_ords].astype(np.float64)
            if cond_ord is not None:
                cond_vals = [str(v) for v in rows[:, cond_ord]]
                uniq = sorted(set(cond_vals))
                cmap = {v: i for i, v in enumerate(uniq)}
                labels = np.asarray([cmap[v] for v in cond_vals], np.int32)
            else:
                uniq = [""]
                labels = np.zeros(len(rows), np.int32)
            # per-(chunk, group) finite-mean shift — same stabilization as
            # the whole-input path, anchored per chunk (translated to a
            # global anchor at finalize)
            shift = np.zeros((len(uniq), a))
            for ci in range(len(uniq)):
                sel = vals64[labels == ci]
                fin = np.isfinite(sel)
                n_fin = fin.sum(axis=0)
                shift[ci] = np.where(
                    n_fin > 0,
                    np.where(fin, sel, 0.0).sum(axis=0) / np.maximum(n_fin, 1),
                    0.0)
            vals = (vals64 - shift[labels]).astype(np.float32)
            vals_b, labels_b = maybe_shard_batch(mesh, vals, labels)
            cnt, s1, s2 = (np.asarray(t, np.float64) for t in
                           agg.class_moments(vals_b, labels_b, len(uniq)))
            for ci, g in enumerate(uniq):
                if not cnt[ci]:
                    continue
                sel = vals64[labels == ci]
                snap = np.stack([
                    np.full(a, cnt[ci]), s1[ci], s2[ci], shift[ci],
                    sel.min(axis=0), sel.max(axis=0)])
                state[f"c{idx:012d}:{g}"] = snap
                state_bytes += snap.nbytes
                if state_bytes > max_state_bytes:
                    overflow = (
                        f"NumericalAttrStats snapshot state exceeds "
                        f"stream.stats.max.state.mb="
                        f"{max_state_bytes >> 20} after {len(state)} "
                        f"(chunk, group) snapshots — state grows as "
                        f"O(chunks × groups); raise stream.chunk.rows, "
                        f"reduce cond.attr.ord cardinality, or lift the cap")
                    break
            if overflow:
                break
        merged_rows = nrows
        if distributed:
            # the guard must not strand peers: every process enters the
            # end-of-stream collective exactly once, an overflow flag rides
            # the same packed gather, and ALL processes raise together
            # (same error-through-the-collective pattern as the LR resume
            # broadcast in jobs/regress.py)
            from avenir_tpu.parallel.mesh import all_process_sum_state
            state["__rows__"] = np.array([nrows], np.int64)
            state["__overflow__"] = np.array([1 if overflow else 0], np.int64)
            state = all_process_sum_state(state)
            merged_rows = int(state.pop("__rows__")[0])
            if int(state.pop("__overflow__")[0]):
                raise ConfigError(overflow or (
                    "a peer process exceeded stream.stats.max.state.mb "
                    "(O(chunks × groups) snapshot growth); raise "
                    "stream.chunk.rows, reduce cond.attr.ord cardinality, "
                    "or lift the cap"))
        if overflow:
            raise ConfigError(overflow)

        # finalize: group → snapshots in ascending chunk order (keys are
        # zero-padded to a fixed 12-digit width, so lexicographic == numeric)
        by_group: dict = {}
        for k in sorted(state):                    # ascending chunk index
            by_group.setdefault(k.split(":", 1)[1], []).append(state[k])
        d = conf.field_delim
        out: List[str] = []
        totals = {}
        for g, snaps in by_group.items():
            anchor = snaps[0][3]                             # [A] m*
            n_tot = np.zeros(a)
            s1_tot = np.zeros(a)
            s2_tot = np.zeros(a)
            mn = np.full(a, np.inf)
            mx = np.full(a, -np.inf)
            for snap in snaps:
                n_c, s1_c, s2_c, m_c, mn_c, mx_c = snap
                dm = m_c - anchor
                n_tot = n_tot + n_c
                s1_tot = s1_tot + (s1_c + n_c * dm)
                s2_tot = s2_tot + (s2_c + 2.0 * dm * s1_c + n_c * dm * dm)
                mn = np.minimum(mn, mn_c)
                mx = np.maximum(mx, mx_c)
            totals[g] = (anchor, n_tot, s1_tot, s2_tot, mn, mx)
        for ai, aord in enumerate(attr_ords):
            for g in sorted(totals):
                anchor, n_tot, s1_tot, s2_tot, mn, mx = totals[g]
                n = n_tot[ai]
                if not n:
                    continue
                m = float(anchor[ai])
                mean_s = s1_tot[ai] / n
                var = max(s2_tot[ai] / n - mean_s * mean_s, 0.0)
                raw_sum = s1_tot[ai] + n * m
                raw_sumsq = s2_tot[ai] + 2.0 * m * s1_tot[ai] + n * m * m
                fields = [str(aord)] + ([g] if cond_ord is not None else [])
                fields += [_fmt(float(n)), _fmt_full(float(raw_sum)),
                           _fmt_full(float(raw_sumsq)),
                           _fmt_full(float(mean_s + m)),
                           _fmt_full(float(var)),
                           _fmt_full(float(np.sqrt(var))),
                           _fmt_full(float(mn[ai])),
                           _fmt_full(float(mx[ai]))]
                out.append(d.join(fields))
        if self.is_output_writer():
            write_output(output_path, out)
        counters.set("Records", "Processed", merged_rows)
