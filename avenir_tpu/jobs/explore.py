"""Exploration jobs — mutual information, categorical correlation, and class
samplers (explore/MutualInformation.java, CramerCorrelation.java,
HeterogeneityReductionCorrelation.java, BaggingSampler.java,
UnderSamplingBalancer.java) on the in-process TPU engine.
"""

from __future__ import annotations

from typing import List

import jax
import numpy as np

from avenir_tpu.core.config import JobConfig
from avenir_tpu.jobs.base import Job, write_output
from avenir_tpu.models import correlation as corr
from avenir_tpu.models import mutual_info as mi
from avenir_tpu.models import samplers
from avenir_tpu.utils.metrics import Counters


def mi_output_lines(conf: JobConfig, result, names: List[str]) -> List[str]:
    """The MutualInformation job's output lines from a finished result —
    the ONE assembly used by both the standalone job and the SharedScan
    fused path (``pipeline/scan.py``), so the two can never drift."""
    delim = conf.field_delim
    lines: List[str] = []
    if conf.get_bool("output.mutual.info", True):
        lines.extend(result.to_lines(delim=delim))
    for algo in conf.get_list("mutual.info.score.algorithms", ["mim"]):
        kwargs = {}
        if algo == "mifs":
            kwargs["redundancy_factor"] = conf.get_float(
                "mutual.info.redundancy.factor", 1.0)
        ranked = mi.score_features(result, algo, **kwargs)
        lines.append(f"featureScore:{algo}")
        lines.extend(
            delim.join([names[f], f"{score:.6f}"]) for f, score in ranked)
    return lines


def correlation_plan(conf: JobConfig, schema, enc):
    """(src_idx, dst_idx, against_class, names) for a correlation job's
    attribute selection — shared by the standalone jobs and the SharedScan
    fused path.  Source/dest attribute lists arrive as schema ordinals
    (CramerCorrelation.java:95-100) and are mapped to binned indices; a
    dest list of exactly the class ordinal selects against-class mode."""
    binned_ords = [f.ordinal for f in enc.binned_fields]
    names = [schema.field_by_ordinal(o).name for o in binned_ords]
    ord_to_idx = {o: i for i, o in enumerate(binned_ords)}
    src = conf.get_int_list("source.attributes")
    dst = conf.get_int_list("dest.attributes")
    class_ord = schema.class_field.ordinal if schema.class_field else None
    against_class = dst is not None and class_ord is not None and dst == [class_ord]
    src_idx = [ord_to_idx[o] for o in src] if src else None
    dst_idx = (None if against_class or dst is None
               else [ord_to_idx[o] for o in dst])
    return src_idx, dst_idx, against_class, names


class MutualInformation(Job):
    """One-pass distributions + MI + feature-selection scores.

    Output sections mirror the reference reducer's cleanup
    (MutualInformation.java:462-471): all distributions, mutual-information
    values, then one ranked feature subset per algorithm in
    ``mutual.info.score.algorithms`` (mim/mifs/jmi/disr/mrmr;
    MutualInformationScore.java).
    """

    name = "MutualInformation"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        schema = self.load_schema(conf)
        mesh = self.auto_mesh(conf)
        ckpt = self.stream_checkpointer(conf)
        # multi-process execution: see BayesianDistribution.execute
        owner, acc, distributed = self.distributed_plan(conf, ckpt)
        enc, data, rows_fn = self.encoded_data_source(conf, input_path, counters,
                                                      mesh=mesh,
                                                      checkpointer=ckpt,
                                                      owner=owner)
        names = [schema.field_by_ordinal(f.ordinal).name
                 for f in enc.binned_fields]
        merged: dict = {}
        if distributed:
            data = self.distributed_stream(data, acc, rows_fn, merged)
            result = self.distributed_fit(
                lambda d: mi.MutualInformation(mesh=mesh).fit(
                    d, feature_names=names, accumulator=acc),
                data, acc, merged)
            if result is None:             # zero-chunk non-writer process
                counters.set("Records", "Processed", merged["rows"])
                return
        else:
            result = mi.MutualInformation(mesh=mesh).fit(
                data, feature_names=names, accumulator=acc)
        lines = mi_output_lines(conf, result, names)
        rows = merged["rows"] if distributed else rows_fn()
        if self.is_output_writer():
            write_output(output_path, lines)
        if ckpt:
            ckpt.finish()
        counters.set("Records", "Processed", rows)


class _CorrelationJob(Job):
    algorithm = "cramerIndex"

    def _algorithm(self, conf: JobConfig) -> str:
        return self.algorithm

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim
        schema = self.load_schema(conf)
        mesh = self.auto_mesh(conf)
        ckpt = self.stream_checkpointer(conf)
        # multi-process execution: see BayesianDistribution.execute — the
        # reference ran this same Tool across N machines
        # (CramerCorrelation.java:83); contingency counts are exact
        # integers, so the end-of-stream merge is order-free
        owner, acc, distributed = self.distributed_plan(conf, ckpt)
        enc, data, rows_fn = self.encoded_data_source(conf, input_path, counters,
                                                      mesh=mesh,
                                                      checkpointer=ckpt,
                                                      owner=owner)
        src_idx, dst_idx, against_class, names = correlation_plan(conf, schema, enc)
        job = corr.CategoricalCorrelation(algorithm=self._algorithm(conf),
                                          mesh=mesh)
        fit = lambda d: job.fit(
            d,
            src=src_idx,
            dst=dst_idx,
            against_class=against_class,
            feature_names=names,
            accumulator=acc,
        )
        merged: dict = {}
        if distributed:
            data = self.distributed_stream(data, acc, rows_fn, merged)
            result = self.distributed_fit(fit, data, acc, merged)
        else:
            result = fit(data)
        rows = merged["rows"] if distributed else rows_fn()
        if result is not None and self.is_output_writer():
            write_output(output_path, result.to_lines(delim=delim))
        if ckpt:
            ckpt.finish()
        counters.set("Records", "Processed", rows)


class CramerCorrelation(_CorrelationJob):
    name = "CramerCorrelation"
    algorithm = "cramerIndex"


class HeterogeneityReductionCorrelation(_CorrelationJob):
    name = "HeterogeneityReductionCorrelation"

    def _algorithm(self, conf: JobConfig) -> str:
        # reference values: concentration | uncertainty
        # (HeterogeneityReductionCorrelation.java:70-84)
        algo = conf.get("heterogeneity.algorithm", "concentration")
        return {"concentration": "concentrationCoeff",
                "uncertainty": "uncertaintyCoeff"}.get(algo, algo)


class BaggingSampler(Job):
    """Bootstrap sample with replacement (BaggingSampler.java:100-122) —
    row-level resampling of the raw CSV, batch by batch."""

    name = "BaggingSampler"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        # pure row-level resampling: fields are never inspected, so read raw
        # lines (no CSV parse, no schema needed) and emit them verbatim
        from avenir_tpu.jobs.base import read_lines

        lines = read_lines(input_path)
        batch = conf.get_int("batch.size", 10_000)
        key = jax.random.PRNGKey(conf.get_int("seed", 0))
        out: List[str] = []
        for s in range(0, len(lines), batch):
            chunk = lines[s:s + batch]
            key, sub = jax.random.split(key)
            idx = np.asarray(samplers.bootstrap_indices(sub, len(chunk)))
            out.extend(chunk[i] for i in idx)
        write_output(output_path, out)
        counters.set("Records", "Processed", len(lines))
        counters.set("Records", "Emitted", len(out))


class UnderSamplingBalancer(Job):
    """Majority-class undersampler (UnderSamplingBalancer.java:92-164): keep
    minority rows, thin majority rows to p = minCount/classCount."""

    name = "UnderSamplingBalancer"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        import jax.numpy as jnp

        from avenir_tpu.jobs.base import read_lines

        # only the class column is inspected: read raw lines and slice the
        # class field per row — feature columns are never parsed, so data
        # the downstream jobs would reject (sentinels in numeric columns,
        # class values outside a declared cardinality) still samples fine,
        # exactly as the reference's mapper behaved
        schema = self.load_schema(conf)
        if schema.class_field is None:
            raise ValueError("undersampling requires a class attribute")
        class_ord = schema.class_field.ordinal
        delim = conf.field_delim_regex
        lines = read_lines(input_path)
        labels_raw = [ln.split(delim)[class_ord] for ln in lines]
        _values, inverse, cts = np.unique(
            np.asarray(labels_raw, dtype=object).astype(str),
            return_inverse=True, return_counts=True)
        key = jax.random.PRNGKey(conf.get_int("seed", 0))
        mask = np.asarray(samplers.undersample_mask(
            key, jnp.asarray(inverse.astype(np.int32)),
            jnp.asarray(cts.astype(np.float32))))
        out = [lines[i] for i in np.nonzero(mask)[0]]
        write_output(output_path, out)
        counters.set("Records", "Processed", len(lines))
        counters.set("Records", "Emitted", len(out))
