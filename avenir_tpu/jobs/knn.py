"""kNN jobs — the reference's 4-stage pipeline collapsed onto the in-process
engine.

The reference pipeline (resource/knn.sh:16-137): 1) sifarish
SameTypeSimilarity computes all-pairs distances (external); 2-3) optional
BayesianDistribution + BayesianPredictor produce per-record class posteriors;
4) FeatureCondProbJoiner attaches them to neighbor rows; 5) NearestNeighbor
classifies/regresses over the top-k neighbors. Here the distance matrix is an
in-tree MXU matmul (models/knn.py), so:

- :class:`SameTypeSimilarity` emits the (testID, trainID, scaled distance)
  pair file for pipeline compatibility;
- :class:`FeatureCondProbJoiner` performs the same join in memory;
- :class:`NearestNeighbor` runs end-to-end from raw CSVs (train via
  ``training.data.path``), honoring the reference's kernel / weighting /
  arbitration properties — no precomputed distance file needed.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.jobs.base import Job, read_lines, write_output
from avenir_tpu.models import knn as mknn
from avenir_tpu.models import naive_bayes as nb
from avenir_tpu.utils.metrics import Counters


def _train_model(conf: JobConfig, enc=None, need_rows: bool = True):
    train_path = conf.get("training.data.path")
    if not train_path:
        raise ConfigError("training.data.path not set")
    return Job.encode_input(conf, train_path, encoder=enc,
                            need_rows=need_rows)


class SameTypeSimilarity(Job):
    """All-pairs top-k distance job (the external sifarish step the reference
    shells out to, resource/knn.sh:47-60) — (testID, trainID, intDistance)
    rows from a tiled device matmul."""

    name = "SameTypeSimilarity"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim
        enc, train_ds, _train_rows = _train_model(conf, need_rows=False)
        _enc, test_ds, _test_rows = self.encode_input(
            conf, input_path, with_labels=False, encoder=enc,
            need_rows=False)
        model = mknn.fit_knn(train_ds)
        k = conf.get_int("top.match.count", 10)
        ids = (test_ds.ids if test_ds.ids is not None
               else [str(i) for i in range(test_ds.num_rows)])
        lines = mknn.pairwise_distance_lines(
            model, test_ds, [str(i) for i in ids], k,
            distance_scale=conf.get_int("distance.scale", 1000), delim=delim,
            ref_ids=train_ds.ids)
        write_output(output_path, lines)
        counters.set("Records", "Test", test_ds.num_rows)
        counters.set("Records", "Train", train_ds.num_rows)


class FeatureCondProbJoiner(Job):
    """Join class-conditional posteriors onto neighbor rows
    (knn/FeatureCondProbJoiner.java:153-178): input = distance-pair file,
    ``feature.prob.file.path`` = BayesianPredictor ``output.feature.prob.only``
    rows (id, classVal, prob); output rows gain the train record's per-class
    probs."""

    name = "FeatureCondProbJoiner"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim
        prob_path = conf.get("feature.prob.file.path")
        if not prob_path:
            raise ConfigError("feature.prob.file.path not set")
        probs: Dict[str, List[str]] = {}
        for ln in read_lines(prob_path):
            rid, cv, p = ln.split(delim)
            probs.setdefault(rid, []).extend([cv, p])
        out = []
        for ln in read_lines(input_path):
            parts = ln.split(delim)
            out.append(delim.join(parts + probs.get(parts[1], [])))
        write_output(output_path, out)
        counters.set("Records", "Joined", len(out))


class NearestNeighbor(Job):
    """Classification/regression over the k nearest neighbors, end-to-end.

    Honored properties (knn/NearestNeighbor.java): ``top.match.count``,
    ``kernel.function`` (none|linearMultiplicative|linearAdditive|gaussian),
    ``kernel.param``, ``class.condition.weighted`` (+ its misspelled twin
    ``class.condtion.weighted``, which the reference also reads),
    ``inverse.distance.weighted``, ``decision.threshold`` +
    ``positive.class.value``, ``use.cost.based.classifier`` + cost props,
    ``validation.mode``, ``prediction.mode`` = regression with
    ``regression.method`` (average|median|linear).
    """

    name = "NearestNeighbor"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        from avenir_tpu.jobs.bayesian import _cost_matrix
        delim = conf.field_delim
        regression = conf.get("prediction.mode") == "regression"
        validate = conf.get_bool("validation.mode", False)
        enc, train_ds, train_rows = _train_model(conf, need_rows=regression)
        if regression:
            _e, test_ds, test_rows = self.encode_input(
                conf, input_path, with_labels=False, encoder=enc)
            test_lines = None
        else:
            _e, test_ds, test_lines = self.encode_input_with_lines(
                conf, input_path, with_labels=validate, encoder=enc)
            test_rows = None

        class_cond = (conf.get_bool("class.condition.weighted", False)
                      or conf.get_bool("class.condtion.weighted", False))
        class_probs = None
        if class_cond:
            model_path = conf.get("bayesian.model.file.path")
            if not model_path:
                raise ConfigError("class-conditional weighting requires "
                                 "bayesian.model.file.path")
            bayes = nb.model_from_lines(read_lines(model_path), enc, delim=delim)
            class_probs = nb.NaiveBayes().predict(bayes, train_ds).probs

        cost = (_cost_matrix(conf, train_ds.class_values)
                if conf.get_bool("use.cost.based.classifier") else None)
        est = mknn.KNN(
            k=conf.get_int("top.match.count", 10),
            kernel=conf.get("kernel.function", "none"),
            kernel_sigma=conf.get_float("kernel.param", 0.3),
            inverse_distance=conf.get_bool("inverse.distance.weighted", False),
            class_cond_weighting=class_cond,
            decision_threshold=conf.get_float("decision.threshold"),
            pos_class=conf.get("positive.class.value"),
            cost=cost,
            search_mode=conf.get("knn.search.mode", "exact"),
            mesh=self.auto_mesh(conf),
        )
        out: List[str] = []
        if regression:
            target_ord = conf.get_int("regression.target.ordinal")
            if target_ord is None:
                raise ConfigError("regression mode requires regression.target.ordinal")
            values = train_rows[:, target_ord].astype(np.float64)
            model = est.fit(train_ds, values=values)
            method = conf.get("regression.method", "average")
            kwargs = {}
            if method == "linear":
                in_ord = conf.get_int("regression.input.var.ordinal")
                if in_ord is None:
                    raise ConfigError("regression.method=linear requires "
                                     "regression.input.var.ordinal")
                kwargs = dict(
                    input_var=np.asarray([r[in_ord] for r in test_rows], np.float64),
                    ref_input_var=train_rows[:, in_ord].astype(np.float64))
            pred = est.regress(model, test_ds, method=method, **kwargs)
            for row, p in zip(test_rows, pred):
                out.append(delim.join(list(row) + [f"{p:.6f}"]))
        else:
            model = est.fit(train_ds, class_probs=class_probs)
            result = est.predict(model, test_ds, validate=validate)
            for i, line in enumerate(test_lines):
                out.append(delim.join(
                    [line, train_ds.class_values[int(result.predicted[i])]]))
            if result.counters is not None:
                counters.merge(result.counters)
        write_output(output_path, out)
        counters.set("Records", "Processed", test_ds.num_rows)
