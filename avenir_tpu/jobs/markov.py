"""Sequence-model jobs — Markov chain trainer, HMM builder, Viterbi predictor
(markov/MarkovStateTransitionModel.java, HiddenMarkovModelBuilder.java,
ViterbiStatePredictor.java).

Input rows are ``id, token, token, ...`` sequences (the reference's
Projection-extracted sequence files). Sub-token structure (``obs:state``)
follows ``sub.field.delim``.
"""

from __future__ import annotations

from typing import List

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.jobs.base import Job, read_lines, write_output
from avenir_tpu.models import markov as mk
from avenir_tpu.utils.metrics import Counters


def _seq_rows(path: str, delim: str) -> List[List[str]]:
    """Sequence files are naturally ragged (one row per record, variable
    length) — read raw lines, not the rectangular CSV reader."""
    from avenir_tpu.jobs.base import input_files
    rows: List[List[str]] = []
    for f in input_files(path):
        with open(f) as fh:
            for line in fh:
                line = line.rstrip("\n").rstrip("\r")
                if line:
                    rows.append(line.split(delim))
    return rows


def _sequences(path: str, delim: str, skip: int = 1) -> List[List[str]]:
    return [[t for t in row[skip:] if t != ""] for row in _seq_rows(path, delim)]


def _fit_streaming(job: Job, conf, input_path, counters, fit_chunks_fn,
                   delim, skip):
    """Shared streaming/distributed driver for sequence-model jobs: chunked
    line stream (owner-assigned under jax.distributed), end-of-stream
    partial merge, rows counter set to the GLOBAL sequence count on every
    process."""
    if conf.get("stream.checkpoint.dir"):
        from avenir_tpu.core.config import ConfigError
        raise ConfigError(
            "stream.checkpoint.dir is not supported on the sequence-model "
            "streaming path (no cursor snapshots are wired for ragged line "
            "streams yet) — configuring it must fail loudly rather than "
            "silently run without durability; rely on per-chunk retry + "
            "job re-run, or unset the key")
    owner, acc, distributed = job.distributed_plan(conf, None)
    box = {"n": 0}

    def seq_chunks():
        for lines in job.iter_line_chunks_retrying(
                conf, input_path, counters, owner=owner):
            box["n"] += len(lines)
            yield [[t for t in ln.split(delim)[skip:] if t != ""]
                   for ln in lines]

    merged: dict = {}
    data = seq_chunks()
    if distributed:
        from avenir_tpu.ops import agg
        acc = acc if acc is not None else agg.Accumulator()
        data = job.distributed_stream(data, acc, lambda: box["n"], merged)
        model = job.distributed_fit(
            lambda d: fit_chunks_fn(d, acc), data, acc, merged)
    else:
        model = fit_chunks_fn(data, acc)
    counters.set("Records", "Processed",
                 merged["rows"] if distributed else box["n"])
    return model


class MarkovStateTransitionModel(Job):
    """First-order transition matrix with Laplace smoothing; int-scaled rows
    when ``trans.prob.scale`` > 1 (StateTransitionProbability.java:65-95)."""

    name = "MarkovStateTransitionModel"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        skip = conf.get_int("skip.field.count", 1)
        states = conf.get_list("model.states")
        enc = mk.SequenceEncoder(states) if states else None
        scale = conf.get_int("trans.prob.scale", 1)
        chain = mk.MarkovChain(
            mesh=self.auto_mesh(conf),
            laplace=conf.get_float("laplace.smoothing", 1.0),
            scale=scale if scale > 1 else None)
        if conf.get("stream.chunk.rows"):
            # streaming/multi-process path (the reference ran this Tool
            # across N machines — MarkovStateTransitionModel.java:60);
            # transition counts are exact ints, so the end-of-stream merge
            # is order-free. Stable codes need a declared vocabulary.
            if enc is None:
                from avenir_tpu.core.config import ConfigError
                raise ConfigError(
                    "stream.chunk.rows on MarkovStateTransitionModel "
                    "requires model.states (a chunked stream cannot "
                    "discover a stable state vocabulary)")
            model = _fit_streaming(
                self, conf, input_path, counters,
                lambda chunks, acc: chain.fit_chunks(chunks, enc,
                                                     accumulator=acc)[0],
                delim, skip)
        else:
            seqs = _sequences(input_path, delim, skip)
            model, enc = chain.fit(seqs, encoder=enc)
            counters.set("Records", "Processed", len(seqs))
        if model is not None and self.is_output_writer():
            write_output(output_path, model.to_lines(delim=conf.field_delim))



class HiddenMarkovModelBuilder(Job):
    """Supervised HMM estimation. Fully-tagged mode: tokens are
    ``obs<sub>state``; partially-tagged mode (``partially.tagged=true``):
    state names appear inline, surrounding observations attributed by the
    ``window.function`` distance-decay weights
    (HiddenMarkovModelBuilder.java:136-260)."""

    name = "HiddenMarkovModelBuilder"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        sub = conf.get("sub.field.delim", ":")
        skip = conf.get_int("skip.field.count", 1)
        builder = mk.HMMBuilder(mesh=self.auto_mesh(conf), laplace=conf.get_float("laplace.smoothing", 1.0))
        states = conf.get_list("model.states")
        obs_vocab = conf.get_list("model.observations")
        obs_enc = mk.SequenceEncoder(obs_vocab) if obs_vocab else None
        partial = conf.get_bool("partially.tagged", False)
        if partial and not states:
            raise ConfigError("partially.tagged mode requires model.states")
        window = conf.get_float_list("window.function", [1.0, 0.75, 0.5, 0.25])
        if conf.get("stream.chunk.rows"):
            # streaming/multi-process path (HiddenMarkovModelBuilder.java
            # ran across N machines like every Tool); needs declared
            # vocabularies for chunk-order-independent codes
            if not states or obs_enc is None:
                from avenir_tpu.core.config import ConfigError
                raise ConfigError(
                    "stream.chunk.rows on HiddenMarkovModelBuilder requires "
                    "model.states and model.observations (a chunked stream "
                    "cannot discover stable vocabularies)")
            st_enc = mk.SequenceEncoder(states)
            if partial:
                fit = lambda chunks, acc: builder.fit_partially_tagged_chunks(
                    chunks, states, obs_enc, window_function=window,
                    accumulator=acc)
            else:
                fit = lambda chunks, acc: builder.fit_tagged_chunks(
                    (([[tuple(t.split(sub, 1)) for t in seq] for seq in ck])
                     for ck in chunks),
                    st_enc, obs_enc, accumulator=acc)
            model = _fit_streaming(self, conf, input_path, counters, fit,
                                   delim, skip)
        else:
            seqs = _sequences(input_path, delim, skip)
            if partial:
                model = builder.fit_partially_tagged(
                    seqs, states, window_function=window, obs_encoder=obs_enc)
            else:
                tagged = [[tuple(t.split(sub, 1)) for t in seq] for seq in seqs]
                st_enc = mk.SequenceEncoder(states) if states else None
                model = builder.fit_tagged(tagged, state_encoder=st_enc,
                                           obs_encoder=obs_enc)
            counters.set("Records", "Processed", len(seqs))
        if model is not None and self.is_output_writer():
            write_output(output_path, model.to_lines(delim=conf.field_delim))


class ViterbiStatePredictor(Job):
    """Decode rows of (id, obs...) to state paths; ``output.state.only``
    controls plain-path vs ``obs:state`` pair output
    (ViterbiStatePredictor.java:114-142)."""

    name = "ViterbiStatePredictor"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        model_path = conf.get("hmm.model.file.path") or conf.get("model.file.path")
        if not model_path:
            raise ConfigError("hmm.model.file.path not set")
        model = mk.HMMModel.from_lines(read_lines(model_path),
                                       delim=conf.field_delim)
        pair_output = not conf.get_bool("output.state.only", True)
        predictor = mk.ViterbiStatePredictor(model, mesh=self.auto_mesh(conf), pair_output=pair_output,
                                             delim=conf.field_delim)
        skip = conf.get_int("skip.field.count", 1)
        rows = [[conf.field_delim.join(r[:skip])] + list(r[skip:])
                for r in _seq_rows(input_path, delim)]
        write_output(output_path, predictor.predict_lines(rows))
        counters.set("Records", "Processed", len(rows))
