"""Sequence-model jobs — Markov chain trainer, HMM builder, Viterbi predictor
(markov/MarkovStateTransitionModel.java, HiddenMarkovModelBuilder.java,
ViterbiStatePredictor.java).

Input rows are ``id, token, token, ...`` sequences (the reference's
Projection-extracted sequence files). Sub-token structure (``obs:state``)
follows ``sub.field.delim``.
"""

from __future__ import annotations

from typing import List

from avenir_tpu.core.config import JobConfig
from avenir_tpu.jobs.base import Job, read_lines, write_output
from avenir_tpu.models import markov as mk
from avenir_tpu.utils.metrics import Counters


def _seq_rows(path: str, delim: str) -> List[List[str]]:
    """Sequence files are naturally ragged (one row per record, variable
    length) — read raw lines, not the rectangular CSV reader."""
    from avenir_tpu.jobs.base import input_files
    rows: List[List[str]] = []
    for f in input_files(path):
        with open(f) as fh:
            for line in fh:
                line = line.rstrip("\n").rstrip("\r")
                if line:
                    rows.append(line.split(delim))
    return rows


def _sequences(path: str, delim: str, skip: int = 1) -> List[List[str]]:
    return [[t for t in row[skip:] if t != ""] for row in _seq_rows(path, delim)]


class MarkovStateTransitionModel(Job):
    """First-order transition matrix with Laplace smoothing; int-scaled rows
    when ``trans.prob.scale`` > 1 (StateTransitionProbability.java:65-95)."""

    name = "MarkovStateTransitionModel"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        skip = conf.get_int("skip.field.count", 1)
        seqs = _sequences(input_path, delim, skip)
        states = conf.get_list("model.states")
        enc = mk.SequenceEncoder(states) if states else None
        scale = conf.get_int("trans.prob.scale", 1)
        model, enc = mk.MarkovChain(
            mesh=self.auto_mesh(conf),
            laplace=conf.get_float("laplace.smoothing", 1.0),
            scale=scale if scale > 1 else None).fit(seqs, encoder=enc)
        write_output(output_path, model.to_lines(delim=conf.field_delim))
        counters.set("Records", "Processed", len(seqs))


class HiddenMarkovModelBuilder(Job):
    """Supervised HMM estimation. Fully-tagged mode: tokens are
    ``obs<sub>state``; partially-tagged mode (``partially.tagged=true``):
    state names appear inline, surrounding observations attributed by the
    ``window.function`` distance-decay weights
    (HiddenMarkovModelBuilder.java:136-260)."""

    name = "HiddenMarkovModelBuilder"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        sub = conf.get("sub.field.delim", ":")
        skip = conf.get_int("skip.field.count", 1)
        seqs = _sequences(input_path, delim, skip)
        builder = mk.HMMBuilder(mesh=self.auto_mesh(conf), laplace=conf.get_float("laplace.smoothing", 1.0))
        states = conf.get_list("model.states")
        obs_vocab = conf.get_list("model.observations")
        obs_enc = mk.SequenceEncoder(obs_vocab) if obs_vocab else None
        if conf.get_bool("partially.tagged", False):
            if not states:
                raise ValueError("partially.tagged mode requires model.states")
            window = conf.get_float_list("window.function", [1.0, 0.75, 0.5, 0.25])
            model = builder.fit_partially_tagged(
                seqs, states, window_function=window, obs_encoder=obs_enc)
        else:
            tagged = [[tuple(t.split(sub, 1)) for t in seq] for seq in seqs]
            st_enc = mk.SequenceEncoder(states) if states else None
            model = builder.fit_tagged(tagged, state_encoder=st_enc,
                                       obs_encoder=obs_enc)
        write_output(output_path, model.to_lines(delim=conf.field_delim))
        counters.set("Records", "Processed", len(seqs))


class ViterbiStatePredictor(Job):
    """Decode rows of (id, obs...) to state paths; ``output.state.only``
    controls plain-path vs ``obs:state`` pair output
    (ViterbiStatePredictor.java:114-142)."""

    name = "ViterbiStatePredictor"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        model_path = conf.get("hmm.model.file.path") or conf.get("model.file.path")
        if not model_path:
            raise ValueError("hmm.model.file.path not set")
        model = mk.HMMModel.from_lines(read_lines(model_path),
                                       delim=conf.field_delim)
        pair_output = not conf.get_bool("output.state.only", True)
        predictor = mk.ViterbiStatePredictor(model, mesh=self.auto_mesh(conf), pair_output=pair_output,
                                             delim=conf.field_delim)
        skip = conf.get_int("skip.field.count", 1)
        rows = [[conf.field_delim.join(r[:skip])] + list(r[skip:])
                for r in _seq_rows(input_path, delim)]
        write_output(output_path, predictor.predict_lines(rows))
        counters.set("Records", "Processed", len(rows))
