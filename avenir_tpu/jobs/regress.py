"""Regression jobs — iterative logistic regression and the Fisher
discriminant (regress/LogisticRegressionJob.java,
discriminant/FisherDiscriminant.java).
"""

from __future__ import annotations

import os
import numpy as np

from avenir_tpu.core.config import JobConfig
from avenir_tpu.jobs.base import Job, write_output
from avenir_tpu.models import fisher as mfisher
from avenir_tpu.models import logistic as mlr
from avenir_tpu.utils.locking import FileLock, atomic_write
from avenir_tpu.utils.metrics import Counters


class LogisticRegressionJob(Job):
    """Batch-gradient LR to convergence, with the reference's coefficient
    history file as the checkpoint/resume artifact
    (LogisticRegressionJob.java:238-255,279-289). The driver do/while loop and
    the per-iteration MR job collapse into one compiled gradient loop, and —
    the documented fix — an actual learning rate is applied.

    Properties: ``coeff.file.path`` (history; resumes if present),
    ``iteration.limit``, ``convergence.criteria`` (all|average),
    ``convergence.threshold`` (percent), ``learning.rate``, ``l2.weight``.
    """

    name = "LogisticRegressionJob"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        import contextlib

        coeff_path = conf.get("coeff.file.path") or os.path.join(
            output_path, "coefficients.txt")
        est = mlr.LogisticRegression(
            learning_rate=conf.get_float("learning.rate", 0.5),
            max_iterations=conf.get_int("iteration.limit", 200),
            convergence=conf.get("convergence.criteria", "average"),
            threshold_pct=conf.get_float("convergence.threshold", 0.5),
            l2=conf.get_float("l2.weight", 0.0),
            mesh=self.auto_mesh(conf),
        )
        # the coefficient-history rewrite is the reference's one cross-task
        # mutable-state hazard (LogisticRegressionJob.java:238-255, safe
        # there only via num.reducer=1): hold an exclusive lock for the
        # whole read-resume-train-rewrite cycle so a concurrent run is
        # detected (LockHeldError) instead of silently interleaving, and
        # replace the file atomically so readers never see a torn history.
        # Under jax.distributed only process 0 (the writer) takes the lock
        # and reads the resume history; peers receive it through the
        # ``all_process_sum_state`` handshake — an unlocked independent
        # peer read could observe a different (mid-rewrite or newer) file
        # than the writer resumed from, silently desynchronizing the
        # lockstep gradient fold.
        os.makedirs(os.path.dirname(coeff_path) or ".", exist_ok=True)
        lock = (FileLock(coeff_path,
                         timeout_s=conf.get_float("coeff.lock.timeout.sec", 10.0))
                if self.is_output_writer() else contextlib.nullcontext())
        import jax

        with lock:
            resume = None
            read_err = None
            if self.is_output_writer() and os.path.exists(coeff_path):
                try:
                    with open(coeff_path) as fh:
                        lines = [ln for ln in fh if ln.strip()]
                    if lines:
                        resume = mlr.LogisticRegressionModel.from_history_lines(
                            lines, delim=conf.field_delim)
                except Exception as e:
                    # multi-process: the failure must travel THROUGH the
                    # broadcast collective — peers no longer read the file
                    # themselves, and a writer that raised before entering
                    # the handshake would leave them hung in the allgather
                    if jax.process_count() <= 1:
                        raise
                    read_err = f"{type(e).__name__}: {e}"
            resume = self._broadcast_resume(resume, read_err)
            if conf.get("stream.chunk.rows"):
                model, n_rows = self._fit_streaming(conf, input_path,
                                                    counters, est, resume)
            else:
                _enc, ds, _rows = self.encode_input(conf, input_path,
                                                    need_rows=False)
                x = mlr.design_matrix(ds)
                y = np.asarray(ds.labels, np.float32)
                model = est.fit(x, y, resume_from=resume)
                n_rows = ds.num_rows
            hist = model.history_lines(delim=conf.field_delim)
            if self.is_output_writer():
                with atomic_write(coeff_path) as fh:
                    fh.write("\n".join(hist) + "\n")
        status = "converged" if model.converged else "iterationLimit"
        if self.is_output_writer():
            write_output(output_path,
                         hist + [f"status{conf.field_delim}{status}"])
        counters.set("Records", "Processed", n_rows)
        counters.set("Iterations", "Run", model.iterations)
        counters.set("Iterations", "Converged", int(model.converged))

    @staticmethod
    def _broadcast_resume(resume, read_err=None):
        """Ship the writer's lock-protected resume history to every peer
        through the same packed-gather collective the gradient fold uses
        (``all_process_sum_state``): process 0 contributes the [iters, D]
        history stack, peers contribute nothing (a missing key folds as
        absent), and all processes reconstruct the identical model —
        bitwise, since the raw float64 rows ride the wire rather than a
        repr round-trip.  Every process enters exactly one collective, so
        the sequence stays aligned with the per-iteration merges that
        follow.  A writer-side read/pack failure (``read_err``, or a
        ragged history that fails to stack) rides the same payload and
        re-raises on EVERY process — for those failures the one
        collective still happens, so no peer is left hung in the
        allgather.  (A writer that dies BEFORE this point — e.g.
        ``LockHeldError`` at lock acquisition — still strands peers at
        their next collective; that is the pre-existing
        writer-death-mid-job failure mode of every distributed run,
        bounded by the distributed-runtime timeout, not something this
        handshake changes.)  Single-process runs return ``resume``
        untouched."""
        import jax

        if jax.process_count() <= 1:
            return resume
        from avenir_tpu.parallel.mesh import all_process_sum_state

        state = {}
        if read_err is None and resume is not None:
            try:
                state["lr_resume_hist"] = np.stack(resume.history).astype(
                    np.float64)
            except Exception as e:   # e.g. ragged rows — must not skip the
                read_err = f"{type(e).__name__}: {e}"   # collective below
        if read_err is not None:
            state["lr_resume_error"] = np.frombuffer(
                read_err.encode(), np.uint8).copy()
        folded = all_process_sum_state(state)
        err = folded.get("lr_resume_error")
        if err is not None:
            raise ValueError(
                "coefficient-history resume failed on the writer: "
                + err.tobytes().decode(errors="replace"))
        hist = folded.get("lr_resume_hist")
        if hist is None:
            return None
        rows = [np.asarray(r) for r in hist]
        return mlr.LogisticRegressionModel(
            weights=rows[-1], history=rows, converged=False,
            iterations=len(rows))

    def _fit_streaming(self, conf: JobConfig, input_path: str,
                       counters: Counters, est, resume):
        """Streaming/multi-process LR: owned chunks are encoded into
        design-matrix blocks kept device-resident across iterations; each
        iteration folds per-chunk gradient partials across processes in
        global chunk order (byte-identical for any nprocs — see
        ``LogisticRegression.fit_chunked``).  The Hadoop analog is the
        per-iteration MR job whose mappers each emitted one partial
        gradient (LogisticRegressionJob.java:169-176,279-289)."""
        if conf.get("stream.checkpoint.dir"):
            from avenir_tpu.core.config import ConfigError
            raise ConfigError(
                "stream.checkpoint.dir does not apply to "
                "LogisticRegressionJob: the coefficient history file IS "
                "the checkpoint (every completed iteration is durable and a "
                "re-run resumes from its last row, "
                "LogisticRegressionJob.java:238-255) — unset the key")
        owner, _acc, distributed = self.distributed_plan(conf, None)
        enc = self.encoder_for(conf)
        chunks = []
        for ds, cur in self.iter_encoded_retrying(
                conf, input_path, enc, counters, emit_cursor=True,
                owner=owner):
            chunks.append((cur["chunk"] - 1, mlr.design_matrix(ds),
                           np.asarray(ds.labels, np.float32)))
        merge = None
        if distributed:
            from avenir_tpu.parallel.mesh import all_process_sum_state
            merge = all_process_sum_state
        model = est.fit_chunked(chunks, resume_from=resume, merge=merge)
        # fit_chunked's handshake already folded the global row count —
        # n_rows rides on the model, no second collective needed
        return model, model.n_rows


class FisherDiscriminant(Job):
    """Per-attribute univariate Fisher/LDA for a binary class: pooled
    variance, log-odds prior, decision boundary
    (FisherDiscriminant.java:83-117)."""

    name = "FisherDiscriminant"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        _enc, ds, _rows = self.encode_input(conf, input_path, need_rows=False)
        schema = self.load_schema(conf)
        names = [schema.field_by_ordinal(o).name for o in ds.cont_ordinals]
        model = mfisher.FisherDiscriminant(mesh=self.auto_mesh(conf)).fit(ds)
        write_output(output_path,
                     model.to_lines(feature_names=names, delim=conf.field_delim))
        counters.set("Records", "Processed", ds.num_rows)
