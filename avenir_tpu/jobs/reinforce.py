"""Bandit jobs — one round of arm selection per group over the reference's
``group,item,count,reward`` row format (reinforce/GreedyRandomBandit.java,
AuerDeterministic.java, SoftMaxBandit.java, RandomFirstGreedyBandit.java).

An external loop (the tutorial's runbook, resource/price_optimize_tutorial.txt:
42-78) updates rewards between rounds and bumps ``current.round.num`` — the
same contract here, minus the cluster submit.
"""

from __future__ import annotations

from avenir_tpu.core.config import JobConfig
from avenir_tpu.jobs.base import Job, read_input, write_output
from avenir_tpu.models.bandits import BanditJob
from avenir_tpu.utils.metrics import Counters


class _BanditRound(Job):
    algorithm = ""

    def _algorithm(self, conf: JobConfig) -> str:
        return self.algorithm

    def _kwargs(self, conf: JobConfig) -> dict:
        return {}

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        delim = conf.field_delim_regex
        rows = [list(r) for r in read_input(input_path, delim=delim)]
        job = BanditJob(self._algorithm(conf), seed=conf.get_int("seed", 0),
                        **self._kwargs(conf))
        round_num = conf.get_int("current.round.num", 1)
        lines = job.select_lines(rows, round_num, delim=conf.field_delim,
                                 count_ord=conf.get_int("count.ordinal", 2),
                                 reward_ord=conf.get_int("reward.ordinal", 3))
        write_output(output_path, lines)
        counters.set("Groups", "Selected", len(lines))
        counters.set("Round", "Number", round_num)


class GreedyRandomBandit(_BanditRound):
    """ε-greedy with linear / log-linear decay, plus the AuerGreedy variant
    (GreedyRandomBandit.java:196-274). ``prob.reduction.algorithm``:
    linear | loglinear | auer."""

    name = "GreedyRandomBandit"

    def _algorithm(self, conf: JobConfig) -> str:
        return {"linear": "greedyRandomLinear",
                "loglinear": "greedyRandomLogLinear",
                "logLinear": "greedyRandomLogLinear",
                "auer": "auerGreedy"}[
            conf.get("prob.reduction.algorithm", "linear")]

    def _kwargs(self, conf: JobConfig) -> dict:
        return dict(
            epsilon=conf.get_float("random.selection.prob", 1.0),
            prob_reduction_constant=conf.get_float("prob.reduction.constant", 1.0),
            auer_constant=conf.get_float("auer.greedy.constant", 5.0),
        )


class AuerDeterministic(_BanditRound):
    """UCB1 (AuerDeterministic.java:200-223)."""

    name = "AuerDeterministic"
    algorithm = "auerDeterministic"


class SoftMaxBandit(_BanditRound):
    """Boltzmann selection with temperature ``temp.constant``
    (SoftMaxBandit.java:182-198)."""

    name = "SoftMaxBandit"
    algorithm = "softMax"

    def _kwargs(self, conf: JobConfig) -> dict:
        return dict(tau=conf.get_float("temp.constant", 0.1))


class RandomFirstGreedyBandit(_BanditRound):
    """Explore-first: budget = factor·K or the PAC bound
    (RandomFirstGreedyBandit.java:138-147)."""

    name = "RandomFirstGreedyBandit"
    algorithm = "randomFirstGreedy"

    def _kwargs(self, conf: JobConfig) -> dict:
        return dict(
            strategy=conf.get("exploration.count.strategy", "simple"),
            exploration_count_factor=conf.get_int("exploration.count.factor", 3),
            reward_diff=conf.get_float("pac.reward.diff", 0.5),
            prob_diff=conf.get_float("pac.prob.diff", 0.1),
        )
