"""Word-count job (text/WordCounter.java): text field by ordinal or the whole
line (:101-107), analyzer tokenization (:117-128), word,count rows out."""

from __future__ import annotations

from avenir_tpu.core.config import JobConfig
from avenir_tpu.jobs.base import Job, input_files, write_output
from avenir_tpu.text.wordcount import WordCount
from avenir_tpu.utils.metrics import Counters


class WordCounter(Job):
    name = "WordCounter"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        ordinal = conf.get_int("text.field.ordinal", -1)
        delim = conf.field_delim_regex
        wc = WordCount(stopwords=conf.get_bool("remove.stop.words", True),
                       stem=conf.get_bool("stem.words", False))
        n = 0
        for f in input_files(input_path):
            with open(f) as fh:
                lines = []
                for line in fh:
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    n += 1
                    if ordinal >= 0:
                        parts = line.split(delim)
                        lines.append(parts[ordinal] if ordinal < len(parts) else "")
                    else:
                        lines.append(line)
                wc.add_lines(lines)
        write_output(output_path, wc.to_lines(delim=conf.field_delim))
        counters.set("Records", "Processed", n)
        counters.set("Words", "Distinct", len(wc.vocab))
