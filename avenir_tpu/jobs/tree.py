"""Decision-tree jobs.

The reference grows a tree by alternating two MR jobs per node level —
candidate-split evaluation (tree/SplitGenerator.java wrapping
explore/ClassPartitionGenerator.java) and data partitioning into an HDFS
directory tree (tree/DataPartitioner.java), with a human/script driving the
recursion. Here:

- :class:`ClassPartitionGenerator` emits scored candidate splits for one node
  level (the reference's split-file contract);
- :class:`DataPartitioner` applies the best split and writes
  ``split=<key>/segment=<i>/data/partition.txt`` directories — the same
  on-disk layout, for runbook continuity;
- :class:`DecisionTreeBuilder` is the TPU-native replacement: the whole
  recursion as one in-memory frontier loop (models/tree.py), emitting the
  final tree as JSON. One process, zero intermediate files.
"""

from __future__ import annotations

import json
import os
from typing import List

import numpy as np

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.jobs.base import Job, read_lines, write_output
from avenir_tpu.models import tree as dtree
from avenir_tpu.utils.metrics import ConfusionMatrix, Counters

import jax
import jax.numpy as jnp


def _tree_params(conf: JobConfig) -> dict:
    return dict(
        algorithm=conf.get("split.algorithm", "entropy"),
        max_split=conf.get_int("max.cat.attr.split.groups",
                               conf.get_int("max.split", 3)),
        attr_strategy={"userSpecified": "userSpecified", "all": "all",
                       "random": "randomK"}.get(
            conf.get("split.attribute.selection.strategy", "all"), "all"),
        user_attrs=conf.get_int_list("split.attributes"),
        random_k=conf.get_int("random.split.set.size"),
        top_n=conf.get_int("num.top.splits", 1),
        # split.selection.path device|host: where per-level split scoring/
        # selection runs (byte-identical trees either way — see
        # models/tree.py); split.search exhaustive|binary picks the
        # candidate family (binary = sorted-threshold sklearn-comparable);
        # tree.hist.mode direct|cumsum|subtract picks the level-table /
        # split-histogram strategy (cumsum = one bin-axis prefix sum
        # serves every binary threshold; subtract = sibling-subtraction
        # level tables — both byte-identical to direct)
        selection=conf.get("split.selection.path", "device"),
        split_search=conf.get("split.search", "exhaustive"),
        hist_mode=conf.get("tree.hist.mode", "direct"),
        # tree.level.packed auto|on|off — PackGraft per-level sibling
        # packing (one wide disjoint gram per frontier); auto packs only
        # where the joint shape rides the TPU kernel
        level_packed=conf.get("tree.level.packed", "auto"),
    )


class ClassPartitionGenerator(Job):
    """One-level candidate-split scoring: emits
    ``attr;splitKey;stat[;segment class distributions]`` rows, the contract
    DataPartitioner consumes (ClassPartitionGenerator.java:513-566)."""

    name = "ClassPartitionGenerator"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        _enc, ds, _rows = self.encode_input(conf, input_path, need_rows=False)
        p = _tree_params(conf)
        if conf.get_bool("at.root"):
            # phase-1 bootstrap of the reference's two-job tree runbook:
            # emit only the dataset-level info content
            # (ClassPartitionGenerator.java:206-209,516-519)
            from avenir_tpu.ops import info as oinfo
            counts = jnp.bincount(jnp.asarray(ds.labels),
                                  length=ds.num_classes).astype(jnp.float32)
            stat_fn = (oinfo.entropy_from_counts if p["algorithm"] == "entropy"
                       else oinfo.gini_from_counts)
            write_output(output_path, [f"{float(stat_fn(counts)):.6f}"])
            counters.set("Records", "Processed", ds.num_rows)
            return
        schema = self.load_schema(conf)
        is_cat = [schema.field_by_ordinal(o).is_categorical
                  for o in ds.binned_ordinals]
        all_splits = dtree.candidate_splits_for(
            ds, p["split_search"], p["max_split"], is_cat)
        # honor the reference's externally supplied parent info content (from
        # the at.root bootstrap); default = derive from the node itself
        parent_info = conf.get_float("parent.info")
        from avenir_tpu.parallel.mesh import maybe_shard_batch
        mesh = self.auto_mesh(conf)
        codes_dev, labels, node_ids = maybe_shard_batch(
            mesh, ds.codes, ds.labels, np.zeros(ds.num_rows, np.int32))
        # ONE device contraction for the whole job: the [F, B, 1, C] table
        # (the same factoring — and the same single-TPU cross-gram fast
        # path — DecisionTree.fit uses per level)
        from avenir_tpu.ops import pallas_hist
        if (mesh is None and pallas_hist.on_tpu_single_device()
                and pallas_hist.cross_applicable(
                    ds.num_binned, ds.max_bins, ds.num_classes)):
            table_dev = dtree._level_table_cross(
                codes_dev.T, node_ids, labels, 1, ds.num_classes,
                ds.max_bins)
        else:
            table_dev = dtree.node_bin_class_counts(
                codes_dev, node_ids, labels, 1, ds.num_classes, ds.max_bins)
        out_distr = conf.get_bool("output.split.prob", False)
        split_chunk = conf.get_int("split.chunk", 128)

        def emit_row(sp, score, hh) -> str:
            # ONE formatter for both scoring paths — the device/host
            # line-identity contract is asserted by
            # test_class_partition_generator_device_matches_host
            row = [str(ds.binned_ordinals[sp.attr]), sp.key,
                   f"{float(score):.6f}"]
            if out_distr:                                 # hh: [G, C]
                tot = np.maximum(hh.sum(-1, keepdims=True), 1e-9)
                for g in range(sp.num_segments):
                    row.append(":".join(
                        f"{v:.4f}" for v in (hh[g] / tot[g])))
            return ";".join(row)

        lines: List[str] = []
        flat = (dtree.flatten_splits(all_splits, ds.max_bins, split_chunk)
                if p["selection"] == "device" else None)
        if flat is not None and flat.num_real:
            # batched device scoring: every candidate's histogram + score in
            # one dispatch against the resident table; the fetch is the
            # [S, 1] score sheet (plus the small [S, G, 1, C] histograms
            # only when the distribution columns are requested), never
            # the table.  tree.hist.mode cumsum/subtract + an all-binary
            # candidate family routes the histograms through the
            # cumulative-table gather (bit-identical scores)
            binary = p["hist_mode"] != "direct" and flat.all_binary
            scores, hist = jax.device_get(dtree._device_score_all(
                table_dev, flat.seg_tab_dev, flat.attr_dev, flat.nseg_dev,
                jnp.float32(parent_info or 0.0),
                flat.thr_dev if binary else None, algorithm=p["algorithm"],
                gmax=flat.gmax, chunk=flat.chunk,
                has_parent=parent_info is not None, want_hist=out_distr,
                binary=binary))
            lines = [emit_row(sp, scores[si, 0],
                              hist[si, :, 0, :] if out_distr else None)
                     for si, sp in enumerate(flat.splits)]
        else:
            table = np.asarray(table_dev)
            for _a, chunk, scores, hist in dtree.iter_scored_splits(
                    table, all_splits, p["algorithm"], split_chunk,
                    parent_info=parent_info):
                lines.extend(emit_row(sp, scores[si, 0], hist[si, :, 0, :])
                             for si, sp in enumerate(chunk))
        write_output(output_path, lines)
        counters.set("Records", "Processed", ds.num_rows)
        counters.set("Splits", "Evaluated", len(lines))


class SplitGenerator(ClassPartitionGenerator):
    """Path-convention subclass (tree/SplitGenerator.java:39-54): reads
    ``project.base.path``/``split.path`` to derive in/out dirs; writes the
    candidate-splits file to the sibling ``splits`` dir."""

    name = "SplitGenerator"

    def run(self, conf: JobConfig, input_path: str = "", output_path: str = "") -> Counters:
        base = conf.get("project.base.path", "")
        rel = conf.get("split.path", "")
        inp = input_path or os.path.join(base, rel, "data")
        out = output_path or os.path.join(base, rel, "splits")
        return super().run(conf, inp, out)


class DataPartitioner(Job):
    """Apply the best candidate split: reads the splits file
    (``split.file.path`` or ``<input>/../splits``), selects best or
    random-from-top-N (DataPartitioner.java:157-201), and writes each
    record into ``split=<attr>/segment=<seg>/data/partition.txt`` under the
    output dir (:114-129)."""

    name = "DataPartitioner"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        splits_path = conf.get("split.file.path") or os.path.join(
            os.path.dirname(input_path.rstrip(os.sep)), "splits")
        rows_split = [ln.split(";") for ln in read_lines(splits_path)
                      if not ln.startswith("featureScore")]
        scored = sorted(((float(r[2]), int(r[0]), r[1]) for r in rows_split),
                        reverse=True)
        top_n = conf.get_int("num.top.splits", 1)
        strategy = conf.get("split.selection.strategy", "best")
        rng = np.random.default_rng(conf.get_int("seed", 0))
        pick = scored[0] if strategy == "best" or top_n <= 1 else \
            scored[int(rng.integers(min(top_n, len(scored))))]
        _score, attr_ord, key = pick

        enc, ds, lines = self.encode_input_with_lines(conf, input_path)
        schema = self.load_schema(conf)
        is_cat = [schema.field_by_ordinal(o).is_categorical
                  for o in ds.binned_ordinals]
        a = ds.binned_ordinals.index(attr_ord)
        p = _tree_params(conf)
        all_splits = dtree.candidate_splits_for(
            ds, p["split_search"], p["max_split"], is_cat, attrs=[a])
        sp = next((s for s in all_splits[a] if s.key == key), None)
        if sp is None:
            raise ValueError(f"split key {key!r} not found for attribute {attr_ord}")
        segs = sp.seg_of_bin[ds.codes[:, a]]
        for g in range(sp.num_segments):
            seg_dir = os.path.join(output_path, f"split={attr_ord}",
                                   f"segment={g}", "data")
            os.makedirs(seg_dir, exist_ok=True)
            with open(os.path.join(seg_dir, "partition.txt"), "w") as fh:
                for i in np.nonzero(segs == g)[0]:
                    fh.write(lines[i])
                    fh.write("\n")
        counters.set("Records", "Processed", ds.num_rows)
        counters.set("Splits", "Segments", int(sp.num_segments))


class DecisionTreeBuilder(Job):
    """Whole-tree induction in one job (the in-memory frontier loop that
    replaces the per-level SplitGenerator/DataPartitioner alternation).
    Output: the tree as a JSON model line plus a fitted-encoder-state line
    (the tree's ``seg_of_bin`` tables are keyed by raw train-time bin codes,
    so scoring must reuse the train-time code space, not re-fit on its
    input); validation mode adds confusion counters."""

    name = "DecisionTreeBuilder"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        if conf.get("tree.model.file.path"):
            self._predict(conf, input_path, output_path, counters)
            return
        enc, ds, _rows = self.encode_input(conf, input_path, need_rows=False)
        schema = self.load_schema(conf)
        is_cat = [schema.field_by_ordinal(o).is_categorical
                  for o in ds.binned_ordinals]
        p = _tree_params(conf)
        trainer = dtree.DecisionTree(
            algorithm=p["algorithm"], max_split=p["max_split"],
            attr_strategy=p["attr_strategy"], user_attrs=p["user_attrs"],
            random_k=p["random_k"], top_n=p["top_n"],
            max_depth=conf.get_int("max.depth", 4),
            min_node_size=conf.get_int("min.node.size", 32),
            seed=conf.get_int("seed", 0),
            mesh=self.auto_mesh(conf),
            selection=p["selection"], split_search=p["split_search"],
            hist_mode=p["hist_mode"], level_packed=p["level_packed"],
            collect_phase_stats=conf.get_bool("tree.hist.phase.stats", False),
        )
        model = trainer.fit(ds, is_cat)
        # opt-in per-level phase breakdown (table-build / score / partition
        # µs as TreePhase counters — the attribution artifact behind the
        # benchmarks' hist-mode comparison)
        for st in trainer.level_stats:
            lv = st["level"]
            counters.set("TreePhase", f"level.{lv}.table.us",
                         int(st["table_ms"] * 1e3))
            counters.set("TreePhase", f"level.{lv}.select.us",
                         int(st["select_ms"] * 1e3))
            counters.set("TreePhase", f"level.{lv}.partition.us",
                         int(st["partition_ms"] * 1e3))
        write_output(output_path, [model.to_string(),
                                   json.dumps({"encoder": enc.state_dict()})])
        if conf.get("prediction.mode") == "validation":
            _pred, _distr, cm, c2 = trainer.predict(
                model, ds, validate=True,
                pos_class=conf.get("positive.class.value"))
            counters.merge(c2)
        counters.set("Records", "Processed", ds.num_rows)
        counters.set("Tree", "Nodes", len(model.nodes))

    def _predict(self, conf: JobConfig, input_path: str, output_path: str,
                 counters: Counters) -> None:
        """Score new rows with a saved JSON tree model
        (``tree.model.file.path``), appending the predicted class — the same
        output contract as BayesianPredictor. The model file's second line
        carries the fitted encoder state, restored here so codes (and label
        indices, in validation mode) live in the train-time space."""
        model_lines = read_lines(conf.get("tree.model.file.path"))
        model = dtree.DecisionTreeModel.from_string(model_lines[0])
        enc = self.encoder_for(conf)
        if len(model_lines) > 1:
            enc.load_state_dict(json.loads(model_lines[1])["encoder"])
        else:
            # never re-fit on the scoring input: codes would shift whenever
            # its value range/vocabulary differs from training
            missing = [f.name for f in enc.binned_fields
                       if f.ordinal not in enc.vocab
                       and f.ordinal not in enc.bin_offset]
            if missing or not enc.class_values:
                raise ValueError(
                    "tree model file has no encoder-state line and the schema "
                    f"does not fully specify the encoding (missing: {missing}"
                    f"{'' if enc.class_values else ', class cardinality'}); "
                    "re-train with this version to embed encoder state")
            enc._fitted = True
        validation = conf.get("prediction.mode") == "validation"
        _enc, ds, rows = self.encode_input(conf, input_path,
                                           with_labels=validation,
                                           encoder=enc)
        if validation and ds.labels is None:
            raise ConfigError("prediction.mode=validation requires labeled "
                             "input (class column missing)")
        walk = dtree.predict_fn(model)
        pred, _distr = walk(jnp.asarray(ds.codes))
        pred = np.asarray(pred)
        delim = conf.field_delim
        lines = [delim.join(list(r) + [model.class_values[int(p)]])
                 for r, p in zip(rows, pred)]
        write_output(output_path, lines)
        if validation:
            cm = ConfusionMatrix(model.class_values,
                                 pos_class=conf.get("positive.class.value"))
            cm.add_batch(ds.labels, pred)
            cm.publish(counters)
        counters.set("Records", "Processed", ds.num_rows)
