"""CrossGraft fleet launcher — the process plane under the global mesh.

The reference's N-machine story was Hadoop's: a JobTracker hands map
tasks to task trackers that some operator already provisioned.  This
package is that provisioning step for the jax-distributed runtime, in
one process-shaped verb::

    python -m avenir_tpu.launch --nprocs 2 -- BayesianDistribution \\
        -Dconf.path=churn.properties train.csv out/

It spawns N local worker processes (or, inside an externally provisioned
pod, discovers its own rank from the environment and execs the worker in
place), wires every worker's coordinator join through the HARDENED
:func:`avenir_tpu.parallel.mesh.init_distributed` (bounded jittered
retry, typed :class:`LaunchError` naming the coordinator on timeout —
never a hang), assigns each worker its own journal shard via
``trace.writer.suffix``/``AVENIR_WRITER_SUFFIX``, and on teardown merges
the per-process journal shards into one fleet view and propagates the
first non-zero exit.

Stdlib-only at import time (no jax): the launcher itself must start
instantly and survive on a machine whose jax is broken — that is
precisely when its error messages matter.  Workers do the jax work.

Env contract (the worker side reads these; the launcher writes them):

- ``AVENIR_COORDINATOR_ADDRESS`` — ``host:port`` of process 0's
  coordinator service;
- ``AVENIR_NUM_PROCESSES`` / ``AVENIR_PROCESS_ID`` — fleet size / rank;
- ``AVENIR_JOIN_TIMEOUT_SEC`` / ``AVENIR_JOIN_ATTEMPTS`` — the hardened
  join's bounds (defaults 300 s / 3);
- ``AVENIR_WRITER_SUFFIX`` — per-process journal-shard suffix
  (``w<rank>``); ``python -m avenir_tpu`` adopts it as
  ``trace.writer.suffix`` unless the conf sets one explicitly.

An externally provisioned pod (slurm-style: every rank launched by the
cluster) sets the same variables per rank and runs the SAME command on
every rank WITHOUT ``--nprocs``; :func:`pod_env` discovers the rank and
the launcher execs the worker in place instead of spawning.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

ENV_COORD = "AVENIR_COORDINATOR_ADDRESS"
ENV_NPROCS = "AVENIR_NUM_PROCESSES"
ENV_PID = "AVENIR_PROCESS_ID"
ENV_SUFFIX = "AVENIR_WRITER_SUFFIX"
ENV_JOIN_TIMEOUT = "AVENIR_JOIN_TIMEOUT_SEC"
ENV_JOIN_ATTEMPTS = "AVENIR_JOIN_ATTEMPTS"


class LaunchError(RuntimeError):
    """A fleet that could not be brought up or torn down cleanly: a
    coordinator join that timed out (the message names the coordinator
    address), a worker that outlived the launch deadline, or an argv the
    launcher cannot interpret.  Typed so supervisors retry or alert on
    launch failures distinctly from workload errors."""


def free_port() -> int:
    """An OS-assigned free TCP port on localhost — the default
    coordinator port for locally spawned fleets."""
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def pod_env(environ: Optional[Dict[str, str]] = None) -> Optional[dict]:
    """Externally provisioned pod discovery: when the environment already
    names this process's rank (``AVENIR_PROCESS_ID`` + fleet size +
    coordinator), return ``{"coordinator", "nprocs", "process_id"}``;
    else None.  This is how one launcher command line works both on a
    laptop (spawn mode) and under a cluster scheduler that starts every
    rank itself (join mode)."""
    env = os.environ if environ is None else environ
    if ENV_PID not in env or ENV_NPROCS not in env:
        return None
    return {"coordinator": env.get(ENV_COORD, ""),
            "nprocs": int(env[ENV_NPROCS]),
            "process_id": int(env[ENV_PID])}


def join_from_env(environ: Optional[Dict[str, str]] = None) -> int:
    """Worker-side bootstrap: join the fleet the environment describes
    (no-op rank 0 of 1 when it describes none) through the hardened
    coordinator join.  Returns this process's rank.  The ONE call every
    worker entry point makes before touching jax — ``python -m
    avenir_tpu`` calls it automatically when ``AVENIR_NUM_PROCESSES`` is
    set, so any job CLI invocation is fleet-ready."""
    env = os.environ if environ is None else environ
    from avenir_tpu.parallel.mesh import init_distributed

    pod = pod_env(env)
    if pod is None:
        return init_distributed()          # pod/TPU env discovery inside
    return init_distributed(
        coordinator_address=pod["coordinator"] or None,
        num_processes=pod["nprocs"], process_id=pod["process_id"],
        timeout_s=float(env.get(ENV_JOIN_TIMEOUT, "300")),
        attempts=int(env.get(ENV_JOIN_ATTEMPTS, "3")))


def worker_command(argv: Sequence[str]) -> List[str]:
    """The child command line for one worker: ``<JobName> …`` runs the
    job CLI (``python -m avenir_tpu …``), ``<script>.py …`` runs the
    script, ``-m <module> …`` runs the module — the three shapes jobs,
    benchmarks, and tests launch as."""
    argv = list(argv)
    if not argv:
        raise LaunchError("no worker argv after '--': pass the job CLI "
                          "argv (JobName -D… <in> <out>), a script.py, "
                          "or -m <module>")
    if argv[0] == "-m":
        if len(argv) < 2:
            raise LaunchError("'-m' needs a module name")
        return [sys.executable, "-m", argv[1], *argv[2:]]
    if argv[0].endswith(".py"):
        return [sys.executable, *argv]
    return [sys.executable, "-m", "avenir_tpu", *argv]


@dataclass
class WorkerResult:
    """One worker's teardown record."""

    rank: int
    returncode: Optional[int]
    output: str = ""
    finished_at: float = 0.0


@dataclass
class FleetResult:
    """What a local launch returned: per-worker records, the propagated
    exit code (the FIRST non-zero exit in completion order — the worker
    that died first is the one whose error explains the fleet), and the
    merged journal path when one was produced."""

    workers: List[WorkerResult] = field(default_factory=list)
    exit_code: int = 0
    merged_journal: Optional[str] = None
    # GraftBox: dead workers' forensics bundles swept at teardown (one
    # record per bundle: dir/reason/status/events/journaled)
    bundles: List[dict] = field(default_factory=list)

    def output_of(self, rank: int) -> str:
        return next(w.output for w in self.workers if w.rank == rank)


def merge_fleet_journal(journal_dir: str,
                        run_id: Optional[str] = None) -> Optional[str]:
    """Merge one run's per-process journal shards under ``journal_dir``
    into one time-ordered ``fleet-<run>.jsonl`` view
    (``telemetry/journal.py::merge_journals`` — torn tails and missing
    crashed-worker shards tolerated).  Sweeps EVERY writer suffix of the
    run — scan workers' ``w<k>``, serving replicas, tenant planes and the
    GlobalServe router alike (the shard pattern is
    ``run-<id>.proc-<k>[-<suffix>].jsonl``; nothing here assumes ``w<k>``)
    — so one file holds the whole fleet.  ``run_id`` pins WHICH run when
    the caller knows it (GlobalServe teardown, where a long-lived journal
    dir may hold earlier runs); default is the newest run in the
    directory.  Returns the merged path, or None when the directory holds
    no shards (tracing was off)."""
    from avenir_tpu.telemetry.journal import merge_journals

    run_id, shards, events = merge_journals(journal_dir, run_id=run_id)
    if run_id is None:
        return None
    out_path = os.path.join(journal_dir, f"fleet-{run_id}.jsonl")
    import json

    with open(out_path, "w", encoding="utf-8") as fh:
        for e in events:
            fh.write(json.dumps(e, separators=(",", ":")))
            fh.write("\n")
    return out_path


def _worker_env(base: Dict[str, str], rank: int, nprocs: int,
                coordinator: str, devices_per_proc: Optional[int],
                join_timeout_s: float, join_attempts: int) -> Dict[str, str]:
    env = dict(base)
    env[ENV_COORD] = coordinator
    env[ENV_NPROCS] = str(nprocs)
    env[ENV_PID] = str(rank)
    env[ENV_SUFFIX] = f"w{rank}"
    env[ENV_JOIN_TIMEOUT] = str(join_timeout_s)
    env[ENV_JOIN_ATTEMPTS] = str(join_attempts)
    if devices_per_proc:
        # host-mesh workers: K virtual CPU devices each (the tier-1
        # trick per process); strip any inherited forced count first so
        # the worker's mesh is exactly K wide
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(
            f"--xla_force_host_platform_device_count={devices_per_proc}")
        env["XLA_FLAGS"] = " ".join(flags)
        env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def launch_local(child_argv: Sequence[str], nprocs: int, *,
                 devices_per_proc: Optional[int] = None,
                 coordinator: Optional[str] = None,
                 join_timeout_s: float = 300.0, join_attempts: int = 3,
                 timeout_s: float = 0.0, grace_s: float = 15.0,
                 env: Optional[Dict[str, str]] = None,
                 journal_dir: Optional[str] = None,
                 echo: bool = True) -> FleetResult:
    """Spawn ``nprocs`` local workers running ``child_argv`` (see
    :func:`worker_command`) as one jax-distributed fleet and tear it
    down: stream every worker's output (prefixed ``[p<k>]``), enforce
    the optional wall deadline (``timeout_s`` > 0 — expiry kills the
    fleet and raises :class:`LaunchError`), give surviving workers
    ``grace_s`` to notice a dead peer before killing them (the
    coordinator's health check is not instant), merge journal shards
    from ``journal_dir`` when given, and propagate the first non-zero
    exit in completion order."""
    import subprocess

    if nprocs < 1:
        raise LaunchError(f"--nprocs must be >= 1, got {nprocs}")
    cmd = worker_command(child_argv)
    coordinator = coordinator or f"localhost:{free_port()}"
    base_env = dict(os.environ if env is None else env)
    procs = []
    for rank in range(nprocs):
        wenv = _worker_env(base_env, rank, nprocs, coordinator,
                           devices_per_proc, join_timeout_s, join_attempts)
        procs.append(subprocess.Popen(
            cmd, env=wenv, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))

    outputs: List[List[str]] = [[] for _ in range(nprocs)]
    lock = threading.Lock()

    def pump(rank: int) -> None:
        try:
            for line in procs[rank].stdout:
                outputs[rank].append(line)
                if echo:
                    with lock:
                        sys.stdout.write(f"[p{rank}] {line}")
                        sys.stdout.flush()
        except Exception as e:            # noqa: BLE001
            # route into the captured transcript the supervisor reports —
            # a dead reader must not silently truncate a worker's output
            outputs[rank].append(f"[launcher] output pump died: {e!r}\n")

    readers = [threading.Thread(target=pump, args=(r,), daemon=True)
               for r in range(nprocs)]
    for t in readers:
        t.start()

    deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
    finished: Dict[int, float] = {}
    first_failure_at: Optional[float] = None
    try:
        while len(finished) < nprocs:
            now = time.monotonic()
            for rank, p in enumerate(procs):
                if rank not in finished and p.poll() is not None:
                    finished[rank] = now
                    if p.returncode != 0 and first_failure_at is None:
                        first_failure_at = now
            if len(finished) == nprocs:
                break
            if deadline is not None and now > deadline:
                for p in procs:
                    p.kill()
                raise LaunchError(
                    f"fleet launch exceeded the {timeout_s:g}s deadline; "
                    f"still running: "
                    f"{sorted(set(range(nprocs)) - set(finished))} — "
                    f"workers killed")
            if first_failure_at is not None and \
                    now - first_failure_at > grace_s:
                # a worker died and its peers did not follow within the
                # grace window (wedged in a collective the dead peer will
                # never enter): kill the stragglers, keep their output
                for rank, p in enumerate(procs):
                    if rank not in finished:
                        p.kill()
            time.sleep(0.05)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in readers:
            t.join(timeout=10)

    result = FleetResult()
    order = sorted(range(nprocs), key=lambda r: finished.get(r, float("inf")))
    for rank in range(nprocs):
        result.workers.append(WorkerResult(
            rank=rank, returncode=procs[rank].returncode,
            output="".join(outputs[rank]),
            finished_at=finished.get(rank, 0.0)))
    for rank in order:                       # first non-zero IN TIME ORDER
        rc = procs[rank].returncode
        if rc:
            result.exit_code = int(rc)
            break
    if journal_dir:
        # GraftBox: sweep dead workers' bundles FIRST — the sweep shard's
        # bundle.written records must exist before the fleet merge reads
        # the directory, so the merged journal accounts for every death
        from avenir_tpu.telemetry.blackbox import sweep as _sweep_bundles

        for bb_dir in (journal_dir, os.path.join(journal_dir, "blackbox")):
            result.bundles.extend(_sweep_bundles(bb_dir,
                                                 journal_dir=journal_dir))
        result.merged_journal = merge_fleet_journal(journal_dir)
    return result
