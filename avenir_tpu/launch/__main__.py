"""Fleet launcher CLI — ``python -m avenir_tpu.launch``.

Three modes, one command line (docs/jobs.md "Fleet launcher"):

- **spawn** (``--nprocs N``): bring up N local worker processes as one
  jax-distributed fleet over a local coordinator, run the worker argv in
  each, merge journal shards, propagate the first non-zero exit;
- **join** (no ``--nprocs``, ``AVENIR_PROCESS_ID`` set): the process was
  provisioned externally (cluster scheduler started every rank) — exec
  the worker argv in place; the worker joins through the same hardened
  coordinator join via its environment;
- **serve** (``--serve --conf serve.properties --nprocs N``): GlobalServe
  (round 20) — bring up N full serving planes (one
  ``python -m avenir_tpu.serving`` process each, a ReplicaPool inside
  when ``pool.*`` is armed) and front them with the tenant-aware
  :class:`~avenir_tpu.serving.global_pool.GlobalRouter` on
  ``fleet.http.port``; on teardown every shard — workers, tenants and the
  router — merges into one ``fleet-<run>.jsonl``
  (docs/deployment.md "Cross-host serving").

Examples::

    # 2 workers × 4 virtual CPU devices each, job CLI argv
    python -m avenir_tpu.launch --nprocs 2 --devices-per-proc 4 -- \\
        BayesianDistribution -Dconf.path=churn.properties train.csv out/

    # a benchmark script across 2 workers, journals merged
    python -m avenir_tpu.launch --nprocs 2 --journal-dir /tmp/tel -- \\
        benchmarks/multichip_scan.py --nprocs 2

    # a 2-process serving fleet behind one logical frontend
    python -m avenir_tpu.launch --serve --conf serve.properties --nprocs 2
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from avenir_tpu.launch import (LaunchError, launch_local, pod_env,
                               worker_command)


def main(argv: List[str]) -> int:
    if "--" in argv:
        cut = argv.index("--")
        opts, child = argv[:cut], argv[cut + 1:]
    else:
        opts, child = argv, []
    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.launch",
        description="Spawn (or join) a jax-distributed worker fleet and "
                    "run a job/pipeline argv in every worker")
    ap.add_argument("--nprocs", type=int, default=0,
                    help="workers to spawn locally (omit inside an "
                         "externally provisioned pod)")
    ap.add_argument("--devices-per-proc", type=int, default=0,
                    help="virtual CPU devices per worker "
                         "(xla_force_host_platform_device_count)")
    ap.add_argument("--coordinator", default=None,
                    help="coordinator host:port (default: localhost on a "
                         "free port)")
    ap.add_argument("--join-timeout-sec", type=float, default=300.0,
                    help="per-attempt coordinator-join timeout (default "
                         "300; a bad address fails typed, never hangs)")
    ap.add_argument("--join-attempts", type=int, default=3,
                    help="coordinator-join attempts under decorrelated "
                         "jitter (default 3)")
    ap.add_argument("--timeout-sec", type=float, default=0.0,
                    help="overall fleet wall deadline (0 = none)")
    ap.add_argument("--journal-dir", default=None,
                    help="trace.journal.dir of the workers; shards are "
                         "merged into fleet-<run>.jsonl on teardown")
    ap.add_argument("--serve", action="store_true",
                    help="GlobalServe mode: front --nprocs serving worker "
                         "processes (built from --conf) with one "
                         "GlobalRouter on fleet.http.port")
    ap.add_argument("--conf", default=None,
                    help="(--serve) serving properties file, shared by "
                         "every worker process")
    ap.add_argument("--http-port", type=int, default=None,
                    help="(--serve) override fleet.http.port for the "
                         "router frontend")
    args = ap.parse_args(opts)

    if args.serve:
        if not args.conf:
            ap.error("--serve requires --conf <serve.properties>")
        if args.nprocs < 1:
            ap.error("--serve requires --nprocs >= 1")
        # lazy import: the launcher module itself stays stdlib-only at
        # import (the join-mode exec path must not pay a jax import)
        from avenir_tpu.serving.global_pool import serve_fleet

        try:
            return serve_fleet(args.conf, args.nprocs,
                               http_port=args.http_port)
        except LaunchError as e:
            print(f"launch error: {e}", file=sys.stderr)
            return 3

    try:
        if not args.nprocs:
            pod = pod_env()
            if pod is None:
                ap.error("--nprocs is required outside an externally "
                         "provisioned pod (AVENIR_PROCESS_ID / "
                         "AVENIR_NUM_PROCESSES unset)")
            # join mode: the environment already names this rank — exec
            # the worker in place (it joins via its env); no double join
            cmd = worker_command(child)
            os.execv(cmd[0], cmd)                      # never returns
        result = launch_local(
            child, args.nprocs,
            devices_per_proc=args.devices_per_proc or None,
            coordinator=args.coordinator,       # None → launch_local picks

            join_timeout_s=args.join_timeout_sec,
            join_attempts=args.join_attempts,
            timeout_s=args.timeout_sec,
            journal_dir=args.journal_dir)
    except LaunchError as e:
        print(f"launch error: {e}", file=sys.stderr)
        return 3
    for w in result.workers:
        print(f"[launch] worker p{w.rank} exit={w.returncode}",
              file=sys.stderr)
    for b in result.bundles:
        print(f"[launch] blackbox bundle: {b['dir']} ({b['reason']})",
              file=sys.stderr)
    if result.merged_journal:
        print(f"[launch] merged fleet journal: {result.merged_journal}",
              file=sys.stderr)
    return result.exit_code


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
