from avenir_tpu.models.naive_bayes import NaiveBayes, NaiveBayesModel
from avenir_tpu.models.mutual_info import MutualInformation, MutualInfoResult, score_features
from avenir_tpu.models.correlation import (
    CategoricalCorrelation,
    CramerCorrelation,
    HeterogeneityReductionCorrelation,
)
from avenir_tpu.models.samplers import bagging_sample, undersample, StreamingUnderSampler

__all__ = [
    "NaiveBayes",
    "NaiveBayesModel",
    "MutualInformation",
    "MutualInfoResult",
    "score_features",
    "CategoricalCorrelation",
    "CramerCorrelation",
    "HeterogeneityReductionCorrelation",
    "bagging_sample",
    "undersample",
    "StreamingUnderSampler",
]
