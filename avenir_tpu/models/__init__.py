from avenir_tpu.models.naive_bayes import NaiveBayes, NaiveBayesModel

__all__ = ["NaiveBayes", "NaiveBayesModel"]
