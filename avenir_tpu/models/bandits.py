"""Batch multi-armed bandits — vectorized over groups.

Capability parity with the reference's round-based MR bandit jobs (input
rows ``group,item,count,reward``; one batch of selections per group per
round, with an external loop updating rewards and bumping
``current.round.num`` — resource/price_optimize_tutorial.txt:42-78):

- ``GreedyRandomBandit.java`` — ε-greedy with linear ε·c/t or log-linear
  ε·c·ln t/t decay (:196-224) and the AuerGreedy variant with
  ε_t = min(1, d·K/(Δ²·t)) (:232-274). NOTE: the reference's AuerGreedy
  draws the greedy arm with probability ε_t and explores with 1−ε_t
  (``prob < Math.random()`` at :263), inverting Auer's schedule; this
  implementation explores with probability ε_t as the algorithm intends —
  a documented deliberate fix.
- ``AuerDeterministic.java`` — UCB1: value = r̄/r̄_max + √(2·ln t / n_i)
  (:200-223), untried items first (:191-196).
- ``SoftMaxBandit.java`` — Boltzmann sampling ∝ exp((r/r_max)/τ) (:182-198).
- ``RandomFirstGreedyBandit.java`` — explore-first with budget =
  factor·K (simple) or the PAC bound 4/Δ² + ln(2K/δ) (:138-147), a rolling
  exploration window over item indices (ExplorationCounter.java:52-77),
  then greedy.

TPU design: group state is dense [G, K] count/reward arrays (−inf-masked
padding for ragged groups); each algorithm is a jitted selection kernel over
those arrays, so one call serves 100 products × 12 arms or 1M groups alike.
The ``group,item,count,reward`` row contract is preserved by
:class:`BanditJob`.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field as dc_field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG = -1e30


def _masked_argmax(x: jax.Array, valid: jax.Array) -> jax.Array:
    return jnp.argmax(jnp.where(valid, x, NEG), axis=-1)


def _random_valid(key: jax.Array, valid: jax.Array) -> jax.Array:
    """Uniform pick among valid arms per group. valid [G, K] → [G]."""
    g = jax.random.gumbel(key, valid.shape)
    return jnp.argmax(jnp.where(valid, g, NEG), axis=-1)


def mean_reward(counts: jax.Array, rewards: jax.Array) -> jax.Array:
    """The reference tracks cumulative reward-per-trial as ints; inputs here
    are (trial count, average reward) per arm as in its data files, so the
    mean is the reward column itself; arms never tried report 0."""
    return jnp.where(counts > 0, rewards, 0.0)


@functools.partial(jax.jit, static_argnames=())
def epsilon_greedy_select(key, counts, rewards, valid, epsilon):
    """[G] arm: explore uniformly with prob ε, else argmax mean reward."""
    kx, ke = jax.random.split(key)
    explore = jax.random.uniform(ke, (counts.shape[0],)) < epsilon
    rand = _random_valid(kx, valid)
    greedy = _masked_argmax(mean_reward(counts, rewards), valid)
    return jnp.where(explore, rand, greedy).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=())
def ucb1_select(key, counts, rewards, valid):
    """UCB1 on r̄ normalized by the group max (AuerDeterministic.java:212)."""
    del key
    t = jnp.maximum(jnp.sum(jnp.where(valid, counts, 0), axis=1, keepdims=True), 1.0)
    rbar = mean_reward(counts, rewards)
    rmax = jnp.maximum(jnp.max(jnp.where(valid, rbar, 0.0), axis=1, keepdims=True), 1e-9)
    bonus = jnp.sqrt(2.0 * jnp.log(t) / jnp.maximum(counts, 1.0))
    value = rbar / rmax + bonus
    untried = valid & (counts == 0)
    any_untried = untried.any(axis=1)
    first_untried = jnp.argmax(untried, axis=1)
    return jnp.where(any_untried, first_untried,
                     _masked_argmax(value, valid)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=())
def softmax_select(key, counts, rewards, valid, tau):
    """Boltzmann: P(i) ∝ exp((r̄_i/r̄_max)/τ) over valid arms; untried arms
    first (cold-start guard — at low τ a pure Boltzmann draw locks onto the
    first arm sampled and explores the rest with probability ~e^(−1/τ))."""
    rbar = mean_reward(counts, rewards)
    rmax = jnp.maximum(jnp.max(jnp.where(valid, rbar, 0.0), axis=1, keepdims=True), 1e-9)
    logits = jnp.where(valid, (rbar / rmax) / jnp.maximum(tau, 1e-6), NEG)
    drawn = jax.random.categorical(key, logits, axis=-1)
    untried = valid & (counts == 0)
    return jnp.where(untried.any(axis=1), jnp.argmax(untried, axis=1),
                     drawn).astype(jnp.int32)


def _epsilon_for_round(algorithm: str, round_num: int, batch_size: int,
                       epsilon: float, c: float, auer_d: float,
                       k: int, reward_diff: float) -> float:
    t = max((round_num - 1) * batch_size + 1, 1)
    if algorithm == "linear":
        return min(epsilon * c / t, epsilon)
    if algorithm == "logLinear":
        return min(epsilon * c * np.log(max(t, 2)) / t, epsilon)
    if algorithm == "auer":
        return min(auer_d * k / (max(reward_diff, 1e-6) ** 2 * t), 1.0)
    raise ValueError(f"unknown algorithm {algorithm!r}")


class GreedyRandomBandit:
    """ε-greedy family with decay schedules (incl. AuerGreedy ε_t)."""

    def __init__(self, algorithm: str = "linear", epsilon: float = 1.0,
                 prob_reduction_constant: float = 1.0, auer_constant: float = 1.0,
                 batch_size: int = 1):
        if algorithm not in ("linear", "logLinear", "auer"):
            raise ValueError(f"unknown algorithm {algorithm!r}")
        self.algorithm = algorithm
        self.epsilon = epsilon
        self.c = prob_reduction_constant
        self.auer_d = auer_constant
        self.batch_size = batch_size

    def select(self, key, counts: np.ndarray, rewards: np.ndarray,
               valid: np.ndarray, round_num: int) -> np.ndarray:
        rbar = np.where(counts > 0, rewards, 0.0)
        if self.algorithm == "auer":
            # per-group Δ = (max − second max)/max of mean rewards
            top2 = np.sort(np.where(valid, rbar, -np.inf), axis=1)[:, -2:]
            diff = np.where(top2[:, 1] > 0,
                            (top2[:, 1] - np.maximum(top2[:, 0], 0)) / np.maximum(top2[:, 1], 1e-9),
                            1.0)
            eps = np.array([
                _epsilon_for_round("auer", round_num, self.batch_size, self.epsilon,
                                   self.c, self.auer_d, valid.shape[1], float(d))
                for d in diff])
        else:
            e = _epsilon_for_round(self.algorithm, round_num, self.batch_size,
                                   self.epsilon, self.c, self.auer_d, valid.shape[1], 1.0)
            eps = np.full(counts.shape[0], e)
        return np.asarray(epsilon_greedy_select(
            key, jnp.asarray(counts, jnp.float32), jnp.asarray(rewards, jnp.float32),
            jnp.asarray(valid), jnp.asarray(eps, jnp.float32)))


class AuerDeterministicBandit:
    """UCB1 (deterministic)."""

    def select(self, key, counts, rewards, valid, round_num: int) -> np.ndarray:
        del round_num
        return np.asarray(ucb1_select(key, jnp.asarray(counts, jnp.float32),
                                      jnp.asarray(rewards, jnp.float32), jnp.asarray(valid)))


class SoftMaxBandit:
    def __init__(self, tau: float = 0.1):
        self.tau = tau

    def select(self, key, counts, rewards, valid, round_num: int) -> np.ndarray:
        del round_num
        return np.asarray(softmax_select(key, jnp.asarray(counts, jnp.float32),
                                         jnp.asarray(rewards, jnp.float32),
                                         jnp.asarray(valid), jnp.float32(self.tau)))


class RandomFirstGreedyBandit:
    """Explore-first: sweep arms round-robin for the exploration budget, then
    pure greedy."""

    def __init__(self, strategy: str = "simple", exploration_count_factor: int = 3,
                 reward_diff: float = 0.5, prob_diff: float = 0.1, batch_size: int = 1):
        if strategy not in ("simple", "pac"):
            raise ValueError(f"unknown strategy {strategy!r}")
        self.strategy = strategy
        self.factor = exploration_count_factor
        self.reward_diff = reward_diff
        self.prob_diff = prob_diff
        self.batch_size = batch_size

    def exploration_count(self, k: int) -> int:
        if self.strategy == "simple":
            return self.factor * k
        return int(4.0 / (self.reward_diff ** 2) + np.log(2.0 * k / self.prob_diff))

    def select(self, key, counts, rewards, valid, round_num: int) -> np.ndarray:
        g, k = counts.shape
        n_arms = valid.sum(axis=1)
        expl = np.array([self.exploration_count(int(ka)) for ka in n_arms])
        consumed = (round_num - 1) * self.batch_size
        remaining = expl - consumed
        # rolling window position (ExplorationCounter.java:52-77)
        idx = np.where(n_arms > 0, remaining % np.maximum(n_arms, 1), 0).astype(np.int64)
        greedy = np.asarray(_masked_argmax(
            mean_reward(jnp.asarray(counts, jnp.float32), jnp.asarray(rewards, jnp.float32)),
            jnp.asarray(valid)))
        return np.where(remaining > 0, idx, greedy).astype(np.int32)


ALGORITHM_REGISTRY = {
    "greedyRandomLinear": lambda **kw: GreedyRandomBandit("linear", **kw),
    "greedyRandomLogLinear": lambda **kw: GreedyRandomBandit("logLinear", **kw),
    "auerGreedy": lambda **kw: GreedyRandomBandit("auer", **kw),
    "auerDeterministic": lambda **kw: AuerDeterministicBandit(**kw),
    "softMax": lambda **kw: SoftMaxBandit(**kw),
    "randomFirstGreedy": lambda **kw: RandomFirstGreedyBandit(**kw),
}


# ---------------------------------------------------------------------------
# the job facade over group,item,count,reward rows
# ---------------------------------------------------------------------------

@dataclass
class GroupState:
    """Dense per-group arm state built from the reference's row format."""

    groups: List[str]
    items: List[List[str]]               # per group arm ids
    counts: np.ndarray                   # [G, K]
    rewards: np.ndarray                  # [G, K] mean reward
    valid: np.ndarray                    # [G, K] bool

    @classmethod
    def from_rows(cls, rows: Iterable[Sequence[str]], count_ord: int = 2,
                  reward_ord: int = 3) -> "GroupState":
        """``count_ord``/``reward_ord`` mirror the reference's
        ``count.ordinal``/``reward.ordinal`` config — the RunningAggregator
        loop feeds 5-column ``group,item,count,sum,avg`` rows with
        count.ordinal=2 / reward.ordinal=4
        (resource/price_optimize_tutorial.txt:70-90)."""
        by_group: Dict[str, List[Tuple[str, float, float]]] = {}
        for r in rows:
            by_group.setdefault(str(r[0]), []).append(
                (str(r[1]), float(r[count_ord]), float(r[reward_ord])))
        groups = sorted(by_group)
        k = max(len(v) for v in by_group.values())
        g = len(groups)
        counts = np.zeros((g, k), np.float64)
        rewards = np.zeros((g, k), np.float64)
        valid = np.zeros((g, k), bool)
        items: List[List[str]] = []
        for gi, grp in enumerate(groups):
            arms = by_group[grp]
            items.append([a for a, _, _ in arms])
            for ai, (_, cnt, rew) in enumerate(arms):
                counts[gi, ai] = cnt
                rewards[gi, ai] = rew
                valid[gi, ai] = True
        return cls(groups, items, counts, rewards, valid)

    def update(self, group: str, item: str, reward: float) -> None:
        gi = self.groups.index(group)
        ai = self.items[gi].index(item)
        c = self.counts[gi, ai]
        self.rewards[gi, ai] = (self.rewards[gi, ai] * c + reward) / (c + 1)
        self.counts[gi, ai] = c + 1

    def to_rows(self) -> List[List[str]]:
        out = []
        for gi, grp in enumerate(self.groups):
            for ai, item in enumerate(self.items[gi]):
                out.append([grp, item, str(int(self.counts[gi, ai])),
                            str(self.rewards[gi, ai])])
        return out


class BanditJob:
    """Round driver: rows in → per-group selection lines out (the MR job's
    CSV contract, minus the cluster)."""

    def __init__(self, algorithm: str, seed: int = 0, **kwargs):
        try:
            self.bandit = ALGORITHM_REGISTRY[algorithm](**kwargs)
        except KeyError:
            raise ValueError(f"unknown bandit algorithm {algorithm!r}; "
                             f"known: {sorted(ALGORITHM_REGISTRY)}") from None
        self.key = jax.random.PRNGKey(seed)

    def select(self, state: GroupState, round_num: int) -> List[Tuple[str, str]]:
        self.key, sub = jax.random.split(self.key)
        arm = self.bandit.select(sub, state.counts, state.rewards, state.valid, round_num)
        return [(g, state.items[gi][int(arm[gi])]) for gi, g in enumerate(state.groups)]

    def select_lines(self, rows: Iterable[Sequence[str]], round_num: int,
                     delim: str = ",", count_ord: int = 2,
                     reward_ord: int = 3) -> List[str]:
        state = GroupState.from_rows(rows, count_ord=count_ord,
                                     reward_ord=reward_ord)
        return [f"{g}{delim}{item}" for g, item in self.select(state, round_num)]
