"""Categorical correlation jobs — Cramér index and heterogeneity reduction.

Capability parity with the reference's correlation family:
``explore/CramerCorrelation.java`` (per-(src,dst) attribute-pair contingency
matrices aggregated map-side :152-182, Cramér index in the reducer :217-235),
``explore/CategoricalCorrelation.java`` (the same mapper as a reusable base
with a pluggable statistic hook :155-208), and
``explore/HeterogeneityReductionCorrelation.java`` (Gini concentration or
uncertainty coefficient selected by ``heterogeneity.algorithm`` :70-84).

TPU design: all (src, dst) pairs are evaluated in lockstep as a single
[P, B, B] pair-count einsum per chunk; the statistic is a vectorized map over
the leading pair axis. The pluggable-hook subclassing collapses into passing
a statistic name.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from avenir_tpu.core.encoding import EncodedDataset, peek_chunks
from avenir_tpu.ops import agg, info

STATS: Dict[str, Callable] = {
    "cramerIndex": info.cramer_index,
    "concentrationCoeff": info.concentration_coefficient,
    "uncertaintyCoeff": info.uncertainty_coefficient,
}


def _einsum_key_prefix(f: int, b_dst: int, pairs) -> str:
    """Einsum-path accumulator key prefix (chunk keys are
    ``"<prefix>:<chunk_start>"``), qualifying every key with a fingerprint
    of the pair list: num binned features, destination cardinality, pair
    count, and a digest of the actual (src, dst) index pairs — count
    alone would collide for different same-sized selections, e.g.
    ``src=[0,1,2]`` vs ``src=[3,4,5]``.  A checkpoint restored after the
    attribute lists change would otherwise carry same-named keys whose
    [P_chunk, B, B] partials are shape-compatible by accident yet count
    DIFFERENT pairs — the resume gate in ``fit`` rejects it loudly
    instead of silently summing incompatible partials.  Computed ONCE per
    fit (the digest is fit-invariant; hashing the pair list per chunk key
    would be pure hot-loop churn on wide schemas)."""
    import hashlib

    # canonicalize to python ints: repr of numpy scalars is type- and
    # version-dependent ('np.int64(3)' under numpy 2), and src/dst often
    # arrive as numpy arrays — the digest must depend on values only
    canon = repr([(int(a), int(b)) for a, b in pairs])
    digest = hashlib.blake2s(canon.encode(), digest_size=4).hexdigest()
    return f"c{f}x{b_dst}p{len(pairs)}h{digest}"


def result_from_counts(
    algorithm: str,
    pairs: List[Tuple[int, int]],
    pair_names: List[Tuple[str, str]],
    contingency: np.ndarray,
    n_bins: np.ndarray,
    num_classes: int,
) -> "CorrelationResult":
    """:class:`CorrelationResult` from an already-aggregated [P, Bd, Bd]
    contingency stack, without touching data — the finalize step of
    :meth:`CategoricalCorrelation.fit` and the SharedScan seam
    (``pipeline/scan.py``): every pair's contingency table is a read-out
    of the shared co-occurrence gram (class-summed for feature pairs, the
    [F, B, C] diagonal block for against-class pairs).  ``pairs`` use the
    fit contract (dst index −1 = the class attribute)."""
    if algorithm not in STATS:
        raise ValueError(f"unknown algorithm {algorithm!r}; known: {sorted(STATS)}")
    # statistic over the true (rows, cols) support of each pair; tiny
    # tensors — keep the per-pair ops on the local CPU backend
    stat = np.zeros(len(pairs))
    stat_fn = STATS[algorithm]
    with info.on_host():
        for k, (i, j) in enumerate(pairs):
            rows = int(n_bins[i])
            cols = int(num_classes) if j < 0 else int(n_bins[j])
            stat[k] = float(stat_fn(
                jnp.asarray(contingency[k, :rows, :cols], jnp.float32)))
    return CorrelationResult(
        pairs=pairs, pair_names=pair_names, stat=stat,
        algorithm=algorithm, contingency=contingency,
    )


@dataclass
class CorrelationResult:
    pairs: List[Tuple[int, int]]         # (src binned-index, dst binned-index)
    pair_names: List[Tuple[str, str]]
    stat: np.ndarray                     # [P]
    algorithm: str
    contingency: np.ndarray              # [P, B, B] counts

    def to_lines(self, delim: str = ",") -> List[str]:
        return [delim.join([a, b, f"{v:.6f}"])
                for (a, b), v in zip(self.pair_names, self.stat)]

    def top(self, k: int = 10) -> List[Tuple[Tuple[str, str], float]]:
        order = np.argsort(-self.stat)[:k]
        return [(self.pair_names[i], float(self.stat[i])) for i in order]


class CategoricalCorrelation:
    """All-pairs categorical association over binned features.

    ``src`` / ``dst`` are binned-feature indices (defaults: all × all i<j).
    To correlate features against the class attribute (the churn tutorial's
    use), pass ``against_class=True`` — the class column is treated as the
    destination variable of every pair.
    """

    def __init__(self, algorithm: str = "cramerIndex", pair_chunk: int = 512,
                 mesh=None):
        if algorithm not in STATS:
            raise ValueError(f"unknown algorithm {algorithm!r}; known: {sorted(STATS)}")
        self.algorithm = algorithm
        self.pair_chunk = pair_chunk
        self.mesh = mesh          # optional data mesh (parallel/mesh.py)

    def fit(
        self,
        data: Union[EncodedDataset, Iterable[EncodedDataset]],
        src: Optional[Sequence[int]] = None,
        dst: Optional[Sequence[int]] = None,
        against_class: bool = False,
        feature_names: Optional[Sequence[str]] = None,
        accumulator=None,
    ) -> CorrelationResult:
        """``accumulator``: an externally-owned accumulator (the
        multi-process jobs path injects one whose totals are merged across
        processes at end of stream — all counts here are exact integers, so
        the merge is order-free); by default a private one is used."""
        meta, chunks = peek_chunks(data)           # lazy: stream-friendly
        f, b = meta.num_binned, meta.max_bins
        names = list(feature_names) if feature_names is not None else [
            f"f{o}" for o in meta.binned_ordinals]
        if against_class:
            if meta.labels is None:
                raise ValueError("against_class requires labels")
            src_idx = list(src) if src is not None else list(range(f))
            pairs = [(i, -1) for i in src_idx]
            pair_names = [(names[i], "class") for i in src_idx]
        else:
            src_idx = list(src) if src is not None else list(range(f))
            dst_idx = list(dst) if dst is not None else list(range(f))
            pairs = [(i, j) for i in src_idx for j in dst_idx if i < j]
            pair_names = [(names[i], names[j]) for i, j in pairs]
        b_dst = max(b, meta.num_classes) if against_class else b
        acc = accumulator if accumulator is not None else agg.Accumulator()
        from avenir_tpu.parallel.mesh import maybe_shard_batch

        # single-TPU fast path: feature-pair contingency tables are exactly
        # the co-occurrence gram with ONE class (labels ≡ 0, W = F·B), and
        # against_class tables are the gram's [F, B, C] diagonal with the
        # real labels — so the MXU count kernel serves the Cramér/
        # heterogeneity jobs in both modes; the einsum stays for meshes
        # and CPU runs
        from avenir_tpu.ops import pallas_hist
        n_cls = meta.num_classes if against_class else 1
        fast = pallas_hist.use_kernel(f, b, n_cls, mesh=self.mesh)
        # layout-qualified kernel key + stale-path rejection (mirrors
        # mutual_info.py's resume gate): a checkpoint written on the OTHER
        # count path (or another kernel layout) must fail loudly — silently
        # preferring one key family would discard every chunk accumulated
        # under the other (pre- or post-resume) and corrupt the statistics
        gk = pallas_hist.g_key(f, b, n_cls) if fast else None
        ek = None if fast else _einsum_key_prefix(f, b_dst, pairs)
        if accumulator is not None:
            expected = {gk} if fast else {
                f"{ek}:{s}"
                for s in range(0, len(pairs), self.pair_chunk)}
            stale = [k for k in accumulator.names() if k not in expected]
            if stale:
                raise ValueError(
                    f"restored correlation accumulator holds keys {stale} "
                    f"incompatible with this run's count path "
                    f"({'kernel ' + gk if fast else 'einsum'}) or pair "
                    f"list (F={f}, B_dst={b_dst}, {len(pairs)} pairs); the "
                    f"snapshot was written under a different device/kernel "
                    f"layout or attribute selection — clear the checkpoint "
                    f"directory and re-run")
        for ds in chunks:
            codes, lab = maybe_shard_batch(self.mesh, ds.codes, ds.labels)
            if fast:
                y = lab if against_class else jnp.zeros(codes.shape[0],
                                                        jnp.int32)
                acc.add(gk, pallas_hist.cooc_counts(codes, y, b, n_cls))
                continue
            for s in range(0, len(pairs), self.pair_chunk):
                sl = pairs[s:s + self.pair_chunk]
                ci = codes[:, [p[0] for p in sl]]
                if against_class:
                    # codes.shape[0], not ds.num_rows: the sharded batch may
                    # carry count-neutral pad rows
                    cj = jnp.broadcast_to(lab[:, None], (codes.shape[0], len(sl)))
                else:
                    cj = codes[:, [p[1] for p in sl]]
                acc.add(f"{ek}:{s}", agg.pair_counts(ci, cj, b_dst))
        if fast and gk in acc and against_class:
            fbc, _ = pallas_hist.counts_from_cooc(
                acc.get(gk), f, b, n_cls, np.zeros(0, np.int64),
                np.zeros(0, np.int64))                   # [F, B, C]
            cont = np.zeros((len(pairs), b_dst, b_dst), fbc.dtype)
            cont[:, :b, :n_cls] = fbc[src_idx]
        elif fast and gk in acc:
            _, pair4 = pallas_hist.counts_from_cooc(
                acc.get(gk), f, b, 1,
                np.array([p[0] for p in pairs], np.int64),
                np.array([p[1] for p in pairs], np.int64))
            cont = pair4[:, :, :, 0]                     # [P, B, B]
        elif pairs:
            cont = np.concatenate([
                acc.get(f"{ek}:{s}")
                for s in range(0, len(pairs), self.pair_chunk)])
        else:
            cont = np.zeros((0, b_dst, b_dst), np.int64)
        return result_from_counts(self.algorithm, pairs, pair_names, cont,
                                  meta.n_bins, meta.num_classes)


class CramerCorrelation(CategoricalCorrelation):
    """Convenience subclass matching the reference job name."""

    def __init__(self, pair_chunk: int = 512, mesh=None):
        super().__init__("cramerIndex", pair_chunk, mesh=mesh)


class HeterogeneityReductionCorrelation(CategoricalCorrelation):
    """Concentration (Gini) or uncertainty coefficient, selected by the
    reference's ``heterogeneity.algorithm`` property values."""

    def __init__(self, algorithm: str = "concentrationCoeff", pair_chunk: int = 512,
                 mesh=None):
        super().__init__(algorithm, pair_chunk, mesh=mesh)
