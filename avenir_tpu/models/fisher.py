"""Univariate Fisher discriminant (binary LDA per attribute).

Capability parity with ``discriminant/FisherDiscriminant.java``: per-(attr,
class) count/mean/variance accumulation (the reference reuses chombo
``NumericalAttrStats`` mappers :56-58), then per attribute the pooled
variance, the log-odds of the class priors, and the decision boundary
``(μ₀+μ₁)/2 − logOdds·σ²_pooled/(μ₀−μ₁)`` (:83-96, reducer collect :98-117).

TPU design: all attributes' class-conditional moments come from one
:func:`avenir_tpu.ops.agg.class_moments` einsum; boundaries are a vectorized
closed form. Classification (not present in the reference job, which only
emits boundaries) follows naturally: predict class 1 when the value is on
class 1's side of the boundary.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Optional, Union

import jax.numpy as jnp
import numpy as np

from avenir_tpu.core.encoding import EncodedDataset
from avenir_tpu.ops import agg


@dataclass
class FisherDiscriminantModel:
    class_values: List[str]              # exactly two
    mean: np.ndarray                     # [2, F]
    var: np.ndarray                      # [2, F] unbiased per-class variance
    count: np.ndarray                    # [2]
    pooled_var: np.ndarray               # [F]
    log_odds: float                      # log(P(c1)/P(c0))
    boundary: np.ndarray                 # [F]

    def to_lines(self, feature_names: Optional[List[str]] = None, delim: str = ",") -> List[str]:
        names = feature_names or [f"f{i}" for i in range(self.mean.shape[1])]
        return [
            delim.join([
                names[f],
                repr(float(self.pooled_var[f])),
                repr(float(self.log_odds)),
                repr(float(self.boundary[f])),
            ])
            for f in range(self.mean.shape[1])
        ]


def model_from_moments(class_values: List[str], cnt: np.ndarray,
                       s1: np.ndarray, s2: np.ndarray) -> FisherDiscriminantModel:
    """:class:`FisherDiscriminantModel` from already-aggregated per-class
    (count [2], Σx [2, F], Σx² [2, F]) moment sums, without touching data —
    the finalize step of :meth:`FisherDiscriminant.fit` and the SharedScan
    seam (``pipeline/scan.py``): the moments come from the same
    ``class_moments`` contraction the shared scan runs on its resident
    chunk, fused into the gram dispatch."""
    if len(class_values) != 2:
        raise ValueError("Fisher discriminant requires exactly two classes")
    if s1.shape[1] == 0:
        raise ValueError("Fisher discriminant requires continuous features")
    cnt = np.asarray(cnt, np.float64)                 # [2]
    s1 = np.asarray(s1, np.float64)                   # [2, F]
    s2 = np.asarray(s2, np.float64)
    n = np.maximum(cnt, 1.0)[:, None]
    mean = s1 / n
    var_b = np.maximum(s2 / n - mean ** 2, 1e-12)
    var = var_b * (n / np.maximum(n - 1.0, 1.0))      # unbiased, as (n−1) division
    pooled = (((n - 1.0) * var).sum(axis=0) / np.maximum(cnt.sum() - 2.0, 1.0))
    log_odds = float(np.log(max(cnt[1], 1e-9) / max(cnt[0], 1e-9)))
    delta = mean[0] - mean[1]
    safe_delta = np.where(np.abs(delta) > 1e-9, delta, 1e-9)
    boundary = (mean[0] + mean[1]) / 2.0 - log_odds * pooled / safe_delta
    return FisherDiscriminantModel(
        class_values=list(class_values), mean=mean, var=var, count=cnt,
        pooled_var=pooled, log_odds=log_odds, boundary=boundary,
    )


class FisherDiscriminant:
    def __init__(self, mesh=None):
        self.mesh = mesh          # optional data mesh (parallel/mesh.py)

    def fit(self, data: Union[EncodedDataset, Iterable[EncodedDataset]]) -> FisherDiscriminantModel:
        from avenir_tpu.parallel.mesh import maybe_shard_batch
        chunks = [data] if isinstance(data, EncodedDataset) else data
        acc = agg.Accumulator()
        meta = None
        for ds in chunks:
            meta = ds
            if ds.labels is None:
                raise ValueError("fit requires labels")
            cont_b, lab_b = maybe_shard_batch(self.mesh, ds.cont, ds.labels)
            cnt, s1, s2 = agg.class_moments(cont_b, lab_b,
                                            ds.num_classes)
            acc.add("cnt", cnt)
            acc.add("s1", s1)
            acc.add("s2", s2)
        if meta is None:
            from avenir_tpu.core.encoding import NoDataError
            raise NoDataError("no data")
        if meta.num_classes != 2:
            raise ValueError("Fisher discriminant requires exactly two classes")
        if meta.num_cont == 0:
            raise ValueError("Fisher discriminant requires continuous features")
        return model_from_moments(list(meta.class_values), acc.get("cnt"),
                                  acc.get("s1"), acc.get("s2"))

    @staticmethod
    def predict(model: FisherDiscriminantModel, values: np.ndarray, attr: int = 0) -> np.ndarray:
        """[N] class index using a single attribute's boundary: side of the
        boundary closer to class 1's mean wins."""
        b = model.boundary[attr]
        class1_above = model.mean[1, attr] > model.mean[0, attr]
        above = values[:, attr] > b
        return np.where(above == class1_above, 1, 0).astype(np.int32)
