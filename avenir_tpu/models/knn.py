"""k-nearest-neighbor engine — tiled all-pairs distance + top-k on device.

Capability parity with the reference's kNN stack: the external all-pairs
distance job it outsources to sifarish ``SameTypeSimilarity``
(resource/knn.sh:47-60, per-attribute distances scaled to ints by
``distance.scale``), ``knn/NearestNeighbor.java`` (top ``top.match.count``
neighbors via secondary sort :317-349) and ``knn/Neighborhood.java``:

- kernels none / linearMultiplicative (SCALE/d) / linearAdditive (SCALE−d) /
  gaussian (SCALE·exp(−½(d/σ)²)) (:150-218 with KERNEL_SCALE :38);
- class-conditional probability weighting — each neighbor's vote scaled by
  its Naive-Bayes posterior for its own class (:207-217; the reference
  obtains these via the BayesianPredictor→FeatureCondProbJoiner pipeline
  stages, replaced here by passing the [N, C] posterior array directly);
- inverse-distance weighting (:242 in NearestNeighbor);
- classification by argmax, positive-score-ratio decision threshold
  (:253-262), or cost-based arbitration (:264-278);
- regression average / median / linear (SimpleRegression over a chosen input
  field, Neighborhood.java:223-250);
- validation-mode confusion matrix (:280-311).

TPU design: distances are computed test-tile × train-tile entirely as
matmuls — categorical mismatch counts via a flattened one-hot product and
numeric squared distance via the ‖a‖²+‖b‖²−2a·b expansion — so the O(M·N)
hot loop the reference farms out to a Hadoop job runs on the MXU. Top-k is
maintained with a running ``lax.top_k`` merge across train tiles, never
materializing the full distance matrix (SURVEY.md §7 'top-k at 1M×N scale').
Distances are true floats in [0, 1]; the reference's ×1000 integer scaling is
applied only in the serde view (a documented deliberate fix).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.core.encoding import EncodedDataset
from avenir_tpu.ops import agg
from avenir_tpu.utils.metrics import ConfusionMatrix, CostBasedArbitrator, Counters

KERNELS = ("none", "linearMultiplicative", "linearAdditive", "gaussian")

# The fused Pallas TPU kernel (ops/pallas_knn.py) is used automatically on
# TPU backends for the euclidean metric; set to False to force the XLA scan.
USE_PALLAS = True


@dataclass
class KNNModel:
    """Reference set held on device-ready arrays."""

    codes: np.ndarray                   # [N, F] int32 categorical/binned codes
    cont: np.ndarray                    # [N, Fc] float32 raw continuous
    labels: Optional[np.ndarray]        # [N] class ids (classification)
    values: Optional[np.ndarray]        # [N] float regression targets
    class_probs: Optional[np.ndarray]   # [N, C] NB posteriors (class-cond weighting)
    n_bins: np.ndarray
    class_values: List[str]
    cont_lo: np.ndarray                 # [Fc] train min (normalization)
    cont_hi: np.ndarray                 # [Fc] train max

    @property
    def num_refs(self) -> int:
        return self.codes.shape[0] if self.codes.size else self.cont.shape[0]

    def cont01(self) -> np.ndarray:
        """Train-range-normalized continuous columns (cached)."""
        c = self.__dict__.get("_cont01")
        if c is None:
            c = self.__dict__["_cont01"] = _normalize01(
                self.cont, self.cont_lo, self.cont_hi)
        return c

    def device_packed(self, num_bins: int):
        """Packed bf16 operand for the fused pallas kernel (cached: repeated
        queries must not re-pack or re-upload the reference set)."""
        from avenir_tpu.ops import pallas_knn
        cache = self.__dict__.setdefault("_dev_packed", {})
        if num_bins not in cache:
            cache[num_bins] = pallas_knn.prepare_refs(
                self.codes, self.cont01(), num_bins)
        return cache[num_bins]

    def device_rerank_arrays(self):
        """Reference codes + normalized continuous columns resident on
        device (cached) — the fused search's exact re-rank gathers candidate
        rows from these instead of running single-core numpy per batch."""
        import jax.numpy as jnp
        c = self.__dict__.get("_dev_rerank")
        if c is None:
            c = self.__dict__["_dev_rerank"] = (
                jnp.asarray(self.codes), jnp.asarray(self.cont01()))
        return c

    def device_tiles(self, ref_tile: int):
        """Reference set as resident device arrays [T, ref_tile, ·], padded to
        a whole number of tiles (pad rows masked out by index in the scan).
        Cached per tile size: repeated queries must not re-upload the refs."""
        cache = self.__dict__.setdefault("_dev_tiles", {})
        if ref_tile not in cache:
            n = self.num_refs
            t = max(-(-n // ref_tile), 1)
            pad = t * ref_tile - n
            codes = np.pad(self.codes, ((0, pad), (0, 0)))
            cont = np.pad(self.cont, ((0, pad), (0, 0)))
            cache[ref_tile] = (
                jnp.asarray(codes.reshape(t, ref_tile, -1)),
                jnp.asarray(cont.reshape(t, ref_tile, -1)),
            )
        return cache[ref_tile]


def fit_knn(
    ds: EncodedDataset,
    values: Optional[np.ndarray] = None,
    class_probs: Optional[np.ndarray] = None,
) -> KNNModel:
    lo = ds.cont.min(axis=0) if ds.num_cont else np.zeros(0, np.float32)
    hi = ds.cont.max(axis=0) if ds.num_cont else np.zeros(0, np.float32)
    return KNNModel(
        codes=ds.codes, cont=ds.cont, labels=ds.labels,
        values=None if values is None else np.asarray(values, np.float32),
        class_probs=None if class_probs is None else np.asarray(class_probs, np.float32),
        n_bins=ds.n_bins, class_values=list(ds.class_values),
        cont_lo=lo.astype(np.float32), cont_hi=hi.astype(np.float32),
    )


# ---------------------------------------------------------------------------
# tiled distance + running top-k
# ---------------------------------------------------------------------------

def _normalize_cont(cont, lo, hi):
    span = jnp.maximum(hi - lo, 1e-9)
    return jnp.clip((cont - lo) / span, 0.0, 1.0)


def _normalize01(cont: np.ndarray, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    span = np.maximum(hi - lo, 1e-9)
    return np.clip((cont - lo) / span, 0.0, 1.0).astype(np.float32)


def _tile_distances(
    test_codes: jax.Array, test_cont: jax.Array,     # [M, F], [M, Fc]
    ref_codes: jax.Array, ref_cont: jax.Array,       # [T, F], [T, Fc]
    cont_lo: jax.Array, cont_hi: jax.Array,
    num_bins: int, metric: str = "euclidean",
) -> jax.Array:
    """[M, T] mean per-attribute distance in [0, 1].

    Categorical attribute distance = 0/1 mismatch; numeric = |Δ| on the
    train-range-normalized value (squared for euclidean). Both lower to
    matmuls: mismatch count = F − ⟨onehot, onehot⟩, squared numeric distance
    via the norm expansion.
    """
    m = test_codes.shape[0] if test_codes.ndim else 0
    f = test_codes.shape[1]
    fc = test_cont.shape[1]
    total_attrs = max(f + fc, 1)
    parts = []
    if f:
        a = agg.one_hot(test_codes, num_bins).reshape(test_codes.shape[0], -1)
        bmat = agg.one_hot(ref_codes, num_bins).reshape(ref_codes.shape[0], -1)
        matches = jnp.einsum("mk,tk->mt", a, bmat, precision="highest")
        parts.append(f - matches)                                  # mismatch count
    if fc:
        x = _normalize_cont(test_cont, cont_lo, cont_hi)
        y = _normalize_cont(ref_cont, cont_lo, cont_hi)
        if metric == "euclidean":
            sq = (jnp.sum(x * x, 1)[:, None] + jnp.sum(y * y, 1)[None, :]
                  - 2.0 * jnp.einsum("mf,tf->mt", x, y, precision="highest"))
            parts.append(jnp.maximum(sq, 0.0))
        else:  # manhattan — no matmul form; fine for small Fc
            parts.append(jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1))
    d = sum(parts) / total_attrs
    if metric == "euclidean":
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    return jnp.clip(d, 0.0, 1.0)


@functools.partial(jax.jit,
                   static_argnames=("k", "num_bins", "metric", "approx"))
def _topk_over_tiles(test_codes, test_cont, ref_codes_t, ref_cont_t, n_real,
                     cont_lo, cont_hi, k: int, num_bins: int, metric: str,
                     approx: bool = False):
    """One compiled pass: lax.scan over resident reference tiles
    ([T, tile, ·]), fusing distance + running top-k merge, so the N×M
    distance matrix never materializes and no per-tile dispatch/upload
    happens. Pad rows (index ≥ n_real) are masked to +inf.

    ``approx=True`` swaps only the per-tile candidate selection for
    ``jax.lax.approx_min_k`` (the TPU PartialReduce unit; measured 0.9988
    end-to-end recall at 1M refs / k=10, BASELINE.md — on CPU/GPU backends
    approx_min_k falls back to exact top-k). The cross-tile merge of the 2k
    running candidates stays exact either way, so recall loss is bounded to
    the within-tile approximation."""
    m = test_codes.shape[0] if test_codes.size else test_cont.shape[0]
    tile = ref_codes_t.shape[1] if ref_codes_t.size else ref_cont_t.shape[1]

    def body(carry, xs):
        best_d, best_i, t0 = carry
        rc, rx = xs
        d = _tile_distances(test_codes, test_cont, rc, rx,
                            cont_lo, cont_hi, num_bins, metric)
        idx = t0 + jnp.arange(tile, dtype=jnp.int32)
        d = jnp.where(idx[None, :] < n_real, d, jnp.inf)
        if approx:
            td, tpos = jax.lax.approx_min_k(d, k)
            ti = t0 + tpos.astype(jnp.int32)
        else:
            td, ti = d, jnp.broadcast_to(idx[None, :], d.shape)
        cd = jnp.concatenate([best_d, td], axis=1)
        cix = jnp.concatenate([best_i, ti], axis=1)
        neg, pos = jax.lax.top_k(-cd, k)
        return (-neg, jnp.take_along_axis(cix, pos, axis=1),
                t0 + jnp.int32(tile)), None

    best_d = jnp.full((m, k), jnp.inf, jnp.float32)
    best_i = jnp.full((m, k), -1, jnp.int32)
    (best_d, best_i, _), _ = jax.lax.scan(
        body, (best_d, best_i, jnp.int32(0)), (ref_codes_t, ref_cont_t))
    return best_d, best_i


def _pallas_available(metric: str, k: int) -> bool:
    if not USE_PALLAS or metric != "euclidean":
        return False
    from avenir_tpu.ops import pallas_knn
    if k + 1 > pallas_knn.SLOTS:
        return False
    try:
        # the Mosaic kernel lowers on TPU only — never dispatch it on gpu
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _nearest_neighbors_pallas(model: KNNModel, test: EncodedDataset, k: int
                              ) -> Tuple[np.ndarray, np.ndarray]:
    """Fused-kernel path: ONE jitted dispatch runs query pack → pallas
    candidate kernel → exact f32 re-rank + per-row exactness certificate
    (ops/pallas_knn.py::search_fused). Host work per batch is only the raw
    query transfer and the tiny [M,k] result read-back — the single-core
    numpy pack/re-rank and the extra device round-trip the previous
    host-side path paid (~115 ms + ~100 ms per 4096-query batch on the dev
    rig) are gone."""
    from avenir_tpu.ops import pallas_knn
    nb = int(model.n_bins.max()) if model.n_bins.size else 1
    r_mat, n = model.device_packed(nb)
    codes_r_dev, cont01_r_dev = model.device_rerank_arrays()
    cont01_q = _normalize01(test.cont, model.cont_lo, model.cont_hi)
    d_dev, i_dev, cert_dev = pallas_knn.search_fused(
        test.codes, cont01_q, r_mat, codes_r_dev, cont01_r_dev, n, nb, k,
        test.codes.shape[1] + test.cont.shape[1])
    d = np.asarray(d_dev)
    idx = np.asarray(i_dev)
    cert = np.asarray(cert_dev)
    if not cert.all():
        # np.asarray of a device array is a read-only view; the fallback
        # writes row-wise
        d, idx = d.copy(), idx.copy()
        # certificate failed for some rows (approx candidate set might miss a
        # true neighbor): recompute those rows with the exact XLA scan
        rows = np.flatnonzero(~cert)
        sub = EncodedDataset(
            codes=test.codes[rows], cont=test.cont[rows],
            labels=None if test.labels is None else test.labels[rows],
            ids=None, n_bins=test.n_bins, class_values=test.class_values,
            binned_ordinals=test.binned_ordinals,
            cont_ordinals=test.cont_ordinals)
        d_sub, i_sub = _nearest_neighbors_xla(model, sub, k, "euclidean",
                                              65536, 8192)
        d[rows] = d_sub
        idx[rows] = i_sub
    return d, idx


def _shard_rows(n: int, d_par: int) -> int:
    """ceil(n / d_par) — the per-device shard row count; one spelling shared
    by the mesh routing gate and the sharded search path."""
    return max(-(-n // d_par), 1)


def _pad_topk(d: np.ndarray, i: np.ndarray, k: int, k_eff: int
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Keep the [M, k] contract when the reference set has fewer than k
    rows: pad with +inf distances and -1 indices."""
    if k_eff < k:
        d = np.pad(d, ((0, 0), (0, k - k_eff)), constant_values=np.inf)
        i = np.pad(i, ((0, 0), (0, k - k_eff)), constant_values=-1)
    return d, i


def _nearest_neighbors_sharded(model: KNNModel, test: EncodedDataset, k: int,
                               metric: str, mesh, test_tile: int,
                               ref_tile: int = 65536,
                               ) -> Tuple[np.ndarray, np.ndarray]:
    """Reference rows sharded over the mesh's ``data`` axis, exact global
    top-k via one all_gather merge (parallel/collectives.sharded_knn_topk,
    lru-cached so repeated queries reuse the compiled program). The sharded
    reference set is cached on the model like device_tiles; each device
    scans its shard in ``ref_tile``-row tiles, so per-device memory is
    bounded exactly like the single-device scan."""
    from avenir_tpu.parallel import collectives
    from avenir_tpu.parallel.mesh import data_sharding, pad_batch

    n = model.num_refs
    d_par = mesh.shape["data"]
    nb = int(model.n_bins.max()) if model.n_bins.size else 1
    k_eff = min(k, n)
    shard = _shard_rows(n, d_par)
    tile = min(ref_tile, shard)
    padded_local = -(-shard // tile) * tile        # whole tiles per device
    npad = padded_local * d_par
    cache = model.__dict__.setdefault("_dev_sharded", {})
    key = (mesh, tile)                             # Mesh is hashable
    if key not in cache:
        # pad fill −1 is safe: pad rows are masked by global index ≥ n_real
        rc, rx = pad_batch(npad, model.codes, model.cont)
        cache[key] = (jax.device_put(rc, data_sharding(mesh, 2)),
                      jax.device_put(rx, data_sharding(mesh, 2)))
    rc_s, rx_s = cache[key]
    step = collectives.sharded_knn_topk(mesh, k=k_eff, num_bins=nb,
                                        metric=metric, ref_tile=tile)
    lo, hi = jnp.asarray(model.cont_lo), jnp.asarray(model.cont_hi)
    out_d, out_i = [], []
    for m0 in range(0, test.num_rows, test_tile):
        bd, bi = step(jnp.asarray(test.codes[m0:m0 + test_tile]),
                      jnp.asarray(test.cont[m0:m0 + test_tile]),
                      rc_s, rx_s, lo, hi, jnp.int32(n))
        out_d.append(np.asarray(bd))
        out_i.append(np.asarray(bi))
    return _pad_topk(np.concatenate(out_d), np.concatenate(out_i), k, k_eff)


def nearest_neighbors(
    model: KNNModel, test: EncodedDataset, k: int,
    metric: str = "euclidean", ref_tile: int = 65536, test_tile: int = 8192,
    mode: str = "exact", mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """([M, k] distances, [M, k] reference indices), ascending by distance.

    ``mode="exact"`` (default): on TPU backends the euclidean metric
    dispatches to the fused Pallas search (segment key-tournament + exact
    re-rank, ~9× the XLA scan at 1M refs — BASELINE.md); everything else
    uses the compiled XLA tile scan. ``mode="approx"``: a quality floor,
    not a method — when the fused exact path applies it is BOTH faster and
    exact, so an approx request routes there (≥-quality results, like the
    sharded route below); only configurations the kernel cannot serve
    (manhattan metric, k > kernel slots, non-TPU backends) run the
    per-tile ``lax.approx_min_k`` + exact cross-tile merge (0.9988
    measured end-to-end recall at 1M refs, k=10) — a capability knob the
    reference has no analog for, OFF unless asked for."""
    if mode not in ("exact", "approx"):
        raise ValueError(f"unknown search mode {mode!r}; use exact|approx")
    if mesh is not None and mesh.shape.get("data", 1) > 1:
        # the sharded-reference path is exact AND parallel, so it serves
        # both modes (an approx request gets ≥-quality results); the
        # all_gather merge needs k candidates per device shard
        if min(k, model.num_refs) <= _shard_rows(model.num_refs,
                                                 mesh.shape["data"]):
            return _nearest_neighbors_sharded(model, test, k, metric, mesh,
                                              test_tile, ref_tile)
    if _pallas_available(metric, k) and min(k, model.num_refs) == k:
        return _nearest_neighbors_pallas(model, test, k)
    if mode == "approx":
        return _nearest_neighbors_xla(model, test, k, metric, ref_tile,
                                      test_tile, approx=True)
    return _nearest_neighbors_xla(model, test, k, metric, ref_tile, test_tile)


def _nearest_neighbors_xla(
    model: KNNModel, test: EncodedDataset, k: int,
    metric: str = "euclidean", ref_tile: int = 65536, test_tile: int = 8192,
    approx: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    n = model.num_refs
    nb = int(model.n_bins.max()) if model.n_bins.size else 1
    lo, hi = jnp.asarray(model.cont_lo), jnp.asarray(model.cont_hi)
    ref_tile = min(ref_tile, max(-(-n // 8), 1024))   # ≤8 scan steps small-N
    rc_t, rx_t = model.device_tiles(ref_tile)
    k_eff = min(k, n)
    out_d, out_i = [], []
    for m0 in range(0, test.num_rows, test_tile):
        tc = jnp.asarray(test.codes[m0:m0 + test_tile])
        tx = jnp.asarray(test.cont[m0:m0 + test_tile])
        best_d, best_i = _topk_over_tiles(
            tc, tx, rc_t, rx_t, jnp.int32(n), lo, hi, k_eff, nb, metric,
            approx=approx)
        out_d.append(np.asarray(best_d))
        out_i.append(np.asarray(best_i))
    # degenerate tiny reference sets: keep the [M, k] shape
    return _pad_topk(np.concatenate(out_d), np.concatenate(out_i), k, k_eff)


# ---------------------------------------------------------------------------
# neighborhood scoring
# ---------------------------------------------------------------------------

def kernel_weights(dists: np.ndarray, kernel: str, sigma: float = 0.3,
                   inverse_distance: bool = False) -> np.ndarray:
    """[M, k] vote weights from [0,1] distances (float forms of
    Neighborhood.java's integer-scaled kernels)."""
    if kernel == "none":
        w = np.ones_like(dists)
    elif kernel == "linearMultiplicative":
        w = 1.0 / np.maximum(dists, 5e-4)          # d==0 → 2×SCALE in the reference
    elif kernel == "linearAdditive":
        w = 1.0 - dists
    elif kernel == "gaussian":
        w = np.exp(-0.5 * (dists / max(sigma, 1e-6)) ** 2)
    else:
        raise ValueError(f"unknown kernel {kernel!r}; known: {KERNELS}")
    if inverse_distance and kernel not in ("linearMultiplicative",):
        w = w / np.maximum(dists, 5e-4)
    return w


@dataclass
class KNNResult:
    predicted: np.ndarray              # [M]
    class_scores: np.ndarray           # [M, C] normalized vote shares
    neighbor_idx: np.ndarray           # [M, k]
    neighbor_dist: np.ndarray          # [M, k]
    confusion: Optional[ConfusionMatrix] = None
    counters: Optional[Counters] = None


class KNN:
    """Estimator facade: classification + regression over a fitted model."""

    def __init__(
        self,
        k: int = 5,
        metric: str = "euclidean",
        kernel: str = "none",
        kernel_sigma: float = 0.3,
        inverse_distance: bool = False,
        class_cond_weighting: bool = False,
        decision_threshold: Optional[float] = None,
        pos_class: Optional[str] = None,
        cost: Optional[np.ndarray] = None,
        ref_tile: int = 65536,
        test_tile: int = 8192,
        search_mode: str = "exact",
        mesh=None,
    ):
        if kernel not in KERNELS:
            raise ValueError(f"unknown kernel {kernel!r}; known: {KERNELS}")
        if search_mode not in ("exact", "approx"):
            raise ValueError(f"unknown search_mode {search_mode!r}; use exact|approx")
        self.k = k
        self.metric = metric
        self.search_mode = search_mode
        self.kernel = kernel
        self.kernel_sigma = kernel_sigma
        self.inverse_distance = inverse_distance
        self.class_cond_weighting = class_cond_weighting
        self.decision_threshold = decision_threshold
        self.pos_class = pos_class
        self.cost = cost
        self.ref_tile = ref_tile
        self.test_tile = test_tile
        self.mesh = mesh          # optional data mesh: shards the reference set

    def fit(self, ds: EncodedDataset, values: Optional[np.ndarray] = None,
            class_probs: Optional[np.ndarray] = None) -> KNNModel:
        return fit_knn(ds, values=values, class_probs=class_probs)

    # -- classification ------------------------------------------------------
    def predict(self, model: KNNModel, test: EncodedDataset,
                validate: bool = False) -> KNNResult:
        if model.labels is None:
            raise ValueError("classification requires labels in the reference set")
        dists, idx = nearest_neighbors(model, test, self.k, self.metric,
                                       self.ref_tile, self.test_tile,
                                       mode=self.search_mode, mesh=self.mesh)
        w = kernel_weights(dists, self.kernel, self.kernel_sigma, self.inverse_distance)
        neigh_labels = model.labels[idx]                        # [M, k]
        c = len(model.class_values)
        if self.class_cond_weighting:
            if model.class_probs is None:
                raise ValueError("class_cond_weighting requires class_probs in the model")
            post = np.take_along_axis(model.class_probs[idx], neigh_labels[..., None],
                                      axis=2)[..., 0]           # [M, k]
            w = w * post
        scores = np.zeros((dists.shape[0], c), np.float32)
        for cls in range(c):
            scores[:, cls] = (w * (neigh_labels == cls)).sum(axis=1)
        shares = scores / np.maximum(scores.sum(axis=1, keepdims=True), 1e-9)
        if self.cost is not None:
            predicted = CostBasedArbitrator(model.class_values, self.cost).arbitrate(shares)
        elif self.decision_threshold is not None:
            # binary pos-score threshold, as in NearestNeighbor.java:253-262
            if self.pos_class is None:
                raise ValueError("decision_threshold requires pos_class")
            if c != 2:
                raise ValueError("decision_threshold supports binary classification only")
            p = model.class_values.index(self.pos_class)
            predicted = np.where(shares[:, p] >= self.decision_threshold, p, 1 - p).astype(np.int32)
        else:
            predicted = np.argmax(shares, axis=1).astype(np.int32)
        result = KNNResult(predicted=predicted, class_scores=shares,
                           neighbor_idx=idx, neighbor_dist=dists)
        if validate:
            if test.labels is None:
                raise ValueError("validation requires test labels")
            cm = ConfusionMatrix(model.class_values, pos_class=self.pos_class)
            cm.add_batch(test.labels, predicted)
            counters = Counters()
            cm.publish(counters)
            result.confusion = cm
            result.counters = counters
        return result

    # -- regression ----------------------------------------------------------
    def regress(self, model: KNNModel, test: EncodedDataset,
                method: str = "average",
                input_var: Optional[np.ndarray] = None,
                ref_input_var: Optional[np.ndarray] = None) -> np.ndarray:
        """[M] predictions. ``linear`` fits a per-test-record simple
        regression of neighbor target on ``ref_input_var`` evaluated at the
        test record's ``input_var`` (Neighborhood.java:244-250)."""
        if model.values is None:
            raise ValueError("regression requires target values in the model")
        dists, idx = nearest_neighbors(model, test, self.k, self.metric,
                                       self.ref_tile, self.test_tile,
                                       mode=self.search_mode, mesh=self.mesh)
        vals = model.values[idx]                                # [M, k]
        if method == "average":
            w = kernel_weights(dists, self.kernel, self.kernel_sigma, self.inverse_distance)
            return (w * vals).sum(1) / np.maximum(w.sum(1), 1e-9)
        if method == "median":
            return np.median(vals, axis=1)
        if method == "linear":
            if input_var is None or ref_input_var is None:
                raise ValueError("linear regression requires input_var and ref_input_var")
            x = ref_input_var[idx].astype(np.float64)           # [M, k]
            y = vals.astype(np.float64)
            xm, ym = x.mean(1, keepdims=True), y.mean(1, keepdims=True)
            sxx = ((x - xm) ** 2).sum(1)
            sxy = ((x - xm) * (y - ym)).sum(1)
            slope = np.where(sxx > 1e-12, sxy / np.maximum(sxx, 1e-12), 0.0)
            intercept = ym[:, 0] - slope * xm[:, 0]
            return slope * np.asarray(input_var, np.float64) + intercept
        raise ValueError(f"unknown regression method {method!r}")


# ---------------------------------------------------------------------------
# all-pairs distance serde (the sifarish SameTypeSimilarity drop-in view)
# ---------------------------------------------------------------------------

def pairwise_distance_lines(
    model: KNNModel, test: EncodedDataset, test_ids: Sequence[str],
    k: int, distance_scale: int = 1000, delim: str = ",",
    metric: str = "euclidean", ref_ids: Optional[Sequence[str]] = None,
) -> List[str]:
    """(testID, refID, scaledIntDistance) rows — the record-pair distance
    file format the reference's pipeline stages exchange. ``ref_ids``
    defaults to reference-row indices."""
    dists, idx = nearest_neighbors(model, test, k, metric)
    if ref_ids is None:
        ref_ids = [str(i) for i in range(model.num_refs)]
    else:
        ref_ids = [str(r) for r in ref_ids]
    lines = []
    for m, tid in enumerate(test_ids):
        for j in range(k):
            lines.append(delim.join([
                str(tid), ref_ids[idx[m, j]], str(int(round(dists[m, j] * distance_scale)))]))
    return lines
