"""Binary logistic regression — compiled full-batch gradient loop.

Capability parity with the reference's iterative MR trainer
(regress/LogisticRegressionJob.java): per-mapper gradient accumulation
Σ x·(y−σ(wᵀx)) (:178-195 via regress/LogisticRegressor.java:61-73), single
reducer summing partials (:261-273), coefficient history appended per
iteration to a file that doubles as checkpoint/resume (:238-255), driver loop
re-submitting until converged (:279-289), convergence = iteration limit or
all/average relative coefficient delta below a percent threshold (:95-119
via LogisticRegressor.java:105-163).

Deliberate fixes (SURVEY.md §6 notes the reference emits raw aggregates as
the next coefficients with no learning-rate application): a real
gradient-ascent update with learning rate and optional L2, on float
probabilities. The convergence criteria and the append-only coefficient
history contract are preserved.

TPU design: one jitted step computes the full-batch gradient as a matvec
(batch-sharded under a mesh, XLA all-reduces the partials — exactly the
mapper/reducer split); the Python driver loop owns the history/convergence,
mirroring the reference's multi-job driver but in-process.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field as dc_field
from typing import Iterable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.core.encoding import EncodedDataset


def design_matrix(ds: EncodedDataset, include_binned: bool = True,
                  intercept: bool = True) -> np.ndarray:
    """[N, D] float design matrix: continuous features, one-hot binned
    features (the TPU-friendly encoding of categoricals), optional leading
    intercept column."""
    parts = []
    if intercept:
        parts.append(np.ones((ds.num_rows, 1), np.float32))
    if ds.num_cont:
        parts.append(ds.cont)
    if include_binned and ds.num_binned:
        onehot = np.eye(ds.max_bins, dtype=np.float32)[ds.codes]     # [N, F, B]
        mask = ds.bin_mask()                                          # [F, B]
        parts.append(onehot[:, mask])
    return np.concatenate(parts, axis=1) if parts else np.zeros((ds.num_rows, 0), np.float32)


@jax.jit
def _grad_step(w: jax.Array, x: jax.Array, y: jax.Array, n: jax.Array,
               lr: jax.Array, l2: jax.Array) -> jax.Array:
    """One full-batch gradient-ascent step on the log-likelihood.

    ``n`` is the TRUE row count — under a data mesh the batch may carry
    zero pad rows (x=0 ⇒ zero gradient contribution) that must not dilute
    the 1/n scaling."""
    p = jax.nn.sigmoid(x @ w)
    grad = x.T @ (y - p) / n - l2 * w
    return w + lr * grad


@jax.jit
def _chunk_grad(w: jax.Array, x: jax.Array, y: jax.Array) -> jax.Array:
    """One chunk's UNSCALED gradient partial Σ x·(y−σ(wᵀx)) — the exact
    quantity a reference mapper emitted in cleanup
    (LogisticRegressionJob.java:169-176 via LogisticRegressor.java:61-73)."""
    p = jax.nn.sigmoid(x @ w)
    return x.T @ (y - p)


@jax.jit
def _sigmoid_scores(w: jax.Array, x: jax.Array) -> jax.Array:
    """[N] σ(x·w) — the scoring matvec, jitted so the serving plane runs it
    from device-resident weights against its fixed bucket shapes."""
    return jax.nn.sigmoid(x @ w)


def predict_batch(model_or_weights, x: np.ndarray,
                  threshold: float = 0.5) -> Tuple[np.ndarray, np.ndarray]:
    """([N] f32 probabilities, [N] int32 0/1 labels) from the jitted device
    scorer — the thin online-scoring entry the batch trainer never needed
    (the reference scores LR offline through generic chombo tooling; the
    serving plane is this port's first LR scoring surface).  Per-row dot
    products make the result independent of the batch padding the serving
    microbatcher applies.  Accepts a :class:`LogisticRegressionModel` or a
    raw weight vector (pass a pre-uploaded ``jax.Array`` to keep the
    weights device-resident across calls)."""
    w = getattr(model_or_weights, "weights", model_or_weights)
    if not isinstance(w, jax.Array):
        w = jnp.asarray(np.asarray(w), jnp.float32)
    probs = np.asarray(_sigmoid_scores(w, jnp.asarray(np.asarray(x, np.float32))))
    return probs, (probs >= threshold).astype(np.int32)


def _converged(prev: np.ndarray, cur: np.ndarray, criterion: str,
               threshold_pct: float) -> bool:
    """Relative per-coefficient change in percent (LogisticRegressor.java:105-163):
    'all' = every coefficient under threshold, 'average' = mean under it."""
    denom = np.maximum(np.abs(prev), 1e-9)
    diff_pct = 100.0 * np.abs(cur - prev) / denom
    if criterion == "all":
        return bool((diff_pct < threshold_pct).all())
    if criterion == "average":
        return bool(diff_pct.mean() < threshold_pct)
    raise ValueError(f"unknown convergence criterion {criterion!r}")


@dataclass
class LogisticRegressionModel:
    weights: np.ndarray                      # [D]
    history: List[np.ndarray] = dc_field(default_factory=list)   # per-iteration coeffs
    converged: bool = False
    iterations: int = 0
    n_rows: int = 0                          # global rows fit saw (chunked path)

    # -- coefficient-history serde (the reference's coeff file contract) -----
    def history_lines(self, delim: str = ",") -> List[str]:
        return [delim.join(repr(float(v)) for v in row) for row in self.history]

    @classmethod
    def from_history_lines(cls, lines: Iterable[str], delim: str = ",") -> "LogisticRegressionModel":
        hist = [np.array([float(v) for v in line.split(delim)]) for line in lines if line.strip()]
        if not hist:
            raise ValueError("empty coefficient history")
        return cls(weights=hist[-1], history=hist, converged=False, iterations=len(hist))


class LogisticRegression:
    def __init__(
        self,
        learning_rate: float = 0.5,
        max_iterations: int = 200,
        convergence: str = "average",        # 'all' | 'average'
        threshold_pct: float = 0.5,
        l2: float = 0.0,
        mesh=None,
    ):
        if convergence not in ("all", "average"):
            raise ValueError("convergence must be 'all' or 'average'")
        self.learning_rate = learning_rate
        self.max_iterations = max_iterations
        self.convergence = convergence
        self.threshold_pct = threshold_pct
        self.mesh = mesh          # optional data mesh (parallel/mesh.py)
        self.l2 = l2

    def fit(self, x: np.ndarray, y: np.ndarray,
            resume_from: Optional[LogisticRegressionModel] = None) -> LogisticRegressionModel:
        """y must be 0/1. ``resume_from`` continues a previous run from its
        last coefficient row (the reference restarts its driver loop reading
        the last line of the coefficient file)."""
        from avenir_tpu.parallel.mesh import maybe_shard_batch
        # zero pad rows contribute a zero gradient term; _grad_step scales
        # by the true n passed below, so sharding is transparent up to
        # float reduction order
        xd, yd = maybe_shard_batch(self.mesh,
                                   np.asarray(x, np.float32),
                                   np.asarray(y, np.float32))
        n_true = jnp.float32(x.shape[0])
        lr = jnp.float32(self.learning_rate)
        l2 = jnp.float32(self.l2)
        if resume_from is not None:
            w = jnp.asarray(resume_from.weights, jnp.float32)
            history = list(resume_from.history)
        else:
            w = jnp.zeros(x.shape[1], jnp.float32)
            history = []
        converged = False
        it = 0
        for it in range(1, self.max_iterations + 1):
            w_new = _grad_step(w, xd, yd, n_true, lr, l2)
            cur = np.asarray(w_new)
            history.append(cur)
            if len(history) >= 2 and _converged(history[-2], cur,
                                                self.convergence, self.threshold_pct):
                converged = True
                w = w_new
                break
            w = w_new
        return LogisticRegressionModel(weights=np.asarray(w), history=history,
                                       converged=converged, iterations=len(history))

    def fit_chunked(self, chunks, resume_from: Optional[LogisticRegressionModel] = None,
                    merge=None) -> LogisticRegressionModel:
        """Streaming/multi-process fit over pre-encoded design-matrix chunks.

        ``chunks``: list of ``(global_chunk_index, x [n_c, D] f32, y [n_c])``
        — under jax.distributed each process passes only its OWNED chunks
        (round-robin by index, the analog of the reference's per-mapper
        gradient partials, LogisticRegressionJob.java:169-176).  ``merge``:
        callable folding a ``{key: array}`` state across processes
        (``parallel.mesh.all_process_sum_state``); None = single-process.

        Byte-identical across process counts BY CONSTRUCTION: each chunk's
        gradient partial is computed on device in f32 (shape-identical work
        regardless of which process runs it), fetched to host f64, and the
        global gradient is summed in GLOBAL CHUNK ORDER — so the f64
        addition sequence, the weight update, and the convergence decisions
        are identical for any nprocs.  Every process must call this with
        the same iteration config: the per-iteration merge is a collective.

        The weight vector lives in host f64 (the reducer role); the per-
        chunk matvec runs in f32 on device (the mapper role) — mirroring
        the reference's mapper/reducer numerics split (float map-side
        accumulation, exact reduce-side fold)."""
        merge = merge if merge is not None else (
            lambda s: {k: np.asarray(v) for k, v in s.items()})
        local_n = sum(x.shape[0] for _, x, _ in chunks)
        local_d = max((x.shape[1] for _, x, _ in chunks), default=0)
        hand = merge({"n": np.array([local_n], np.int64),
                      "max:d": np.array([local_d], np.int64)})
        n_total = int(hand["n"][0])
        d = int(hand["max:d"][0])
        if n_total == 0:
            from avenir_tpu.core.encoding import NoDataError
            raise NoDataError("no data")
        for _, x, _ in chunks:
            if x.shape[1] != d:
                raise ValueError(
                    f"chunk design width {x.shape[1]} != global width {d} — "
                    "schema mismatch across chunks/processes")
        for idx, _x, _y in chunks:
            if idx >= 10 ** 8:
                # the gradient keys below are 8-digit zero-padded and the
                # per-iteration fold sums them in sorted() order — an index
                # past the width would silently reorder the f64 addition
                # sequence and break the byte-identity contract (GL003)
                from avenir_tpu.core.config import ConfigError
                raise ConfigError(
                    f"chunk index {idx} exceeds the 8-digit gradient-key "
                    f"width; raise stream.chunk.rows")
        dev = [(idx, jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32))
               for idx, x, y in chunks]
        if resume_from is not None:
            w = np.asarray(resume_from.weights, np.float64)
            history = list(resume_from.history)
        else:
            w = np.zeros(d, np.float64)
            history = []
        converged = False
        for _ in range(self.max_iterations):
            wf = jnp.asarray(w, jnp.float32)
            state = {f"g{idx:08d}": np.asarray(_chunk_grad(wf, xd, yd),
                                               np.float64)
                     for idx, xd, yd in dev}
            tot = merge(state)
            grad = np.zeros(d, np.float64)
            for k in sorted(tot):                    # global chunk order
                grad = grad + tot[k]
            w = w + self.learning_rate * (grad / n_total - self.l2 * w)
            history.append(w.copy())
            if len(history) >= 2 and _converged(history[-2], history[-1],
                                                self.convergence,
                                                self.threshold_pct):
                converged = True
                break
        return LogisticRegressionModel(weights=w.copy(), history=history,
                                       converged=converged,
                                       iterations=len(history),
                                       n_rows=n_total)

    @staticmethod
    def predict_proba(model: LogisticRegressionModel, x: np.ndarray) -> np.ndarray:
        z = x @ model.weights
        return 1.0 / (1.0 + np.exp(-z))

    @staticmethod
    def predict_batch(model_or_weights, x: np.ndarray,
                      threshold: float = 0.5) -> Tuple[np.ndarray, np.ndarray]:
        return predict_batch(model_or_weights, x, threshold=threshold)

    @staticmethod
    def predict(model: LogisticRegressionModel, x: np.ndarray,
                threshold: float = 0.5) -> np.ndarray:
        return (LogisticRegression.predict_proba(model, x) >= threshold).astype(np.int32)
