"""Markov-chain and hidden-Markov sequence models + Viterbi decoding.

Capability parity with the reference's ``org.avenir.markov`` package:

- ``MarkovStateTransitionModel.java`` — first-order chain trainer: adjacent
  state-pair counts (:98-108), combiner sums (:112-125), row-normalized
  transition matrix with Laplace smoothing serialized row-wise
  (:141-179, via util/StateTransitionProbability.java:65-126 incl. the
  int-scale ×1000 or double modes);
- ``HiddenMarkovModelBuilder.java`` — supervised HMM trainer, fully-tagged
  ``obs:state`` mode (:136-166) and partially-tagged mode where inline state
  tokens claim surrounding observations with a distance-decay
  ``window.function`` weight vector (:174-260). NOTE: the reference's window
  bounds contain an operator-precedence slip (``a − b / 2`` for
  ``(a − b) / 2``, :197,205); this implementation uses the intended midpoint
  semantics — a documented deliberate fix;
- ``HiddenMarkovModel.java`` — model file layout (line order: states,
  observations, A rows, B rows, π — :46-70);
- ``ViterbiDecoder.java`` — max-product decoding (:66-105 init/iterate,
  :111-143 backtrack); ``ViterbiStatePredictor.java`` — map-only batch
  decoding job (:114-142).

TPU design: sequences pad to [R, T] int arrays (−1 pad); transition/emission
counts are one-hot einsums over the flattened adjacent-pair stream (the MR
shuffle collapsed); Viterbi runs in log space as a ``lax.scan`` over time
vmapped over records — padded steps are identity transitions so ragged
batches decode in one fixed-shape program.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.ops import agg

DELIM = ","


# ---------------------------------------------------------------------------
# sequence encoding
# ---------------------------------------------------------------------------

class SequenceEncoder:
    """Symbol-name ↔ code mapping with padding to rectangular batches."""

    def __init__(self, symbols: Optional[Sequence[str]] = None):
        self.symbols: List[str] = list(symbols) if symbols else []
        self._map: Dict[str, int] = {s: i for i, s in enumerate(self.symbols)}

    def fit(self, seqs: Iterable[Sequence[str]]) -> "SequenceEncoder":
        for seq in seqs:
            for s in seq:
                if s not in self._map:
                    self._map[s] = len(self.symbols)
                    self.symbols.append(s)
        return self

    def encode(self, seqs: Sequence[Sequence[str]], pad_to: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """([R, T] codes with −1 pad, [R] lengths)."""
        t = pad_to if pad_to is not None else max((len(s) for s in seqs), default=0)
        out = np.full((len(seqs), t), -1, np.int32)
        lens = np.zeros(len(seqs), np.int32)
        for r, seq in enumerate(seqs):
            lens[r] = len(seq)
            for j, s in enumerate(seq):
                out[r, j] = self._map[s]
        return out, lens

    def decode(self, codes: Sequence[int]) -> List[str]:
        return [self.symbols[c] for c in codes if c >= 0]

    def __len__(self) -> int:
        return len(self.symbols)


def adjacent_pairs(seqs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten [R, T] padded sequences into (src, dst) adjacent-pair streams;
    pairs touching pad (−1) become (−1, −1) → count-neutral."""
    a, b = seqs[:, :-1], seqs[:, 1:]
    valid = (a >= 0) & (b >= 0)
    return np.where(valid, a, -1).ravel(), np.where(valid, b, -1).ravel()


# ---------------------------------------------------------------------------
# Markov chain
# ---------------------------------------------------------------------------

@dataclass
class MarkovChainModel:
    states: List[str]
    counts: np.ndarray                   # [S, S] transition counts
    laplace: float = 1.0
    scale: Optional[int] = None          # int-scale mode (reference ×1000); None = float

    def transition_probs(self) -> np.ndarray:
        c = self.counts + self.laplace
        p = c / c.sum(axis=1, keepdims=True)
        if self.scale:
            return np.rint(p * self.scale) / self.scale
        return p

    # row-wise serde, as StateTransitionProbability emits
    def to_lines(self, delim: str = DELIM) -> List[str]:
        probs = self.transition_probs()
        lines = [delim.join(self.states)]
        for row in probs:
            if self.scale:
                lines.append(delim.join(str(int(v * self.scale)) for v in row))
            else:
                lines.append(delim.join(repr(float(v)) for v in row))
        return lines

    @classmethod
    def from_lines(cls, lines: Sequence[str], delim: str = DELIM,
                   scale: Optional[int] = None) -> "MarkovChainModel":
        states = lines[0].split(delim)
        s = len(states)
        probs = np.array([[float(v) for v in lines[1 + i].split(delim)] for i in range(s)])
        if scale:
            probs = probs / scale
        # store probabilities as pseudo-counts; laplace 0 so they round-trip
        return cls(states=states, counts=probs, laplace=0.0, scale=None)


class MarkovChain:
    """First-order chain trainer over state-name sequences."""

    def __init__(self, laplace: float = 1.0, scale: Optional[int] = None,
                 mesh=None):
        self.laplace = laplace
        self.scale = scale
        self.mesh = mesh          # optional data mesh (parallel/mesh.py)

    def fit(self, seqs: Sequence[Sequence[str]],
            encoder: Optional[SequenceEncoder] = None) -> Tuple[MarkovChainModel, SequenceEncoder]:
        enc = encoder if encoder is not None else SequenceEncoder().fit(seqs)
        acc = agg.Accumulator()
        self.accumulate(seqs, enc, acc)
        return self.finalize(enc, acc), enc

    def accumulate(self, seqs: Sequence[Sequence[str]],
                   encoder: SequenceEncoder, acc) -> None:
        """Fold one batch of sequences into ``acc["trans"]`` (exact int64)."""
        codes, _ = encoder.encode(seqs)
        s = len(encoder)
        a, b = adjacent_pairs(codes)
        from avenir_tpu.parallel.mesh import maybe_shard_batch
        a_b, b_b = maybe_shard_batch(self.mesh, a, b)   # -1 pads count-neutral
        acc.add("trans", agg.transition_counts(a_b, b_b, s, s))

    def finalize(self, encoder: SequenceEncoder, acc) -> MarkovChainModel:
        counts = np.asarray(acc.get("trans"), np.float64)
        return MarkovChainModel(states=list(encoder.symbols), counts=counts,
                                laplace=self.laplace, scale=self.scale)

    def fit_chunks(self, chunks: Iterable[Sequence[Sequence[str]]],
                   encoder: SequenceEncoder,
                   accumulator=None) -> Tuple[MarkovChainModel, SequenceEncoder]:
        """Streaming fit over an iterable of sequence batches.

        Requires a pre-built ``encoder`` (``model.states``): with chunked
        input, codes must be stable before the first chunk — vocabulary
        discovery would assign chunk-order-dependent codes.  ``accumulator``
        may be externally owned (multi-process jobs inject one whose totals
        are merged across processes when the stream exhausts; transition
        counts are exact integers, so the merge is order-free).  Raises
        :class:`~avenir_tpu.core.encoding.NoDataError` when no process
        contributed any sequence — after the merge collective, matching
        ``Job.distributed_fit``'s zero-chunk tolerance."""
        acc = accumulator if accumulator is not None else agg.Accumulator()
        for seqs in chunks:
            self.accumulate(seqs, encoder, acc)
        if "trans" not in acc:
            from avenir_tpu.core.encoding import NoDataError
            raise NoDataError("no data")
        return self.finalize(encoder, acc), encoder


# ---------------------------------------------------------------------------
# HMM
# ---------------------------------------------------------------------------

@dataclass
class HMMModel:
    states: List[str]
    observations: List[str]
    transition: np.ndarray       # [S, S] row-normalized A
    emission: np.ndarray         # [S, O] row-normalized B
    initial: np.ndarray          # [S] π

    # -- the reference file layout: states / observations / A rows / B rows / π
    def to_lines(self, delim: str = DELIM) -> List[str]:
        lines = [delim.join(self.states), delim.join(self.observations)]
        for row in self.transition:
            lines.append(delim.join(repr(float(v)) for v in row))
        for row in self.emission:
            lines.append(delim.join(repr(float(v)) for v in row))
        lines.append(delim.join(repr(float(v)) for v in self.initial))
        return lines

    @classmethod
    def from_lines(cls, lines: Sequence[str], delim: str = DELIM) -> "HMMModel":
        states = lines[0].split(delim)
        observations = lines[1].split(delim)
        s = len(states)
        cur = 2
        a = np.array([[float(v) for v in lines[cur + i].split(delim)] for i in range(s)])
        cur += s
        b = np.array([[float(v) for v in lines[cur + i].split(delim)] for i in range(s)])
        cur += s
        pi = np.array([float(v) for v in lines[cur].split(delim)])
        return cls(states, observations, a, b, pi)


class HMMBuilder:
    """Supervised HMM estimation from tagged sequences."""

    def __init__(self, laplace: float = 1.0, mesh=None):
        self.laplace = laplace
        self.mesh = mesh          # optional data mesh (parallel/mesh.py)

    def fit_tagged(
        self,
        seqs: Sequence[Sequence[Tuple[str, str]]],   # [(obs, state), ...] per record
        state_encoder: Optional[SequenceEncoder] = None,
        obs_encoder: Optional[SequenceEncoder] = None,
    ) -> HMMModel:
        """Fully-tagged mode: every token is obs:state
        (HiddenMarkovModelBuilder.java:136-166)."""
        st_enc = state_encoder or SequenceEncoder().fit([[s for _, s in seq] for seq in seqs])
        ob_enc = obs_encoder or SequenceEncoder().fit([[o for o, _ in seq] for seq in seqs])
        acc = agg.Accumulator()
        self.accumulate_tagged(seqs, st_enc, ob_enc, acc)
        return self.finalize(st_enc, ob_enc, acc)

    def accumulate_tagged(self, seqs, st_enc: SequenceEncoder,
                          ob_enc: SequenceEncoder, acc) -> None:
        """Fold one batch of tagged sequences into ``acc`` (keys ``init``,
        ``trans``, ``emit`` — all exact int64 counts)."""
        st_codes, _ = st_enc.encode([[s for _, s in seq] for seq in seqs])
        ob_codes, _ = ob_enc.encode([[o for o, _ in seq] for seq in seqs])
        s, o = len(st_enc), len(ob_enc)
        if not st_codes.size:
            return
        # initial states
        acc.add("init", np.bincount(st_codes[:, 0][st_codes[:, 0] >= 0],
                                    minlength=s).astype(np.int64))
        from avenir_tpu.parallel.mesh import maybe_shard_batch
        # transitions (−1 pads are count-neutral under one-hot)
        a_src, a_dst = maybe_shard_batch(self.mesh, *adjacent_pairs(st_codes))
        acc.add("trans", agg.transition_counts(a_src, a_dst, s, s))
        # emissions: state/obs pairs at the same position
        valid = (st_codes >= 0) & (ob_codes >= 0)
        st_flat, ob_flat = maybe_shard_batch(
            self.mesh,
            np.where(valid, st_codes, -1).ravel(),
            np.where(valid, ob_codes, -1).ravel())
        acc.add("emit", agg.transition_counts(st_flat, ob_flat, s, o))

    def finalize(self, st_enc: SequenceEncoder, ob_enc: SequenceEncoder,
                 acc) -> HMMModel:
        s, o = len(st_enc), len(ob_enc)
        get = lambda k, shape: (np.asarray(acc.get(k), np.float64)
                                if k in acc else np.zeros(shape))
        return self._normalize(st_enc, ob_enc, get("trans", (s, s)),
                               get("emit", (s, o)), get("init", (s,)))

    def fit_tagged_chunks(self, chunks, state_encoder: SequenceEncoder,
                          obs_encoder: SequenceEncoder,
                          accumulator=None) -> HMMModel:
        """Streaming fully-tagged fit over an iterable of sequence batches;
        both encoders must be pre-built (``model.states`` /
        ``model.observations``) for chunk-order-independent codes.  All
        counts are exact integers, so a multi-process merge of the
        injected ``accumulator`` is order-free.  Raises ``NoDataError``
        when no process contributed anything (after the merge collective,
        mirroring :meth:`MarkovChain.fit_chunks`)."""
        acc = accumulator if accumulator is not None else agg.Accumulator()
        for seqs in chunks:
            self.accumulate_tagged(seqs, state_encoder, obs_encoder, acc)
        if "trans" not in acc:
            from avenir_tpu.core.encoding import NoDataError
            raise NoDataError("no data")
        return self.finalize(state_encoder, obs_encoder, acc)

    def fit_partially_tagged(
        self,
        token_seqs: Sequence[Sequence[str]],
        states: Sequence[str],
        window_function: Sequence[float] = (1.0, 0.75, 0.5, 0.25),
        obs_encoder: Optional[SequenceEncoder] = None,
    ) -> HMMModel:
        """Partially-tagged mode: state names appear inline among observation
        tokens; each state claims the observations out to the midpoint toward
        its neighboring states, weighted by distance through
        ``window_function`` (HiddenMarkovModelBuilder.java:174-260, with the
        midpoint computed as intended rather than with the reference's
        precedence slip)."""
        state_set = set(states)
        st_enc = SequenceEncoder(list(states))
        ob_enc = obs_encoder or SequenceEncoder().fit(
            [[t for t in seq if t not in state_set] for seq in token_seqs])
        acc = agg.Accumulator()
        self.accumulate_partial(token_seqs, st_enc, ob_enc, window_function,
                                acc)
        return self.finalize(st_enc, ob_enc, acc)

    def fit_partially_tagged_chunks(self, chunks, states: Sequence[str],
                                    obs_encoder: SequenceEncoder,
                                    window_function: Sequence[float] = (1.0, 0.75, 0.5, 0.25),
                                    accumulator=None) -> HMMModel:
        """Streaming partially-tagged fit; ``obs_encoder`` must be pre-built
        (``model.observations``).  ``init``/``trans`` counts are exact
        integers; ``emit`` sums window weights in float64 — with the
        default dyadic window (1, .75, .5, .25) those sums are exact too,
        so a multi-process merge stays byte-identical; non-dyadic custom
        windows may differ from a single-process run in the last ulp."""
        st_enc = SequenceEncoder(list(states))
        acc = accumulator if accumulator is not None else agg.Accumulator()
        for seqs in chunks:
            self.accumulate_partial(seqs, st_enc, obs_encoder,
                                    window_function, acc)
        if "init" not in acc:
            from avenir_tpu.core.encoding import NoDataError
            raise NoDataError("no data")
        return self.finalize(st_enc, obs_encoder, acc)

    def accumulate_partial(self, token_seqs, st_enc: SequenceEncoder,
                           ob_enc: SequenceEncoder,
                           window_function: Sequence[float], acc) -> None:
        """Fold one batch of partially-tagged sequences into ``acc``."""
        state_set = set(st_enc.symbols)
        s, o = len(st_enc), len(ob_enc)
        init = np.zeros(s, np.int64)
        trans = np.zeros((s, s), np.int64)
        st_list: List[int] = []
        ob_list: List[int] = []
        w_list: List[float] = []
        wf = list(window_function)
        for seq in token_seqs:
            pos = [i for i, t in enumerate(seq) if t in state_set]
            if not pos:
                continue
            init[st_enc._map[seq[pos[0]]]] += 1
            for i in range(len(pos) - 1):
                trans[st_enc._map[seq[pos[i]]], st_enc._map[seq[pos[i + 1]]]] += 1
            for i, p in enumerate(pos):
                left = (p + pos[i - 1]) // 2 + 1 if i > 0 else None
                right = (p + pos[i + 1]) // 2 if i < len(pos) - 1 else None
                if left is None:
                    span = (right - p) if right is not None else (len(seq) - 1 - p) // 2
                    left = max(p - span, 0)
                if right is None:
                    span = p - left
                    right = min(p + span, len(seq) - 1)
                sc = st_enc._map[seq[p]]
                for j in range(p - 1, left - 1, -1):
                    if seq[j] in state_set:
                        continue
                    k = p - 1 - j
                    st_list.append(sc)
                    ob_list.append(ob_enc._map[seq[j]])
                    w_list.append(wf[k] if k < len(wf) else wf[-1])
                for j in range(p + 1, right + 1):
                    if seq[j] in state_set:
                        continue
                    k = j - p - 1
                    st_list.append(sc)
                    ob_list.append(ob_enc._map[seq[j]])
                    w_list.append(wf[k] if k < len(wf) else wf[-1])
        emit = np.zeros((s, o))
        if st_list:
            from avenir_tpu.parallel.mesh import maybe_shard_batch

            st_all = np.array(st_list, np.int32)
            ob_all = np.array(ob_list, np.int32)
            w_all = np.array(w_list, np.float32)
            # chunked accumulation in float64 on host: stays under the
            # kernel's per-chunk cap on any corpus size and bounds f32
            # rounding in the on-device partial sums. Mesh pad rows are
            # neutral (−1 codes one-hot to zero, w pads to 0.0); float
            # reduction order may differ in the last ulp under a mesh.
            # The step is a multiple of the data-axis size so that mesh
            # padding (up to the next multiple of d) can never push a full
            # chunk to >= the cap.
            d = (self.mesh.shape.get("data", 1)
                 if self.mesh is not None else 1) or 1
            # max(·, d) keeps the loop well-formed even for a (theoretical)
            # data axis wider than the chunk cap, where the floored multiple
            # would be 0 and range(0, n, 0) would raise (round-2 advisory)
            step = max(((agg.MAX_EXACT_CHUNK_ROWS - 1) // d) * d, d)
            for s0 in range(0, len(st_all), step):
                st_b, ob_b, w_b = maybe_shard_batch(
                    self.mesh, st_all[s0:s0 + step], ob_all[s0:s0 + step],
                    w_all[s0:s0 + step])
                emit += np.asarray(agg.weighted_transition_counts(
                    st_b, ob_b, w_b, s, o), np.float64)
        acc.add("init", init)
        acc.add("trans", trans)
        acc.add("emit", emit)

    def _normalize(self, st_enc, ob_enc, trans, emit, init) -> HMMModel:
        lam = self.laplace
        a = (trans + lam) / (trans + lam).sum(axis=1, keepdims=True)
        b = (emit + lam) / (emit + lam).sum(axis=1, keepdims=True)
        pi = (init + lam) / (init + lam).sum()
        return HMMModel(list(st_enc.symbols), list(ob_enc.symbols), a, b, pi)


# ---------------------------------------------------------------------------
# Viterbi
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=())
def _viterbi_batch(log_a: jax.Array, log_b: jax.Array, log_pi: jax.Array,
                   obs: jax.Array) -> jax.Array:
    """obs [R, T] (−1 pad) → [R, T] best state path (−1 on pads).

    Forward max-product scan with backpointers; padded steps are identity
    (δ carried, backpointer = self) so one compiled program serves ragged
    batches."""
    s = log_a.shape[0]

    def decode_one(o):
        t = o.shape[0]
        valid0 = o[0] >= 0
        delta0 = jnp.where(valid0, log_pi + log_b[:, jnp.maximum(o[0], 0)],
                           jnp.zeros(s))

        def step(delta, ot):
            valid = ot >= 0
            cand = delta[:, None] + log_a                     # [S_prev, S]
            best_prev = jnp.argmax(cand, axis=0)              # [S]
            best_val = jnp.max(cand, axis=0) + log_b[:, jnp.maximum(ot, 0)]
            new_delta = jnp.where(valid, best_val, delta)
            ptr = jnp.where(valid, best_prev, jnp.arange(s))
            return new_delta, ptr

        delta_t, ptrs = jax.lax.scan(step, delta0, o[1:])     # ptrs [T-1, S]
        last = jnp.argmax(delta_t)

        def back(state, ptr):
            prev = ptr[state]
            return prev, prev        # emit path[t], not the incoming path[t+1]

        _, path_rev = jax.lax.scan(back, last, ptrs, reverse=True)
        path = jnp.concatenate([path_rev, jnp.array([last])])
        return jnp.where(o >= 0, path, -1)

    return jax.vmap(decode_one)(obs)


_NEG = -1.0e30          # max-plus "-inf" kept finite (NaN-safe under XLA)


def _step_matrices(log_a: jax.Array, log_b: jax.Array, obs: jax.Array) -> jax.Array:
    """[T-1, S, S] max-plus step matrices M_t[i,j] = A[i,j] + B[j, o_t] for
    t ≥ 1; padded steps (o_t < 0) become the max-plus identity (0 diagonal,
    -BIG elsewhere) so δ is carried unchanged."""
    s = log_a.shape[0]
    steps = log_a[None, :, :] + log_b[:, jnp.maximum(obs[1:], 0)].T[:, None, :]
    eye = jnp.where(jnp.eye(s, dtype=bool), 0.0, _NEG)
    return jnp.where((obs[1:] >= 0)[:, None, None], steps, eye[None])


def _maxplus(a: jax.Array, b: jax.Array) -> jax.Array:
    """(a ⊗ b)[i,j] = max_k a[i,k] + b[k,j] — the associative max-plus
    matrix product underlying the Viterbi recurrence."""
    return jnp.max(a[..., :, :, None] + b[..., None, :, :], axis=-2)


def _viterbi_assoc_batch(log_a: jax.Array, log_b: jax.Array, log_pi: jax.Array,
                         obs: jax.Array) -> jax.Array:
    """Log-depth Viterbi: ``associative_scan`` over max-plus step matrices.

    Same results as :func:`_viterbi_batch` but O(log T) depth at O(T·S³)
    work — the long-sequence form (SURVEY.md §2.12: 'associative-scan for
    the max-plus recurrence if long sequences matter'). Backpointers are
    recomputed in parallel from the prefix δ's, so only the final [T]
    backtrack is sequential.
    """
    s = log_a.shape[0]

    def decode_one(o):
        valid0 = o[0] >= 0
        delta0 = jnp.where(valid0, log_pi + log_b[:, jnp.maximum(o[0], 0)],
                           jnp.zeros(s))
        steps = _step_matrices(log_a, log_b, o)               # [T-1, S, S]
        prefix = jax.lax.associative_scan(_maxplus, steps)    # [T-1, S, S]
        # δ_t for t ≥ 1, all at once: δ_t = δ_0 ⊗ prefix_t
        deltas = jnp.max(delta0[None, :, None] + prefix, axis=1)   # [T-1, S]
        all_deltas = jnp.concatenate([delta0[None], deltas])       # [T, S]
        # backpointers in parallel: ψ_t[j] = argmax_i δ_{t-1}[i] + M_t[i,j]
        ptrs = jnp.argmax(all_deltas[:-1, :, None] + steps, axis=1)  # [T-1, S]
        # padded steps have identity M: argmax column j is j (carry) ✓
        last = jnp.argmax(all_deltas[-1])

        def back(state, ptr):
            prev = ptr[state]
            return prev, prev

        _, path_rev = jax.lax.scan(back, last, ptrs, reverse=True)
        path = jnp.concatenate([path_rev, jnp.array([last])])
        return jnp.where(o >= 0, path, -1)

    return jax.vmap(decode_one)(obs)


def viterbi_time_sharded(log_a: jax.Array, log_b: jax.Array, log_pi: jax.Array,
                         obs_row: jax.Array, mesh, axis: str = "data"
                         ) -> jax.Array:
    """Context-parallel Viterbi: ONE long sequence with its time axis
    sharded over a mesh axis.

    The sequence-parallelism pattern the task's long-context requirement
    maps to in this framework: each device runs a local ``associative_scan``
    over its chunk of max-plus step matrices, a single ``all_gather`` of the
    [D, S, S] per-chunk products (ICI/DCN traffic independent of T) gives
    every device its exclusive offset, and local prefixes are rebased — the
    max-plus analog of blockwise-parallel attention's chunked softmax
    rebasing. Backtrack pointers are computed locally and the final [T]
    pointer chase runs once, after gather.

    obs_row: [T] observation codes (−1 pad), T divisible by the axis size.
    Returns [T] state path.
    """
    import functools as _ft

    try:
        from jax import shard_map
    except ImportError:                    # pre-move jax (parallel/collectives)
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    s = log_a.shape[0]
    d = mesh.shape[axis]
    ring = [(i, (i + 1) % d) for i in range(d)]

    @_ft.partial(shard_map, mesh=mesh,
                 in_specs=(P(), P(), P(), P(axis)),
                 out_specs=(P(axis), P(axis)))
    def forward(la, lb, lpi, o_loc):
        # o_loc [L = T/D]: chunk d's step matrices cover the transitions
        # INTO its positions; the first one needs the previous chunk's last
        # observation (one scalar ppermute hop around the ring)
        idx = jax.lax.axis_index(axis)
        prev_tail = jax.lax.ppermute(o_loc[-1], axis, ring)
        o_ext = jnp.concatenate([prev_tail[None], o_loc])      # [L + 1]
        steps = _step_matrices(la, lb, o_ext)                  # [L, S, S]
        # global position 0 has no incoming transition: identity
        eye = jnp.where(jnp.eye(s, dtype=bool), 0.0, _NEG)
        steps = steps.at[0].set(jnp.where(idx == 0, eye, steps[0]))
        prefix = jax.lax.associative_scan(_maxplus, steps)     # [L, S, S]
        # exclusive offset = max-plus product of all previous chunks' totals:
        # ONE [D, S, S] all_gather — cross-device traffic independent of T
        totals = jax.lax.all_gather(prefix[-1], axis)          # [D, S, S]

        def offset_scan(carry, x):
            return _maxplus(carry, x), carry

        # newer jax's varying-type system needs the closed-over constant
        # cast to device-varying before the scan; pre-varying jax treats
        # every array as device-local already, so the cast is an identity
        pcast = getattr(jax.lax, "pcast", None)
        init = pcast(eye, (axis,), to="varying") if pcast else eye
        _, excl = jax.lax.scan(offset_scan, init, totals)      # [D, S, S]
        global_prefix = _maxplus(excl[idx][None], prefix)      # [L, S, S]
        # δ_t = δ_0 ⊗ (M_1 … M_t); δ_0 from the replicated first observation
        o0 = jax.lax.all_gather(o_loc[0], axis)[0]
        delta0 = jnp.where(o0 >= 0, lpi + lb[:, jnp.maximum(o0, 0)],
                           jnp.zeros(s))
        deltas = jnp.max(delta0[None, :, None] + global_prefix, axis=1)  # [L, S]
        # backpointers need δ_{t-1}: shift deltas by one along the ring
        prev_last = jax.lax.ppermute(deltas[-1], axis, ring)
        delta_prev = jnp.concatenate([prev_last[None], deltas[:-1]])
        delta_prev = jnp.where(idx == 0,
                               jnp.concatenate([delta0[None], deltas[:-1]]),
                               delta_prev)
        # position 0 overall: ψ unused (identity step makes argmax = j)
        psi = jnp.argmax(delta_prev[:, :, None] + steps, axis=1)  # [L, S]
        return deltas, psi

    deltas, psi = forward(log_a, log_b, log_pi,
                          jnp.asarray(obs_row, jnp.int32))

    @jax.jit
    def backtrack(deltas, psi):
        last = jnp.argmax(deltas[-1])

        def back(state, ptr):
            prev = ptr[state]
            return prev, prev

        _, path_rev = jax.lax.scan(back, last, psi[1:], reverse=True)
        return jnp.concatenate([path_rev, jnp.array([last])])

    path = np.asarray(backtrack(deltas, psi))
    valid = np.asarray(obs_row) >= 0
    return np.where(valid, path, -1)


class ViterbiDecoder:
    """Batch Viterbi decoding over an HMM model.

    ``method``: ``"scan"`` (sequential ``lax.scan`` over time, O(T·S²) work —
    the default for typical short per-record sequences) or ``"assoc"``
    (log-depth ``associative_scan`` over max-plus step matrices, O(T·S³)
    work — for long sequences). :func:`viterbi_time_sharded` additionally
    shards one sequence's time axis over a device mesh."""

    def __init__(self, model: HMMModel, method: str = "scan", mesh=None):
        if method not in ("scan", "assoc"):
            raise ValueError(f"unknown viterbi method {method!r}")
        self.model = model
        self.method = method
        self.mesh = mesh          # optional data mesh: records shard over it
        eps = 1e-12
        self._log_a = jnp.asarray(np.log(np.maximum(model.transition, eps)), jnp.float32)
        self._log_b = jnp.asarray(np.log(np.maximum(model.emission, eps)), jnp.float32)
        self._log_pi = jnp.asarray(np.log(np.maximum(model.initial, eps)), jnp.float32)
        self._obs_map = {o: i for i, o in enumerate(model.observations)}

    def decode_codes(self, obs: np.ndarray) -> np.ndarray:
        """[R, T] obs codes (−1 pad) → [R, T] state codes (−1 pad).

        Under a data mesh the record axis shards across devices (all-−1 pad
        rows decode to all-−1 and are trimmed) — the map-only prediction
        job's record parallelism."""
        from avenir_tpu.parallel.mesh import maybe_shard_batch

        fn = _viterbi_batch if self.method == "scan" else _viterbi_assoc_batch
        obs = np.asarray(obs, np.int32)
        n = obs.shape[0]
        obs_b = maybe_shard_batch(self.mesh, obs)[0]
        return np.asarray(fn(self._log_a, self._log_b, self._log_pi,
                             obs_b))[:n]

    def decode(self, obs_seqs: Sequence[Sequence[str]],
               pad_to: Optional[int] = None) -> List[List[str]]:
        """``pad_to`` pins the time axis to a fixed length instead of the
        batch max — the serving plane's shape discipline (one compiled
        program per bucket, regardless of the sequences in it).  Padded
        steps are max-plus identities, so the decoded path of each record
        is identical for any ``pad_to`` ≥ its length; longer sequences
        raise (a serving request must fail loudly, not silently truncate)."""
        t = max((len(s) for s in obs_seqs), default=0)
        if pad_to is not None:
            if t > pad_to:
                raise ValueError(
                    f"sequence of length {t} exceeds pad_to={pad_to}")
            t = pad_to
        codes = np.full((len(obs_seqs), t), -1, np.int32)
        for r, seq in enumerate(obs_seqs):
            for j, o in enumerate(seq):
                codes[r, j] = self._obs_map[o]
        paths = self.decode_codes(codes)
        return [[self.model.states[c] for c in row if c >= 0] for row in paths]


class ViterbiStatePredictor:
    """The map-only prediction job: rows of (id, obs...) → decoded states
    (ViterbiStatePredictor.java:114-142; ``obs:state`` pair output mode)."""

    def __init__(self, model: HMMModel, pair_output: bool = False,
                 delim: str = DELIM, mesh=None):
        self.decoder = ViterbiDecoder(model, mesh=mesh)
        self.pair_output = pair_output
        self.delim = delim

    def predict_lines(self, rows: Sequence[Sequence[str]],
                      pad_to: Optional[int] = None) -> List[str]:
        ids = [r[0] for r in rows]
        seqs = [list(r[1:]) for r in rows]
        paths = self.decoder.decode(seqs, pad_to=pad_to)
        out = []
        for rid, seq, path in zip(ids, seqs, paths):
            if self.pair_output:
                body = self.delim.join(f"{o}:{s}" for o, s in zip(seq, path))
            else:
                body = self.delim.join(path)
            out.append(f"{rid}{self.delim}{body}")
        return out
