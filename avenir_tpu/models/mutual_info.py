"""Mutual-information feature analysis — the flagship exploration job.

Capability parity with the reference's ``explore/MutualInformation.java``
(mapper emits 7 distribution families per record :61-67,136-214; single
reducer materializes joints and prints MI values :598-784 and
feature-selection scores :792-823) plus ``MutualInformationScore.java``
(MIM :98-101, MIFS with redundancy factor :116-153, JMI :177-179,
DISR :185-187, MRMR :265-300).

TPU design: where the reference shuffles O(records · F²) emitted tuples to
one reducer, this computes the exact same joint distributions as one-hot
einsum contractions per chunk — [F,B,C] feature-class and [P,B,B,C]
pair-class count tensors — accumulated in 64-bit on host. All seven
reference distribution families are marginals of these two tensors plus the
class vector, so a single pass yields everything. Feature pairs are processed
in bounded-size chunks to keep the [P,B,B,C] tensor inside HBM
(SURVEY.md §7 'high-cardinality joint-distribution tensors').

MI values are in nats (the reference uses log2-free ``Math.log`` too).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.core.encoding import EncodedDataset, peek_chunks
from avenir_tpu.ops import agg, info


@dataclass
class MutualInfoResult:
    """All distributions + MI statistics from one pass over the data."""

    feature_names: List[str]                 # [F] display names (binned features)
    class_values: List[str]
    n_bins: np.ndarray                       # [F]
    class_counts: np.ndarray                 # [C]
    feature_class_counts: np.ndarray         # [F, B, C]
    pair_index: np.ndarray                   # [P, 2] (i, j) with i < j
    pair_class_counts: np.ndarray            # [P, B, B, C]

    # derived statistics (computed in finish())
    feature_class_mi: Optional[np.ndarray] = None        # [F]  I(f; class)
    feature_pair_mi: Optional[np.ndarray] = None         # [P]  I(fi; fj)
    pair_class_mi: Optional[np.ndarray] = None           # [P]  I((fi,fj); class)
    pair_class_entropy: Optional[np.ndarray] = None      # [P]  H(fi, fj, class)
    feature_pair_class_cond_mi: Optional[np.ndarray] = None  # [P] I(fi; fj | class)
    feature_entropy: Optional[np.ndarray] = None         # [F]  H(f)
    class_entropy: Optional[float] = None

    @property
    def num_features(self) -> int:
        return len(self.feature_names)

    # -- distribution views (the reference's 7 families) ---------------------
    def class_distr(self) -> np.ndarray:
        return self.class_counts / self.class_counts.sum()

    def feature_distr(self) -> np.ndarray:
        fc = self.feature_class_counts.sum(-1)
        return fc / np.maximum(fc.sum(-1, keepdims=True), 1)

    def feature_pair_distr(self) -> np.ndarray:
        pc = self.pair_class_counts.sum(-1)
        return pc / np.maximum(pc.sum((-2, -1), keepdims=True), 1)

    def feature_class_cond_distr(self) -> np.ndarray:
        """[F, B, C] P(bin | class) — the reference's feature-class-conditional."""
        fcc = self.feature_class_counts
        return fcc / np.maximum(fcc.sum(1, keepdims=True), 1)

    def feature_pair_class_cond_distr(self) -> np.ndarray:
        """[P, B, B, C] P(bin_i, bin_j | class)."""
        pcc = self.pair_class_counts
        return pcc / np.maximum(pcc.sum((1, 2), keepdims=True), 1)

    def finish(self) -> "MutualInfoResult":
        # one fused jitted kernel on the LOCAL CPU backend: the derived
        # statistics are ~10^4 elements of math, but spelled as ~100 eager
        # jnp ops they each pay a dispatch (and, against a remote TPU, a
        # ~60 ms round trip) — fused + host-local, the whole phase is one
        # sub-millisecond call after a one-time compile
        with info.on_host():
            (fc_mi, f_ent, c_ent, fp_mi, pc_mi, pc_ent, cond) = _derived_stats(
                jnp.asarray(self.feature_class_counts, jnp.float32),
                jnp.asarray(self.pair_class_counts, jnp.float32),
                jnp.asarray(self.class_counts, jnp.float32))
        self.feature_class_mi = np.asarray(fc_mi)
        self.feature_entropy = np.asarray(f_ent)
        self.class_entropy = float(c_ent)
        self.feature_pair_mi = np.asarray(fp_mi)
        self.pair_class_mi = np.asarray(pc_mi)
        self.pair_class_entropy = np.asarray(pc_ent)
        self.feature_pair_class_cond_mi = np.asarray(cond)
        return self

    # -- lookup helpers ------------------------------------------------------
    def pair_pos(self) -> Dict[Tuple[int, int], int]:
        return {(int(i), int(j)): k for k, (i, j) in enumerate(self.pair_index)}

    def to_lines(self, delim: str = ",") -> List[str]:
        """Statistic rows in the spirit of the reference's reducer output:
        tagged rows for each MI family, ordered by feature/pair."""
        lines = []
        for f, name in enumerate(self.feature_names):
            lines.append(delim.join(["featureClassMI", name, f"{self.feature_class_mi[f]:.6f}"]))
        for k, (i, j) in enumerate(self.pair_index):
            a, b = self.feature_names[i], self.feature_names[j]
            lines.append(delim.join(["featurePairMI", a, b, f"{self.feature_pair_mi[k]:.6f}"]))
            lines.append(delim.join(["featurePairClassMI", a, b, f"{self.pair_class_mi[k]:.6f}"]))
            lines.append(delim.join(
                ["featurePairClassCondMI", a, b, f"{self.feature_pair_class_cond_mi[k]:.6f}"]))
        return lines




def result_from_counts(
    feature_names: Sequence[str],
    class_values: Sequence[str],
    n_bins: np.ndarray,
    class_counts: np.ndarray,
    feature_class_counts: np.ndarray,
    pair_index: np.ndarray,
    pair_class_counts: np.ndarray,
) -> MutualInfoResult:
    """Finished :class:`MutualInfoResult` from already-aggregated count
    tensors, without touching data — the finalize step of
    :meth:`MutualInformation.fit` and the SharedScan seam
    (``pipeline/scan.py``): both the [F, B, C] and [P, B, B, C] tensors
    are read-outs of the shared co-occurrence gram."""
    return MutualInfoResult(
        feature_names=list(feature_names),
        class_values=list(class_values),
        n_bins=np.asarray(n_bins, np.int64),
        class_counts=np.asarray(class_counts),
        feature_class_counts=np.asarray(feature_class_counts),
        pair_index=np.asarray(pair_index),
        pair_class_counts=np.asarray(pair_class_counts),
    ).finish()


@jax.jit
def _derived_stats(fcc, pcc, cc):
    """All of finish()'s derived statistics as ONE compiled program.

    fcc [F,B,C], pcc [P,B,B,C], cc [C] float32 counts →
    (featureClassMI [F], featureEntropy [F], classEntropy [],
     featurePairMI [P], pairClassMI [P], pairClassEntropy [P],
     featurePairClassCondMI [P])."""
    p, b, _, c = pcc.shape
    return (info.mutual_information(fcc),
            info.entropy_from_counts(fcc.sum(-1), axis=-1),
            info.entropy_from_counts(cc),
            info.mutual_information(pcc.sum(-1)),
            info.mutual_information(pcc.reshape(p, b * b, c)),
            info.entropy_from_counts(pcc.reshape(p, -1), axis=-1),
            info.conditional_mutual_information(pcc))


class MutualInformation:
    """One-pass MI/distribution engine over encoded chunks.

    ``pair_chunk`` bounds the feature-pair dimension of the on-device
    [P, B, B, C] tensor; pairs are swept in slices and accumulated on host.
    """

    def __init__(self, pair_chunk: int = 256, mesh=None):
        """``mesh``: optional ``jax.sharding.Mesh`` with a ``data`` axis —
        chunks are then batch-sharded over the mesh and XLA inserts the
        cross-device count reduction (−1 pad rows are count-neutral);
        integer counts make the result bit-identical to single-device."""
        self.pair_chunk = pair_chunk
        self.mesh = mesh

    def fit(self, data: Union[EncodedDataset, Iterable[EncodedDataset]],
            feature_names: Optional[Sequence[str]] = None,
            accumulator=None) -> MutualInfoResult:
        """``accumulator``: an externally-owned (possibly checkpoint-restored)
        ``agg.Accumulator`` — the streaming jobs pass their
        StreamCheckpointer's so mid-stream snapshots see the totals."""
        meta, chunks = peek_chunks(data)           # lazy: stream-friendly
        if meta.labels is None:
            raise ValueError("mutual information requires a class attribute")
        f, b, c = meta.num_binned, meta.max_bins, meta.num_classes
        pair_index = np.array([(i, j) for i in range(f) for j in range(i + 1, f)],
                              np.int32).reshape(-1, 2)
        acc = accumulator if accumulator is not None else agg.Accumulator()
        # single-TPU fast path: one MXU co-occurrence kernel per chunk
        # (ops/pallas_hist.py, ~4-5× the einsum form) accumulates the
        # [Wp, Wp] G matrix; the [F,B,C] tensor and every pair's [B,B,C]
        # joint are read out of the int64 G total ONCE at the end on host
        # (device-side extraction measured slower than the kernel itself).
        # TPU MESHES (round 4) run the same kernel under shard_map — each
        # device grams its local rows and ONE psum over ``data`` merges
        # (collectives.sharded_cooc_step, the shuffle analog the dryrun
        # attests). The einsum loop remains for CPU runs, non-TPU meshes,
        # and shapes past every kernel gate — bit-identical counts.
        from avenir_tpu.ops import pallas_hist
        step = None                        # kernel route when set
        if pallas_hist.use_kernel(f, b, c, mesh=self.mesh):
            step = lambda cd, lb: pallas_hist.cooc_counts(cd, lb, b, c)
        elif (pallas_hist.applicable(f, b, c)
                and pallas_hist.mesh_on_tpu(self.mesh)):
            from avenir_tpu.parallel import collectives
            step = collectives.sharded_cooc_step(self.mesh, b, c)
        gk = pallas_hist.g_key(f, b, c)
        # a checkpoint-restored accumulator dictates the path: counts from a
        # crashed run on the OTHER path must not be silently dropped. A
        # kernel-path snapshot (layout-qualified G key) resumed where the
        # kernel no longer applies converts G into the einsum path's tensors
        # (exact); an einsum-path snapshot simply continues on the einsum
        # path.  A G key from a DIFFERENT kernel layout/version (e.g. the
        # round-3 j-major "g") cannot be read with this build's indexing —
        # reject it loudly rather than corrupt counts.
        if accumulator is not None:
            stale = [k for k in accumulator.names()
                     if (k == "g" or k.startswith("g:")) and k != gk]
            if stale:
                raise ValueError(
                    f"checkpoint holds count matrix {stale[0]!r} from an "
                    f"incompatible kernel layout (this build uses {gk!r}); "
                    f"restart the job without --resume")
            if gk in accumulator and step is None:
                g = accumulator.state()
                fc0, pcc0 = pallas_hist.counts_from_cooc(
                    g.pop(gk), f, b, c, pair_index[:, 0], pair_index[:, 1])
                g["fc"] = fc0
                for s in range(0, len(pair_index), self.pair_chunk):
                    g[f"pcc{s}"] = pcc0[s:s + self.pair_chunk]
                accumulator.load(g)
            elif "fc" in accumulator and step is not None:
                step = None
        for ds in chunks:
            from avenir_tpu.parallel.mesh import maybe_shard_batch
            codes, labels = maybe_shard_batch(self.mesh, ds.codes, ds.labels)
            acc.add("class", agg.class_counts(labels, c))
            if step is not None:
                acc.add(gk, step(codes, labels))
                continue
            acc.add("fc", agg.feature_class_counts(codes, labels, c, b))
            for s in range(0, len(pair_index), self.pair_chunk):
                sl = pair_index[s:s + self.pair_chunk]
                pcc = agg.pair_class_counts(
                    codes[:, sl[:, 0]], codes[:, sl[:, 1]], labels, c, b)
                # the expected-set resume gate above rejects stale key
                # families, and MI always counts ALL pairs for a given F,
                # so the pcc chunk keys are fully determined by (F, B, C)
                # which the gate validates — an explicit fingerprint would
                # invalidate every existing checkpoint for no added safety
                # graftlint: disable=GL002
                acc.add(f"pcc{s}", pcc)
        if gk in acc:
            fc_full, pcc_full = pallas_hist.counts_from_cooc(
                acc.get(gk), f, b, c, pair_index[:, 0], pair_index[:, 1])
        elif len(pair_index):
            fc_full = acc.get("fc")
            pcc_full = np.concatenate(
                [acc.get(f"pcc{s}")
                 for s in range(0, len(pair_index), self.pair_chunk)])
        else:
            fc_full = acc.get("fc")
            pcc_full = np.zeros((0, b, b, c), np.int64)
        names = list(feature_names) if feature_names is not None else [
            f"f{o}" for o in meta.binned_ordinals]
        return result_from_counts(
            feature_names=names,
            class_values=list(meta.class_values),
            n_bins=meta.n_bins,
            class_counts=acc.get("class"),
            feature_class_counts=fc_full,
            pair_index=pair_index,
            pair_class_counts=pcc_full,
        )


# ---------------------------------------------------------------------------
# feature-subset scoring (host-side greedy, as in MutualInformationScore.java)
# ---------------------------------------------------------------------------

def _greedy(num_features: int, first: int, gain) -> List[Tuple[int, float]]:
    """Shared greedy loop: start from ``first``, repeatedly add argmax gain."""
    selected = [first]
    out = [(first, float("nan"))]
    while len(selected) < num_features:
        best, best_score = -1, -np.inf
        for f in range(num_features):
            if f in selected:
                continue
            s = gain(f, selected)
            if s > best_score:
                best, best_score = f, s
        selected.append(best)
        out.append((best, best_score))
    return out


def mim_score(result: MutualInfoResult) -> List[Tuple[int, float]]:
    """Mutual Information Maximization: rank by I(f; class)."""
    order = np.argsort(-result.feature_class_mi)
    return [(int(f), float(result.feature_class_mi[f])) for f in order]


def mifs_score(result: MutualInfoResult, redundancy_factor: float = 1.0) -> List[Tuple[int, float]]:
    """MIFS greedy: gain = I(f;c) − β · Σ_{s∈S} I(f;s)."""
    mi_c = result.feature_class_mi
    pos = result.pair_pos()
    pmi = result.feature_pair_mi

    def pair_mi(a, bf):
        return pmi[pos[(min(a, bf), max(a, bf))]]

    def gain(f, sel):
        return mi_c[f] - redundancy_factor * sum(pair_mi(f, s) for s in sel)

    first = int(np.argmax(mi_c))
    out = _greedy(result.num_features, first, gain)
    return [(f, (float(mi_c[f]) if np.isnan(s) else s)) for f, s in out]


def jmi_score(result: MutualInfoResult) -> List[Tuple[int, float]]:
    """Joint Mutual Information greedy: gain = Σ_{s∈S} I((f,s); class)."""
    pos = result.pair_pos()
    jmi = result.pair_class_mi

    def gain(f, sel):
        return sum(jmi[pos[(min(f, s), max(f, s))]] for s in sel)

    first = int(np.argmax(result.feature_class_mi))
    out = _greedy(result.num_features, first, gain)
    return [(f, (float(result.feature_class_mi[f]) if np.isnan(s) else s)) for f, s in out]


def disr_score(result: MutualInfoResult) -> List[Tuple[int, float]]:
    """Double Input Symmetrical Relevance: gain = Σ_s I((f,s);c) / H(f,s,c)."""
    pos = result.pair_pos()
    jmi = result.pair_class_mi
    ent = result.pair_class_entropy

    def gain(f, sel):
        return sum(jmi[k] / max(ent[k], 1e-12)
                   for k in (pos[(min(f, s), max(f, s))] for s in sel))

    first = int(np.argmax(result.feature_class_mi))
    out = _greedy(result.num_features, first, gain)
    return [(f, (float(result.feature_class_mi[f]) if np.isnan(s) else s)) for f, s in out]


def mrmr_score(result: MutualInfoResult) -> List[Tuple[int, float]]:
    """min-Redundancy-Max-Relevance greedy: gain = I(f;c) − mean_{s∈S} I(f;s)."""
    mi_c = result.feature_class_mi
    pos = result.pair_pos()
    pmi = result.feature_pair_mi

    def gain(f, sel):
        red = sum(pmi[pos[(min(f, s), max(f, s))]] for s in sel) / len(sel)
        return mi_c[f] - red

    first = int(np.argmax(mi_c))
    out = _greedy(result.num_features, first, gain)
    return [(f, (float(mi_c[f]) if np.isnan(s) else s)) for f, s in out]


SCORE_ALGORITHMS = {
    "mutual.info.maximization": mim_score,
    "mutual.info.selection": mifs_score,
    "joint.mutual.info": jmi_score,
    "double.input.symmetrical.relevance": disr_score,
    "min.redundancy.max.relevance": mrmr_score,
    # short aliases
    "mim": mim_score, "mifs": mifs_score, "jmi": jmi_score,
    "disr": disr_score, "mrmr": mrmr_score,
}


def score_features(result: MutualInfoResult, algorithm: str, **kwargs) -> List[Tuple[int, float]]:
    try:
        fn = SCORE_ALGORITHMS[algorithm]
    except KeyError:
        raise ValueError(f"unknown scoring algorithm {algorithm!r}; "
                         f"known: {sorted(set(SCORE_ALGORITHMS))}") from None
    return fn(result, **kwargs)
