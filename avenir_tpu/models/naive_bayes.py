"""Naive Bayes — training and scoring, TPU-native.

Capability parity with the reference's Bayesian suite
(bayesian/BayesianDistribution.java — training MR;
bayesian/BayesianPredictor.java — map-only scoring MR;
bayesian/BayesianModel.java + FeaturePosterior.java — in-memory model):

- binned features (categorical, or numeric with ``bucketWidth``) →
  class-conditional multinomial bins;
- unbinned numeric features → Gaussian class-conditional densities from
  (count, Σx, Σx²) accumulation (reference :156-171, :282-297);
- class priors, feature priors, posterior product scoring
  (BayesianModel.java:50-74), argmax or cost-based arbitration with an
  ambiguity flag on the top-two probability gap
  (BayesianPredictor.java:319-391);
- model-file serde in the reference's CSV row layout
  (BayesianPredictor.java:186-224) for drop-in continuity;
- validation-mode confusion matrix published to counters
  (BayesianPredictor.java:170-180).

Architecture: training is one einsum-aggregation pass per chunk
(:func:`avenir_tpu.ops.agg.feature_class_counts` + :func:`class_moments`) —
the mapper/combiner/reducer triple collapsed into a contraction the MXU
executes directly; scoring is a jitted gather of log-probabilities. Deliberate
fixes over the reference (documented per SURVEY.md §7): float probabilities
instead of ×100 ints, true float mean/σ instead of integer division, optional
Laplace smoothing instead of silent zero probabilities.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field as dc_field
from typing import Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.core.encoding import DatasetEncoder, EncodedDataset, peek_chunks
from avenir_tpu.ops import agg
from avenir_tpu.utils.metrics import ConfusionMatrix, CostBasedArbitrator, Counters

_LOG2PI = float(np.log(2.0 * np.pi))


@dataclass
class NaiveBayesModel:
    """Sufficient statistics + derived log-probability tables."""

    class_values: List[str]
    n_bins: np.ndarray                                  # int [F]
    bin_counts: np.ndarray                              # float64 [F, B, C]
    class_counts: np.ndarray                            # float64 [C]
    cont_count: Optional[np.ndarray] = None             # float64 [C]
    cont_sum: Optional[np.ndarray] = None               # float64 [C, Fc]
    cont_sumsq: Optional[np.ndarray] = None             # float64 [C, Fc]
    laplace: float = 1.0

    # -- derived tables (the analog of BayesianModel.finishUp) ---------------
    @functools.cached_property
    def log_prior(self) -> np.ndarray:
        c = self.class_counts
        return np.log(np.maximum(c, 1e-300) / max(c.sum(), 1e-300))

    @functools.cached_property
    def log_posterior(self) -> np.ndarray:
        """[F, B, C] log P(bin | class), Laplace-smoothed over valid bins."""
        f, b, _ = self.bin_counts.shape
        valid = (np.arange(b)[None, :] < self.n_bins[:, None])[..., None]   # [F,B,1]
        counts = self.bin_counts + self.laplace * valid
        totals = counts.sum(axis=1, keepdims=True)                          # [F,1,C]
        probs = np.where(valid, counts / np.maximum(totals, 1e-300), 1.0)
        return np.log(np.maximum(probs, 1e-300))

    @functools.cached_property
    def cont_stats(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """([C,Fc] mean, [C,Fc] std) for continuous features, or None."""
        if self.cont_sum is None or self.cont_sum.size == 0:
            return None
        cnt = np.maximum(self.cont_count, 1.0)[:, None]
        mean = self.cont_sum / cnt
        var = np.maximum(self.cont_sumsq / cnt - mean ** 2, 1e-12)
        # unbiased correction to match sample σ (reference divides by n−1)
        var = var * (cnt / np.maximum(cnt - 1.0, 1.0))
        return mean, np.sqrt(var)

    @property
    def num_classes(self) -> int:
        return len(self.class_values)

    def scoring_params(self):
        """Device-ready arrays for the jitted scorer, cached on the model —
        repeated scoring calls (the serving plane's steady state) must not
        re-upload the tables per batch."""
        cached = self.__dict__.get("_scoring_params")
        if cached is None:
            mean_std = self.cont_stats
            if mean_std is None:
                mean = std = np.zeros((self.num_classes, 0), np.float32)
            else:
                mean, std = mean_std
            cached = self.__dict__["_scoring_params"] = (
                jnp.asarray(self.log_posterior, jnp.float32),
                jnp.asarray(self.log_prior, jnp.float32),
                jnp.asarray(mean, jnp.float32),
                jnp.asarray(std, jnp.float32),
            )
        return cached


def model_from_counts(
    class_values: Sequence[str],
    n_bins: np.ndarray,
    bin_counts: Optional[np.ndarray],
    class_counts: np.ndarray,
    cont_count: Optional[np.ndarray] = None,
    cont_sum: Optional[np.ndarray] = None,
    cont_sumsq: Optional[np.ndarray] = None,
    laplace: float = 1.0,
) -> NaiveBayesModel:
    """Build a :class:`NaiveBayesModel` from already-aggregated count
    tables, without touching data — the finalize step of :meth:`NaiveBayes.fit`
    and the SharedScan seam (``pipeline/scan.py``): the [F, B, C] table is
    the diagonal block of the shared co-occurrence gram, so a scan that
    already computed G builds this model for free.  ``bin_counts=None``
    means no binned features (an all-zero table is substituted)."""
    n_bins = np.asarray(n_bins, np.int64)
    f = len(n_bins)
    bmax = int(n_bins.max()) if f else 0
    c = len(class_values)
    if bin_counts is None:
        bin_counts = np.zeros((f, bmax, c))
    return NaiveBayesModel(
        class_values=list(class_values),
        n_bins=n_bins,
        bin_counts=np.asarray(bin_counts).astype(np.float64),
        class_counts=np.asarray(class_counts).astype(np.float64),
        cont_count=cont_count,
        cont_sum=cont_sum,
        cont_sumsq=cont_sumsq,
        laplace=laplace,
    )


@jax.jit
def nb_log_scores(
    log_posterior: jax.Array,   # [F, B, C]
    log_prior: jax.Array,       # [C]
    cont_mean: jax.Array,       # [C, Fc]
    cont_std: jax.Array,        # [C, Fc]
    codes: jax.Array,           # [N, F]
    cont: jax.Array,            # [N, Fc]
) -> jax.Array:
    """[N, C] unnormalized log P(c | x) = log P(c) + Σ_f log P(x_f | c)."""
    # gather per-record bin log-probs: [N, F, C]
    gathered = jnp.take_along_axis(
        log_posterior[None, :, :, :],            # [1, F, B, C]
        codes[:, :, None, None].astype(jnp.int32).clip(0),  # [N, F, 1, 1]
        axis=2,
    )[:, :, 0, :]
    scores = log_prior[None, :] + jnp.sum(gathered, axis=1)
    if cont_mean.shape[1]:
        x = cont[:, None, :]                     # [N, 1, Fc]
        mu = cont_mean[None, :, :]               # [1, C, Fc]
        sd = jnp.maximum(cont_std[None, :, :], 1e-6)
        logpdf = -0.5 * (((x - mu) / sd) ** 2) - jnp.log(sd) - 0.5 * _LOG2PI
        scores = scores + jnp.sum(logpdf, axis=2)
    return scores


def predict_batch(model: NaiveBayesModel, codes: np.ndarray,
                  cont: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """([N, C] log scores, [N, C] normalized posteriors) — the ONE scoring
    entry both the batch predictor (:meth:`NaiveBayes.predict`) and the
    serving plane route through, so their numerics agree by construction.
    Device tables come from the model's cached :meth:`scoring_params`
    (uploaded once); the jitted gather compiles per batch shape, which the
    serving microbatcher pins to its fixed bucket sizes."""
    params = model.scoring_params()
    scores = np.asarray(nb_log_scores(*params, jnp.asarray(codes),
                                      jnp.asarray(cont)))
    shifted = scores - scores.max(axis=1, keepdims=True)
    expd = np.exp(shifted)
    probs = expd / expd.sum(axis=1, keepdims=True)
    return scores, probs


@dataclass
class PredictionResult:
    log_scores: np.ndarray          # [N, C]
    probs: np.ndarray               # [N, C] normalized posteriors
    predicted: np.ndarray           # [N] class index after arbitration
    ambiguous: Optional[np.ndarray] = None      # [N] bool
    confusion: Optional[ConfusionMatrix] = None
    counters: Counters = dc_field(default_factory=Counters)

    def predicted_labels(self, class_values: Sequence[str]) -> List[str]:
        return [class_values[i] for i in self.predicted]


class NaiveBayes:
    """Estimator facade: fit over encoded chunks, predict with arbitration.

    The reference's job pair (BayesianDistribution → model file →
    BayesianPredictor) becomes ``fit`` → :class:`NaiveBayesModel` →
    ``predict``; the model file remains available via
    :func:`model_to_lines` / :func:`model_from_lines`.
    """

    def __init__(self, laplace: float = 1.0, mesh=None):
        """``mesh``: optional ``jax.sharding.Mesh`` with a ``data`` axis —
        each chunk's batch axis is then sharded over the mesh and XLA
        auto-inserts the cross-device reduction for the count tensors (the
        reference's combiner+shuffle over ICI). Pad rows use −1 codes/
        labels, which are count-neutral under one-hot (tests/test_agg.py).
        Count tensors are integers, so binned/categorical results are
        bit-identical to single-device; Gaussian moment sums (Σx, Σx²) are
        float reductions whose cross-device order may differ in the last
        ulp. Single-process only (see parallel/mesh.py)."""
        self.laplace = laplace
        self.mesh = mesh

    def _batch(self, *arrays):
        from avenir_tpu.parallel.mesh import maybe_shard_batch
        return maybe_shard_batch(self.mesh, *arrays)

    def fit(self, data: Union[EncodedDataset, Iterable[EncodedDataset]],
            accumulator=None) -> NaiveBayesModel:
        """``accumulator``: an externally-owned (possibly checkpoint-restored)
        ``agg.Accumulator`` — the streaming jobs pass their
        StreamCheckpointer's so mid-stream snapshots see the totals."""
        meta, chunks = peek_chunks(data)
        acc = accumulator if accumulator is not None else agg.Accumulator()
        for ds in chunks:
            meta = ds
            if ds.labels is None:
                raise ValueError("fit requires labels (class attribute column)")
            c, b = ds.num_classes, ds.max_bins
            codes, labels, cont = self._batch(ds.codes, ds.labels, ds.cont)
            if ds.num_binned:
                acc.add("bin_counts", agg.feature_class_counts(codes, labels, c, b))
            acc.add("class_counts", agg.class_counts(labels, c))
            if ds.num_cont:
                cnt, s1, s2 = agg.class_moments(cont, labels, c)
                acc.add("cont_count", cnt)
                acc.add("cont_sum", s1)
                acc.add("cont_sumsq", s2)
        return model_from_counts(
            class_values=list(meta.class_values),
            n_bins=np.asarray(meta.n_bins, np.int64),
            bin_counts=(acc.get("bin_counts") if "bin_counts" in acc else None),
            class_counts=acc.get("class_counts"),
            cont_count=(acc.get("cont_count") if "cont_count" in acc else None),
            cont_sum=(acc.get("cont_sum") if "cont_sum" in acc else None),
            cont_sumsq=(acc.get("cont_sumsq") if "cont_sumsq" in acc else None),
            laplace=self.laplace,
        )

    def predict(
        self,
        model: NaiveBayesModel,
        ds: EncodedDataset,
        cost: Optional[np.ndarray] = None,
        ambiguity_threshold: Optional[float] = None,
        validate: bool = False,
        pos_class: Optional[str] = None,
    ) -> PredictionResult:
        scores, probs = predict_batch(model, ds.codes, ds.cont)
        if cost is not None:
            predicted = CostBasedArbitrator(model.class_values, cost).arbitrate(probs)
        else:
            predicted = np.argmax(probs, axis=1).astype(np.int32)
        ambiguous = None
        if ambiguity_threshold is not None:
            top2 = np.sort(probs, axis=1)[:, -2:]
            ambiguous = (top2[:, 1] - top2[:, 0]) < ambiguity_threshold
        result = PredictionResult(log_scores=scores, probs=probs, predicted=predicted, ambiguous=ambiguous)
        if validate:
            if ds.labels is None:
                raise ValueError("validation mode requires labels")
            cm = ConfusionMatrix(model.class_values, pos_class=pos_class)
            cm.add_batch(ds.labels, predicted)
            cm.publish(result.counters)
            result.confusion = cm
        return result


# ---------------------------------------------------------------------------
# model-file serde — the reference's CSV layout (BayesianPredictor.java:186-224)
# ---------------------------------------------------------------------------
#   classVal,featureOrd,bin,count            feature posterior (binned)
#   classVal,featureOrd,,mean,stdDev         feature posterior (continuous)
#   classVal,,,count                         class prior
#   ,featureOrd,bin,count                    feature prior (binned)
#   ,featureOrd,,mean,stdDev                 feature prior (continuous)

def model_to_lines(model: NaiveBayesModel, encoder: DatasetEncoder, delim: str = ",") -> List[str]:
    lines: List[str] = []
    ords = [f.ordinal for f in encoder.binned_fields]
    cont_ords = [f.ordinal for f in encoder.cont_fields]
    # feature posteriors + priors (binned)
    for fi, ordinal in enumerate(ords):
        nb = int(model.n_bins[fi])
        for b in range(nb):
            label = encoder.bin_label(fi, b)
            total = 0
            for ci, cv in enumerate(model.class_values):
                cnt = int(model.bin_counts[fi, b, ci])
                total += cnt
                if cnt:
                    lines.append(delim.join([cv, str(ordinal), label, str(cnt)]))
            if total:
                lines.append(delim.join(["", str(ordinal), label, str(total)]))
    # class priors
    for ci, cv in enumerate(model.class_values):
        lines.append(delim.join([cv, "", "", str(int(model.class_counts[ci]))]))
    # continuous posteriors + priors
    if model.cont_stats is not None:
        mean, std = model.cont_stats
        for fj, ordinal in enumerate(cont_ords):
            for ci, cv in enumerate(model.class_values):
                lines.append(delim.join([cv, str(ordinal), "", repr(float(mean[ci, fj])), repr(float(std[ci, fj]))]))
            cnt = model.cont_count
            tot = max(float(cnt.sum()), 1.0)
            pm = float((cnt * mean[:, fj]).sum() / tot)
            # pooled prior σ from total moments
            s2 = float(model.cont_sumsq[:, fj].sum())
            pv = max(s2 / tot - pm * pm, 1e-12) * (tot / max(tot - 1.0, 1.0))
            lines.append(delim.join(["", str(ordinal), "", repr(pm), repr(float(np.sqrt(pv)))]))
    return lines


def model_from_lines(
    lines: Iterable[str], encoder: DatasetEncoder, laplace: float = 1.0, delim: str = ","
) -> NaiveBayesModel:
    """Rebuild a model from the reference-layout CSV rows.

    Continuous rows carry (mean, std) rather than raw moments, so the moments
    are reconstituted with a nominal count — scoring depends only on
    (mean, std), which round-trips exactly.
    """
    ords = [f.ordinal for f in encoder.binned_fields]
    cont_ords = [f.ordinal for f in encoder.cont_fields]
    ord_to_fi = {o: i for i, o in enumerate(ords)}
    ord_to_cj = {o: j for j, o in enumerate(cont_ords)}
    class_values = list(encoder.class_values)
    cmap = {v: i for i, v in enumerate(class_values)}
    f = len(ords)
    nb = np.array([encoder.n_bins[o] for o in ords], np.int64) if f else np.zeros(0, np.int64)
    bmax = int(nb.max()) if f else 0
    c = len(class_values)
    bin_counts = np.zeros((f, bmax, c))
    class_counts = np.zeros(c)
    fc = len(cont_ords)
    mean = np.zeros((c, fc))
    std = np.ones((c, fc))
    n_nominal = 1000.0
    for line in lines:
        items = line.rstrip("\n").split(delim)
        if not any(items):
            continue
        featur_ord = int(items[1]) if items[1] != "" else -1
        if items[0] == "":
            continue  # feature priors are derivable; skip
        if items[1] == "" and items[2] == "":
            class_counts[cmap[items[0]]] += float(items[3])
        elif items[2] != "":
            fi = ord_to_fi[featur_ord]
            code = encoder.bin_code(fi, items[2])
            bin_counts[fi, code, cmap[items[0]]] += float(items[3])
        else:
            cj = ord_to_cj[featur_ord]
            ci = cmap[items[0]]
            mean[ci, cj] = float(items[3])
            std[ci, cj] = float(items[4])
    cont_count = cont_sum = cont_sumsq = None
    if fc:
        cont_count = np.full(c, n_nominal)
        cont_sum = mean * n_nominal
        # invert the unbiased-σ derivation in cont_stats for round-trip
        var_b = (std ** 2) * ((n_nominal - 1.0) / n_nominal)
        cont_sumsq = (var_b + mean ** 2) * n_nominal
    return NaiveBayesModel(
        class_values=class_values, n_bins=nb, bin_counts=bin_counts,
        class_counts=class_counts, cont_count=cont_count,
        cont_sum=cont_sum, cont_sumsq=cont_sumsq, laplace=laplace,
    )
