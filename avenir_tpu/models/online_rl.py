"""Online reinforcement learners — the real-time serving brain.

Capability parity with the reference's online learner library (no Hadoop
imports; used by the Storm bolt):

- ``ReinforcementLearner.java`` — abstract base with ``withActions``,
  ``withBatchSize``, ``initialize(config)``, ``nextActions(round)``,
  ``setReward(action, reward)`` (:28-86);
- ``ReinforcementLearnerFactory.java`` — name → instance (:35-46);
- ``IntervalEstimator.java`` — per-action reward histogram, select the max
  upper-confidence-bound arm, confidence limit annealed from
  ``confidence.limit`` toward ``min.confidence.limit`` by
  ``confidence.limit.reduction.step`` every
  ``confidence.limit.reduction.round.interval`` rounds (:78-149); random
  until every action has ``min.reward.distr.sample`` samples (:83-105);
- ``SampsonSampler.java`` — Thompson-style draw from the empirical reward
  sample, random up to ``max.reward`` below the minimum sample count
  (:56-79); ``OptimisticSampsonSampler.java`` — draw floored at the action
  mean (:49-52);
- ``RandomGreedyLearner.java`` — online ε-greedy with linear/log-linear
  decay (:50-78);
- ``GroupedItems.java`` (:94-141) and ``ExplorationCounter.java`` (:52-77)
  pool utilities.

These run on host by design — per-event latency beats batch throughput here,
matching the reference's per-bolt-instance in-memory state. The batch/TPU
versions of the same policies live in :mod:`avenir_tpu.models.bandits`;
learner state is plain numpy and checkpointable (the capability the
reference lacks — its bolt state dies on restart, SURVEY.md §3.5).
"""

from __future__ import annotations

import random as _random
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence

import numpy as np


class ReinforcementLearner:
    """Abstract online learner with the reference's builder-style API."""

    def __init__(self):
        self.actions: List[str] = []
        self.batch_size: int = 1
        self.rng = _random.Random(0)

    def with_actions(self, actions: Sequence[str]) -> "ReinforcementLearner":
        self.actions = list(actions)
        return self

    def with_batch_size(self, batch_size: int) -> "ReinforcementLearner":
        self.batch_size = batch_size
        return self

    def with_seed(self, seed: int) -> "ReinforcementLearner":
        self.rng = _random.Random(seed)
        return self

    def initialize(self, config: Dict) -> "ReinforcementLearner":
        return self

    def next_actions(self, round_num: int) -> List[str]:
        raise NotImplementedError

    def set_reward(self, action: str, reward: float) -> None:
        raise NotImplementedError

    # -- checkpointing (absent in the reference — bolt restart loses state) --
    def get_state(self) -> Dict:
        raise NotImplementedError

    def set_state(self, state: Dict) -> None:
        raise NotImplementedError


@dataclass
class _ActionStat:
    rewards: List[float] = dc_field(default_factory=list)

    @property
    def count(self) -> int:
        return len(self.rewards)

    @property
    def mean(self) -> float:
        return float(np.mean(self.rewards)) if self.rewards else 0.0


class IntervalEstimator(ReinforcementLearner):
    """Histogram upper-confidence-bound learner with annealed confidence."""

    def initialize(self, config: Dict) -> "IntervalEstimator":
        self.bin_width = float(config.get("bin.width", 1.0))
        self.confidence_limit = float(config.get("confidence.limit", 95.0))
        self.min_confidence_limit = float(config.get("min.confidence.limit", 50.0))
        self.reduction_step = float(config.get("confidence.limit.reduction.step", 5.0))
        self.reduction_interval = int(config.get("confidence.limit.reduction.round.interval", 50))
        self.min_distr_sample = int(config.get("min.reward.distr.sample", 10))
        self.cur_confidence = self.confidence_limit
        self.last_round = 0
        self.stats: Dict[str, _ActionStat] = {a: _ActionStat() for a in self.actions}
        return self

    def _upper_bound(self, stat: _ActionStat) -> float:
        """Upper bound of the reward histogram at the current confidence
        percentile (chombo HistogramStat.getConfidenceBounds equivalent:
        symmetric percentile bounds around the median of the empirical
        distribution)."""
        if not stat.rewards:
            return 0.0
        return float(np.percentile(stat.rewards, min(self.cur_confidence, 100.0)))

    def _adjust(self, round_num: int) -> None:
        if self.cur_confidence > self.min_confidence_limit:
            steps = (round_num - self.last_round) // max(self.reduction_interval, 1)
            if steps > 0:
                self.cur_confidence = max(self.cur_confidence - steps * self.reduction_step,
                                          self.min_confidence_limit)
                self.last_round = round_num

    def next_actions(self, round_num: int) -> List[str]:
        low_sample = any(self.stats[a].count < self.min_distr_sample for a in self.actions)
        out = []
        for _ in range(self.batch_size):
            if low_sample:
                out.append(self.rng.choice(self.actions))
            else:
                self._adjust(round_num)
                out.append(max(self.actions, key=lambda a: self._upper_bound(self.stats[a])))
        return out

    def set_reward(self, action: str, reward: float) -> None:
        self.stats[action].rewards.append(float(reward))

    def get_state(self) -> Dict:
        return {"rewards": {a: list(s.rewards) for a, s in self.stats.items()},
                "cur_confidence": self.cur_confidence, "last_round": self.last_round}

    def set_state(self, state: Dict) -> None:
        for a, r in state["rewards"].items():
            self.stats[a] = _ActionStat(list(r))
        self.cur_confidence = state["cur_confidence"]
        self.last_round = state["last_round"]


class SampsonSampler(ReinforcementLearner):
    """Thompson-style sampler over the empirical reward sample."""

    def initialize(self, config: Dict) -> "SampsonSampler":
        self.min_sample = int(config.get("min.sample", 10))
        self.max_reward = float(config.get("max.reward", 100.0))
        self.stats: Dict[str, _ActionStat] = {a: _ActionStat() for a in self.actions}
        return self

    def sample_reward(self, action: str) -> float:
        stat = self.stats[action]
        if stat.count < self.min_sample:
            return self.rng.uniform(0.0, self.max_reward)
        return stat.rewards[self.rng.randrange(stat.count)]

    def next_actions(self, round_num: int) -> List[str]:
        return [max(self.actions, key=self.sample_reward) for _ in range(self.batch_size)]

    def set_reward(self, action: str, reward: float) -> None:
        self.stats[action].rewards.append(float(reward))

    def get_state(self) -> Dict:
        return {"rewards": {a: list(s.rewards) for a, s in self.stats.items()}}

    def set_state(self, state: Dict) -> None:
        for a, r in state["rewards"].items():
            self.stats[a] = _ActionStat(list(r))


class OptimisticSampsonSampler(SampsonSampler):
    """Sampled reward floored at the action's mean (:49-52)."""

    def sample_reward(self, action: str) -> float:
        drawn = super().sample_reward(action)
        return max(drawn, self.stats[action].mean)


class RandomGreedyLearner(ReinforcementLearner):
    """Online ε-greedy with decaying exploration."""

    def initialize(self, config: Dict) -> "RandomGreedyLearner":
        self.epsilon = float(config.get("random.selection.prob", 1.0))
        self.decay = str(config.get("prob.reduction.algorithm", "linear"))
        self.c = float(config.get("prob.reduction.constant", 1.0))
        self.stats: Dict[str, _ActionStat] = {a: _ActionStat() for a in self.actions}
        return self

    def _epsilon(self, round_num: int) -> float:
        t = max(round_num, 1)
        if self.decay == "linear":
            return min(self.epsilon * self.c / t, self.epsilon)
        if self.decay == "logLinear":
            return min(self.epsilon * self.c * np.log(max(t, 2)) / t, self.epsilon)
        return self.epsilon

    def next_actions(self, round_num: int) -> List[str]:
        eps = self._epsilon(round_num)
        out = []
        for _ in range(self.batch_size):
            if self.rng.random() < eps:
                out.append(self.rng.choice(self.actions))
            else:
                out.append(max(self.actions, key=lambda a: self.stats[a].mean))
        return out

    def set_reward(self, action: str, reward: float) -> None:
        self.stats[action].rewards.append(float(reward))

    def get_state(self) -> Dict:
        return {"rewards": {a: list(s.rewards) for a, s in self.stats.items()}}

    def set_state(self, state: Dict) -> None:
        for a, r in state["rewards"].items():
            self.stats[a] = _ActionStat(list(r))


LEARNER_REGISTRY = {
    "intervalEstimator": IntervalEstimator,
    "sampsonSampler": SampsonSampler,
    "optimisticSampsonSampler": OptimisticSampsonSampler,
    "randomGreedy": RandomGreedyLearner,
}


def create_learner(name: str, actions: Sequence[str], config: Optional[Dict] = None,
                   batch_size: int = 1, seed: int = 0) -> ReinforcementLearner:
    """The factory (ReinforcementLearnerFactory.java:35-46)."""
    try:
        cls = LEARNER_REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown learner {name!r}; known: {sorted(LEARNER_REGISTRY)}") from None
    return (cls().with_actions(actions).with_batch_size(batch_size)
            .with_seed(seed).initialize(config or {}))


# ---------------------------------------------------------------------------
# pool utilities (API parity with GroupedItems / ExplorationCounter)
# ---------------------------------------------------------------------------

@dataclass
class Item:
    item_id: str
    count: int = 0
    reward: float = 0.0


class GroupedItems:
    """Arm-pool ops: not-tried collection, random select, max reward."""

    def __init__(self, items: Optional[Sequence[Item]] = None, seed: int = 0):
        self.items: List[Item] = list(items or [])
        self.rng = _random.Random(seed)

    def add(self, item: Item) -> None:
        self.items.append(item)

    def size(self) -> int:
        return len(self.items)

    def collect_items_not_tried(self, batch_size: int) -> List[Item]:
        return [it for it in self.items if it.count == 0][:batch_size]

    def select_random(self) -> Item:
        return self.items[self.rng.randrange(len(self.items))]

    def get_max_reward_item(self) -> Item:
        return max(self.items, key=lambda it: it.reward)


class ExplorationCounter:
    """Rolling exploration-window math over the item indices."""

    def __init__(self, count: int, batch_size: int, exploration_count: int):
        self.count = count
        self.batch_size = batch_size
        self.exploration_count = exploration_count
        self.selections: List[range] = []

    def select_next_round(self, round_num: int) -> None:
        remaining = self.exploration_count - (round_num - 1) * self.batch_size
        self.selections = []
        if remaining > 0:
            beg = remaining % self.count
            end = beg + self.batch_size - 1
            if end >= self.count:
                self.selections = [range(beg, self.count), range(0, end - self.count + 1)]
            else:
                self.selections = [range(beg, end + 1)]

    def in_exploration(self) -> bool:
        return bool(self.selections)

    def selected_indices(self) -> List[int]:
        return [i for r in self.selections for i in r]
