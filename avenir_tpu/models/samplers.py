"""Class-balancing and bootstrap samplers.

Capability parity with ``explore/BaggingSampler.java`` (map-only bootstrap
sampling with replacement per in-memory batch of ``batch.size`` rows
:100-122) and ``explore/UnderSamplingBalancer.java`` (streaming majority-class
undersampler: bootstrap the class distribution from the first
``distr.batch.size`` rows, then always emit minority rows and emit majority
rows with probability minCount/count :92-164).

TPU design: sampling decisions are vectorized jax.random kernels over whole
batches (index draws / keep-masks) rather than per-record RNG calls; the
streaming variant keeps the running class counts on host exactly like the
reference's streaming estimate.
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.core.encoding import EncodedDataset


@functools.partial(jax.jit, static_argnames=("n", "k"))
def bootstrap_indices(key: jax.Array, n: int, k: Optional[int] = None) -> jax.Array:
    """k (default n) indices drawn uniformly with replacement from [0, n)."""
    return jax.random.randint(key, ((k if k is not None else n),), 0, n)


def bagging_sample(key: jax.Array, ds: EncodedDataset, k: Optional[int] = None) -> EncodedDataset:
    """Bootstrap resample of a batch (with replacement), preserving all columns."""
    idx = np.asarray(bootstrap_indices(key, ds.num_rows, k))
    return EncodedDataset(
        codes=ds.codes[idx], cont=ds.cont[idx],
        labels=None if ds.labels is None else ds.labels[idx],
        ids=None if ds.ids is None else ds.ids[idx],
        n_bins=ds.n_bins, class_values=ds.class_values,
        binned_ordinals=ds.binned_ordinals, cont_ordinals=ds.cont_ordinals,
    )


@jax.jit
def undersample_mask(key: jax.Array, labels: jax.Array, class_counts: jax.Array) -> jax.Array:
    """Keep-mask balancing classes: minority rows always kept; class c rows
    kept with probability min_count / count_c (the reference's acceptance
    rule)."""
    counts = jnp.maximum(class_counts.astype(jnp.float32), 1.0)
    min_count = jnp.min(jnp.where(class_counts > 0, counts, jnp.inf))
    keep_prob = min_count / counts                      # [C]
    u = jax.random.uniform(key, labels.shape)
    return u < keep_prob[labels]


def undersample(key: jax.Array, ds: EncodedDataset,
                class_counts: Optional[np.ndarray] = None) -> EncodedDataset:
    """Balanced subsample of a batch. ``class_counts`` defaults to the batch's
    own counts (whole-dataset mode); pass running counts for streaming."""
    if ds.labels is None:
        raise ValueError("undersampling requires labels")
    if class_counts is None:
        class_counts = np.bincount(ds.labels, minlength=ds.num_classes)
    mask = np.asarray(undersample_mask(key, jnp.asarray(ds.labels),
                                       jnp.asarray(class_counts)))
    idx = np.flatnonzero(mask)
    return EncodedDataset(
        codes=ds.codes[idx], cont=ds.cont[idx], labels=ds.labels[idx],
        ids=None if ds.ids is None else ds.ids[idx],
        n_bins=ds.n_bins, class_values=ds.class_values,
        binned_ordinals=ds.binned_ordinals, cont_ordinals=ds.cont_ordinals,
    )


class StreamingUnderSampler:
    """Streaming variant: like the reference, the class distribution is
    estimated from the rows seen so far (first batches are buffered until
    ``bootstrap_rows`` rows have arrived, then flushed and sampling begins)."""

    def __init__(self, key: jax.Array, bootstrap_rows: int = 10_000):
        self.key = key
        self.bootstrap_rows = bootstrap_rows
        self._counts: Optional[np.ndarray] = None
        self._buffered = 0

    def process(self, chunks: Iterable[EncodedDataset]) -> Iterator[EncodedDataset]:
        pending = []
        for ds in chunks:
            if ds.labels is None:
                raise ValueError("undersampling requires labels")
            batch_counts = np.bincount(ds.labels, minlength=ds.num_classes)
            self._counts = batch_counts if self._counts is None else self._counts + batch_counts
            if self._buffered < self.bootstrap_rows:
                pending.append(ds)
                self._buffered += ds.num_rows
                if self._buffered >= self.bootstrap_rows:
                    for p in pending:
                        yield self._sample(p)
                    pending = []
            else:
                yield self._sample(ds)
        for p in pending:  # stream ended before bootstrap filled
            yield self._sample(p)

    def _sample(self, ds: EncodedDataset) -> EncodedDataset:
        self.key, sub = jax.random.split(self.key)
        return undersample(sub, ds, self._counts)
