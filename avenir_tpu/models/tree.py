"""Decision-tree induction — candidate-split search + frontier growth.

Capability parity with the reference's tree stack:

- candidate-split enumeration (explore/ClassPartitionGenerator.java: numeric =
  all increasing split-point sets on the bucketWidth grid with up to
  maxSplit−1 points :280-311; categorical = all partitions of the value set
  into 2..maxSplit groups :318-432);
- attribute-selection strategies all / userSpecified / random-k
  (Random-Forest-style) (:160-196);
- split quality from per-split segment×class histograms with algorithms
  entropy / gini (gain ratio, util/AttributeSplitStat.java:85-93,179-218),
  hellingerDistance (binary class, :228-284) and classConfidenceRatio
  (:291-339); dataset-level info content for the root
  (util/InfoContentStat.java:55-85);
- tree growth (tree/SplitGenerator.java + tree/DataPartitioner.java): best or
  random-from-top-N split selection (:181-185) and recursive partitioning.

TPU re-design: the reference runs TWO MapReduce jobs per tree node per level
and encodes the tree as an HDFS directory layout (DataPartitioner.java:114-148).
Here the whole frontier grows in memory: records carry a node-id vector, every
candidate split of every active node is scored in one batched einsum
([S, G, K, C] = splits × segments × nodes × classes) per attribute chunk, and
partitioning is a vectorized segment-table gather — no data movement at all.
Prediction compiles the tree into flat arrays (attr / segment-table / child /
leaf-distribution) walked by a fixed-depth jitted gather loop.
"""

from __future__ import annotations

import functools
import json
import time
from dataclasses import dataclass, field as dc_field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.core.encoding import EncodedDataset
from avenir_tpu.ops import agg, info
from avenir_tpu.utils.metrics import ConfusionMatrix, Counters

ALGORITHMS = ("entropy", "giniIndex", "hellingerDistance", "classConfidenceRatio")

# level-table / split-histogram strategy (``tree.hist.mode``):
# ``direct``   — today's path: one full contraction per level, per-split
#                histograms via the segment einsum;
# ``cumsum``   — binary-threshold candidates score from ONE bin-axis
#                cumsum of the level table (info.binary_split_histograms;
#                a B× cut in per-level scoring work); non-binary
#                candidate sets keep the einsum;
# ``subtract`` — cumsum scoring PLUS sibling-subtraction level tables:
#                per level only the smaller children of each split are
#                contracted (through the same int8-MXU cross-gram path
#                when applicable) and each largest sibling is derived by
#                exact parent-slice subtraction — roughly halving the
#                per-level gram work for binary trees.
# Every mode grows trees byte-identical to the ``selection="host"``
# oracle: counts are exact integer folds either way and tie-breaking is
# unchanged (asserted across all four algorithms in tests/test_tree.py).
HIST_MODES = ("direct", "cumsum", "subtract")


# ---------------------------------------------------------------------------
# candidate splits
# ---------------------------------------------------------------------------

@dataclass
class CandidateSplit:
    """A way to segment one binned attribute.

    ``seg_of_bin[b]`` maps the attribute's bin code to a segment index —
    the device-friendly compilation of the reference's
    AttributeSplitHandler.Split containers (IntegerSplit: segment = first
    split point ≥ value :135-168; CategoricalSplit: group membership
    :174-234). ``key`` is a human-readable split id in the same spirit as the
    reference's serialized split keys.
    """

    attr: int
    kind: str                    # "numeric" | "categorical"
    seg_of_bin: np.ndarray       # [B] int32
    num_segments: int
    key: str


def enumerate_numeric_splits(
    n_bins: int, max_split: int, pad_bins: int, max_candidates: int = 512,
) -> List[Tuple[Tuple[int, ...], np.ndarray]]:
    """All increasing threshold tuples (1..max_split−1 points) on the bin grid.

    A threshold t means codes < t go left of that point; k thresholds make
    k+1 segments. Mirrors createNumPartitions' recursion over the bucketWidth
    grid (thresholds here are bin indices; bin b ≡ grid value offset+b)."""
    out: List[Tuple[Tuple[int, ...], np.ndarray]] = []

    def seg_map(thresholds: Tuple[int, ...]) -> np.ndarray:
        segs = np.zeros(pad_bins, np.int32)
        arange = np.arange(pad_bins)
        for t in thresholds:
            segs += (arange >= t).astype(np.int32)
        return segs

    def rec(prev: Tuple[int, ...]):
        if len(out) >= max_candidates or len(prev) >= max_split - 1:
            return
        start = (prev[-1] + 1) if prev else 1
        for t in range(start, n_bins):
            cur = prev + (t,)
            out.append((cur, seg_map(cur)))
            if len(out) >= max_candidates:
                return
            rec(cur)

    rec(())
    return out


def enumerate_categorical_partitions(
    n_values: int, max_split: int, pad_bins: int, max_candidates: int = 512,
) -> List[Tuple[Tuple[int, ...], np.ndarray]]:
    """All partitions of value indices into 2..max_split groups, via
    restricted-growth strings (canonical set-partition enumeration — the
    counterpart of createCatPartitions' group shuffling)."""
    out: List[Tuple[Tuple[int, ...], np.ndarray]] = []

    def rec(prefix: List[int], used: int):
        if len(out) >= max_candidates:
            return
        if len(prefix) == n_values:
            groups = used + 1
            if 2 <= groups <= max_split:
                segs = np.zeros(pad_bins, np.int32)
                segs[:n_values] = prefix
                # OOV / padding bins fall into segment 0
                out.append((tuple(prefix), segs))
            return
        for g in range(min(used + 1, max_split - 1) + 1):
            rec(prefix + [g], max(used, g))

    rec([0], 0)   # first value always group 0 (canonical form)
    return out


def generate_candidate_splits(
    ds: EncodedDataset,
    max_split: int = 3,
    is_categorical: Optional[Sequence[bool]] = None,
    max_candidates_per_attr: int = 256,
    attrs: Optional[Sequence[int]] = None,
) -> Dict[int, List[CandidateSplit]]:
    """Enumerate splits for each binned attribute (host-side, tiny)."""
    b = ds.max_bins
    result: Dict[int, List[CandidateSplit]] = {}
    attr_list = list(attrs) if attrs is not None else list(range(ds.num_binned))
    for a in attr_list:
        nb = int(ds.n_bins[a])
        cat = bool(is_categorical[a]) if is_categorical is not None else True
        splits: List[CandidateSplit] = []
        if cat:
            # exclude the reserved OOV slot from the partitioned value set
            for prefix, segs in enumerate_categorical_partitions(
                    max(nb - 1, 1), max_split, b, max_candidates_per_attr):
                key = f"attr{a}:cat:{''.join(map(str, prefix))}"
                splits.append(CandidateSplit(a, "categorical", segs,
                                             int(segs[:max(nb - 1, 1)].max()) + 1, key))
        else:
            for thresholds, segs in enumerate_numeric_splits(
                    nb, max_split, b, max_candidates_per_attr):
                key = f"attr{a}:num:{','.join(map(str, thresholds))}"
                splits.append(CandidateSplit(a, "numeric", segs, len(thresholds) + 1, key))
        result[a] = splits
    return result


def candidate_splits_for(
    ds: EncodedDataset,
    split_search: str,
    max_split: int,
    is_categorical: Optional[Sequence[bool]],
    max_candidates_per_attr: int = 256,
    attrs: Optional[Sequence[int]] = None,
) -> Dict[int, List[CandidateSplit]]:
    """The ONE mapping from ``split_search`` to a candidate family, shared
    by DecisionTree.fit and the ClassPartitionGenerator / DataPartitioner
    jobs — the same enumeration must produce the same keys everywhere or
    DataPartitioner's split-key lookup breaks.  ``binary`` = one sorted
    threshold on the bin-code grid for EVERY attribute (ordinal
    semantics, sklearn's candidate family); ``exhaustive`` = the
    reference's multi-way numeric/categorical enumeration."""
    if split_search == "binary":
        return generate_candidate_splits(
            ds, 2, [False] * ds.num_binned, max_candidates_per_attr,
            attrs=attrs)
    return generate_candidate_splits(
        ds, max_split, is_categorical, max_candidates_per_attr, attrs=attrs)


# ---------------------------------------------------------------------------
# split evaluation on device
# ---------------------------------------------------------------------------

# rows per f32-exact einsum block in node_bin_class_counts; module-level so
# tests can shrink it to exercise the scanned multi-block path cheaply
_EINSUM_BLOCK = 1 << 23


@functools.partial(jax.jit, static_argnames=("num_nodes", "num_classes",
                                             "num_bins"))
def node_bin_class_counts(
    codes: jax.Array,        # [N, F]
    node_ids: jax.Array,     # [N] active-node index (−1 = inactive/settled)
    labels: jax.Array,       # [N]
    num_nodes: int, num_classes: int, num_bins: int,
) -> jax.Array:
    """[F, B, K, C] per-(feature bin, frontier node, class) counts — the
    level's ONE device contraction (an fbc count over the composite
    (node, class) code, i.e. an MXU matmul over one-hots; rows beyond the
    f32-exact einsum block limit are scanned in count-neutral-padded
    blocks with int32 accumulation, so any N is exact).

    Every candidate split's [S, G, K, C] histogram is a tiny host
    contraction of this table with the split's bin→segment one-hot
    (:func:`split_histograms_from_table`) — independent of N.  This
    replaces the round-3 per-split-chunk [N, S] segment-code gather +
    upload, which measured ~8k rows/s on the dev rig because every split
    chunk re-uploaded an N-row operand; the reference pays the analogous
    cost as one MR shuffle per candidate-split evaluation
    (ClassPartitionGenerator.java:199-230)."""
    c = num_classes
    valid = (node_ids >= 0) & (labels >= 0) & (labels < c)
    comp = jnp.where(valid, node_ids * c + labels, -1)
    kc = num_nodes * c

    def block(cd, cp):
        oh_b = agg.one_hot(cd, num_bins)               # [n, F, B]
        oh_k = agg.one_hot(cp, kc)                     # [n, KC]
        return jnp.einsum("nfb,nk->fbk", oh_b, oh_k,
                          precision="highest").astype(jnp.int32)

    n = codes.shape[0]
    lim = _EINSUM_BLOCK            # f32-exact einsum counts per block
    if n <= lim:
        t = block(codes, comp)
    else:
        npad = -(-n // lim) * lim
        cd = jnp.pad(codes, ((0, npad - n), (0, 0)), constant_values=-1)
        cp = jnp.pad(comp, (0, npad - n), constant_values=-1)
        f = codes.shape[1]
        t = jax.lax.scan(
            lambda acc, xs: (acc + block(xs[0], xs[1]), None),
            jnp.zeros((f, num_bins, kc), jnp.int32),
            (cd.reshape(-1, lim, f), cp.reshape(-1, lim)))[0]
    return t.reshape(t.shape[0], t.shape[1], num_nodes, c)


@functools.partial(jax.jit, static_argnames=("num_nodes", "num_classes",
                                             "num_bins", "interpret"))
def _level_table_cross(codes_t: jax.Array, node_ids: jax.Array,
                       labels: jax.Array, num_nodes: int, num_classes: int,
                       num_bins: int, interpret: bool = False) -> jax.Array:
    """The level table via the fused cross-gram kernel
    (``pallas_hist.cross_cooc_counts_cols``): X = (feature, bin) one-hot,
    Y = (node, class) one-hot, table = XᵀY on the int8 MXU with both
    expansions in VMEM — the einsum form's [N, F, B] HBM one-hot
    (~400 B/row/level) becomes a ~24 B/row code stream.  Bit-identical
    counts (int8 0/1 operands, int32 accumulation; invalid codes, settled
    rows and out-of-range labels all drop out exactly as the einsum's
    zero one-hot rows)."""
    from avenir_tpu.ops import pallas_hist

    c = num_classes
    valid = (node_ids >= 0) & (labels >= 0) & (labels < c)
    sel = jnp.where(valid, node_ids * c + labels, -1)
    t = pallas_hist.cross_cooc_counts_cols.__wrapped__(
        codes_t, sel, num_bins, num_nodes * c, interpret=interpret)
    return t.reshape(t.shape[0], t.shape[1], num_nodes, c)


@functools.partial(jax.jit, static_argnames=("pplan", "kernel", "interpret"))
def _level_table_packed(codes_t: jax.Array, node_ids: jax.Array,
                        labels: jax.Array, pplan, kernel: bool,
                        interpret: bool = False) -> jax.Array:
    """The level table via a PackGraft disjoint pack: the K frontier
    nodes' [F, B, C] tables ride ONE wide gram over K bin stripes
    (composite code = code + node·stripe_bins, ``pallas_hist.pack_disjoint``)
    so sibling tables the subtraction plan still contracts one-by-one
    inherit the wide-gram width tier.  The readout is the pack's diagonal
    gather — exact: rows off the frontier (node −1) drop whole and
    out-of-range codes drop per-feature, the same validity
    ``node_bin_class_counts`` masks, and cross-member cells are
    structurally zero (one node per row).  ``kernel`` routes the joint
    shape onto the int8 MXU kernel; off it the exact einsum gram runs
    the same layout.  Returns [F, B, K, C]."""
    from avenir_tpu.ops import pallas_hist

    c = pplan.num_classes
    comp = pallas_hist.packed_codes.__wrapped__(
        codes_t, node_ids, pplan.stripe_bins, pplan.members[0].num_bins)
    if kernel:
        g = pallas_hist.cooc_counts_cols.__wrapped__(
            comp, labels, pplan.num_bins, c, interpret=interpret)
    else:
        g = pallas_hist.gram_counts_cols.__wrapped__(
            comp, labels, pplan.num_bins, c)
    wi = jnp.asarray(pallas_hist.packed_diag_index(pplan))   # [F, B, K, C]
    if g.ndim == 3:                          # cls/clsb: per-class diagonal
        w2 = wi[..., 0]                      # [F, B, K] — same cell per class
        t = jnp.moveaxis(g[:, w2, w2], 0, -1)
    else:                                    # fmaj/jmaj: class rides the cell
        t = g[wi, wi]
    return t.astype(jnp.int32)


@jax.jit
def _remap_nodes(node: jax.Array, remap: jax.Array) -> jax.Array:
    """[N] absolute node ids → frontier-local indices (−1 = settled)."""
    return remap[jnp.maximum(node, 0)]


@jax.jit
def _apply_level_partition(codes: jax.Array, node: jax.Array,
                           remap: jax.Array, attr: jax.Array,
                           child_tab: jax.Array) -> jax.Array:
    """Device-side frontier partition: rows of frontier node ki whose
    level-chosen split routes bin b to child ``child_tab[ki, b]`` move
    there; settled rows and unsplit frontier rows (child −1) keep their
    id.  The [N] node vector thus lives ON DEVICE across levels — per
    level only KB-sized tables travel (remap, per-node split attr, the
    bin→child table), replacing the round-4 host partition + full [N]
    re-upload whose tunnel round trips dominated induction time on the
    dev rig (and are pure waste on any host).

    A −1 (invalid) code indexes the LAST bin — the same semantics the
    host path inherited from numpy's negative indexing, kept so the
    device partition is bit-identical to it."""
    local = _remap_nodes.__wrapped__(node, remap)
    lc = jnp.maximum(local, 0)
    a = attr[lc]                                             # [N]
    code = jnp.take_along_axis(codes, a[:, None], axis=1)[:, 0]
    b = child_tab.shape[1]
    code = jnp.where(code < 0, code + b, code)
    code = jnp.clip(code, 0, b - 1)
    new = child_tab[lc, code]
    return jnp.where((local >= 0) & (new >= 0), new, node)


def split_histograms_from_table(table_a: np.ndarray,
                                chunk: Sequence["CandidateSplit"],
                                gmax: int) -> np.ndarray:
    """table_a [B, K, C] (one attribute's slice of the level table) →
    [S, G, K, C] histograms for a chunk of candidate splits — pure host
    numpy over segment maps; no N-dependent work."""
    seg_tab = np.stack([sp.seg_of_bin for sp in chunk])          # [S, B]
    m = (seg_tab[:, None, :] == np.arange(gmax)[None, :, None])  # [S, G, B]
    return np.einsum("sgb,bkc->sgkc", m, table_a)


def _chunk_seg_mask(chunk: Sequence["CandidateSplit"], gmax: int) -> np.ndarray:
    """[S, G] validity mask: segment g is real for split s iff
    g < num_segments — shared by the host and device scoring paths so
    padded segments never leak into a score (classConfidenceRatio is the
    one algorithm not zero-count-invariant: an empty padded segment would
    contribute confidence (0+1)/(0+1) = 1, making the score depend on
    which splits happened to share a chunk/padding width)."""
    nsegs = np.array([sp.num_segments for sp in chunk], np.int32)
    return nsegs[:, None] > np.arange(gmax, dtype=np.int32)[None, :]


def iter_scored_splits(table: np.ndarray, all_splits, algorithm: str,
                       split_chunk: int, attrs=None, parent_info=None):
    """Yield (attr, chunk, scores [S, K], hist [S, G, K, C]) per candidate
    split chunk, all derived from the level table on the LOCAL host
    backend — the host reference pipeline behind ``selection="host"`` and
    the device-selection equivalence tests.

    Scores go through the JITTED ``split_scores`` (``_split_scores_jit``):
    the compiled graph rounds identically whether it runs standalone here
    or fused inside the device-selection dispatch, and it is invariant to
    chunk shape and zero-segment padding (measured: 0 mismatching bits
    across all four algorithms on the retarget candidate set) — eager
    per-op scoring differs from the fused form in the last float bit,
    which would break the byte-identical-tree contract between paths."""
    with info.on_host():
        for a in (attrs if attrs is not None else sorted(all_splits)):
            splits = all_splits[a]
            if not splits:
                continue
            for s0 in range(0, len(splits), split_chunk):
                chunk = splits[s0:s0 + split_chunk]
                gmax = max(sp.num_segments for sp in chunk)
                hist = split_histograms_from_table(table[a], chunk, gmax)
                scores = np.asarray(_split_scores_jit(
                    jnp.asarray(hist, jnp.float32), algorithm,
                    parent_info=parent_info,
                    seg_mask=jnp.asarray(_chunk_seg_mask(chunk, gmax))))
                yield a, chunk, scores, hist


def split_scores(hist: jax.Array, algorithm: str,
                 parent_info: Optional[float] = None,
                 seg_mask: Optional[jax.Array] = None) -> jax.Array:
    """hist [S, G, K, C] → score [S, K]; higher is better for every algorithm.

    entropy/giniIndex → gain ratio: (parent impurity − weighted child
    impurity) / split info content (AttributeSplitStat.java:85-93,153-218).
    ``parent_info``, when given, substitutes the reference's externally
    supplied ``parent.info`` property (ClassPartitionGenerator.java:510,533
    — produced by the ``at.root`` bootstrap job) for the parent impurity
    computed from the node's own histogram (the self-contained default).
    hellingerDistance → distance between the per-class segment distributions
    (binary class, :228-284). classConfidenceRatio → entropy of the
    normalized per-segment class-confidence ratios (:291-339); lower entropy
    = more skew = better, so the score is negated entropy.

    ``seg_mask`` [S, G] marks which segments are real for each split (the
    histogram may be zero-padded to a common G).  entropy / gini /
    hellinger are bit-invariant to all-zero padded segments (each
    contributes an exact +0.0 term), so the mask only gates
    classConfidenceRatio, whose +1 Laplace smoothing would otherwise count
    phantom segments.  With the mask, scores are independent of chunk
    composition and padding width — the property the device and host
    selection paths rely on for byte-identical trees.
    """
    h = hist.astype(jnp.float32)                          # [S, G, K, C]
    seg_tot = h.sum(-1)                                   # [S, G, K]
    node_tot = jnp.maximum(seg_tot.sum(1), 1e-9)          # [S, K]
    w = seg_tot / node_tot[:, None, :]                    # segment weights
    parent = h.sum(1)                                     # [S, K, C]
    if algorithm in ("entropy", "giniIndex"):
        imp = info.entropy_from_counts if algorithm == "entropy" else info.gini_from_counts
        child = imp(h, axis=-1)                           # [S, G, K]
        weighted = jnp.sum(w * child, axis=1)             # [S, K]
        p_imp = (imp(parent, axis=-1) if parent_info is None
                 else jnp.float32(parent_info))
        gain = p_imp - weighted
        split_info = info.entropy(jnp.swapaxes(w, 1, 2), axis=-1)   # [S, K]
        return gain / jnp.maximum(split_info, 1e-6)
    if algorithm == "hellingerDistance":
        cls_tot = jnp.maximum(h.sum(1, keepdims=True), 1e-9)        # [S, 1, K, C]
        p_seg_given_c = h / cls_tot                                  # [S, G, K, C]
        d = (jnp.sqrt(p_seg_given_c[..., 0]) - jnp.sqrt(p_seg_given_c[..., 1])) ** 2
        return jnp.sqrt(jnp.maximum(d.sum(1), 0.0)) / jnp.sqrt(2.0)  # [S, K]
    if algorithm == "classConfidenceRatio":
        conf = (h[..., 0] + 1.0) / (h[..., 1] + 1.0)                 # [S, G, K]
        if seg_mask is not None:
            conf = jnp.where(seg_mask[:, :, None], conf, 0.0)
        ratio = conf / jnp.maximum(conf.sum(1, keepdims=True), 1e-9)
        return -info.entropy(jnp.swapaxes(ratio, 1, 2), axis=-1)
    raise ValueError(f"unknown algorithm {algorithm!r}; known: {ALGORITHMS}")


# the one compiled scoring graph shared by the host pipeline and (inlined)
# the device-selection dispatch — see iter_scored_splits on why eager
# scoring is not bit-compatible with the fused form
_split_scores_jit = jax.jit(split_scores, static_argnames=("algorithm",))


# ---------------------------------------------------------------------------
# device-resident split selection
# ---------------------------------------------------------------------------

@dataclass
class FlatSplits:
    """Per-fit static candidate-split metadata, compiled once into padded
    device arrays so the per-level selection dispatch is jit-stable across
    levels (only the frontier width K varies).

    ``splits`` holds the CandidateSplit objects in device flat order —
    ascending attribute, then enumeration order within the attribute (the
    same order the host path iterates, so argmax/top-k tie-breaking by
    lowest flat index reproduces the host's stable sort).  The arrays are
    padded to a multiple of ``chunk`` rows; pad rows have ``valid`` False
    and are force-masked to −inf before selection.
    """

    splits: List[CandidateSplit]
    attr_of: np.ndarray                  # [S_pad] int32 (host copy, for masks)
    valid: np.ndarray                    # [S_pad] bool — False on pad rows
    gmax: int
    chunk: int
    seg_tab_dev: jax.Array               # [S_pad, B] int32
    attr_dev: jax.Array                  # [S_pad] int32
    nseg_dev: jax.Array                  # [S_pad] int32
    # binary-threshold metadata for the cumsum fast path: thr_of[s] = the
    # single sorted threshold of split s (0 on pad rows), meaningful only
    # when ``all_binary`` — every real split is a two-segment numeric
    # threshold (codes < t left), i.e. the split.search=binary family
    thr_of: np.ndarray = dc_field(default_factory=lambda: np.zeros(0, np.int32))
    thr_dev: Optional[jax.Array] = None
    all_binary: bool = False

    @property
    def num_real(self) -> int:
        return len(self.splits)

    def allow_vector(self, attrs: Sequence[int]) -> np.ndarray:
        """[S_pad] bool — splits whose attribute the level's strategy
        selected (randomK / userSpecified), excluding pad rows.  A tiny
        per-level host→device upload; everything else is fit-static."""
        return self.valid & np.isin(
            self.attr_of, np.asarray(list(attrs), np.int32))


def flatten_splits(all_splits: Dict[int, List[CandidateSplit]],
                   max_bins: int, split_chunk: int) -> FlatSplits:
    """Compile the per-attr candidate dict into FlatSplits device arrays."""
    flat = [sp for a in sorted(all_splits) for sp in all_splits[a]]
    s = len(flat)
    gmax = max([sp.num_segments for sp in flat] or [1])
    chunk = max(1, min(split_chunk, max(s, 1)))
    s_pad = max(-(-s // chunk) * chunk, chunk)
    seg_tab = np.zeros((s_pad, max_bins), np.int32)
    attr_of = np.zeros(s_pad, np.int32)
    nseg = np.ones(s_pad, np.int32)
    valid = np.zeros(s_pad, bool)
    thr = np.zeros(s_pad, np.int32)
    all_binary = s > 0
    for i, sp in enumerate(flat):
        seg_tab[i] = sp.seg_of_bin
        attr_of[i] = sp.attr
        nseg[i] = sp.num_segments
        valid[i] = True
        t = int(np.argmax(sp.seg_of_bin == 1)) if sp.num_segments == 2 else 0
        if (sp.kind == "numeric" and sp.num_segments == 2 and t > 0
                and np.array_equal(
                    sp.seg_of_bin,
                    (np.arange(len(sp.seg_of_bin)) >= t).astype(np.int32))):
            thr[i] = t
        else:
            all_binary = False
    return FlatSplits(
        splits=flat, attr_of=attr_of, valid=valid, gmax=gmax, chunk=chunk,
        seg_tab_dev=jnp.asarray(seg_tab), attr_dev=jnp.asarray(attr_of),
        nseg_dev=jnp.asarray(nseg), thr_of=thr, thr_dev=jnp.asarray(thr),
        all_binary=all_binary)


def _scored_chunks(table: jax.Array, seg_tab: jax.Array, attr_of: jax.Array,
                   nseg: jax.Array, algorithm: str, gmax: int, chunk: int,
                   parent_info=None, want_hist: bool = False,
                   thr: Optional[jax.Array] = None, binary: bool = False):
    """Score every padded candidate split against the device level table in
    ``chunk``-sized blocks under ``lax.map`` (bounds the [s, B, K, C]
    gather working set).  Returns scores [S_pad, K] and, when
    ``want_hist``, the [S_pad, G, K, C] int32 histograms.

    With ``binary`` (the cumsum fast path, ``tree.hist.mode`` cumsum /
    subtract + an all-binary candidate family), the per-split histogram
    is two gathers against ONE bin-axis cumsum of the table
    (:func:`info.binary_split_histograms`) instead of the per-split
    segment einsum — identical int32 histograms (exact prefix sums), the
    same block structure and the same ``split_scores`` graph on the same
    shapes, so scores stay bit-identical to the einsum form."""
    s_pad, b = seg_tab.shape
    nc = s_pad // chunk
    grange = jnp.arange(gmax, dtype=jnp.int32)
    cum = info.cumulative_level_table(table) if binary else None
    if binary:
        assert gmax == 2, "binary cumsum path requires two-segment splits"

    def block(args):
        if binary:
            th, ao, ns = args                               # [s] [s] [s]
            h = info.binary_split_histograms(cum, ao, th)
        else:
            st, ao, ns = args                               # [s,B] [s] [s]
            h = info.split_segment_histograms(table, st, ao, gmax)
        mask = grange[None, :] < ns[:, None]                # [s, G]
        sc = split_scores(h.astype(jnp.float32), algorithm,
                          parent_info=parent_info, seg_mask=mask)
        return (sc, h) if want_hist else (sc,)

    lead = (thr.reshape(nc, chunk) if binary
            else seg_tab.reshape(nc, chunk, b))
    out = jax.lax.map(block, (lead, attr_of.reshape(nc, chunk),
                              nseg.reshape(nc, chunk)))
    k = table.shape[2]
    scores = out[0].reshape(s_pad, k)
    if want_hist:
        return scores, out[1].reshape(s_pad, gmax, k, table.shape[3])
    return scores, None


@functools.partial(jax.jit, static_argnames=("algorithm", "gmax", "top_k",
                                             "chunk", "binary"))
def _device_select_splits(table: jax.Array, seg_tab: jax.Array,
                          attr_of: jax.Array, nseg: jax.Array,
                          allow: jax.Array, thr: Optional[jax.Array] = None,
                          *, algorithm: str, gmax: int,
                          top_k: int, chunk: int, binary: bool = False):
    """Device-resident split selection for one frontier level: build every
    candidate's segment histogram from the on-device [F, B, K, C] table
    (``info.split_segment_histograms`` — a device einsum, not a host numpy
    pass), score with the ``split_scores`` kernels, and take the top-k
    winners PER FRONTIER NODE on device.  The host fetches only the
    KB-sized descriptors (score, flat split index, [G, C] winner
    histogram) — replacing the full-table fetch + host fold whose ~100 ms
    tunnel RTT per level dominated induction wall time (BENCH_r05
    ``families.tree``).

    Returns (vals [K, P], idx [K, P], hist [K, P, G, C] int32), P = top_k,
    sorted best-first; ``lax.top_k`` breaks ties toward the lowest flat
    index, matching the host path's stable sort over its iteration order.
    Disallowed (strategy-masked) and pad candidates come back as −inf.
    """
    scores, _ = _scored_chunks(table, seg_tab, attr_of, nseg,
                               algorithm, gmax, chunk, thr=thr, binary=binary)
    scores = jnp.where(allow[:, None] & ~jnp.isnan(scores), scores, -jnp.inf)
    vals, idx = jax.lax.top_k(scores.T, top_k)              # [K, P] each
    k = table.shape[2]
    grange = jnp.arange(gmax, dtype=jnp.int32)
    tt = jnp.transpose(table, (2, 0, 1, 3))                 # [K, F, B, C]
    w_ta = tt[jnp.arange(k)[:, None], attr_of[idx]]         # [K, P, B, C]
    w_m = (seg_tab[idx][:, :, None, :] ==
           grange[None, None, :, None]).astype(jnp.int32)   # [K, P, G, B]
    w_hist = jnp.einsum("kpgb,kpbc->kpgc", w_m, w_ta)       # int32
    return vals, idx, w_hist


@functools.partial(jax.jit, static_argnames=("algorithm", "gmax", "chunk",
                                             "has_parent", "want_hist",
                                             "binary"))
def _device_score_all(table: jax.Array, seg_tab: jax.Array,
                      attr_of: jax.Array, nseg: jax.Array, parent_info,
                      thr: Optional[jax.Array] = None,
                      *, algorithm: str, gmax: int, chunk: int,
                      has_parent: bool, want_hist: bool = False,
                      binary: bool = False):
    """Score EVERY candidate split on device and return (scores [S_pad, K],
    hist [S_pad, G, K, C] or None) — the batched entry behind the
    ClassPartitionGenerator job, whose contract is the full scored list
    rather than a per-node winner.  One dispatch; the fetch is the
    [S, K] score sheet (plus, only when ``want_hist``, the small
    histograms for the optional segment-distribution output columns),
    never the [F, B, K, C] table."""
    return _scored_chunks(table, seg_tab, attr_of, nseg, algorithm, gmax,
                          chunk, parent_info=parent_info if has_parent
                          else None, want_hist=want_hist, thr=thr,
                          binary=binary)


@jax.jit
def _assemble_subtract_table(direct_table: jax.Array, prev_table: jax.Array,
                             dslot: jax.Array, pslot: jax.Array,
                             sib_mat: jax.Array) -> jax.Array:
    """Sibling-subtraction level-table assembly (``tree.hist.mode``
    subtract): the frontier's [F, B, K, C] table from the [F, B, D, C]
    DIRECT table (only the smaller children of each split were
    contracted) plus the parent level's resident table.

    Node k is either direct (``dslot[k]`` ≥ 0 → its own contraction
    slice) or derived: its parent's previous-level slice
    (``pslot[k]``) minus the sum of its directly-contracted siblings
    (``sib_mat[k]`` one-hot over direct slots).  Every row of a split
    parent routes to exactly one child segment and label-invalid rows
    are excluded identically from parent and child counts, so the
    int32 subtraction is EXACT — the derived slice equals the direct
    contraction bit-for-bit (asserted in tests/test_tree.py)."""
    direct_part = direct_table[:, :, jnp.maximum(dslot, 0), :]
    parent_part = prev_table[:, :, jnp.maximum(pslot, 0), :]
    sib_sum = jnp.einsum("fbdc,kd->fbkc", direct_table, sib_mat)
    return jnp.where((dslot >= 0)[None, None, :, None],
                     direct_part, parent_part - sib_sum)


# ---------------------------------------------------------------------------
# tree model
# ---------------------------------------------------------------------------

@dataclass
class TreeNode:
    node_id: int
    depth: int
    class_counts: np.ndarray            # [C]
    split: Optional[CandidateSplit] = None
    children: List[int] = dc_field(default_factory=list)
    score: float = 0.0

    @property
    def is_leaf(self) -> bool:
        return self.split is None


@dataclass
class DecisionTreeModel:
    nodes: List[TreeNode]
    class_values: List[str]
    max_bins: int
    algorithm: str
    # the CONFIGURED depth / segment caps the tree was grown under (None
    # on legacy artifacts).  predict_shape_signature buckets on these,
    # not on what the tree happened to grow, so a retrain at the same
    # caps that grows shallower or narrower still lands in the same
    # compiled-walker bucket
    depth_cap: Optional[int] = None
    split_cap: Optional[int] = None

    # compiled arrays for jitted prediction
    def compile_arrays(self, pad: bool = False):
        """Flat device arrays for the jitted walker.  With ``pad``, the
        node and segment axes round up to power-of-two buckets
        (:func:`_pow2_bucket`): pad node rows are self-loop leaves with a
        zero distribution and are unreachable from the root, so padded
        and unpadded walks are byte-identical — what lets a retrained
        tree of a different size land in the SAME compiled scoring
        program (see :func:`predict_fn`; the StreamGraft
        drift→retrain→hot-swap path relies on it for zero swap
        recompiles)."""
        m = len(self.nodes)
        gmax = max([n.split.num_segments for n in self.nodes if n.split] or [1])
        if pad:
            _dp, mp, gp, _b, _c = predict_shape_signature(self)
        else:
            mp, gp = m, gmax
        attr = np.full(mp, 0, np.int32)
        seg_table = np.zeros((mp, self.max_bins), np.int32)
        child = np.tile(np.arange(mp, dtype=np.int32)[:, None], (1, gp))
        c = len(self.class_values)
        distr = np.zeros((mp, c), np.float32)
        for n in self.nodes:
            tot = max(n.class_counts.sum(), 1.0)
            distr[n.node_id] = n.class_counts / tot
            if n.split is not None:
                attr[n.node_id] = n.split.attr
                seg_table[n.node_id] = n.split.seg_of_bin
                for g, ch in enumerate(n.children):
                    child[n.node_id, g] = ch
        return (jnp.asarray(attr), jnp.asarray(seg_table), jnp.asarray(child),
                jnp.asarray(distr))

    @property
    def max_depth(self) -> int:
        return max(n.depth for n in self.nodes)

    # -- serde ---------------------------------------------------------------
    def to_json(self) -> Dict:
        return {
            "class_values": self.class_values,
            "max_bins": self.max_bins,
            "algorithm": self.algorithm,
            "depth_cap": self.depth_cap,
            "split_cap": self.split_cap,
            "nodes": [
                {
                    "id": n.node_id, "depth": n.depth,
                    "counts": n.class_counts.tolist(),
                    "children": n.children, "score": n.score,
                    "split": None if n.split is None else {
                        "attr": n.split.attr, "kind": n.split.kind,
                        "seg_of_bin": n.split.seg_of_bin.tolist(),
                        "num_segments": n.split.num_segments, "key": n.split.key,
                    },
                }
                for n in self.nodes
            ],
        }

    @classmethod
    def from_json(cls, obj: Dict) -> "DecisionTreeModel":
        nodes = []
        for d in obj["nodes"]:
            sp = d["split"]
            nodes.append(TreeNode(
                node_id=d["id"], depth=d["depth"],
                class_counts=np.asarray(d["counts"], np.float64),
                split=None if sp is None else CandidateSplit(
                    sp["attr"], sp["kind"], np.asarray(sp["seg_of_bin"], np.int32),
                    sp["num_segments"], sp["key"]),
                children=list(d["children"]), score=d["score"],
            ))
        dcap = obj.get("depth_cap")
        scap = obj.get("split_cap")
        return cls(nodes=nodes, class_values=list(obj["class_values"]),
                   max_bins=int(obj["max_bins"]), algorithm=obj["algorithm"],
                   depth_cap=None if dcap is None else int(dcap),
                   split_cap=None if scap is None else int(scap))

    def to_string(self) -> str:
        return json.dumps(self.to_json())

    @classmethod
    def from_string(cls, s: str) -> "DecisionTreeModel":
        return cls.from_json(json.loads(s))


def _pow2_bucket(n: int) -> int:
    """Smallest power of two ≥ n (n ≥ 1 → 1, 2, 4, 8, …)."""
    return 1 << max(n - 1, 0).bit_length()


@functools.partial(jax.jit, static_argnames=("depth",))
def _tree_walk(attr: jax.Array, seg_table: jax.Array, child: jax.Array,
               distr: jax.Array, codes: jax.Array, *, depth: int):
    """The ONE compiled tree walker, shared across models: the tree
    arrays are ARGUMENTS (not closure constants), so the jit cache keys
    on their shapes — two trees with the same padded bucket shapes and
    depth bucket reuse the same executable.  Extra ``depth`` iterations
    past a tree's real depth are identities (leaves self-loop via the
    child table's diagonal default)."""
    node = jnp.zeros(codes.shape[0], jnp.int32)
    for _ in range(depth):
        a = attr[node]                                           # [N]
        code = jnp.take_along_axis(codes, a[:, None], axis=1)[:, 0]
        seg = seg_table[node, code]
        node = child[node, seg]
    d = distr[node]
    return jnp.argmax(d, axis=-1).astype(jnp.int32), d


def predict_shape_signature(model: DecisionTreeModel) -> tuple:
    """The padded compile-shape bucket of :func:`predict_fn`'s walker —
    (depth bucket, node bucket, segment bucket, max_bins, classes).  Two
    models with equal signatures share the compiled scoring program for
    any given batch shape; serving uses this as part of its compile key
    so a hot-swap onto an equal-signature tree provably compiles
    nothing.

    The depth and segment buckets come from the CONFIGURED caps the tree
    was grown under (``depth_cap`` / ``split_cap``; the grown shape only
    on legacy artifacts without them), with the segment bucket floored
    at 4 — a retrained tree that happened to grow shallower or narrower
    (e.g. only binary splits under a 5-way cap) must not land in a
    different bucket than its predecessor.  The node bucket is derived
    from the FULL-tree node bound of the depth/segment buckets (capped
    at 4096 so deep exhaustive trees don't inflate the padded arrays),
    not from this tree's own node count — so a drift→retrain of the same
    family at the same caps lands in the SAME bucket regardless of what
    it happened to grow."""
    m = len(model.nodes)
    gmax = max([n.split.num_segments for n in model.nodes if n.split] or [1])
    dp = _pow2_bucket(max(model.depth_cap or model.max_depth, 1))
    gp = max(_pow2_bucket(max(model.split_cap or 1, gmax)), 4)
    full = (gp ** (dp + 1) - 1) // (gp - 1)
    mp = _pow2_bucket(max(m, min(full, 4096)))
    return (dp, mp, gp, model.max_bins, len(model.class_values))


def predict_fn(model: DecisionTreeModel, pad_shapes: bool = True):
    """Build a jitted [N,F] codes → ([N] class idx, [N,C] distr) walker.

    With ``pad_shapes`` (default) the tree arrays pad to power-of-two
    node/segment buckets and the walk depth rounds up to a power-of-two
    bucket, so a retrained tree of a different depth/size within the
    same buckets REUSES the compiled program (:func:`_tree_walk` keys on
    shapes, not identity) — predictions are byte-identical either way
    (pad nodes unreachable, extra levels identity self-loops)."""
    attr, seg_table, child, distr = model.compile_arrays(pad=pad_shapes)
    if pad_shapes:
        depth = predict_shape_signature(model)[0]
    else:
        depth = max(model.max_depth, 1)

    def walk(codes: jax.Array):
        return _tree_walk(attr, seg_table, child, distr, codes, depth=depth)

    return walk


# ---------------------------------------------------------------------------
# estimator
# ---------------------------------------------------------------------------

class DecisionTree:
    """Frontier-growth decision-tree trainer.

    Parameters mirror the reference's job properties:
    ``algorithm`` (split.algorithm), ``max_depth`` (recursion depth of the
    SplitGenerator/DataPartitioner loop), ``min_node_size``, ``min_gain``
    (stopping), ``max_split`` (maxSplit per field), ``attr_strategy``
    all|userSpecified|randomK (split.attribute.selection.strategy),
    ``top_n`` random-from-top-N split selection (custom.base.attribute.ordinals /
    DataPartitioner.java:181-185).

    ``selection`` picks where per-level split selection runs:

    - ``"device"`` (default) — candidate histograms, scores and the
      per-node top-k winner all run on device against the resident level
      table; the host fetches only KB-sized chosen-split descriptors per
      level.  One dispatch + one small fetch per level, composing with the
      device-resident node vector (``_apply_level_partition``).
    - ``"host"`` — the prior pipeline: fetch the whole [F, B, K, C] table
      and fold it on host (``iter_scored_splits``).  Kept as the
      equivalence oracle; both paths grow byte-identical trees (asserted
      in tests across all four algorithms).  For tie-breaks to agree, the
      device flat order assumes ascending-attribute iteration; an
      unsorted ``user_attrs`` list can differ on exact score ties only.
      Byte-identity is a same-backend guarantee (the tier-1 equivalence
      tests run both paths on CPU): on a TPU the device path scores in
      TPU f32 while the host oracle scores on the local CPU backend, so
      candidates whose true scores differ by under ~1 ulp may pick
      differently there — exact ties still agree (lowest flat index).

    ``split_search`` picks the candidate family:

    - ``"exhaustive"`` (default) — the reference's multi-way search: all
      increasing threshold sets for numeric fields and all set partitions
      for categorical fields up to ``max_split`` groups
      (ClassPartitionGenerator.java:280-432).
    - ``"binary"`` — sorted-threshold binary splits only (every attribute
      treated as ordinal over its bin codes, one threshold, two
      segments) — the candidate family sklearn's DecisionTreeClassifier
      searches over ordinal-encoded inputs, scored by the same kernels;
      the apples-to-apples benchmarking mode.

    ``hist_mode`` picks the level-table / split-histogram strategy (see
    :data:`HIST_MODES`): ``direct`` (default, today's path), ``cumsum``
    (binary-threshold candidates score from one bin-axis cumsum of the
    level table — a B× cut in per-level scoring work; exhaustive
    multi-way search keeps its einsum), ``subtract`` (cumsum scoring
    plus sibling-subtraction level tables — only the smaller children
    of each split are contracted, the largest sibling derives by exact
    parent-slice subtraction, roughly halving per-level gram work).
    All three grow byte-identical trees; ``cumsum``/``subtract``
    scoring applies on the device-selection path (the ``host`` oracle
    always folds the direct form).
    """

    def __init__(
        self,
        algorithm: str = "entropy",
        max_depth: int = 4,
        min_node_size: int = 32,
        min_gain: float = 1e-4,
        max_split: int = 3,
        attr_strategy: str = "all",
        user_attrs: Optional[Sequence[int]] = None,
        random_k: Optional[int] = None,
        top_n: int = 1,
        max_candidates_per_attr: int = 128,
        split_chunk: int = 128,
        seed: int = 0,
        mesh=None,
        selection: str = "device",
        split_search: str = "exhaustive",
        hist_mode: str = "direct",
        level_packed: str = "auto",
        collect_phase_stats: bool = False,
    ):
        if algorithm not in ALGORITHMS:
            raise ValueError(f"unknown algorithm {algorithm!r}; known: {ALGORITHMS}")
        if selection not in ("device", "host"):
            raise ValueError(
                f"unknown selection {selection!r}; known: device, host")
        if split_search not in ("exhaustive", "binary"):
            raise ValueError(f"unknown split_search {split_search!r}; "
                             "known: exhaustive, binary")
        if hist_mode not in HIST_MODES:
            raise ValueError(f"unknown hist_mode {hist_mode!r}; "
                             f"known: {HIST_MODES}")
        if level_packed not in ("auto", "on", "off"):
            raise ValueError(f"unknown level_packed {level_packed!r}; "
                             "known: auto, on, off")
        self.selection = selection
        self.split_search = split_search
        self.hist_mode = hist_mode
        # PackGraft (round 16): "auto" packs frontier sibling tables into
        # one wide disjoint gram when the joint shape rides the TPU
        # kernel; "on" forces packing (einsum gram off-TPU — the testable
        # attestation path); "off" keeps cross/einsum routing only
        self.level_packed = level_packed
        # per-level phase breakdown (table-build / score+select /
        # partition wall ms) — opt-in because honest phase timings need
        # a device sync per phase; read ``self.level_stats`` after fit
        self.collect_phase_stats = collect_phase_stats
        self.level_stats: List[dict] = []
        self.algorithm = algorithm
        self.max_depth = max_depth
        self.min_node_size = min_node_size
        self.min_gain = min_gain
        self.max_split = max_split
        self.attr_strategy = attr_strategy
        self.user_attrs = list(user_attrs) if user_attrs is not None else None
        self.random_k = random_k
        self.top_n = top_n
        self.max_candidates_per_attr = max_candidates_per_attr
        self.split_chunk = split_chunk
        self.seed = seed
        self.mesh = mesh          # optional data mesh (parallel/mesh.py)

    def _attrs_for_node(self, rng: np.random.Generator, num_attrs: int) -> List[int]:
        if self.attr_strategy == "userSpecified":
            if not self.user_attrs:
                raise ValueError("userSpecified strategy requires user_attrs")
            return self.user_attrs
        if self.attr_strategy == "randomK":
            k = self.random_k or max(1, int(np.sqrt(num_attrs)))
            return sorted(rng.choice(num_attrs, size=min(k, num_attrs), replace=False).tolist())
        if self.attr_strategy == "all":
            return list(range(num_attrs))
        raise ValueError(f"unknown attr_strategy {self.attr_strategy!r}")

    def fit(self, ds: EncodedDataset,
            is_categorical: Optional[Sequence[bool]] = None) -> DecisionTreeModel:
        if ds.labels is None:
            raise ValueError("fit requires labels")
        from avenir_tpu.parallel.mesh import maybe_shard_batch

        rng = np.random.default_rng(self.seed)
        n, c = ds.num_rows, ds.num_classes
        # batch-sharded under a data mesh: pad rows carry -1 labels/node ids,
        # all count-neutral in the level contraction. Codes and labels are
        # uploaded ONCE; per level only the [N] node-id vector travels.
        labels_dev = maybe_shard_batch(self.mesh, ds.labels)[0]
        codes_dev = maybe_shard_batch(self.mesh, ds.codes)[0]
        # single-TPU fast path for the level table: the fused cross-gram
        # kernel streams columnar codes (one device transpose, once)
        from avenir_tpu.ops import pallas_hist
        # the X-side gate (feature/bin width) is level-independent: check
        # it before paying the device transpose + second HBM codes copy
        use_cross = (self.mesh is None and pallas_hist.on_tpu_single_device()
                     and pallas_hist.cross_applicable(
                         ds.num_binned, ds.max_bins, max(c, 1)))
        # PackGraft: may the level fold sibling node tables as one wide
        # disjoint gram?  auto = only where the joint shape would ride the
        # TPU kernel (the width-tier climb is the whole point); "on"
        # forces it (exact einsum gram off-TPU).  The decision per level
        # still goes through pack_disjoint's shape gates in build_table.
        may_pack = self.mesh is None and (
            self.level_packed == "on"
            or (self.level_packed == "auto"
                and pallas_hist.on_tpu_single_device()))
        codes_t_dev = codes_dev.T if (use_cross or may_pack) else None
        all_splits = candidate_splits_for(
            ds, self.split_search, self.max_split, is_categorical,
            self.max_candidates_per_attr)
        flat = (flatten_splits(all_splits, ds.max_bins, self.split_chunk)
                if self.selection == "device" else None)
        use_device_sel = flat is not None and flat.num_real > 0

        # cumsum fast path: every candidate is one sorted threshold on the
        # bin grid (split.search=binary), so per-level scoring runs on the
        # cumulative level table instead of the per-split segment einsum
        use_cum = (use_device_sel and flat.all_binary
                   and self.hist_mode in ("cumsum", "subtract"))

        root_counts = np.bincount(ds.labels, minlength=c).astype(np.float64)
        nodes: List[TreeNode] = [TreeNode(0, 0, root_counts)]
        # the [N] per-row node assignment lives ON DEVICE for the whole
        # fit (round 5): per level only KB-sized tables travel — the
        # round-4 form re-uploaded the remapped [N] vector every level
        # and partitioned on host, paying two N-sized tunnel trips per
        # level that dominated induction wall time on the dev rig
        node_dev = jnp.zeros(labels_dev.shape[0], jnp.int32)
        frontier = [0]
        # sibling-subtraction bookkeeping (hist_mode="subtract"): the
        # previous level's resident table plus the host-side plan mapping
        # each frontier child to a direct contraction slot or a derived
        # (parent − direct siblings) slice
        use_subtract = self.hist_mode == "subtract"
        prev_table_dev = None
        sub_plan = None     # (remap_direct, dslot, pslot, sib_mat, kd)
        collect = self.collect_phase_stats
        self.level_stats = []

        def build_table(local_ids, k_slots):
            """The ONE level contraction entry (shared by the full-frontier
            and direct-slot builds): cross-gram kernel when the selector
            width qualifies, the PackGraft disjoint pack where the pack
            planner accepts the frontier, einsum otherwise.  Returns
            (table, path) with path in ("cross", "packed", "einsum")."""
            cross = use_cross and pallas_hist.cross_applicable(
                ds.num_binned, ds.max_bins, k_slots * c)
            if cross:
                return _level_table_cross(
                    codes_t_dev, local_ids, labels_dev, k_slots, c,
                    ds.max_bins), "cross"
            if may_pack and k_slots > 0:
                pplan = pallas_hist.pack_disjoint(
                    k_slots, ds.num_binned, ds.max_bins, max(c, 1))
                if pplan is not None:
                    kernel = (pallas_hist.packed_applicable(pplan)
                              and pallas_hist.on_tpu_single_device())
                    if kernel or self.level_packed == "on":
                        return _level_table_packed(
                            codes_t_dev, local_ids, labels_dev, pplan,
                            kernel), "packed"
            return node_bin_class_counts(
                codes_dev, local_ids, labels_dev, k_slots, c,
                ds.max_bins), "einsum"

        for depth in range(self.max_depth):
            if not frontier:
                break
            t_lv = time.perf_counter()
            k = len(frontier)
            # remap frontier ids to 0..k-1 for the level contraction
            remap = np.full(len(nodes), -1, np.int32)
            for i, nid in enumerate(frontier):
                remap[nid] = i
            remap_dev = jnp.asarray(remap)
            # the [F, B, K, C] level table stays ON DEVICE; under device
            # selection it is never fetched — only the chosen-split
            # descriptors are
            k_contracted = k
            if use_subtract and sub_plan is not None:
                # contract ONLY the direct (smaller-sibling) slots — for
                # binary trees ~half the gram work — and derive each
                # largest sibling by exact parent-slice subtraction
                remap_direct, dslot, pslot, sib_mat, kd = sub_plan
                k_contracted = kd
                local_direct = _remap_nodes(node_dev,
                                            jnp.asarray(remap_direct))
                direct_dev, path_lv = build_table(local_direct, kd)
                table_dev = _assemble_subtract_table(
                    direct_dev, prev_table_dev, jnp.asarray(dslot),
                    jnp.asarray(pslot), jnp.asarray(sib_mat))
            else:
                local_node_dev = _remap_nodes(node_dev, remap_dev)
                table_dev, path_lv = build_table(local_node_dev, k)
            if use_subtract:
                # only the subtract path ever reads the previous level's
                # table; retaining it otherwise would hold a second dead
                # [F, B, K, C] buffer in HBM per level
                prev_table_dev = table_dev
            if collect:
                # honest per-phase walls need a barrier per phase; this
                # probe mode is opt-in (collect_phase_stats /
                # tree.hist.phase.stats), never the production fit loop
                jax.block_until_ready(table_dev)   # graftlint: disable=GL005
                t_tab = time.perf_counter()

            attrs_lv = self._attrs_for_node(rng, ds.num_binned)
            best_per_node: List[List[Tuple[float, CandidateSplit, np.ndarray]]] = [
                [] for _ in range(k)]
            if use_device_sel:
                # one dispatch (histograms + scores + per-node top-k on
                # device), one KB-sized fetch — this sync IS the designed
                # once-per-level descriptor transfer that replaced the
                # full-table fetch (the r05 RTT wall this rule encodes)
                top_k = min(max(self.top_n, 1), flat.seg_tab_dev.shape[0])
                allow_dev = jnp.asarray(flat.allow_vector(attrs_lv))
                thr_dev = flat.thr_dev if use_cum else None
                statics = dict(algorithm=self.algorithm, gmax=flat.gmax,
                               top_k=top_k, chunk=flat.chunk,
                               binary=use_cum)
                from avenir_tpu.telemetry import profile as _profile

                prof = _profile.profiler()
                pkey = None
                if prof.enabled:
                    # GraftProf: the level-selection program, keyed on
                    # the dispatch shapes + statics; the jitted callable
                    # itself is the AOT cost probe (one extra compile
                    # per distinct key — the opt-in price of the table)
                    from avenir_tpu.telemetry.spans import CompileKeyMonitor
                    pkey = CompileKeyMonitor.shape_key(
                        table_dev, flat.seg_tab_dev, thr_dev) + (
                        tuple(sorted(statics.items())),)
                    prof.observe(
                        pkey, site="tree.level",
                        lowerable=_device_select_splits,
                        args=(table_dev, flat.seg_tab_dev, flat.attr_dev,
                              flat.nseg_dev, allow_dev, thr_dev),
                        kwargs=statics)
                    t_disp = time.perf_counter()
                # graftlint: disable=GL005
                vals, idx, whist = jax.device_get(_device_select_splits(
                    table_dev, flat.seg_tab_dev, flat.attr_dev,
                    flat.nseg_dev, allow_dev, thr_dev, **statics))
                if pkey is not None:
                    prof.sample(pkey, "tree.level",
                                time.perf_counter() - t_disp)
                for ki in range(k):
                    for p in range(top_k):
                        s = float(vals[ki, p])
                        if s == -np.inf:        # pad / strategy-masked slot
                            continue
                        best_per_node[ki].append(
                            (s, flat.splits[int(idx[ki, p])], whist[ki, p]))
            else:
                table = np.asarray(table_dev)
                for _a, chunk, scores, hist in iter_scored_splits(
                        table, all_splits, self.algorithm, self.split_chunk,
                        attrs=attrs_lv):
                    for si, sp in enumerate(chunk):
                        for ki in range(k):
                            best_per_node[ki].append(
                                (float(scores[si, ki]), sp,
                                 hist[si, :, ki, :]))
            # select per node: best or random among top_n
            new_frontier: List[int] = []
            attr_arr = np.zeros(k, np.int32)
            child_tab = np.full((k, ds.max_bins), -1, np.int32)
            split_records: List[Tuple[int, List[int], np.ndarray]] = []
            for ki, nid in enumerate(frontier):
                node = nodes[nid]
                cands = sorted(best_per_node[ki], key=lambda t: -t[0])[:max(self.top_n, 1)]
                if not cands:
                    continue
                pick = cands[0] if len(cands) == 1 or self.top_n <= 1 else \
                    cands[int(rng.integers(len(cands)))]
                score, sp, hist = pick
                # stopping rules (DataPartitioner recursion guards)
                if not np.isfinite(score) or score < self.min_gain:
                    continue
                seg_counts = hist.sum(-1)
                live_segs = seg_counts > 0
                if live_segs.sum() < 2 or node.class_counts.sum() < self.min_node_size:
                    continue
                if (node.class_counts > 0).sum() < 2:   # pure node
                    continue
                node.split = sp
                node.score = score
                for g in range(sp.num_segments):
                    ch = TreeNode(len(nodes), depth + 1, hist[g].astype(np.float64))
                    node.children.append(ch.node_id)
                    nodes.append(ch)
                    if seg_counts[g] >= self.min_node_size and depth + 1 < self.max_depth:
                        new_frontier.append(ch.node_id)
                # partition: routed through the device-resident node
                # vector (replaces the one-reducer-per-segment MR job +
                # HDFS renames of DataPartitioner.java:95-129)
                child_ids = np.asarray(node.children, np.int32)
                attr_arr[ki] = sp.attr
                child_tab[ki] = child_ids[sp.seg_of_bin]
                split_records.append((ki, list(node.children), seg_counts))
            if collect:
                t_sel = time.perf_counter()
            # no next level (or nothing split) → the updated vector would
            # never be read; skip the dispatch
            if new_frontier and (child_tab >= 0).any():
                node_dev = _apply_level_partition(
                    codes_dev, node_dev, remap_dev,
                    jnp.asarray(attr_arr), jnp.asarray(child_tab))
                if collect:
                    # see the table-phase barrier note above
                    jax.block_until_ready(node_dev)  # graftlint: disable=GL005
            sub_plan = (self._subtract_plan(split_records, new_frontier,
                                            len(nodes))
                        if use_subtract and new_frontier else None)
            if collect:
                t_end = time.perf_counter()
                self.level_stats.append({
                    "level": depth, "frontier": k,
                    "contracted_slots": k_contracted,
                    "path": path_lv,
                    # the contraction's true dot width ON THE PATH THIS
                    # LEVEL TOOK: the cross kernel pads the selector to
                    # 128-lane tiles, a packed level pays the joint pack
                    # width (pack_disjoint is pure — same plan it built),
                    # the einsum fallback scales with K·C directly
                    "sel_width": (
                        pallas_hist.cross_sel_width(k_contracted * c)
                        if path_lv == "cross" else
                        pallas_hist.pack_disjoint(
                            k_contracted, ds.num_binned, ds.max_bins,
                            max(c, 1)).wp if path_lv == "packed" else
                        k_contracted * c),
                    "table_ms": round((t_tab - t_lv) * 1e3, 3),
                    "select_ms": round((t_sel - t_tab) * 1e3, 3),
                    "partition_ms": round((t_end - t_sel) * 1e3, 3)})
            frontier = new_frontier
        return DecisionTreeModel(nodes=nodes, class_values=list(ds.class_values),
                                 max_bins=ds.max_bins, algorithm=self.algorithm,
                                 depth_cap=self.max_depth,
                                 split_cap=(2 if self.split_search == "binary"
                                            else self.max_split))

    @staticmethod
    def _subtract_plan(split_records, new_frontier, num_nodes: int):
        """Host-side plan (tiny) for the next level's sibling-subtraction
        table: per split parent with frontier children, pick the
        largest-mass segment g* (stable: lowest g on ties) as the DERIVED
        child and mark every other segment's child a DIRECT contraction
        slot (settled siblings included — the subtraction needs them);
        when the g* child itself is settled, only the frontier children
        are contracted (nothing needs deriving there).  Returns
        (remap_direct [num_nodes] abs id → slot, dslot [K] (−1 =
        derived), pslot [K] parent's previous-level local index,
        sib_mat [K, D] direct-sibling one-hot, D)."""
        fs = set(new_frontier)
        direct_ids: List[int] = []
        dslot_of: Dict[int, int] = {}
        derived_info: Dict[int, Tuple[int, List[int]]] = {}
        for ki, child_ids, masses in split_records:
            in_f = [cid for cid in child_ids if cid in fs]
            if not in_f:
                continue
            gstar = int(np.argmax(np.asarray(masses)))
            gstar_child = child_ids[gstar]
            if gstar_child in fs:
                members = [cid for g, cid in enumerate(child_ids)
                           if g != gstar]
                derived_info[gstar_child] = (ki, members)
            else:
                members = in_f
            for cid in members:
                dslot_of[cid] = len(direct_ids)
                direct_ids.append(cid)
        kd = len(direct_ids)
        kf = len(new_frontier)
        remap_direct = np.full(num_nodes, -1, np.int32)
        for cid, sl in dslot_of.items():
            remap_direct[cid] = sl
        dslot = np.full(kf, -1, np.int32)
        pslot = np.zeros(kf, np.int32)
        sib_mat = np.zeros((kf, kd), np.int32)
        for k2, cid in enumerate(new_frontier):
            if cid in derived_info:
                kp, members = derived_info[cid]
                pslot[k2] = kp
                for m in members:
                    sib_mat[k2, dslot_of[m]] = 1
            else:
                dslot[k2] = dslot_of[cid]
        return remap_direct, dslot, pslot, sib_mat, kd

    def predict(self, model: DecisionTreeModel, ds: EncodedDataset,
                validate: bool = False, pos_class: Optional[str] = None):
        walk = predict_fn(model)
        pred, distr = walk(jnp.asarray(ds.codes))
        pred, distr = np.asarray(pred), np.asarray(distr)
        counters = Counters()
        cm = None
        if validate:
            if ds.labels is None:
                raise ValueError("validation requires labels")
            cm = ConfusionMatrix(model.class_values, pos_class=pos_class)
            cm.add_batch(ds.labels, pred)
            cm.publish(counters)
        return pred, distr, cm, counters


class RandomForest:
    """Bagged ensemble of randomK trees (the composition the reference
    gestures at via its random attribute-selection strategy + BaggingSampler)."""

    def __init__(self, num_trees: int = 10, seed: int = 0, **tree_kwargs):
        tree_kwargs.setdefault("attr_strategy", "randomK")
        self.num_trees = num_trees
        self.seed = seed
        self.tree_kwargs = tree_kwargs

    def fit(self, ds: EncodedDataset,
            is_categorical: Optional[Sequence[bool]] = None) -> List[DecisionTreeModel]:
        from avenir_tpu.models.samplers import bagging_sample
        models = []
        for t in range(self.num_trees):
            sample = bagging_sample(jax.random.PRNGKey(self.seed * 1000 + t), ds)
            tree = DecisionTree(seed=self.seed * 1000 + t, **self.tree_kwargs)
            models.append(tree.fit(sample, is_categorical))
        return models

    def predict(self, models: List[DecisionTreeModel], ds: EncodedDataset):
        votes = np.zeros((ds.num_rows, len(models[0].class_values)), np.float32)
        for m in models:
            _, distr, _, _ = DecisionTree().predict(m, ds)
            votes += distr
        votes /= len(models)
        return np.argmax(votes, axis=1).astype(np.int32), votes
