from avenir_tpu.ops import agg, info

__all__ = ["agg", "info"]
