"""Sharded aggregation primitives — the rebuild's communication backend.

Every reducer/shuffle pattern in the reference lowers to one of the kernels
here (see SURVEY.md §2.12): class-conditional count tensors (the Naive-Bayes
shuffle, reference bayesian/BayesianDistribution.java:137-328), contingency
matrices (explore/CramerCorrelation.java:161-235), feature-pair joint
distributions (explore/MutualInformation.java:136-403), per-class moment sums
(discriminant via chombo NumericalAttrStats), split histograms
(explore/ClassPartitionGenerator.java:199-230), gradient partial sums
(regress/LogisticRegressionJob.java:169-176), and state-transition counts
(markov/MarkovStateTransitionModel.java:98-125).

Design: counts are computed as one-hot einsums — dense matmuls that XLA tiles
onto the MXU — in float32 (exact for per-chunk counts < 2^24), then cast to
int32 and accumulated across chunks. Under a sharded ``jax.jit`` the batch
axis is sharded over the mesh's ``data`` axis and XLA inserts the
``psum``-equivalent all-reduce over ICI automatically; the reference's
combiner (map-side pre-aggregation) corresponds exactly to the per-device
partial einsum, and the shuffle to the collective.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# float32 one-hot sums are exact only while every cell stays below 2^24; the
# batch axis bounds any cell, so cap chunk size (checked at trace time).
MAX_EXACT_CHUNK_ROWS = 1 << 24


def _check_chunk(x: jax.Array) -> None:
    if x.shape[0] >= MAX_EXACT_CHUNK_ROWS:
        raise ValueError(
            f"chunk of {x.shape[0]} rows exceeds float32-exact count limit "
            f"{MAX_EXACT_CHUNK_ROWS}; split the stream into smaller chunks")


def one_hot(x: jax.Array, k: int, dtype=jnp.float32) -> jax.Array:
    """One-hot encode; out-of-range indices (e.g. -1) produce all-zero rows."""
    return jax.nn.one_hot(x, k, dtype=dtype)


# ---------------------------------------------------------------------------
# count tensors
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_classes",))
def class_counts(labels: jax.Array, num_classes: int) -> jax.Array:
    """[C] — class-prior counts."""
    _check_chunk(labels)
    return jnp.sum(one_hot(labels, num_classes), axis=0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_bins",))
def feature_counts(codes: jax.Array, num_bins: int) -> jax.Array:
    """codes [N, F] → [F, B] per-feature bin histograms (feature priors)."""
    _check_chunk(codes)
    return jnp.sum(one_hot(codes, num_bins), axis=0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_classes", "num_bins"))
def feature_class_counts(
    codes: jax.Array, labels: jax.Array, num_classes: int, num_bins: int
) -> jax.Array:
    """codes [N, F], labels [N] → [F, B, C] class-conditional bin counts.

    This is the Naive-Bayes training shuffle: the reference emits one
    (classVal, featureOrdinal, bin) → 1 record per feature per row and sums in
    the reducer; here it is a single [N,F,B]×[N,C] contraction.
    """
    _check_chunk(codes)
    oh_b = one_hot(codes, num_bins)            # [N, F, B]
    oh_c = one_hot(labels, num_classes)        # [N, C]
    return jnp.einsum("nfb,nc->fbc", oh_b, oh_c, precision="highest").astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_bins",))
def pair_counts(
    codes_i: jax.Array, codes_j: jax.Array, num_bins: int
) -> jax.Array:
    """codes_i [N, P], codes_j [N, P] → [P, B, B] joint histograms for P
    feature pairs evaluated in lockstep (feature-pair distributions of the MI
    job; Cramér contingency matrices)."""
    _check_chunk(codes_i)
    oh_i = one_hot(codes_i, num_bins)          # [N, P, B]
    oh_j = one_hot(codes_j, num_bins)          # [N, P, B]
    return jnp.einsum("npa,npb->pab", oh_i, oh_j, precision="highest").astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_classes", "num_bins"))
def nb_mi_pipeline_step(codes, labels, ci, cj, num_classes: int, num_bins: int):
    """The NB+MI aggregation step in its einsum form: class-conditional bin
    counts plus all feature-pair-class joint counts in ONE einsum dispatch.

    Round 3: on a single TPU device with a small joint table this is no
    longer the primary path — ``ops/pallas_hist.cooc_counts`` (G = XᵀX over
    the joint (feature, bin, class) one-hot, built in VMEM, int8 MXU pass)
    measures ~4-5× faster, and MutualInformation.fit / bench.py /
    benchmarks/e2e_pipeline.py route to it explicitly (host-side read-out
    of the same tensors via ``pallas_hist.counts_from_cooc``;
    bit-identical int32 counts).  This form remains the multi-device path
    (its data-axis psum is the attested collective), the wide-table path
    (F·B·C > pallas_hist.MAX_W), and the CPU/test path.

    The F diagonal "pairs" (f, f) are appended to the P requested pairs: the
    [a, a, c] diagonal of a (f, f) joint IS the class-conditional bin count,
    so NB's tensor falls out of the same kernel instead of costing a second
    full pass over the chunk (measured ~2.3× total on-chip time as two
    separate einsums — see pair_class_counts for the two-operand form)."""
    f = codes.shape[1]
    diag = jnp.arange(f, dtype=jnp.int32)
    cia = jnp.concatenate([jnp.asarray(ci, jnp.int32), diag])
    cja = jnp.concatenate([jnp.asarray(cj, jnp.int32), diag])
    all_counts = pair_class_counts(codes[:, cia], codes[:, cja], labels,
                                   num_classes, num_bins)
    pair = all_counts[:len(ci)]
    ar = jnp.arange(num_bins)
    fbc = all_counts[len(ci):, ar, ar, :]          # [F, B, C] diagonal
    return fbc, pair


@functools.partial(jax.jit, static_argnames=("num_classes", "num_bins"))
def pair_class_counts(
    codes_i: jax.Array, codes_j: jax.Array, labels: jax.Array,
    num_classes: int, num_bins: int,
) -> jax.Array:
    """→ [P, B, B, C] feature-pair × class joint counts (MI job's pair-class
    and pair-class-conditional distributions come from this one tensor).

    Two-operand form: the second operand one-hots the JOINT (bin_j, class)
    code so the contraction is "npa,npk->pak" — measured 2.3× faster
    on-chip than the three-operand "npa,npb,nc->pabc" (both lower to
    scatter-adds; the joint form scatters once per (row, pair) instead of
    expanding the class axis separately). Round 1 had concluded the
    opposite from timings taken with jax.block_until_ready — which is a
    NO-OP on the tunnel platform; only host fetches synchronize."""
    _check_chunk(codes_i)
    oh_i = one_hot(codes_i, num_bins)                       # [N, P, B]
    # preserve one_hot's drop-invalid contract for the JOINT code: an
    # out-of-range label (e.g. -1 mesh padding on a partially-labeled
    # stream) would otherwise alias into a valid (bin_j, class) cell
    bad = (labels < 0) | (labels >= num_classes)
    joint = jnp.where(bad[:, None], -1,
                      codes_j * num_classes + labels[:, None])
    oh_jc = one_hot(joint, num_bins * num_classes)          # [N, P, B*C]
    pak = jnp.einsum("npa,npk->pak", oh_i, oh_jc, precision="highest")
    return pak.reshape(*pak.shape[:2], num_bins, num_classes).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_classes",))
def class_moments(
    values: jax.Array, labels: jax.Array, num_classes: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """values [N, F] float, labels [N] → (count [C], sum [C,F], sumsq [C,F]).

    The per-(attr, class) count/Σx/Σx² accumulation backing Gaussian Naive
    Bayes and the Fisher discriminant (reference reuses chombo
    NumericalAttrStats for this)."""
    _check_chunk(values)
    oh_c = one_hot(labels, num_classes)        # [N, C]
    cnt = jnp.sum(oh_c, axis=0)
    s1 = jnp.einsum("nc,nf->cf", oh_c, values, precision="highest")
    s2 = jnp.einsum("nc,nf->cf", oh_c, values * values, precision="highest")
    return cnt, s1, s2


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_count(segments: jax.Array, num_segments: int) -> jax.Array:
    """Generic 1-D histogram by segment id."""
    _check_chunk(segments)
    return jnp.sum(one_hot(segments, num_segments), axis=0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_a", "num_b"))
def transition_counts(a: jax.Array, b: jax.Array, num_a: int, num_b: int) -> jax.Array:
    """a [M], b [M] paired codes → [num_a, num_b] co-occurrence counts
    (Markov state-transition counts; also any 2-way contingency off the
    lockstep-pair path)."""
    _check_chunk(a)
    return jnp.einsum("ma,mb->ab", one_hot(a, num_a), one_hot(b, num_b), precision="highest").astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_a", "num_b"))
def weighted_transition_counts(
    a: jax.Array, b: jax.Array, w: jax.Array, num_a: int, num_b: int
) -> jax.Array:
    """Weighted co-occurrence sums (float) — partially-tagged HMM windows.
    −1 codes are count-neutral (zero one-hot rows), so mesh pad rows with
    w=0 contribute nothing either way."""
    _check_chunk(a)
    return jnp.einsum("ma,mb,m->ab", one_hot(a, num_a), one_hot(b, num_b), w, precision="highest")


# ---------------------------------------------------------------------------
# host-side accumulation across chunks
# ---------------------------------------------------------------------------

class Accumulator:
    """Sums per-chunk device results into int64/float64 numpy totals.

    Per-chunk kernels are exact (float32 one-hot sums below 2^24 per bucket);
    cross-chunk accumulation happens here in 64-bit on host so 100M+ row
    streams cannot overflow or lose counts.
    """

    def __init__(self):
        self._totals = {}

    def add(self, name: str, value: jax.Array) -> None:
        arr = np.asarray(value)
        arr = arr.astype(np.int64) if np.issubdtype(arr.dtype, np.integer) else arr.astype(np.float64)
        if name in self._totals:
            self._totals[name] = self._totals[name] + arr
        else:
            self._totals[name] = arr

    def get(self, name: str) -> np.ndarray:
        return self._totals[name]

    def __contains__(self, name: str) -> bool:
        return name in self._totals

    def names(self):
        return list(self._totals)

    # -- checkpointable state (streaming-job mid-stream durability) ----------
    def state(self) -> dict:
        """name → numpy total, a copy safe to hand to checkpoint writers."""
        return {k: np.array(v) for k, v in self._totals.items()}

    def load(self, state: dict) -> None:
        """Replace the totals with a restored snapshot."""
        self._totals = {k: np.asarray(v) for k, v in state.items()}
