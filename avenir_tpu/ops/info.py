"""Information-theoretic and association statistics over count tensors.

Pure functions from (contingency) count tensors to scalars/vectors. These are
the rebuild's equivalents of the reference's reducer-side statistics:
entropy/gini/Hellinger split quality (util/AttributeSplitStat.java:179-339),
dataset info content (util/InfoContentStat.java:55-85), Cramér index /
concentration coefficient / uncertainty coefficient
(util/ContingencyMatrix.java:86-185), and the mutual-information family
(explore/MutualInformation.java:598-784).

All take *float* count tensors (cast at the boundary) and are safe on empty
cells (0·log 0 = 0 via masked logs). They operate on the trailing axes so
they vmap/batch over leading axes (feature pairs, candidate splits, tree
nodes) for free.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

_EPS = 1e-12


def on_host():
    """Context manager placing jnp ops on the local CPU backend.

    The statistics in this module run over tiny count tensors (thousands of
    elements); when the default device is a remote TPU each jnp primitive
    pays a ~60 ms dispatch round-trip, so a ``finish()`` pass of ~100 small
    ops costs seconds while the math itself is microseconds. Wrapping the
    derived-statistics phase in ``with info.on_host():`` keeps it on the
    local CPU. No-op when no CPU backend is registered.  Must be a device
    THIS process addresses: under ``jax.distributed``, ``jax.devices()``
    lists every process's devices, and placing on another host's device
    makes the result unfetchable."""
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:                   # pragma: no cover
        return contextlib.nullcontext()
    return jax.default_device(cpu)


def _safe_log(x: jax.Array) -> jax.Array:
    return jnp.log(jnp.where(x > 0, x, 1.0))


def cumulative_level_table(table: jax.Array) -> jax.Array:
    """[F, B, K, C] level table → its inclusive prefix sum over the bin
    axis: ``cum[f, b] = Σ_{b' ≤ b} table[f, b']``.  Exact in integer
    dtypes (prefix addition commutes with the einsum fold), so every
    statistic derived from it is bit-identical to one derived from the
    raw table.  This is the ONE O(F·B·K·C) pass that replaces the
    per-threshold einsum for binary-threshold split search — every sorted
    threshold's left histogram is a single row of ``cum``
    (:func:`binary_split_histograms`)."""
    return jnp.cumsum(table, axis=1)


def binary_split_histograms(cum: jax.Array, attr_of: jax.Array,
                            thr_of: jax.Array) -> jax.Array:
    """Cumulative-histogram binary splits: ``cum`` [F, B, K, C] (the
    inclusive bin prefix sum of the level table), ``attr_of`` [S] owning
    attribute per split, ``thr_of`` [S] bin threshold (codes < t go
    left) → [S, 2, K, C] segment×class histograms, O(S·K·C) gathers
    instead of the O(S·B·K·C) ``sgb,sbkc->sgkc`` einsum of
    :func:`split_segment_histograms` — for S ≈ F·(B−1) binary candidates
    a B× cut in per-level scoring work.

    left = cum[a, t−1] (all bins < t), right = node total − left
    (node total = cum[a, B−1]).  Integer subtraction of exact integer
    prefix sums: the result is bit-identical to the einsum form's
    histogram for the same (a, t), which the byte-identity property
    tests assert directly."""
    left = cum[attr_of, thr_of - 1]                    # [S, K, C]
    total = cum[attr_of, -1]                           # [S, K, C]
    return jnp.stack([left, total - left], axis=1)     # [S, 2, K, C]


def split_segment_histograms(table: jax.Array, seg_tab: jax.Array,
                             attr_of: jax.Array, gmax: int) -> jax.Array:
    """Batched device scoring entry for tree induction: the [F, B, K, C]
    level table plus flat candidate-split metadata (``seg_tab`` [S, B]
    bin→segment maps, ``attr_of`` [S] owning attribute per split) → the
    [S, G, K, C] per-split segment×class histograms, as ONE device einsum
    over the split axis — no N-dependent work and no host numpy pass.

    The int32 contraction keeps counts exact (the one-hot segment mask
    times integer counts), so the result is bit-identical to the host
    :func:`avenir_tpu.models.tree.split_histograms_from_table` fold it
    replaces on the device path.  Segments ≥ a split's true segment count
    come out all-zero; statistics downstream must be zero-count-invariant
    (or masked — see ``split_scores``'s ``seg_mask``).
    """
    grange = jnp.arange(gmax, dtype=jnp.int32)
    m = (seg_tab[:, None, :] == grange[None, :, None]).astype(jnp.int32)
    return jnp.einsum("sgb,sbkc->sgkc", m, table[attr_of])


def normalize(counts: jax.Array, axis=None) -> jax.Array:
    """Counts → probabilities along ``axis`` (all trailing mass if None)."""
    total = jnp.sum(counts, axis=axis, keepdims=axis is not None)
    return counts / jnp.maximum(total, _EPS)


def entropy(p: jax.Array, axis: int = -1) -> jax.Array:
    """Shannon entropy (nats) of a probability vector along ``axis``."""
    return -jnp.sum(p * _safe_log(p), axis=axis)


def entropy_from_counts(counts: jax.Array, axis: int = -1) -> jax.Array:
    return entropy(normalize(counts, axis=axis), axis=axis)


def gini(p: jax.Array, axis: int = -1) -> jax.Array:
    """Gini impurity 1 − Σp²."""
    return 1.0 - jnp.sum(p * p, axis=axis)


def gini_from_counts(counts: jax.Array, axis: int = -1) -> jax.Array:
    return gini(normalize(counts, axis=axis), axis=axis)


def hellinger_distance(p: jax.Array, q: jax.Array, axis: int = -1) -> jax.Array:
    """Hellinger distance between two distributions along ``axis``."""
    return jnp.sqrt(jnp.maximum(jnp.sum((jnp.sqrt(p) - jnp.sqrt(q)) ** 2, axis=axis), 0.0)) / jnp.sqrt(2.0)


# ---------------------------------------------------------------------------
# mutual information family (joint count matrix [..., A, B])
# ---------------------------------------------------------------------------

def mutual_information(joint_counts: jax.Array) -> jax.Array:
    """MI(X;Y) in nats from joint counts [..., A, B].

    I = Σ_ab p(a,b) · log( p(a,b) / (p(a)·p(b)) ), with empty cells
    contributing zero — matching the reference's skip-if-zero loops.
    """
    c = joint_counts.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(c, axis=(-2, -1), keepdims=True), _EPS)
    p = c / total
    pa = jnp.sum(p, axis=-1, keepdims=True)    # [..., A, 1]
    pb = jnp.sum(p, axis=-2, keepdims=True)    # [..., 1, B]
    ratio = p / jnp.maximum(pa * pb, _EPS)
    return jnp.sum(p * _safe_log(ratio), axis=(-2, -1))


def joint_entropy(joint_counts: jax.Array) -> jax.Array:
    c = joint_counts.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(c, axis=(-2, -1), keepdims=True), _EPS)
    p = c / total
    return -jnp.sum(p * _safe_log(p), axis=(-2, -1))


def conditional_mutual_information(joint_counts_z: jax.Array) -> jax.Array:
    """I(X;Y|Z) from counts [..., A, B, Z]: Σ_z p(z) · MI(X;Y | Z=z)."""
    c = joint_counts_z.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(c, axis=(-3, -2, -1), keepdims=True), _EPS)
    pz = jnp.sum(c, axis=(-3, -2)) / jnp.squeeze(total, (-3, -2))   # [..., Z]
    mi_given_z = mutual_information(jnp.moveaxis(c, -1, -3))        # [..., Z]
    return jnp.sum(pz * mi_given_z, axis=-1)


# ---------------------------------------------------------------------------
# categorical association coefficients (contingency matrix [..., R, C])
# ---------------------------------------------------------------------------

def cramer_index(counts: jax.Array) -> jax.Array:
    """Cramér index φ²/min(R−1, C−1) — the reference's ``cramerIndex``
    (util/ContingencyMatrix.java:86-123): mean-squared deviation of the joint
    from the product of marginals, normalized by matrix dimension.

    Computed as χ²/(N·min(R−1,C−1)) (Cramér's V squared).
    """
    c = counts.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(c, axis=(-2, -1), keepdims=True), _EPS)
    pr = jnp.sum(c, axis=-1, keepdims=True) / n
    pc = jnp.sum(c, axis=-2, keepdims=True) / n
    p = c / n
    e = pr * pc
    chi2_over_n = jnp.sum(jnp.where(e > 0, (p - e) ** 2 / jnp.maximum(e, _EPS), 0.0), axis=(-2, -1))
    r = counts.shape[-2]
    k = counts.shape[-1]
    dof = max(min(r - 1, k - 1), 1)
    return chi2_over_n / dof


def concentration_coefficient(counts: jax.Array) -> jax.Array:
    """Goodman–Kruskal tau (Gini-based concentration coefficient) of the
    column variable given the row variable — the reference's
    ``concentrationCoeff`` (util/ContingencyMatrix.java:141-163):
    (E[gini(col)] − E[gini(col|row)]) / gini(col)."""
    c = counts.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(c, axis=(-2, -1), keepdims=True), _EPS)
    p = c / n                                             # [..., R, C]
    pr = jnp.sum(p, axis=-1)                              # [..., R]
    pc = jnp.sum(p, axis=-2)                              # [..., C]
    vy = 1.0 - jnp.sum(pc * pc, axis=-1)                  # gini of col marginal
    within = jnp.sum(p * p, axis=-1) / jnp.maximum(pr, _EPS)   # Σ_c p(r,c)²/p(r)
    vy_given_x = 1.0 - jnp.sum(within, axis=-1)
    return (vy - vy_given_x) / jnp.maximum(vy, _EPS)


def uncertainty_coefficient(counts: jax.Array) -> jax.Array:
    """Theil's U of the column variable given the row variable — the
    reference's ``uncertaintyCoeff`` (util/ContingencyMatrix.java:165-185):
    (H(col) − H(col|row)) / H(col) = MI/H(col)."""
    c = counts.astype(jnp.float32)
    pc = normalize(jnp.sum(c, axis=-2), axis=-1)
    hy = entropy(pc, axis=-1)
    mi = mutual_information(c)
    return mi / jnp.maximum(hy, _EPS)
