"""MXU co-occurrence histogram — the Pallas count kernel behind NB+MI.

The count tables of the flagship pipeline (the rebuild of the reference's
``explore/MutualInformation.java:236-403`` combiner/reducer and
``bayesian/BayesianDistribution.java:203-328`` shuffle) were previously
one-hot einsums that XLA lowers to scatter-adds — measured wall of
~7 G updates/s (66 updates/row on the hosp_readmit shape, <1% of any
hardware peak; BASELINE.md round-2 perf notes).  This kernel replaces the
scatter lowering entirely:

    every NB/MI count table is a sub-block of  G = Xᵀ X,
    where X is the [N, W] one-hot of the joint (feature, bin, class) code.

X is never materialized in HBM.  The round-4 kernel is FULLY FUSED and
COLUMNAR: it streams the [F, N] int32 code array and the [1, N] labels
through VMEM in column blocks, computes the joint code, expands the block
to Xᵀ int8 in VMEM, and accumulates G = XᵀX on the int8 MXU path in int32.
Nothing but the raw codes ever crosses HBM — no XLA transpose, no joint
materialization (round 4 measured the round-3 prologue at ~11 ms of the
~50 ms 16M-row chunk; benchmarks/cooc_expand_sweep.py).

Three expansion layouts, routed statically by :func:`plan`:

- ``fmaj`` (primary): a 3-D broadcast compare
  ``(joint[:, None, :] == iota_jc32)`` producing int8 directly — jc is
  padded to 32 so the int8 (32, 128) tiling is clean and the reshape to
  [F·jc32, BN] is a no-op tile collapse.  Row w = f·jc32 + (bin·C + cls).
  Used whenever the jc padding does not inflate the padded gram width.
- ``jmaj`` (fallback for shapes where it would): the round-3 tile-
  concatenate + iota//F compare; row w = (bin·C + cls)·F + f.
- ``cls`` (wide schemas, F·B·C beyond MAX_W): G [C, Wcp, Wcp] as C
  per-class grams over w = bin·F + f — the cross-class blocks of the
  joint gram are zero by construction, so the split cuts the dot work
  C× where 2-D blocking of the joint gram would merely repartition the
  same W² work.  This closes the round-3 wide-schema gap (the reference
  handles any cardinality via lazily-sparse reducer maps,
  ``explore/MutualInformation.java:421-432``; here wide shapes
  previously fell silently to the 80-113M rows/s scatter einsum).
- ``clsb`` (round 5, wider still: Wc up to MAX_W_CLSB, C up to
  MAX_C_CLSB): the same per-class gram banded over G's rows — only a
  [C, TR, Wp] accumulator band and one expansion block live in VMEM per
  grid step, so e.g. 100 features × 20 bins × 2 classes (Wc=2048) stays
  on the MXU two tiers past the einsum fallback.

Round-4 bisection (TPU v5 lite, fresh process per variant, chained-
dispatch host-fetch sync, 16M-row chunks, hosp_readmit shape F=11 B=12
C=2, Wp=384 — benchmarks/cooc_expand_sweep.py, dot_orient_probe.py,
xla_gram_probe.py):

- round-3 shipped kernel (XLA transpose + joint prologue + j-major
  in-VMEM expand) vs the fused columnar fmaj kernel, measured
  BACK-TO-BACK in one session: 319M → **381M rows/s median
  (+19%)**, insensitive to block_cols 49k→98k.  Absolute rates on this
  rig drift ±20% on ~30-minute scales (the identical fused config
  re-measured 333M half an hour later; r3's driver artifact captured
  366M for the old kernel) — only same-session A/B deltas are
  comparable, and BENCH_r04.json records whatever the driver's session
  captures;
- zero-expand floor (dot + streaming only): 37.8 ms/chunk — i.e. the
  expand costs ~4 ms (~10%), NOT the ~60% round 3 estimated;
- the governing wall is the W=384 int8 gram itself: ~115-125 effective
  TOPS (~30% of the 394 int8 peak) in BOTH Mosaic and bare XLA (bare-XLA
  dot on a pre-materialized HBM one-hot: 43.5 ms per 16M rows — slower
  than this whole kernel).  bf16 (83 int8-equiv TOPS), int4 (emulated,
  21 TOPS), batched-gram and distinct-operand forms all measure worse;
  XLA's gram efficiency rises with W (255 TOPS at W=1152), so the
  small-output gram is the documented compiler/hardware ceiling at this
  schema width.

Exactness: int8 operands are 0/1, int32 accumulation — per-chunk counts
are exact up to 2^31 rows (the einsum path's f32 accumulation capped
chunks at 2^24; callers keep that cap so both paths stay interchangeable).
Out-of-range codes produce joint codes outside [0, B·C) and drop out, and
out-of-range labels invalidate the whole row — bit-identical semantics to
``ops/agg.py::pair_class_counts``'s drop-invalid contract.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams → CompilerParams; a module-local alias
# (no mutation of the shared pltpu module) keeps the kernels running on
# either side of the rename — pallas_knn and the standalone probes carry
# the same two-liner
COMPILER_PARAMS = (pltpu.CompilerParams if hasattr(pltpu, "CompilerParams")
                   else pltpu.TPUCompilerParams)

# joint-code marker for invalid rows / padding: never equals a selector
# value (selectors are in [0, B·C) plus the pad marker below)
_INVALID = -(1 << 20)
_PAD_SEL = -(1 << 20) - 1

# The XᵀX pass costs ~2·Wp² int8-MXU FLOP per row; past Wp≈768 the joint
# gram loses ground, so wider shapes switch to the per-class mode below
# (and past its gates, to the scatter einsum).
MAX_W = 768

# Per-class mode ("cls", round 4): cross-class blocks of G are zero by
# construction, so C grams of width Wc = F·B cost 2·C·Wc² = 2·W²/C per
# row — a C× FLOP cut that no 2-D blocking of the joint gram can match
# (blocking repartitions the same W² work).  Routed for shapes the joint
# gram can't take; per-class width and class count are gated so the
# [C, Wcp, Wcp] accumulator and the expansion block stay in VMEM.
MAX_W_CLS = 1536
MAX_C_CLS = 8
MAX_G_BYTES_CLS = 25 * 1024 * 1024

# Blocked per-class mode ("clsb", round 5): same per-class gram math as
# "cls", but G [C, wp, wp] lives in HBM and the kernel accumulates one
# [C, TR, wp] ROW BAND per grid step — only the band (≤ the budget below),
# the expansion block and the codes block occupy VMEM, so the per-class
# width extends to MAX_W_CLSB.  The expansion is recomputed once per
# (band, column-block); that costs ~3·wp·BN ops against the band's
# 2·C·TR·wp·BN MAC dot — a ~3/(2·C·TR) ≈ 0.1% overhead, which is why
# banding the OUTPUT (not re-tiling the input) is the right split.
MAX_W_CLSB = 6144
MAX_C_CLSB = 16
_ACC_BYTES_CLSB = 35 * 1024 * 1024

# column-block default for the fmaj (int8-only-VMEM) expand; the jmaj
# fallback materializes an int32 [Wp, BN] block and scales down harder
_DEFAULT_BN = 98304


def _ru(x: int, m: int) -> int:
    return -(-x // m) * m


# Width-slack factor, shared by two routing decisions that trade a wider
# gram against a cheaper program:
#
# - fmaj-vs-jmaj (round 7): the fmaj broadcast expand keeps only int8 in
#   VMEM, while jmaj materializes an int32 [Wp, BN] block — measured
#   round 4 at +19% for fmaj at EQUAL width, and the one-class Cramér
#   gram (jmaj, wp=256) ran at ~33 effective TOPS against the 115-125
#   TOPS the fmaj W=384 gram sustains, i.e. jmaj's expand overhead
#   dwarfs a ≤1.5× wider dot at these widths.  So fmaj is preferred
#   unless its padding widens the gram by MORE than this factor (the
#   Cramér family shape 10×20×1 — wp 384 vs 256 — now rides fmaj).
# - the PackGraft cost model (round 16, :func:`pack_tables`): one joint
#   gram dispatch replaces the chunked-einsum fold's per-table one-hot
#   contractions when the padded gram width stays within this slack of
#   the unpacked fold's per-row cell volume — the same "a modestly wider
#   dot beats a cheaper-on-paper but scatter-lowered program" judgment,
#   anchored by the measured packed-vs-unpacked fold A/B
#   (benchmarks/wide_schema_bench.py --path pack).
WIDTH_SLACK = 1.5


def plan(num_feat: int, num_bins: int, num_classes: int):
    """Static layout plan → (mode, jcp, wp).

    ``fmaj``: w = f·jcp + (bin·C + cls), jcp = jc rounded up to 32 (clean
    int8 tiling for the broadcast expand).  Chosen unless that padding
    would widen the padded gram (wp) by more than ``WIDTH_SLACK`` versus
    the j-major packing — the dot is the dominant cost at large widths,
    but at kernel-eligible widths the int8-only expand buys back a
    modestly wider gram (see WIDTH_SLACK).

    ``cls`` (wide shapes): G is [C, wp, wp] with per-class row index
    w = bin·F + f (j-major within the class) — the per-class gram split
    that cuts the dot work C× versus the joint gram.
    """
    jc = num_bins * num_classes
    jcp32 = _ru(jc, 32)
    wp32 = _ru(num_feat * jcp32, 128)
    wpj = _ru(num_feat * jc, 128)
    if wp32 <= wpj or (wp32 <= MAX_W and wp32 <= WIDTH_SLACK * wpj):
        narrow = ("fmaj", jcp32, wp32)
    else:
        narrow = ("jmaj", jc, wpj)
    if narrow[2] <= MAX_W:
        return narrow
    wcp = _ru(num_feat * num_bins, 128)
    if (wcp <= MAX_W_CLS and 2 <= num_classes <= MAX_C_CLS
            and num_classes * wcp * wcp * 4 <= MAX_G_BYTES_CLS):
        return "cls", num_bins, wcp
    tile = clsb_tile(num_feat, num_bins, num_classes)
    if tile is not None:
        return "clsb", num_bins, tile[1]
    return narrow          # too wide for any kernel; applicable() rejects


def clsb_tile(num_feat: int, num_bins: int, num_classes: int):
    """(row-band height TR, padded per-class width wp) for the blocked
    per-class mode, or None when the shape is outside its gates.

    A band is a WHOLE NUMBER OF BINS (TR = F·k): in the j-major layout
    w = bin·F + f, a bin-aligned band's rows are ``code[i % F]`` compared
    against ``r·k + i//F`` — constructible in-kernel from static concats
    plus the scalar band offset (Mosaic has no dynamic_slice, so the band
    CANNOT be sliced out of a full-width expansion).  k is the largest
    power-of-2 scale with TR ≈ 512 whose [C, TR, wp] int32 accumulator
    band fits the VMEM budget; wp pads the BIN count to a multiple of k
    (pad bins select ``_PAD_SEL`` and stay exactly zero in G).  Pure
    function of the shape — plan(), the kernel and the tests must all
    derive the identical tiling."""
    wcp = _ru(num_feat * num_bins, 128)
    if not (MAX_W_CLS < wcp or num_classes > MAX_C_CLS
            or num_classes * wcp * wcp * 4 > MAX_G_BYTES_CLS):
        return None                      # plain cls mode serves it
    if wcp > MAX_W_CLSB or not 2 <= num_classes <= MAX_C_CLSB:
        return None
    import math

    # Mosaic block rule: the band (second-to-last out dim) must be
    # divisible by 8 — so k must be a multiple of 8/gcd(F, 8).  Among the
    # VMEM-feasible k, prefer the one minimizing the padded width (bin
    # padding inflates the dot work quadratically), then the largest k
    # (fewer bands → less expansion recompute).
    m = 8 // math.gcd(num_feat, 8)
    kmax = _ru(max(512 // num_feat, 1), m) + m
    best = None
    for k in range(m, kmax + 1, m):
        tr = num_feat * k
        wp = num_feat * _ru(num_bins, k)
        if wp > MAX_W_CLSB or num_classes * tr * wp * 4 > _ACC_BYTES_CLSB:
            continue
        key = (wp, -k)
        if best is None or key < best[0]:
            best = (key, (tr, wp))
    return best[1] if best else None


def g_key(num_feat: int, num_bins: int, num_classes: int) -> str:
    """Accumulator/checkpoint key for a G matrix of this shape's layout.
    Layout-qualified so a snapshot written under a DIFFERENT kernel layout
    (e.g. the round-3 j-major key ``"g"``) can never be silently summed
    with this layout's counts — resume code must detect and reject it.
    The w_index layout is a pure function of (F, B, C), so the key carries
    all three: keying on derived quantities alone (mode, jcp, wp) collides
    for distinct schemas — e.g. (F=11,B=12,C=2) and (F=11,B=8,C=4) share
    ('fmaj', 32, 384) but place j = bin·C + cls differently."""
    mode, _, _ = plan(num_feat, num_bins, num_classes)
    return f"g:{mode}:f{num_feat}:b{num_bins}:c{num_classes}"


def w_index(num_feat: int, num_bins: int, num_classes: int) -> np.ndarray:
    """[F, B, C] int64 array of each cell's row/col index in G (layout per
    :func:`plan`) — the single source of truth for G readout and tests.
    In ``cls`` mode the index is within class c's [wp, wp] gram (G is
    [C, wp, wp]); it is the same for every c."""
    mode, jcp, _ = plan(num_feat, num_bins, num_classes)
    if mode in ("cls", "clsb"):
        w2 = np.arange(num_bins)[None, :] * num_feat \
            + np.arange(num_feat)[:, None]
        return np.repeat(w2[:, :, None], num_classes, axis=2).astype(np.int64)
    j = np.arange(num_bins)[:, None] * num_classes + np.arange(num_classes)
    if mode == "fmaj":
        return (np.arange(num_feat)[:, None, None] * jcp + j[None]).astype(
            np.int64)
    return (j[None] * num_feat
            + np.arange(num_feat)[:, None, None]).astype(np.int64)


def default_block_cols(wp: int, mode: str = "fmaj") -> int:
    """Column block sized so the expansion stays inside the ~110 MB VMEM
    budget the kernel compiles against.  fmaj materializes only the int8
    [wp, BN] one-hot; jmaj/cls also hold an int32 [wp, BN] block (cls
    further keeps the [C, wp, wp] accumulator resident)."""
    if mode == "fmaj":
        bn = min(_DEFAULT_BN, (72 * 1024 * 1024) // max(wp, 128))
    elif mode == "cls":
        bn = min(49152, (64 * 1024 * 1024) // (5 * max(wp, 128)))
    elif mode == "clsb":
        # int32 jrept (4 B) + bool hit + int8 xt ≈ 6 B per (w, col) cell,
        # beside the [C, TR, wp] band the budget in clsb_tile reserves
        bn = (50 * 1024 * 1024) // (6 * max(wp, 128))
    else:
        bn = 49152 * 384 // max(wp, 128)
    return max(128, (bn // 128) * 128)


def _cooc_kernel(codes_ref, labels_ref, out_ref, *, f: int, jc: int,
                 jcp: int, wp: int, n: int, nclass: int, mode: str):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ct = codes_ref[:]                                  # [F, BN] int32
    y = labels_ref[:]                                  # [1, BN] int32
    bn = ct.shape[1]
    valid = (y >= 0) & (y < nclass)
    # ragged tail: lanes past the true row count read garbage from the
    # out-of-bounds block — neutralize them here instead of paying a
    # full-array jnp.pad copy outside (~10 ms/chunk at 16M rows)
    if n % bn or n == 0:
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
        valid &= lane < n - i * bn
    joint = jnp.where(valid, ct * nclass + y, _INVALID)
    # out-of-range codes (≥ B) must drop out, not land on fmaj pad cells
    # (jc ≤ iota < jcp): one [F, BN] clamp keeps G's outside-the-index-set
    # cells exactly zero in both modes
    joint = jnp.where(joint < jc, joint, _INVALID)
    if mode == "fmaj":
        # broadcast compare straight to int8 — no int32 [W, BN] copy; the
        # [F, jc32, BN] → [F·jc32, BN] reshape is a no-op tile collapse
        # because jc32 is a whole number of int8 sublane tiles
        jv = jax.lax.broadcasted_iota(jnp.int32, (1, jcp, 1), 1)
        xt = (joint[:, None, :] == jv).astype(jnp.int8)
        xt = xt.reshape(f * jcp, bn)
        if wp > f * jcp:
            xt = jnp.concatenate(
                [xt, jnp.zeros((wp - f * jcp, bn), jnp.int8)], axis=0)
    else:
        # j-major tile-expand: row w of the result is joint[w mod F]
        w = f * jc
        jrept = jnp.concatenate([joint] * jc, axis=0)  # [W, BN]
        if wp > w:
            jrept = jnp.concatenate(
                [jrept, jnp.full((wp - w, bn), _INVALID, jnp.int32)], axis=0)
        jw = jax.lax.broadcasted_iota(jnp.int32, (wp, 1), 0)
        jsel = jnp.where(jw < w, jw // f, _PAD_SEL)
        # int8 one-hot straight from the int32 compare: int8 compare/select
        # is not lowerable (Mosaic), int32→int8 select is
        xt = (jrept == jsel).astype(jnp.int8)          # [Wp, BN] = Xᵀ block
    acc = jax.lax.dot_general(xt, xt, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out_ref[:] += acc


def _cooc_cls_kernel(codes_ref, labels_ref, out_ref, *, f: int, b: int,
                     wp: int, n: int, nclass: int):
    """Per-class gram: one shared j-major expansion compare per block, a
    class mask folded into the one-hot select, C sequential int8 dots."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ct = codes_ref[:]                                  # [F, BN] int32
    y = labels_ref[:]                                  # [1, BN] int32
    bn = ct.shape[1]
    code = jnp.where((ct >= 0) & (ct < b), ct, _INVALID)
    if n % bn or n == 0:
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
        code = jnp.where(lane < n - i * bn, code, _INVALID)
    w = f * b
    jrept = jnp.concatenate([code] * b, axis=0)        # [W, BN]
    if wp > w:
        jrept = jnp.concatenate(
            [jrept, jnp.full((wp - w, bn), _INVALID, jnp.int32)], axis=0)
    jw = jax.lax.broadcasted_iota(jnp.int32, (wp, 1), 0)
    jsel = jnp.where(jw < w, jw // f, _PAD_SEL)
    hit = jrept == jsel                                # class-independent
    for c in range(nclass):
        xt = (hit & (y == c)).astype(jnp.int8)         # [Wp, BN]
        acc = jax.lax.dot_general(xt, xt, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        out_ref[c] += acc


def _cooc_clsb_kernel(codes_ref, labels_ref, out_ref, *, f: int, b: int,
                      wp: int, tr: int, n: int, nclass: int):
    """Blocked per-class gram: grid (row-band, column-block), band outer.
    Each step builds the full-width expansion for the column block plus a
    BAND-LOCAL expansion of the band's TR = F·k rows (a whole number of
    bins — Mosaic has no dynamic_slice, so the band is reconstructed from
    the same static concat with its bin offset ``r·k`` folded into the
    selector; both expansions together are negligible against the band
    dot), then accumulates [C, TR, wp] into the HBM-resident G's band
    (revisited across column blocks, initialized at block 0)."""
    r = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ct = codes_ref[:]                                  # [F, BN] int32
    y = labels_ref[:]                                  # [1, BN] int32
    bn = ct.shape[1]
    k = tr // f                                        # bins per band
    nb_pad = wp // f                                   # padded bin count
    code = jnp.where((ct >= 0) & (ct < b), ct, _INVALID)
    if n % bn or n == 0:
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
        code = jnp.where(lane < n - i * bn, code, _INVALID)
    # full-width expansion: row w holds (code[w % f] == w // f)
    jrept = jnp.concatenate([code] * nb_pad, axis=0)   # [Wp, BN]
    jw = jax.lax.broadcasted_iota(jnp.int32, (wp, 1), 0)
    jsel = jnp.where(jw // f < b, jw // f, _PAD_SEL)
    hit = jrept == jsel                                # [Wp, BN]
    # band-local expansion: bins [r·k, (r+1)·k), same static concat with
    # the scalar bin offset folded into the selector
    brept = jnp.concatenate([code] * k, axis=0)        # [TR, BN]
    bw = jax.lax.broadcasted_iota(jnp.int32, (tr, 1), 0)
    bbin = r * k + bw // f
    bsel = jnp.where(bbin < b, bbin, _PAD_SEL)
    bhit = brept == bsel                               # [TR, BN]
    for c in range(nclass):
        xb = (bhit & (y == c)).astype(jnp.int8)        # [TR, BN]
        xt = (hit & (y == c)).astype(jnp.int8)         # [Wp, BN]
        acc = jax.lax.dot_general(xb, xt, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        out_ref[c] += acc                              # [TR, Wp] band


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_classes", "block_cols", "interpret"))
def cooc_counts_cols(codes_t: jax.Array, labels: jax.Array, num_bins: int,
                     num_classes: int, *, block_cols: int | None = None,
                     interpret: bool = False) -> jax.Array:
    """codes_t [F, N] int (columnar), labels [N] int → G [Wp, Wp] int32
    co-occurrence counts (row/col index per :func:`w_index`).

    G[w1, w2] = #rows whose feature f1 falls in (b1, c) and f2 in (b2, c)
    — all NB/MI count tables at once.  Cross-class blocks are zero by
    construction (a row has one label).  This is the primary entry: it
    streams the codes exactly as stored, with no transpose and no joint
    materialization anywhere (fused into the kernel)."""
    f, n = codes_t.shape
    mode, jcp, wp = plan(f, num_bins, num_classes)
    out_shape = ((num_classes, wp, wp) if mode in ("cls", "clsb")
                 else (wp, wp))
    if n == 0:
        # empty chunk (e.g. a stream's empty final block): zero counts,
        # matching the einsum path — the kernel's OOB block read would
        # not even trace on a zero-row operand
        return jnp.zeros(out_shape, jnp.int32)
    jc = num_bins * num_classes
    bn = block_cols or default_block_cols(wp, mode)
    ct = codes_t.astype(jnp.int32)
    y2 = labels.reshape(1, n).astype(jnp.int32)
    npad = _ru(max(n, bn), bn)
    if mode == "clsb":
        tr, _wp2 = clsb_tile(f, num_bins, num_classes)
        kernel = functools.partial(_cooc_clsb_kernel, f=f, b=num_bins,
                                   wp=wp, tr=tr, n=n, nclass=num_classes)
        return pl.pallas_call(
            kernel,
            grid=(wp // tr, npad // bn),
            in_specs=[pl.BlockSpec((f, bn), lambda r, i: (0, i),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((1, bn), lambda r, i: (0, i),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((num_classes, tr, wp),
                                   lambda r, i: (0, r, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(out_shape, jnp.int32),
            compiler_params=COMPILER_PARAMS(
                dimension_semantics=("arbitrary", "arbitrary"),
                vmem_limit_bytes=110 * 1024 * 1024),
            interpret=interpret,
        )(ct, y2)
    if mode == "cls":
        kernel = functools.partial(_cooc_cls_kernel, f=f, b=num_bins,
                                   wp=wp, n=n, nclass=num_classes)
        out_specs = pl.BlockSpec((num_classes, wp, wp), lambda i: (0, 0, 0),
                                 memory_space=pltpu.VMEM)
    else:
        kernel = functools.partial(_cooc_kernel, f=f, jc=jc, jcp=jcp, wp=wp,
                                   n=n, nclass=num_classes, mode=mode)
        out_specs = pl.BlockSpec((wp, wp), lambda i: (0, 0),
                                 memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(npad // bn,),
        in_specs=[pl.BlockSpec((f, bn), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, bn), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=out_specs,
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.int32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(ct, y2)


def _cross_kernel(codes_ref, sel_ref, out_ref, *, f: int, b: int, jcp: int,
                  wp: int, sp_dim: int, n: int, nsel: int):
    """Cross co-occurrence XᵀY: X = the (feature, bin) one-hot (fmaj
    broadcast expansion, exactly the count kernel's), Y = the one-hot of
    an arbitrary selector code (e.g. node·C + class for the decision
    tree's level table).  Both expansions live only in VMEM."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ct = codes_ref[:]                                  # [F, BN] int32
    s = sel_ref[:]                                     # [1, BN] int32
    bn = ct.shape[1]
    code = jnp.where((ct >= 0) & (ct < b), ct, _INVALID)
    sel = jnp.where((s >= 0) & (s < nsel), s, _INVALID)
    if n % bn or n == 0:
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
        live = lane < n - i * bn
        code = jnp.where(live, code, _INVALID)
        sel = jnp.where(live, sel, _INVALID)
    jv = jax.lax.broadcasted_iota(jnp.int32, (1, jcp, 1), 1)
    xt = (code[:, None, :] == jv).astype(jnp.int8).reshape(f * jcp, bn)
    if wp > f * jcp:
        xt = jnp.concatenate(
            [xt, jnp.zeros((wp - f * jcp, bn), jnp.int8)], axis=0)
    sv = jax.lax.broadcasted_iota(jnp.int32, (sp_dim, 1), 0)
    yt = (sel == sv).astype(jnp.int8)                  # [Sp, BN]
    out_ref[:] += jax.lax.dot_general(xt, yt, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.int32)


MAX_SEL_CROSS = 1024


def cross_sel_width(num_sel: int) -> int:
    """Padded selector lane width of the cross gram's dot (the Y side of
    XᵀY pads to whole 128-lane tiles).  The dot work scales linearly
    with this, which is what makes it the honest unit for the decision
    tree's sibling-subtraction accounting (round 13): halving the
    contracted frontier slots only shrinks the kernel dot when K·C
    crosses a 128-lane boundary — the per-level ``sel_width`` in
    ``DecisionTree.level_stats`` reports exactly that."""
    return _ru(max(num_sel, 1), 128)


def cross_applicable(num_feat: int, num_bins: int, num_sel: int) -> bool:
    """Gate for the cross kernel: the X side obeys the joint-gram width
    cap and the selector side stays small (its padded lane width scales
    the dot work linearly)."""
    if num_feat * num_bins <= 0 or num_sel <= 0:
        return False
    jcp = _ru(num_bins, 32)
    wp = _ru(num_feat * jcp, 128)
    return wp <= MAX_W and num_sel <= MAX_SEL_CROSS


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_sel", "block_cols", "interpret"))
def cross_cooc_counts_cols(codes_t: jax.Array, sel: jax.Array,
                           num_bins: int, num_sel: int, *,
                           block_cols: int | None = None,
                           interpret: bool = False) -> jax.Array:
    """codes_t [F, N] int (columnar), sel [N] int (−1/out-of-range rows
    drop out) → [F, B, num_sel] int32 counts of each (feature, bin,
    selector) co-occurrence — computed as the int8-MXU cross gram XᵀY
    with both one-hots expanded in VMEM (never in HBM).

    The decision tree's per-level [F, B, K, C] table is this with
    sel = node·C + class (``models/tree.py::node_bin_class_counts``):
    the einsum form it replaces materializes the [N, F, B] one-hot in
    HBM (~400 B/row/level at the retarget shape vs the ~24 B/row the
    kernel streams)."""
    f, n = codes_t.shape
    jcp = _ru(num_bins, 32)
    wp = _ru(f * jcp, 128)
    sp_dim = _ru(num_sel, 128)
    if n == 0:
        return jnp.zeros((f, num_bins, num_sel), jnp.int32)
    # budget BOTH int8 expansions ([wp, BN] X and [sp_dim, BN] Y) against
    # the VMEM limit — the fmaj budget alone ignores Y and a large padded
    # selector width could push past vmem_limit_bytes at compile time
    bn = block_cols or max(128, min(
        _DEFAULT_BN,
        (72 * 1024 * 1024) // max(wp + sp_dim, 128)) // 128 * 128)
    ct = codes_t.astype(jnp.int32)
    s2 = sel.reshape(1, n).astype(jnp.int32)
    npad = _ru(max(n, bn), bn)
    kernel = functools.partial(_cross_kernel, f=f, b=num_bins, jcp=jcp,
                               wp=wp, sp_dim=sp_dim, n=n, nsel=num_sel)
    g = pl.pallas_call(
        kernel,
        grid=(npad // bn,),
        in_specs=[pl.BlockSpec((f, bn), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, bn), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((wp, sp_dim), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((wp, sp_dim), jnp.int32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(ct, s2)
    # [Wp, Sp] → [F, B, num_sel]: row f·jcp + b (wp padding dropped), col s
    return g[:f * jcp].reshape(f, jcp, sp_dim)[:, :num_bins, :num_sel]


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_classes", "block_cols", "interpret"))
def cooc_counts(codes: jax.Array, labels: jax.Array, num_bins: int,
                num_classes: int, *, block_cols: int | None = None,
                interpret: bool = False) -> jax.Array:
    """Row-major convenience wrapper: codes [N, F] → one XLA transpose
    (HBM-bound, ~11 ms per 16M rows on the dev rig) then the fused
    columnar kernel.  Callers that hold columnar codes should use
    :func:`cooc_counts_cols` and skip the transpose entirely."""
    return cooc_counts_cols.__wrapped__(
        codes.T, labels, num_bins, num_classes, block_cols=block_cols,
        interpret=interpret)


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_classes", "block_cols", "interpret"))
def gram_moments(codes: jax.Array, labels: jax.Array, cont: jax.Array,
                 num_bins: int, num_classes: int, *,
                 block_cols: int | None = None,
                 interpret: bool = False):
    """Single-dispatch SharedScan step (round 7): the co-occurrence gram G
    of the chunk's binned codes PLUS the class-conditional (count, Σx, Σx²)
    moments of the SAME device-resident continuous block, as ONE compiled
    program — so a scan serving NB + MI + Cramér + Fisher/NumericalAttrStats
    consumers (``pipeline/scan.py``) pays one dispatch per chunk, exactly
    like the single-job fast path.

    codes [N, F] int, labels [N] int, cont [N, Fc] float →
    (G, cnt [C], s1 [C, Fc], s2 [C, Fc]).  G and the count tensors derived
    from it are bit-identical to :func:`cooc_counts`; the moment sums are
    the same ``agg.class_moments`` contraction the standalone fits run."""
    from avenir_tpu.ops import agg

    g = cooc_counts_cols.__wrapped__(codes.T, labels, num_bins, num_classes,
                                     block_cols=block_cols,
                                     interpret=interpret)
    cnt, s1, s2 = agg.class_moments.__wrapped__(cont, labels, num_classes)
    return g, cnt, s1, s2


def _gram_block_rows(num_feat: int, depth: int, wp: int) -> int:
    """Row block for the einsum gram: bounded by a ~64 MB f32 intermediate
    budget (the [br, F, depth] one-hot plus the [br, wp] layout view and
    its dot operand copy) AND by 2^16 so every per-block f32 matmul sum is
    integer-exact with margin (counts ≤ br « 2^24)."""
    per_row = 4 * max(num_feat * depth + 2 * wp, 1)
    return max(256, min(1 << 16, (1 << 26) // per_row) // 128 * 128)


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_classes", "block_rows"))
def gram_counts_cols(codes_t: jax.Array, labels: jax.Array, num_bins: int,
                     num_classes: int, *,
                     block_rows: int | None = None) -> jax.Array:
    """The co-occurrence gram G as ONE exact einsum dispatch — the packed
    fold's device program (PackGraft, round 16) for hosts where the Pallas
    kernel doesn't run (the chunked-einsum routing's territory).

    Bit-identical to :func:`cooc_counts_cols` for EVERY plan mode: the
    one-hot X is laid out per :func:`plan`/:func:`w_index` (fmaj
    w = f·jcp + (bin·C + cls); jmaj w = (bin·C + cls)·F + f; cls/clsb
    per-class w = bin·F + f with G [C, wp, wp]), pad cells stay exactly
    zero, out-of-range codes drop per-feature and out-of-range labels
    drop the whole row — the drop-invalid contract.  Rows are processed
    in f32-exact blocks with int32 accumulation (the same exactness
    argument as ``models/tree.py::node_bin_class_counts``), so any N is
    exact.

    Versus the chunked-einsum fold this ONE [br, wp]ᵀ[br, wp] matmul
    replaces the per-table one-hot contractions XLA lowers to
    scatter-adds — the packing planner (:func:`pack_tables`) decides when
    that trade pays."""
    f, n = codes_t.shape
    mode, jcp, wp = plan(f, num_bins, num_classes)
    cls_mode = mode in ("cls", "clsb")
    out_shape = (num_classes, wp, wp) if cls_mode else (wp, wp)
    if n == 0:
        return jnp.zeros(out_shape, jnp.int32)
    jc = num_bins * num_classes
    depth = (wp // f if mode == "clsb" else
             num_bins if mode == "cls" else
             jcp if mode == "fmaj" else jc)
    br = block_rows or _gram_block_rows(f, depth, wp)
    ct = codes_t.astype(jnp.int32)
    y = labels.astype(jnp.int32)
    npad = _ru(n, br)
    if npad > n:
        # pad rows carry label −1: the row-validity mask below drops them
        # from every mode, so padding is pure shape ballast
        ct = jnp.pad(ct, ((0, 0), (0, npad - n)), constant_values=_INVALID)
        y = jnp.pad(y, (0, npad - n), constant_values=-1)
    lanes = jnp.arange(depth)

    def block_joint(cb, yb):
        # joint code j = bin·C + cls; invalid labels kill the whole row,
        # out-of-range codes kill the cell — the compare against the lane
        # iota then leaves those one-hot rows all-zero (j = −1)
        ok = ((yb >= 0) & (yb < num_classes))[None, :] \
            & (cb >= 0) & (cb < num_bins)
        j = jnp.where(ok, cb * num_classes + yb[None, :], -1)   # [F, br]
        oh = (j[:, :, None] == lanes).astype(jnp.float32)       # [F, br, d]
        if mode == "fmaj":
            x = oh.transpose(1, 0, 2).reshape(br, f * depth)
        else:
            x = oh.transpose(1, 2, 0).reshape(br, depth * f)
        if wp > x.shape[1]:
            x = jnp.pad(x, ((0, 0), (0, wp - x.shape[1])))
        return jnp.dot(x.T, x, precision="highest").astype(jnp.int32)

    def block_cls(cb, yb):
        code = jnp.where((cb >= 0) & (cb < num_bins), cb, -1)   # [F, br]
        oh = (code[:, :, None] == lanes).astype(jnp.float32)    # [F, br, d]
        x = oh.transpose(1, 2, 0).reshape(br, depth * f)        # w = b·F + f
        if wp > x.shape[1]:                    # cls pads past F·B, at the end
            x = jnp.pad(x, ((0, 0), (0, wp - x.shape[1])))
        gs = []
        for c in range(num_classes):
            xc = x * (yb == c).astype(jnp.float32)[:, None]
            gs.append(jnp.dot(xc.T, xc,
                              precision="highest").astype(jnp.int32))
        return jnp.stack(gs)

    block = block_cls if cls_mode else block_joint
    g, _ = jax.lax.scan(
        lambda acc, xs: (acc + block(xs[0], xs[1]), None),
        jnp.zeros(out_shape, jnp.int32),
        (ct.reshape(f, npad // br, br).transpose(1, 0, 2),
         y.reshape(npad // br, br)))
    return g


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_classes", "block_rows"))
def gram_counts(codes: jax.Array, labels: jax.Array, num_bins: int,
                num_classes: int, *,
                block_rows: int | None = None) -> jax.Array:
    """Row-major wrapper of :func:`gram_counts_cols` (codes [N, F]) — the
    packed ChunkFolder step's entry, mirroring :func:`cooc_counts`."""
    return gram_counts_cols.__wrapped__(codes.T, labels, num_bins,
                                        num_classes, block_rows=block_rows)


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_classes", "block_rows"))
def gram_counts_moments(codes: jax.Array, labels: jax.Array,
                        cont: jax.Array, num_bins: int, num_classes: int, *,
                        block_rows: int | None = None):
    """Packed-fold analog of :func:`gram_moments`: the einsum gram PLUS
    the class-conditional continuous moments of the same resident chunk,
    one compiled program — so a packed SharedScan chunk pays one dispatch
    exactly like the kernel fast path does."""
    from avenir_tpu.ops import agg

    g = gram_counts_cols.__wrapped__(codes.T, labels, num_bins, num_classes,
                                     block_rows=block_rows)
    cnt, s1, s2 = agg.class_moments.__wrapped__(cont, labels, num_classes)
    return g, cnt, s1, s2


def counts_from_cooc(g, num_feat: int, num_bins: int, num_classes: int,
                     ci, cj):
    """Host-side (numpy) read-out of the reference-shaped count tensors
    from G:  → (fbc [F, B, C], pair [P, B, B, C]), dtype preserved.

    This runs ONCE per job on a ~100 KB–1 MB matrix (microseconds of
    numpy) — on-device extraction was measured at 20-30 ms/call on the
    dev TPU (every gather / diagonal / batched-einsum formulation lowers
    to scalar loops or pathological small batched GEMMs), i.e. slower
    than the count kernel itself, so the device hands back G and the host
    does the indexing."""
    g = np.asarray(g)
    b, c = num_bins, num_classes
    wf = w_index(num_feat, b, c)                             # [F, B, C]
    ci = np.asarray(ci, np.int64)
    cj = np.asarray(cj, np.int64)
    p = len(ci)
    if g.ndim == 3:                                          # cls mode
        w2 = wf[:, :, 0]                                     # [F, B]
        fbc = np.stack([g[k][w2, w2] for k in range(c)], axis=-1)
        wi = np.broadcast_to(w2[ci][:, :, None], (p, b, b))
        wj = np.broadcast_to(w2[cj][:, None, :], (p, b, b))
        pair = np.stack([g[k][wi, wj] for k in range(c)], axis=-1)
        return fbc, pair
    fbc = g[wf, wf]
    wi = wf[ci][:, :, None, :]                               # [P, B, 1, C]
    wj = wf[cj][:, None, :, :]                               # [P, 1, B, C]
    pair = g[np.broadcast_to(wi, (p, b, b, c)),
             np.broadcast_to(wj, (p, b, b, c))]
    return fbc, pair


# ---------------------------------------------------------------------------
# PackGraft (round 16): block-diagonal gram packing.
#
# The efficiency-vs-width curve (BASELINE.md wide-schema tier: ~77% of int8
# peak at per-class widths ≥ 2000 vs 18-30% at the flagship W=384) makes
# joint width the biggest single-chip lever.  A pack descriptor lays several
# INDEPENDENT narrow tables' one-hot blocks along ONE joint width so all of
# them ride a single wide gram dispatch:
#
#   · cross pack (pack_tables): the members are the FEATURES of one dataset
#     — i.e. the ordinary joint gram G over all features at once, whose
#     off-diagonal blocks are exactly the MI pair tables and whose diagonal
#     blocks are the NB / against-class tables.  "Packing" NB + MI +
#     correlation is then just routing the fold onto ONE G instead of the
#     per-table scatter einsums; byte-identity is by construction
#     (counts_from_cooc reads the same cells the per-table einsums build).
#   · disjoint pack (pack_disjoint): the members are ROW-DISJOINT selectors
#     (e.g. one tree-frontier node per row).  Each member gets a bin STRIPE
#     of the joint bin axis (offset = m·stripe_bins); composite codes
#     code + offset keep every cross-member block structurally zero because
#     no row carries two members.  On clsb the stripe is rounded up to whole
#     bands so members never straddle a band.
#
# The planners return a PackPlan (hashable — usable as a jit static) and the
# pack either routes onto the EXISTING kernels (cooc_counts_cols — the
# joint shape picks its own fmaj/cls/clsb mode, including the banded clsb
# tier) or onto gram_counts_cols, the exact einsum gram, off-TPU.  Packed
# g_keys share the kernel g_key's byte layout but carry a "packed" base so
# checkpoint provenance stays visible to ChunkFolder's foreign-key refusal;
# mesh suffixes attach behind the base exactly as for kernel keys.
# ---------------------------------------------------------------------------


class PackMember(NamedTuple):
    """One table riding a pack: its (F, B, C) shape plus where its block
    starts — a width offset (first w cell) for a cross pack, a bin-stripe
    offset (joint bin = offset + local bin) for a disjoint pack."""
    key: str
    num_feat: int
    num_bins: int
    num_classes: int
    offset: int


class PackPlan(NamedTuple):
    """Descriptor of one packed dispatch: the members plus the JOINT
    (F, B, C) shape handed to plan()/the kernels.  Hashable by
    construction so it can ride jit static_argnames."""
    members: Tuple[PackMember, ...]
    num_feat: int
    num_bins: int          # JOINT bins (disjoint: n_members · stripe_bins)
    num_classes: int
    mode: str              # plan() mode of the joint shape
    wp: int                # padded joint width
    band_bins: int         # clsb band size in bins (0 otherwise)
    stripe_bins: int       # disjoint packs: per-member bin stride, else 0
    disjoint: bool

    @property
    def signature(self) -> str:
        """Composite pack identity for telemetry program registration:
        (site, signature) attributes roofline MFU to THIS packed shape."""
        tag = "d" if self.disjoint else "x"
        return (f"{self.mode}:{tag}{len(self.members)}:f{self.num_feat}"
                f":b{self.num_bins}:c{self.num_classes}:w{self.wp}")

    @property
    def g_key(self) -> str:
        """Checkpoint key of the packed G accumulator — same byte layout
        as g_key(joint shape) (same plan(), same w_index cells), distinct
        base so provenance survives kill-packed → resume-unpacked."""
        return (f"g:packed:{self.mode}:f{self.num_feat}"
                f":b{self.num_bins}:c{self.num_classes}")


def packed_g_key(num_feat: int, num_bins: int, num_classes: int) -> str:
    """The packed-provenance g_key for a joint shape — what a packed
    ChunkFolder writes where an unpacked gram folder writes g_key().
    Byte layout is IDENTICAL to g_key(F, B, C) (both are plan()'s G for
    the same joint shape); only the base string differs, so adopt_state
    can normalize between the two while foreign LAYOUTS still refuse."""
    mode, _, _ = plan(num_feat, num_bins, num_classes)
    return f"g:packed:{mode}:f{num_feat}:b{num_bins}:c{num_classes}"


def pack_tables(num_feat: int, num_bins: int, num_classes: int,
                num_pairs: int, max_width: Optional[int] = None
                ) -> Optional[PackPlan]:
    """Cross-pack planner: fold NB ([F, B, C]) + P MI pair tables
    ([B, B, C] each) + against-class stacks as ONE joint gram, or None
    when the pack does not pay.

    Cost model (shares WIDTH_SLACK with plan()'s fmaj routing): the
    unpacked fold builds F·B + P·B·(1+C) one-hot-contracted cells per
    class-expanded row; the packed gram pays wp² but rides the wide-gram
    MXU tier, so pack iff  wp ≤ WIDTH_SLACK · (F·B + P·B·(1+C))  and wp
    fits the clsb ceiling (the widest tier the kernel attests).  The
    measured CPU einsum crossover (hosp 11×12×2, 55 pairs: 7.2×) sits
    far above this gate; the gate's job is refusing packs where pad
    cells dominate (e.g. pair-poor consumer sets)."""
    if num_feat * num_bins * num_classes <= 0:
        return None
    mode, jcp, wp = plan(num_feat, num_bins, num_classes)
    cap = min(max_width or MAX_W_CLSB, MAX_W_CLSB)
    if wp > cap:
        return None
    cells = num_feat * num_bins + num_pairs * num_bins * (1 + num_classes)
    if wp > WIDTH_SLACK * cells:
        return None
    wf = w_index(num_feat, num_bins, num_classes)
    members = tuple(
        PackMember(key=f"f{i}", num_feat=1, num_bins=num_bins,
                   num_classes=num_classes, offset=int(wf[i].min()))
        for i in range(num_feat))
    band = clsb_tile(num_feat, num_bins, num_classes) if mode == "clsb" \
        else None
    return PackPlan(members=members, num_feat=num_feat, num_bins=num_bins,
                    num_classes=num_classes, mode=mode, wp=wp,
                    band_bins=(band[0] // num_feat if band else 0),
                    stripe_bins=0, disjoint=False)


def pack_disjoint(num_members: int, num_feat: int, num_bins: int,
                  num_classes: int, max_width: Optional[int] = None
                  ) -> Optional[PackPlan]:
    """Disjoint-pack planner: M row-disjoint members (tree sibling nodes),
    each an [F, B, C] table, as one joint gram over M·Bp bins where Bp is
    B rounded up so clsb bands hold WHOLE members (a member never
    straddles a band — its diagonal block stays inside one band and every
    cross-member cell the banded kernel materializes is structurally
    zero).  Returns None when the joint shape exceeds every tier or the
    fixpoint between stripe rounding and clsb's tile choice diverges.

    NOTE the FLOP trade: the joint gram pays ~M× the cells of M separate
    grams (each member's rows also multiply the other members' all-zero
    stripes) — worth it only to reach a faster width tier; callers gate
    on packed_applicable()/platform (architecture.md "when packing does
    NOT pay")."""
    if num_members <= 0 or num_feat * num_bins * num_classes <= 0:
        return None
    bp = num_bins
    mode = wp = None
    for _ in range(4):                       # stripe↔band fixpoint, ≤4 hops
        mode, _, wp = plan(num_feat, num_members * bp, num_classes)
        if mode != "clsb":
            break
        tile = clsb_tile(num_feat, num_members * bp, num_classes)
        if tile is None:
            return None
        k = tile[0] // num_feat              # band size in bins
        bp2 = _ru(num_bins, k)
        if bp2 == bp:
            break
        bp = bp2
    else:
        return None
    cap = min(max_width or MAX_W_CLSB, MAX_W_CLSB)
    if wp > cap or not (mode in ("cls", "clsb") or wp <= MAX_W):
        return None
    members = tuple(
        PackMember(key=f"m{i}", num_feat=num_feat, num_bins=num_bins,
                   num_classes=num_classes, offset=i * bp)
        for i in range(num_members))
    band = clsb_tile(num_feat, num_members * bp, num_classes) \
        if mode == "clsb" else None
    return PackPlan(members=members, num_feat=num_feat,
                    num_bins=num_members * bp, num_classes=num_classes,
                    mode=mode, wp=wp,
                    band_bins=(band[0] // num_feat if band else 0),
                    stripe_bins=bp, disjoint=True)


@functools.partial(jax.jit, static_argnames=("stripe_bins", "member_bins"))
def packed_codes(codes_t: jax.Array, member: jax.Array, stripe_bins: int,
                 member_bins: int) -> jax.Array:
    """Composite codes for a disjoint pack: joint bin = code + m·stripe.

    The mask is against the member's OWN bin count, not the stripe: an
    out-of-range local code must become −1 (dropped by the kernels'
    drop-invalid contract), never bleed into the next member's stripe.
    Rows with member −1 (e.g. tree rows not on the frontier) drop whole."""
    ct = codes_t.astype(jnp.int32)
    mem = member.astype(jnp.int32)
    off = jnp.where(mem >= 0, mem * stripe_bins, 0)[None, :]
    ok = (mem >= 0)[None, :] & (ct >= 0) & (ct < member_bins)
    return jnp.where(ok, ct + off, -1)


def packed_diag_index(pplan: PackPlan) -> np.ndarray:
    """Host-side unpack index for a DISJOINT pack: w cells [F, B, M, C]
    such that G[w, w] (per class for cls modes) is member m's [F, B, C]
    table — the counts_from_cooc-style read-out at joint bin
    offset_m + b."""
    wf = w_index(pplan.num_feat, pplan.num_bins, pplan.num_classes)
    b = pplan.members[0].num_bins
    offs = np.array([mb.offset for mb in pplan.members], np.int64)
    sel = offs[None, :] + np.arange(b)[:, None]              # [B, M]
    return wf[:, sel, :]                                     # [F, B, M, C]


def packed_applicable(pplan: PackPlan) -> bool:
    """Kernel eligibility of the JOINT shape — the packed analog of
    applicable(); routing also needs use_kernel()'s platform gates."""
    return applicable(pplan.num_feat, pplan.num_bins, pplan.num_classes)


def nb_mi_step(codes: jax.Array, labels: jax.Array, ci, cj,
               num_classes: int, num_bins: int, *, interpret: bool = False):
    """Kernel-backed equivalent of
    :func:`avenir_tpu.ops.agg.nb_mi_pipeline_step`:
    → (fbc [F, B, C] int32, pair [P, B, B, C] int32) as numpy arrays.

    Synchronizes (fetches G) — callers that need async chaining should
    run :func:`cooc_counts` per chunk and :func:`counts_from_cooc` once at
    the end, which is how MutualInformation.fit and bench.py use it."""
    g = cooc_counts(codes, labels, num_bins, num_classes,
                    interpret=interpret)
    return counts_from_cooc(g, codes.shape[1], num_bins, num_classes, ci, cj)


def applicable(num_feat: int, num_bins: int, num_classes: int) -> bool:
    """Static shape gate: is some Xᵀ·X form profitable/compilable here?"""
    if num_feat * num_bins * num_classes <= 0:
        return False
    mode, _, wp = plan(num_feat, num_bins, num_classes)
    # the per-class modes are only ever returned with their gates passed
    return mode in ("cls", "clsb") or wp <= MAX_W


def use_kernel(num_feat: int, num_bins: int, num_classes: int,
               mesh=None) -> bool:
    """THE routing predicate for the NB+MI count fast path — single source
    of truth for MutualInformation.fit, bench.py and e2e_pipeline: shape
    applicable, no mesh (the sharded einsum's psum is the attested
    collective), and a single TPU device attached."""
    return (mesh is None and applicable(num_feat, num_bins, num_classes)
            and on_tpu_single_device())


def chunk_pipeline(num_feat: int, num_bins: int, num_classes: int, ci, cj,
                   columnar: bool = False):
    """(step, chain_scalar, is_kernel) for the per-chunk NB+MI device step.

    ``step(codes, labels)`` returns the chunk's count object (G on the
    kernel path, (fbc, pair) on the einsum path); ``chain_scalar(out)``
    extracts the zero int32 scalar benchmarks feed into the next chunk's
    labels operand so one final fetch syncs the whole chain.  Keeping both
    paths' plumbing here means bench.py and e2e_pipeline cannot drift from
    the routing the library itself uses.  With ``columnar=True`` (kernel
    path only) ``step`` takes codes in [F, N] layout and skips the
    transpose."""
    if use_kernel(num_feat, num_bins, num_classes):
        kernel = cooc_counts_cols if columnar else cooc_counts

        def step(codes, labels):
            return kernel(codes, labels, num_bins, num_classes)

        def chain_scalar(out):
            return (out[(0,) * out.ndim] * 0).astype(jnp.int32)

        return step, chain_scalar, True

    from avenir_tpu.ops import agg

    def step(codes, labels):
        return agg.nb_mi_pipeline_step(codes, labels, ci, cj,
                                       num_classes, num_bins)

    def chain_scalar(out):
        return (out[0][0, 0, 0] * 0).astype(jnp.int32)

    return step, chain_scalar, False


def mesh_on_tpu(mesh) -> bool:
    """True when every device of ``mesh`` is a TPU — the gate for running
    the compiled kernel under ``shard_map``
    (``parallel/collectives.sharded_cooc_step``); CPU meshes (tests,
    dryrun) run the same step with ``interpret=True`` instead."""
    if mesh is None:
        return False
    try:
        devices = list(np.asarray(mesh.devices).flat)
    except Exception:                                   # pragma: no cover
        return False
    return bool(devices) and all(
        d.platform == "tpu" or "tpu" in (getattr(d, "device_kind", "") or
                                         "").lower()
        for d in devices)


def on_tpu_single_device(*arrays) -> bool:
    """Runtime gate: default backend is a TPU and no operand is sharded
    across devices (the sharded einsum path owns multi-device execution —
    its psum-over-data collective is what the mesh tests attest)."""
    try:
        dev = jax.devices()[0]
    except Exception:                                   # pragma: no cover
        return False
    kind = getattr(dev, "device_kind", "") or ""
    if dev.platform != "tpu" and "tpu" not in kind.lower():
        return False
    for x in arrays:
        sharding = getattr(x, "sharding", None)
        if sharding is not None and len(getattr(sharding, "device_set", ())) > 1:
            return False
    return True
