"""MXU co-occurrence histogram — the Pallas count kernel behind NB+MI.

The count tables of the flagship pipeline (the rebuild of the reference's
``explore/MutualInformation.java:236-403`` combiner/reducer and
``bayesian/BayesianDistribution.java:203-328`` shuffle) were previously
one-hot einsums that XLA lowers to scatter-adds — measured wall of
~7 G updates/s (66 updates/row on the hosp_readmit shape, <1% of any
hardware peak; BASELINE.md round-2 perf notes).  This kernel replaces the
scatter lowering entirely:

    every NB/MI count table is a sub-block of  G = Xᵀ X,
    where X is the [N, W] one-hot of the joint (feature, bin, class) code,
    W = F·B·C.

X is never materialized in HBM (round 2 measured the dense-matmul-with-
HBM-one-hot form traffic-bound and slower than scatter).  Instead the
kernel streams the [F, N] int32 joint-code array through VMEM in column
blocks, expands each block to Xᵀ in registers/VMEM (tile-concatenate +
compare — no gather), and feeds the int8 MXU path, accumulating G in an
int32 [Wp, Wp] VMEM block across the grid:

    joint  [F, BN]  --tile x JC-->  [W, BN]  ==iota//F==>  Xᵀ int8
    G += Xᵀ·X      (int8 MXU pass, int32 accumulate — exact)

Layout: G's row/col index is j-major, ``w = (bin·C + class)·F + feature``
— the native order of a tile-style repeat (result row w = input row
w mod F).  :func:`nb_mi_step` re-indexes G into the reference-shaped
[F, B, C] and [P, B, B, C] tensors.

Measured round 3 (TPU v5 lite, chained-dispatch host-fetch sync,
16M-row chunks, hosp_readmit shape F=11 B=12 C=2, Wp=384):
~480-500 M rows/s vs ~80-113 M for the einsum/scatter form — the kernel
is int8-MXU-bound (the Xᵀ·X pass alone is ~12.6 ms of the ~34 ms/chunk;
the rest is the VPU expand/compare at W·N cells), not HBM-bound: the
[F, N] int32 joint stream it reads is 44 B/row ≈ 18 GB/s at this rate,
so the roofline resource is MXU occupancy, not bandwidth.

Exactness: int8 operands are 0/1, int32 accumulation — per-chunk counts
are exact up to 2^31 rows (the einsum path's f32 accumulation capped
chunks at 2^24; callers keep that cap so both paths stay interchangeable).
Out-of-range codes produce joint codes outside [0, B·C) and drop out, and
out-of-range labels invalidate the whole row — bit-identical semantics to
``ops/agg.py::pair_class_counts``'s drop-invalid contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# joint-code marker for invalid rows / padding: never equals a selector
# value (selectors are in [0, B·C) plus the pad marker below)
_INVALID = -(1 << 20)
_PAD_SEL = -(1 << 20) - 1

# The Xᵀ·X pass costs ~2·Wp² int8-MXU FLOP per row; past Wp≈768 the kernel
# loses to the scatter einsum (and VMEM for the [Wp, BN] expansion runs
# out), so the dispatcher falls back above this.
MAX_W = 768

# column-block default: ~500 M rows/s optimum on v5e for Wp=384 (sweep in
# round-3 notes); scaled down by the wrapper for wider tables
_DEFAULT_BN = 49152


def _ru(x: int, m: int) -> int:
    return -(-x // m) * m


def default_block_cols(wp: int) -> int:
    """Column block sized so the [wp, BN] int32 expansion + int8 one-hot
    stay inside the ~110 MB VMEM budget the kernel compiles against."""
    bn = _DEFAULT_BN * 384 // max(wp, 128)
    return max(128, (bn // 128) * 128)


def _cooc_kernel(joint_ref, out_ref, *, f: int, jc: int, w: int, wp: int,
                 n: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    joint = joint_ref[:]                               # [F, BN] int32
    bn = joint.shape[1]
    # ragged tail: lanes past the true row count read garbage from the
    # out-of-bounds block — neutralize them here instead of paying a
    # full-array jnp.pad copy outside (~10 ms/chunk at 16M rows)
    if n % bn or n == 0:
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
        joint = jnp.where(lane < n - i * bn, joint, _INVALID)
    # tile-expand: row w of the result is joint[w mod F] (jnp.concatenate
    # measures identical to pltpu.repeat on-chip and also lowers in
    # interpreter mode for the CPU test suite)
    jrept = jnp.concatenate([joint] * jc, axis=0)      # [W, BN]
    if wp > w:
        jrept = jnp.concatenate(
            [jrept, jnp.full((wp - w, bn), _INVALID, jnp.int32)], axis=0)
    jw = jax.lax.broadcasted_iota(jnp.int32, (wp, 1), 0)
    jsel = jnp.where(jw < w, jw // f, _PAD_SEL)
    # int8 one-hot straight from the int32 compare: int8 compare/select is
    # not lowerable (Mosaic), int32→int8 select is — and feeds the int8
    # MXU pass at 2× the bf16 rate
    xt = (jrept == jsel).astype(jnp.int8)              # [Wp, BN] = Xᵀ block
    acc = jax.lax.dot_general(xt, xt, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out_ref[:] += acc


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_classes", "block_cols", "interpret"))
def cooc_counts(codes: jax.Array, labels: jax.Array, num_bins: int,
                num_classes: int, *, block_cols: int | None = None,
                interpret: bool = False) -> jax.Array:
    """codes [N, F] int, labels [N] int → G [Wp, Wp] int32 co-occurrence
    counts in j-major layout (``w = (bin·C + class)·F + feature``).

    G[w1, w2] = #rows whose feature f1 falls in (b1, c) and f2 in (b2, c)
    — all NB/MI count tables at once.  Cross-class blocks are zero by
    construction (a row has one label)."""
    n, f = codes.shape
    jc = num_bins * num_classes
    w = f * jc
    wp = _ru(w, 128)
    if n == 0:
        # empty chunk (e.g. a stream's empty final block): zero counts,
        # matching the einsum path — the kernel's OOB block read would
        # not even trace on a zero-row operand
        return jnp.zeros((wp, wp), jnp.int32)
    bn = block_cols or default_block_cols(wp)
    y = labels[None, :]
    valid = (y >= 0) & (y < num_classes)
    joint = jnp.where(valid, codes.T.astype(jnp.int32) * num_classes + y,
                      _INVALID)                        # [F, N]
    npad = _ru(max(n, bn), bn)
    return pl.pallas_call(
        functools.partial(_cooc_kernel, f=f, jc=jc, w=w, wp=wp, n=n),
        grid=(npad // bn,),
        in_specs=[pl.BlockSpec((f, bn), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((wp, wp), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((wp, wp), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(joint)


def counts_from_cooc(g, num_feat: int, num_bins: int, num_classes: int,
                     ci, cj):
    """Host-side (numpy) read-out of the reference-shaped count tensors
    from G:  → (fbc [F, B, C], pair [P, B, B, C]), dtype preserved.

    This runs ONCE per job on a ~100 KB–1 MB matrix (microseconds of
    numpy) — on-device extraction was measured at 20-30 ms/call on the
    dev TPU (every gather / diagonal / batched-einsum formulation lowers
    to scalar loops or pathological small batched GEMMs), i.e. slower
    than the count kernel itself, so the device hands back G and the host
    does the indexing."""
    import numpy as np
    g = np.asarray(g)
    f, b, c = num_feat, num_bins, num_classes
    w = f * b * c
    ci = np.asarray(ci, np.int64)
    cj = np.asarray(cj, np.int64)
    # w = (bin·C + class)·F + feature  (j-major kernel layout)
    a_ = np.arange(b)[None, :, None]
    c_ = np.arange(c)[None, None, :]
    wf = (a_ * c + c_) * f + np.arange(f)[:, None, None]     # [F, B, C]
    fbc = g[wf, wf]
    grid_a = (np.arange(b)[None, :, None, None] * c
              + np.arange(c)[None, None, None, :]) * f       # [1, B, 1, C]
    grid_b = (np.arange(b)[None, None, :, None] * c
              + np.arange(c)[None, None, None, :]) * f       # [1, 1, B, C]
    idx1 = grid_a + ci[:, None, None, None]                  # [P, B, 1, C]
    idx2 = grid_b + cj[:, None, None, None]                  # [P, 1, B, C]
    p = len(ci)
    pair = g[np.broadcast_to(idx1, (p, b, b, c)),
             np.broadcast_to(idx2, (p, b, b, c))]
    return fbc, pair


def nb_mi_step(codes: jax.Array, labels: jax.Array, ci, cj,
               num_classes: int, num_bins: int, *, interpret: bool = False):
    """Kernel-backed equivalent of
    :func:`avenir_tpu.ops.agg.nb_mi_pipeline_step`:
    → (fbc [F, B, C] int32, pair [P, B, B, C] int32) as numpy arrays.

    Synchronizes (fetches G) — callers that need async chaining should
    run :func:`cooc_counts` per chunk and :func:`counts_from_cooc` once at
    the end, which is how MutualInformation.fit and bench.py use it."""
    g = cooc_counts(codes, labels, num_bins, num_classes,
                    interpret=interpret)
    return counts_from_cooc(g, codes.shape[1], num_bins, num_classes, ci, cj)


def applicable(num_feat: int, num_bins: int, num_classes: int) -> bool:
    """Static shape gate: is the Xᵀ·X form profitable/compilable here?"""
    return 0 < num_feat * num_bins * num_classes <= MAX_W


def use_kernel(num_feat: int, num_bins: int, num_classes: int,
               mesh=None) -> bool:
    """THE routing predicate for the NB+MI count fast path — single source
    of truth for MutualInformation.fit, bench.py and e2e_pipeline: shape
    applicable, no mesh (the sharded einsum's psum is the attested
    collective), and a single TPU device attached."""
    return (mesh is None and applicable(num_feat, num_bins, num_classes)
            and on_tpu_single_device())


def chunk_pipeline(num_feat: int, num_bins: int, num_classes: int, ci, cj):
    """(step, chain_scalar, is_kernel) for the per-chunk NB+MI device step.

    ``step(codes, labels)`` returns the chunk's count object (G on the
    kernel path, (fbc, pair) on the einsum path); ``chain_scalar(out)``
    extracts the zero int32 scalar benchmarks feed into the next chunk's
    labels operand so one final fetch syncs the whole chain.  Keeping both
    paths' plumbing here means bench.py and e2e_pipeline cannot drift from
    the routing the library itself uses."""
    if use_kernel(num_feat, num_bins, num_classes):
        def step(codes, labels):
            return cooc_counts(codes, labels, num_bins, num_classes)

        def chain_scalar(out):
            return (out[0, 0] * 0).astype(jnp.int32)

        return step, chain_scalar, True

    from avenir_tpu.ops import agg

    def step(codes, labels):
        return agg.nb_mi_pipeline_step(codes, labels, ci, cj,
                                       num_classes, num_bins)

    def chain_scalar(out):
        return (out[0][0, 0, 0] * 0).astype(jnp.int32)

    return step, chain_scalar, False


def on_tpu_single_device(*arrays) -> bool:
    """Runtime gate: default backend is a TPU and no operand is sharded
    across devices (the sharded einsum path owns multi-device execution —
    its psum-over-data collective is what the mesh tests attest)."""
    try:
        dev = jax.devices()[0]
    except Exception:                                   # pragma: no cover
        return False
    kind = getattr(dev, "device_kind", "") or ""
    if dev.platform != "tpu" and "tpu" not in kind.lower():
        return False
    for x in arrays:
        sharding = getattr(x, "sharding", None)
        if sharding is not None and len(getattr(sharding, "device_set", ())) > 1:
            return False
    return True
