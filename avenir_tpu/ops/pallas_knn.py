"""Fused kNN distance + exact running top-k as a Pallas TPU kernel.

The XLA scan path (models/knn.py::_topk_over_tiles) materializes a
[test_tile, ref_tile] distance block in HBM each scan step and runs a
full-width ``lax.top_k`` over it — measured on-chip that is ~147 ms of
HBM-bound distance traffic plus ~210 ms of sort work for 4096 queries × 1M
references. This kernel keeps everything in VMEM and feeds the MXU exactly
one bf16 pass per tile:

- The whole squared distance collapses into ONE bf16 matmul,
  d² = −2·(A·Bᵀ), by packing into the contraction axis: the flattened
  categorical one-hots (0/1 and 0/0.5 — mismatch counts are exact in bf16),
  the continuous coordinates split into three bf16 limbs (hi/lo/lo2 with
  cross-limb product columns, so the f32 product is reproduced to ~2⁻²⁶
  relative — Mosaic's native f32 dot costs ~6 MXU passes, measured 6×
  slower than this), and the ‖x‖²/‖y‖² norm terms as limb-split side
  columns. Reference pad rows bake a huge finite norm term (never ±inf: a
  zero padding lane times inf is NaN, and NaN poisons every compare).
- At scale the candidate kernel is the round-3 SEGMENT KEY-TOURNAMENT
  sweep (see its section below): int32 packed sort keys + lane-halving
  min/max merges, per-2048-ref-segment top-2 + truncated third-min bound.
  The merge-loop kernel in this section remains the small-reference-set
  path (too few segments to fill the candidate pool): a running per-row
  top-k' lives in VMEM scratch across the ref-block grid axis, and only
  blocks with an improving candidate run extract-min merge rounds (a
  while_loop whose condition *is* the skip test).
- The caller then re-ranks the k' candidates with exact f32 arithmetic and
  checks an exactness certificate (k-th exact candidate distance vs the
  kernel's k'-th value minus the limb error bound); rows that fail fall
  back to the exact XLA scan. With the 2⁻²⁶ bound the certificate
  essentially never fails, so results are exact top-k, not approximate.

Replaces the O(N²) all-pairs distance job the reference outsources to
sifarish ``SameTypeSimilarity`` (resource/knn.sh:47-60) and the secondary-
sort top-k of knn/NearestNeighbor.java:317-349, as one on-chip pass.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams → CompilerParams; module-local alias,
# same as ops/pallas_hist.py (no mutation of the shared pltpu module)
COMPILER_PARAMS = (pltpu.CompilerParams if hasattr(pltpu, "CompilerParams")
                   else pltpu.TPUCompilerParams)

# Block shapes. TM query rows are resident per grid row; TN reference rows
# stream through VMEM per grid step. Kept candidates live in SLOTS lanes so
# the best-buffer is VPU-tile aligned; unused slots are pinned to -_BIG so
# they are never chosen as the eviction victim.
TM = 512
TN = 2048
SLOTS = 128
MARGIN = 8             # extra candidates kept beyond k for the exact re-rank
# Large finite sentinels — true infinities must never reach the MXU.
_BIG = 3.0e30          # "retired / empty slot" distance
_PADC = 1.0e30         # reference pad-row norm term: dominates any real d²
# Absolute d² error bound of the limb-split dot (see _limbs): each of the
# ~20 contributing terms is reproduced to ~2^-26 relative, magnitudes ≤ ~32.
D2_EPS = 1e-4
_DEBUG_NO_MERGE = False   # trace-time knobs for perf bisection only
_DEBUG_NO_ROWMIN = False
_DEBUG_NO_D2WRITE = False


def _knn_kernel(a_ref, b_ref, best_d_out, best_i_out,
                d2_ref, rowmin_ref, best_d_ref, best_i_ref,
                *, k: int, nblocks: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        slot = jax.lax.broadcasted_iota(jnp.int32, (TM, SLOTS), 1)
        best_d_ref[:] = jnp.where(slot < k, _BIG, -_BIG)
        best_i_ref[:] = jnp.full((TM, SLOTS), -1, jnp.int32)

    # the single bf16 MXU pass: d² = A·Bᵀ (the −2 of the norm expansion is
    # folded into the reference operand at pack time — a separate scale op
    # on the [TM, TN] block measured ~35 ms over the full sweep)
    d2v = jax.lax.dot_general(
        a_ref[:], b_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if not _DEBUG_NO_D2WRITE:
        d2_ref[:] = d2v
    # fused per-row min: the block-skip test below never has to touch the
    # full block again for blocks with no improving candidate
    if not _DEBUG_NO_ROWMIN:
        rowmin_ref[:] = jnp.min(d2v, axis=1)[:, None]

    def any_below(_):
        # [TM] vs [TM]: is any candidate closer than the worst kept?
        wd = jnp.max(best_d_ref[:], axis=1)                      # k-th best
        return jnp.max(jnp.where(rowmin_ref[:, 0] < wd, 1, 0)) > 0

    def merge_round(_):
        # iotas generated inside the (rarely-taken) merge path: hoisting
        # them materializes [TM, TN] tensors on every block, measured ~2×
        # the whole kernel's runtime
        col = jax.lax.broadcasted_iota(jnp.int32, (TM, TN), 1)
        slot = jax.lax.broadcasted_iota(jnp.int32, (TM, SLOTS), 1)
        d2 = d2_ref[:]
        bd = best_d_ref[:]
        wd = jnp.max(bd, axis=1)                                 # [TM]
        bmin = rowmin_ref[:, 0]                                  # [TM]
        bcol = jnp.min(jnp.where(d2 == bmin[:, None], col, TN), axis=1)
        improving = bmin < wd
        # eviction victim = current worst real slot (pads are -_BIG and can
        # never be the max, so wslot ∈ [0, k))
        wslot = jnp.min(jnp.where(bd == wd[:, None], slot, SLOTS), axis=1)
        upd = improving[:, None] & (slot == wslot[:, None])
        best_d_ref[:] = jnp.where(upd, bmin[:, None], bd)
        best_i_ref[:] = jnp.where(upd, (j * TN + bcol)[:, None], best_i_ref[:])
        # retire the extracted candidate (only where it was taken) and
        # refresh the row minima in the same pass
        d2 = jnp.where(improving[:, None] & (col == bcol[:, None]), _BIG, d2)
        d2_ref[:] = d2
        rowmin_ref[:] = jnp.min(d2, axis=1)[:, None]
        return 0

    # while-loop with the skip test as its condition: blocks with no
    # improving candidate fall through after one tiny compare
    if not _DEBUG_NO_MERGE:
        jax.lax.while_loop(any_below, merge_round, 0)

    @pl.when(j == nblocks - 1)
    def _flush():
        best_d_out[:] = best_d_ref[:]
        best_i_out[:] = best_i_ref[:]


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_pallas(a_mat, b_mat, k: int):
    """a_mat [Mpad, K] bf16 queries; b_mat [Npad, K] bf16 references.
    Returns ([Mpad, k] approx d², [Mpad, k] ref indices), ascending."""
    return _topk_pallas_traced(a_mat, b_mat, k)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _bf16_round(x: np.ndarray) -> np.ndarray:
    """Round f32 → nearest-even bf16, returned as f32 (numpy lacks bf16)."""
    u = np.ascontiguousarray(x, np.float32).view(np.uint32)
    rounded = (u + 0x7FFF + ((u >> 16) & 1)) & 0xFFFF0000
    return rounded.view(np.float32)


def _limbs(v: np.ndarray, n: int = 3):
    """Split f32 values into n bf16 limbs: v ≈ Σ limbs (each exactly
    representable in bf16), residual ~2^(-9n)·|v|."""
    out = []
    rem = v.astype(np.float32)
    for _ in range(n):
        hi = _bf16_round(rem)
        out.append(hi)
        rem = rem - hi
    return out


def _width(f: int, num_bins: int, fc: int) -> int:
    # cat | 6 cross-limb cont groups | 3+3 norm columns
    return _round_up(max(f * num_bins + 6 * fc + 6, 1), 128)


def _pack(codes: np.ndarray, cont01: np.ndarray, num_bins: int,
          rows: int, is_ref: bool, extra_norm: float | np.ndarray):
    """Build the packed bf16 operand matrix (see module doc for layout)."""
    n, f = codes.shape
    fc = cont01.shape[1]
    width = _width(f, num_bins, fc)
    mat = np.zeros((rows, width), np.float32)

    if f:
        r = np.repeat(np.arange(n), f)
        c = (np.arange(f) * num_bins)[None, :] + codes
        mat[r, c.ravel()] = 0.5 if is_ref else 1.0

    base = f * num_bins
    hi, lo, lo2 = _limbs(cont01) if fc else (None, None, None)
    norm = (cont01.astype(np.float64) ** 2).sum(axis=1).astype(np.float32)
    if fc:
        if is_ref:      # pairs: (hi,hi) (hi,lo) (lo,hi) (lo,lo) (hi,lo2) (lo2,hi)
            groups = [hi, lo, hi, lo, lo2, hi]
        else:
            groups = [hi, hi, lo, lo, hi, lo2]
        for g, arr in enumerate(groups):
            mat[:n, base + g * fc: base + (g + 1) * fc] = arr
    nb_ = base + 6 * fc

    if is_ref:
        colc = np.full(rows, np.float32(extra_norm), np.float32)
        colc[:n] = norm
        ch, cl, cl2 = _limbs(-0.5 * colc)
        mat[:, nb_ + 0] = ch
        mat[:, nb_ + 1] = cl
        mat[:, nb_ + 2] = cl2
        mat[:, nb_ + 3] = -0.5
        mat[:, nb_ + 4] = -0.5
        mat[:, nb_ + 5] = -0.5
        # fold the norm-expansion's −2 into the reference operand: ×−2 is
        # exact for every entry (one-hots, bf16 limbs, −0.5 constants), so
        # the kernel's dot IS d² with no per-block scale pass
        mat *= -2.0
    else:
        rowc = np.zeros(rows, np.float32)
        rowc[:n] = np.float32(extra_norm) + norm
        mat[:, nb_ + 0] = 1.0
        mat[:, nb_ + 1] = 1.0
        mat[:, nb_ + 2] = 1.0
        rh, rl, rl2 = _limbs(rowc)
        mat[:, nb_ + 3] = rh
        mat[:, nb_ + 4] = rl
        mat[:, nb_ + 5] = rl2
    return jnp.asarray(mat, jnp.bfloat16)


def prepare_refs(codes: np.ndarray, cont01: np.ndarray, num_bins: int
                 ) -> Tuple[jax.Array, int]:
    """Packed device-resident reference operand [Npad, K] bf16.

    Sets larger than one tournament block round up to TB (a multiple of
    the merge kernel's TN tile, so both kernels accept the operand); small
    sets — which can never fill the tournament's candidate pool and always
    route to the merge kernel — round only to TN, avoiding up-to-8× padded
    scan work on every query batch."""
    n = codes.shape[0]
    npad = _round_up(n, TB) if n > TB else _round_up(max(n, TN), TN)
    return _pack(codes, cont01, num_bins, npad, True, _PADC), n


def prepare_queries(codes: np.ndarray, cont01: np.ndarray, num_bins: int
                    ) -> Tuple[jax.Array, int]:
    """Packed query operand [Mpad, K] bf16. The query's constant distance
    term is f (every categorical mismatch contributes ≤ f)."""
    m, f = codes.shape
    mpad = _round_up(max(m, TM), TM)
    return _pack(codes, cont01, num_bins, mpad, False, float(f)), m


def topk_candidates(q_mat, r_mat, k: int, margin: int = MARGIN
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """[Mpad, k+margin] (approx d², ref indices), ascending by approx d²."""
    kk = min(k + margin, SLOTS)
    d2, idx = _topk_pallas(q_mat, r_mat, kk)
    return np.asarray(d2), np.asarray(idx)


# ---------------------------------------------------------------------------
# segmented key-tournament sweep — the round-3 candidate kernel
# ---------------------------------------------------------------------------
# Round 2's block top-2 sweep cost ~26-42 ms/call at 1M refs. A round-3
# bisection (chained-sync, fresh process) re-attributed the cost: the dot
# itself reaches the bare-XLA matmul bound (~11 ms) once the ref block is
# 16K rows (the "3× Mosaic overhead" of round 2 was the 16 MB default
# scoped-VMEM limit forcing 2K-row blocks — raising vmem_limit_bytes
# admits the big tiles), f32 min-reductions carry a ~3× NaN-semantics
# penalty over int32, and every equality-masked extraction pass costs a
# materialized full-array traversal. This kernel:
#   - packs each distance into ONE int32 sort key,
#     (bitcast(max(d2,0)) & ~(SEG-1)) | col — positive-float bitcast is
#     order-preserving, so min-of-key IS argmin, columns ride in the low
#     11 bits, and all comparisons become cheap int32 min/max;
#   - extracts each 2048-ref segment's smallest two keys plus its
#     third-smallest as the non-candidate bound via a lane-halving
#     TOURNAMENT of sorted (m1,m2,m3) triples — pure min/max merges, no
#     equality masks, no data-dependent control flow;
#   - streams refs in 16K-row blocks (8 segments per DMA) so per-grid-step
#     overhead amortizes.
# Measured 22.1 ms/call vs 42.1 for the round-2 structure in the identical
# fresh-process harness (1.9×). Exactness contract is unchanged from the
# top-2 sweep: true top-k ⊆ candidates unless a segment hides ≥3 of the
# true top-k; key truncation only LOWERS the per-segment bound (by
# ≤ 2⁻¹² relative), which can only add cert failures, never unsound ones.

TB = 16384             # reference rows per grid step (one DMA, 8 segments)
SEG = 2048             # certificate granularity: top-2 + third-min bound
# pad-lane key: the int32 bit pattern of _BIG (finite; NEVER 0x7fffffff,
# whose truncated bitcast is NaN and would poison every downstream min)
_PAD_KEY = int(np.float32(_BIG).view(np.int32))


def _knn_tourney_kernel(a_ref, b_ref, k1_out, k2_out, k3_out, *, nbp: int):
    j = pl.program_id(1)
    nseg = TB // SEG
    d2v = jax.lax.dot_general(
        a_ref[:], b_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (TM, TB), 1)
    col = lane & jnp.int32(SEG - 1)
    # max(d2, 0): the limb-split dot can go ~eps negative for near-identical
    # points; negative-float bitcast would invert the int ordering
    di = jax.lax.bitcast_convert_type(jnp.maximum(d2v, 0.0), jnp.int32)
    key = (di & jnp.int32(~(SEG - 1))) | col
    outlane = jax.lax.broadcasted_iota(jnp.int32, (TM, nbp), 1)
    for s in range(nseg):
        seg = key[:, s * SEG:(s + 1) * SEG]
        # round 1: adjacent halves -> sorted pairs
        w = SEG // 2
        a, b = seg[:, :w], seg[:, w:]
        m1 = jnp.minimum(a, b)
        m2 = jnp.maximum(a, b)
        # round 2: two sorted pairs -> sorted triple of 4
        w //= 2
        a1, b1 = m1[:, :w], m1[:, w:]
        a2, b2 = m2[:, :w], m2[:, w:]
        hi1 = jnp.maximum(a1, b1)
        lo2 = jnp.minimum(a2, b2)
        m1 = jnp.minimum(a1, b1)
        m2 = jnp.minimum(hi1, lo2)
        m3 = jnp.maximum(lo2, hi1)
        # sorted-triple merges down to 128 lanes
        while w > 128:
            w //= 2
            a1, b1 = m1[:, :w], m1[:, w:]
            a2, b2 = m2[:, :w], m2[:, w:]
            a3, b3 = m3[:, :w], m3[:, w:]
            hi1 = jnp.maximum(a1, b1)
            lo2 = jnp.minimum(a2, b2)
            hi2 = jnp.maximum(a2, b2)
            m1 = jnp.minimum(a1, b1)
            m2 = jnp.minimum(hi1, lo2)
            m3 = jnp.minimum(jnp.minimum(jnp.maximum(hi1, lo2), hi2),
                             jnp.minimum(a3, b3))
        # final 128 -> 1 by masked extraction on the tiny arrays; keys are
        # unique (distinct col bits), so each mask hits exactly one lane
        t1 = jnp.min(m1, axis=1)
        em = jnp.where(m1 == t1[:, None], m2, m1)
        t2 = jnp.min(em, axis=1)
        em2 = jnp.where(em == t2[:, None],
                        jnp.where(m1 == t1[:, None], m3, m2), em)
        t3 = jnp.min(em2, axis=1)
        sel = outlane == (j * nseg + s)
        k1_out[:] = jnp.where(sel, t1[:, None], k1_out[:])
        k2_out[:] = jnp.where(sel, t2[:, None], k2_out[:])
        k3_out[:] = jnp.where(sel, t3[:, None], k3_out[:])


def _topk_tourney_traced(a_mat, b_mat, k: int):
    """Segment-tournament candidate generation + XLA assembly.

    Returns ([Mpad, k] approx (truncated-key) d² ascending, [Mpad, k] ref
    indices, [Mpad] non-candidate lower bound = min over segments of the
    segment's truncated third-smallest distance).
    Requires 2 * (n/SEG) >= k and n % TB == 0 (prepare_refs pads to TB)."""
    m, n = a_mat.shape[0], b_mat.shape[0]
    nb = n // TB
    nseg = n // SEG
    nbp = _round_up(nseg, 128)
    grid = (m // TM, nb)
    kern = functools.partial(_knn_tourney_kernel, nbp=nbp)
    spec = pl.BlockSpec((TM, nbp), lambda i, j: (i, 0),
                        memory_space=pltpu.VMEM)
    k1, k2, k3 = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TM, a_mat.shape[1]), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, b_mat.shape[1]), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((m, nbp), jnp.int32)] * 3,
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(a_mat, b_mat)
    # unwritten pad lanes (seg >= nseg) hold garbage: pin to the pad key
    pad = jnp.arange(nbp) >= nseg
    pk_ = jnp.int32(_PAD_KEY)
    k1 = jnp.where(pad[None, :], pk_, k1)
    k2 = jnp.where(pad[None, :], pk_, k2)
    k3 = jnp.where(pad[None, :], pk_, k3)
    segmask = jnp.int32(~(SEG - 1))
    seg_base = jnp.arange(nbp, dtype=jnp.int32) * SEG

    def unpack(kk_):
        d = jax.lax.bitcast_convert_type(kk_ & segmask, jnp.float32)
        return d, seg_base[None, :] + (kk_ & jnp.int32(SEG - 1))

    d1, i1 = unpack(k1)
    d2, i2 = unpack(k2)
    b3 = jax.lax.bitcast_convert_type(k3 & segmask, jnp.float32)
    cand_d = jnp.concatenate([d1, d2], axis=1)
    cand_i = jnp.concatenate([i1, i2], axis=1)
    neg, pos = jax.lax.top_k(-cand_d, k)
    idx = jnp.take_along_axis(cand_i, pos, axis=1)
    return -neg, idx, jnp.min(b3, axis=1)


# ---------------------------------------------------------------------------
# fused single-dispatch path: device-side query pack + kernel + exact re-rank
# ---------------------------------------------------------------------------
# The host-side path above costs ~115 ms of single-core numpy per 4096-query
# batch (pack ~86 ms, re-rank ~28 ms) plus one device round-trip whose
# latency through the dev tunnel is ~100 ms — together 3-4× the kernel's own
# amortized time. This path runs pack → pallas → re-rank as ONE jitted
# program: per batch the host transfers only the raw codes/cont arrays
# (~120 KB) and receives [M,k] results + a per-row certificate, so batches
# pipeline back-to-back and the tunnel latency amortizes away.

def _limbs_dev(v: jax.Array, n: int = 3):
    """Device-side bf16 limb split (matches :func:`_limbs`: astype(bf16)
    rounds to nearest-even exactly like _bf16_round)."""
    out = []
    rem = v.astype(jnp.float32)
    for _ in range(n):
        hi = rem.astype(jnp.bfloat16).astype(jnp.float32)
        out.append(hi)
        rem = rem - hi
    return out


def _pack_queries_dev(codes: jax.Array, cont01: jax.Array, num_bins: int,
                      rows: int, extra_norm: float) -> jax.Array:
    """Device-side equivalent of ``_pack(..., is_ref=False)``: [rows, W] bf16.
    ``codes``/``cont01`` may be shorter than ``rows``; the tail is zero
    (pad queries — their results are discarded by the caller)."""
    n, f = codes.shape
    fc = cont01.shape[1]
    width = _width(f, num_bins, fc)
    parts = []
    if f:
        onehot = (codes[:, :, None] ==
                  jnp.arange(num_bins, dtype=codes.dtype)).astype(jnp.float32)
        parts.append(onehot.reshape(n, f * num_bins))
    if fc:
        hi, lo, lo2 = _limbs_dev(cont01)
        parts.extend([hi, hi, lo, lo, hi, lo2])
    norm = (cont01.astype(jnp.float32) ** 2).sum(axis=1)
    rowc = jnp.float32(extra_norm) + norm
    rh, rl, rl2 = _limbs_dev(rowc)
    ones = jnp.ones((n,), jnp.float32)
    parts.append(jnp.stack([ones, ones, ones, rh, rl, rl2], axis=1))
    mat = jnp.concatenate(parts, axis=1)
    mat = jnp.pad(mat, ((0, rows - n), (0, width - mat.shape[1])))
    return mat.astype(jnp.bfloat16)


@functools.partial(jax.jit, static_argnames=("num_bins", "rows", "extra_norm",
                                             "k", "kk", "total_attrs", "eps",
                                             "use_tourney"))
def _search_fused(codes_q: jax.Array, cont01_q: jax.Array, r_mat: jax.Array,
                  codes_r: jax.Array, cont01_r: jax.Array, n_real: int,
                  *, num_bins: int, rows: int, extra_norm: float, k: int,
                  kk: int, total_attrs: int, eps: float, use_tourney: bool):
    """One dispatch: pack queries, run the pallas kernel, exact f32 re-rank.

    Returns ([M, k] distances in [0,1], [M, k] ref indices, [M] certificate)
    for the first ``codes_q.shape[0]`` rows of the padded query block."""
    m = codes_q.shape[0]
    q_mat = _pack_queries_dev(codes_q, cont01_q, num_bins, rows, extra_norm)
    block2 = use_tourney
    if block2:
        # segment key-tournament sweep (1.9× the round-2 top-2 sweep); the
        # per-segment truncated third-min bound keeps the certificate exact
        cand_d2, cand_idx, bound3 = _topk_tourney_traced(q_mat, r_mat, kk)
    else:
        cand_d2, cand_idx = _topk_pallas_traced(q_mat, r_mat, kk)
        bound3 = cand_d2[:, -1]       # merge kernel: kk-th kept IS the bound
    cand_d2, cand_idx, bound3 = cand_d2[:m], cand_idx[:m], bound3[:m]
    # pad reference rows (index ≥ n_real) would gather out of bounds: mark
    # unseen. A pad in the slots also implies every real ref is a candidate.
    cand_idx = jnp.where(cand_idx >= n_real, -1, cand_idx)
    safe_idx = jnp.maximum(cand_idx, 0)
    mism = (codes_q[:, None, :] != codes_r[safe_idx]).sum(-1).astype(jnp.float32)
    diff = cont01_q[:, None, :] - cont01_r[safe_idx]
    d2 = mism + (diff * diff).sum(-1)
    d2 = jnp.where(cand_idx < 0, _BIG, d2)
    neg, order = jax.lax.top_k(-d2, kk)
    d2s = -neg
    idxs = jnp.take_along_axis(cand_idx, order, axis=1)
    kth = d2s[:, min(k, kk) - 1]
    # certificate: nothing outside the candidate set can beat the k-th
    # exact candidate — non-candidates are ≥ both the kk-th approx
    # candidate and (block2 path) every block's third-smallest
    cert = kth <= jnp.minimum(cand_d2[:, -1], bound3) - 2 * eps
    if not block2:
        # merge kernel only: a pad in the last slot proves every real ref
        # was kept (all real d² beat _PADC). On the block2 path a pad in
        # the pool merely means some block ran short of real rows — blocks
        # still hide non-candidates, so the bound term must decide.
        cert = cert | (cand_idx[:, -1] < 0)
    d = jnp.sqrt(jnp.maximum(d2s[:, :k], 0.0) / max(total_attrs, 1))
    return jnp.clip(d, 0.0, 1.0), idxs[:, :k], cert


def _topk_pallas_traced(a_mat, b_mat, k: int):
    """The pallas call without the jit/top-k wrapper (for use inside
    :func:`_search_fused`'s trace)."""
    m, n = a_mat.shape[0], b_mat.shape[0]
    grid = (m // TM, n // TN)
    kern = functools.partial(_knn_kernel, k=k, nblocks=grid[1])
    best_d2, best_i = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TM, a_mat.shape[1]), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TN, b_mat.shape[1]), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((TM, SLOTS), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TM, SLOTS), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, SLOTS), jnp.float32),
            jax.ShapeDtypeStruct((m, SLOTS), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((TM, TN), jnp.float32),
            pltpu.VMEM((TM, 1), jnp.float32),
            pltpu.VMEM((TM, SLOTS), jnp.float32),
            pltpu.VMEM((TM, SLOTS), jnp.int32),
        ],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(a_mat, b_mat)
    neg, pos = jax.lax.top_k(-best_d2[:, :k], k)
    return -neg, jnp.take_along_axis(best_i[:, :k], pos, axis=1)


def search_fused(codes_q: np.ndarray, cont01_q: np.ndarray, r_mat: jax.Array,
                 codes_r_dev: jax.Array, cont01_r_dev: jax.Array, n_real: int,
                 num_bins: int, k: int, total_attrs: int,
                 margin: int = MARGIN):
    """Single-dispatch exact search. Returns device arrays
    ([M,k] dist, [M,k] idx, [M] cert) — the caller syncs (or pipelines)."""
    m, f = codes_q.shape
    fc = cont01_q.shape[1]
    kk = min(k + margin, SLOTS)
    eps = D2_EPS if fc else 0.0
    rows = _round_up(max(m, TM), TM)
    # tournament engages only when enough REAL segments exist to fill the
    # candidate pool — pad-dominated segments would produce a uselessly
    # small bound and fail every certificate
    use_tourney = (2 * -(-n_real // SEG) >= kk
                   and r_mat.shape[0] % TB == 0)
    return _search_fused(
        jnp.asarray(codes_q), jnp.asarray(cont01_q, jnp.float32), r_mat,
        codes_r_dev, cont01_r_dev, n_real,
        num_bins=num_bins, rows=rows, extra_norm=float(f), k=k, kk=kk,
        total_attrs=total_attrs, eps=eps, use_tourney=use_tourney)


def exact_rerank(cand_idx: np.ndarray, cand_d2: np.ndarray,
                 codes_q: np.ndarray, cont_q: np.ndarray,
                 codes_r: np.ndarray, cont_r: np.ndarray,
                 k: int, total_attrs: int, eps: float | None = None,
                 n_real: int | None = None
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Exact f32 re-rank of the kernel's k' candidates.

    Returns ([M, k] distances in [0,1], [M, k] indices, [M] certificate):
    certificate[i] is True when the exact top-k of row i is guaranteed
    (k-th exact candidate d² ≤ k'-th approx d² − 2·eps, so no non-candidate
    can beat it). Rows with certificate False must fall back to the exact
    scan path. With no continuous features the kernel's bf16 arithmetic is
    exact — pass eps=0 so integer-distance ties still certify.
    """
    if eps is None:
        eps = D2_EPS if cont_q.shape[1] else 0.0
    if n_real is None:
        n_real = codes_r.shape[0]
    # pad rows (d² ≈ _PADC) can land in candidate slots when the reference
    # set is barely larger than k' — their indices point past n_real and
    # would index codes_r out of bounds; mark them unseen. A pad in the
    # slots also means every real reference is already among the candidates
    # (all real d² beat _PADC), which the certificate below relies on.
    cand_idx = np.where(cand_idx >= n_real, -1, cand_idx)
    m, kk = cand_idx.shape
    safe_idx = np.maximum(cand_idx, 0)
    mism = (codes_q[:, None, :] != codes_r[safe_idx]).sum(-1).astype(np.float32)
    diff = cont_q[:, None, :] - cont_r[safe_idx]
    d2 = mism + (diff * diff).sum(-1)
    d2[cand_idx < 0] = _BIG
    order = np.argsort(d2, axis=1, kind="stable")
    d2s = np.take_along_axis(d2, order, axis=1)
    idxs = np.take_along_axis(cand_idx, order, axis=1)
    kth = d2s[:, min(k, kk) - 1]
    cert = kth <= cand_d2[:, -1] - 2 * eps
    cert |= cand_idx[:, -1] < 0          # fewer refs than k': all seen
    d = np.sqrt(np.maximum(d2s[:, :k], 0.0) / max(total_attrs, 1))
    return np.clip(d, 0.0, 1.0), idxs[:, :k], cert
