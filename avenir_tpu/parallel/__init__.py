from avenir_tpu.parallel.mesh import (
    make_mesh,
    data_sharding,
    replicated,
    pad_batch,
    shard_pad_target,
    device_put_sharded_batch,
)
from avenir_tpu.parallel.shard import ShardSpec

__all__ = [
    "make_mesh",
    "data_sharding",
    "replicated",
    "pad_batch",
    "shard_pad_target",
    "device_put_sharded_batch",
    "ShardSpec",
]
