from avenir_tpu.parallel.mesh import (
    make_mesh,
    data_sharding,
    replicated,
    pad_batch,
    device_put_sharded_batch,
)

__all__ = [
    "make_mesh",
    "data_sharding",
    "replicated",
    "pad_batch",
    "device_put_sharded_batch",
]
