"""Explicit-collective training steps (shard_map + psum).

The auto-sharding path (sharded inputs under ``jax.jit``) already lets XLA
insert the all-reduce; this module is the explicit SPMD spelling of the same
programs — per-device partial aggregation (the reference's combiner) followed
by ``lax.psum`` over the ``data`` mesh axis (the reference's shuffle), with
the large count tensors optionally sharded over a ``model`` axis (the
reference's key-space partitioners, explore/ClassPartitionGenerator.java:600-606).

Used by sharded fit paths and by ``__graft_entry__.dryrun_multichip`` to
validate multi-chip compilation on a virtual device mesh.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from avenir_tpu.ops.agg import one_hot as _onehot

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def sharded_nb_fit_step(mesh: Mesh, num_classes: int, num_bins: int, num_cont: int):
    """Build a jitted SPMD Naive-Bayes sufficient-statistics step.

    Inputs: codes [N, F] int32, labels [N] int32, cont [N, Fc] float32, all
    sharded over ``data`` on axis 0. Outputs (replicated): [F, B, C] bin
    counts, [C] class counts, ([C], [C,Fc], [C,Fc]) moments.
    """

    def step(codes, labels, cont):
        oh_b = _onehot(codes, num_bins)                      # [n, F, B] local
        oh_c = _onehot(labels, num_classes)                  # [n, C]
        fbc = jnp.einsum("nfb,nc->fbc", oh_b, oh_c, precision="highest")
        cc = jnp.sum(oh_c, axis=0)
        s1 = jnp.einsum("nc,nf->cf", oh_c, cont, precision="highest")
        s2 = jnp.einsum("nc,nf->cf", oh_c, cont * cont, precision="highest")
        # the 'shuffle': one all-reduce over ICI per tensor
        fbc = jax.lax.psum(fbc, "data")
        cc = jax.lax.psum(cc, "data")
        s1 = jax.lax.psum(s1, "data")
        s2 = jax.lax.psum(s2, "data")
        return fbc, cc, cc, s1, s2

    wrapped = _shard_map(
        step, mesh=mesh,
        in_specs=(P("data", None), P("data"), P("data", None)),
        out_specs=(P(), P(), P(), P(), P()),
    )
    return jax.jit(wrapped)


def sharded_nb_fit_step_2d(mesh: Mesh, num_classes: int, num_bins: int):
    """2-D (data × model) variant: batch sharded over ``data``; the [F, B, C]
    count tensor computed and *kept sharded* over ``model`` on the feature
    axis — the layout for high-cardinality tensors that must not be
    replicated per device (SURVEY.md §7 'hard parts').

    F must be divisible by the ``model`` axis size.
    """

    def step(codes, labels):
        # codes arrive [n_local, F_local]: data-sharded rows, model-sharded features
        oh_b = _onehot(codes, num_bins)
        oh_c = _onehot(labels, num_classes)
        fbc = jnp.einsum("nfb,nc->fbc", oh_b, oh_c, precision="highest")
        fbc = jax.lax.psum(fbc, "data")      # reduce over data only; stays model-sharded
        # labels are replicated over 'model', so reducing over 'data' alone
        # already yields the global class counts on every model rank
        cc = jax.lax.psum(jnp.sum(oh_c, axis=0), "data")
        return fbc, cc

    wrapped = _shard_map(
        step, mesh=mesh,
        in_specs=(P("data", "model"), P("data")),
        out_specs=(P("model", None, None), P()),
    )
    return jax.jit(wrapped)
