"""Explicit-collective training steps (shard_map + psum).

The auto-sharding path (sharded inputs under ``jax.jit``) already lets XLA
insert the all-reduce; this module is the explicit SPMD spelling of the same
programs — per-device partial aggregation (the reference's combiner) followed
by ``lax.psum`` over the ``data`` mesh axis (the reference's shuffle), with
the large count tensors optionally sharded over a ``model`` axis (the
reference's key-space partitioners, explore/ClassPartitionGenerator.java:600-606).

Used by sharded fit paths and by ``__graft_entry__.dryrun_multichip`` to
validate multi-chip compilation on a virtual device mesh.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from avenir_tpu.ops.agg import (_check_chunk, one_hot as _onehot,
                                pair_class_counts)

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def _shard_map_norep(step, mesh, in_specs, out_specs):
    """shard_map with the replicated-output check disabled — the kwarg was
    renamed check_rep → check_vma across jax versions, so probe once here
    instead of copy-pasting the shim at every call site."""
    try:
        return _shard_map(step, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover
        return _shard_map(step, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def sharded_nb_fit_step(mesh: Mesh, num_classes: int, num_bins: int, num_cont: int):
    """Build a jitted SPMD Naive-Bayes sufficient-statistics step.

    Inputs: codes [N, F] int32, labels [N] int32, cont [N, Fc] float32, all
    sharded over ``data`` on axis 0. Outputs (replicated): [F, B, C] bin
    counts, [C] class counts, ([C], [C,Fc], [C,Fc]) moments.
    """

    def step(codes, labels, cont):
        oh_b = _onehot(codes, num_bins)                      # [n, F, B] local
        oh_c = _onehot(labels, num_classes)                  # [n, C]
        fbc = jnp.einsum("nfb,nc->fbc", oh_b, oh_c, precision="highest")
        cc = jnp.sum(oh_c, axis=0)
        s1 = jnp.einsum("nc,nf->cf", oh_c, cont, precision="highest")
        s2 = jnp.einsum("nc,nf->cf", oh_c, cont * cont, precision="highest")
        # the 'shuffle': one all-reduce over ICI per tensor
        fbc = jax.lax.psum(fbc, "data")
        cc = jax.lax.psum(cc, "data")
        s1 = jax.lax.psum(s1, "data")
        s2 = jax.lax.psum(s2, "data")
        return fbc, cc, cc, s1, s2

    wrapped = _shard_map(
        step, mesh=mesh,
        in_specs=(P("data", None), P("data"), P("data", None)),
        out_specs=(P(), P(), P(), P(), P()),
    )
    return jax.jit(wrapped)


def sharded_nb_fit_step_2d(mesh: Mesh, num_classes: int, num_bins: int):
    """2-D (data × model) variant: batch sharded over ``data``; the [F, B, C]
    count tensor computed and *kept sharded* over ``model`` on the feature
    axis — the layout for high-cardinality tensors that must not be
    replicated per device (SURVEY.md §7 'hard parts').

    F must be divisible by the ``model`` axis size.
    """

    def step(codes, labels):
        # codes arrive [n_local, F_local]: data-sharded rows, model-sharded features
        oh_b = _onehot(codes, num_bins)
        oh_c = _onehot(labels, num_classes)
        fbc = jnp.einsum("nfb,nc->fbc", oh_b, oh_c, precision="highest")
        fbc = jax.lax.psum(fbc, "data")      # reduce over data only; stays model-sharded
        # labels are replicated over 'model', so reducing over 'data' alone
        # already yields the global class counts on every model rank
        cc = jax.lax.psum(jnp.sum(oh_c, axis=0), "data")
        return fbc, cc

    wrapped = _shard_map(
        step, mesh=mesh,
        in_specs=(P("data", "model"), P("data")),
        out_specs=(P("model", None, None), P()),
    )
    return jax.jit(wrapped)


@functools.lru_cache(maxsize=32)
def sharded_knn_topk(mesh: Mesh, k: int, num_bins: int,
                     metric: str = "euclidean", data_axis: str = "data",
                     ref_tile: int = 65536):
    """Exact global k-NN with the reference set sharded over the mesh.

    The reference outsources its O(M·N) all-pairs distances to a Hadoop job
    (resource/knn.sh:47-60); the multi-chip spelling here shards the
    reference rows over ``data`` (queries replicated), and each device scans
    its local shard in ``ref_tile``-row tiles with a running exact top-k —
    the same bounded-memory discipline as the single-device scan, so
    per-device memory is O(M·ref_tile), never O(M·N/D) — then merges with
    one ``lax.all_gather`` of the [M, k] candidates: k·D values per query
    cross ICI instead of the N-row distance matrix.

    Returns a jitted fn(test_codes, test_cont, ref_codes, ref_cont, lo, hi,
    n_real) → ([M, k] distances, [M, k] global reference indices). The
    reference arrays must be padded so each device's shard is a whole
    number of ``ref_tile`` tiles; pad rows (global index ≥ n_real) are
    masked to +inf so they can never win the top-k. Requires k ≤ local
    shard rows. Cached per (mesh, k, bins, metric, tile) so repeated
    queries reuse the compiled program.
    """
    from avenir_tpu.models.knn import _tile_distances

    def step(tc, tx, rc, rx, lo, hi, n_real):
        local = rc.shape[0]
        # whole shard as one tile when it isn't tile-divisible (direct
        # callers with small shards); _nearest_neighbors_sharded pads the
        # global array so production shards always divide
        tile = ref_tile if local >= ref_tile and local % ref_tile == 0 \
            else local
        t = local // tile
        rc_t = rc.reshape(t, tile, rc.shape[1])
        rx_t = rx.reshape(t, tile, rx.shape[1])
        m = tc.shape[0] if tc.size else tx.shape[0]
        base = jax.lax.axis_index(data_axis) * local

        def body(carry, xs):
            best_d, best_i, t0 = carry
            rct, rxt = xs
            d = _tile_distances(tc, tx, rct, rxt, lo, hi, num_bins, metric)
            idx = base + t0 + jnp.arange(tile, dtype=jnp.int32)
            d = jnp.where(idx[None, :] < n_real, d, jnp.inf)
            cd = jnp.concatenate([best_d, d], axis=1)
            cix = jnp.concatenate(
                [best_i, jnp.broadcast_to(idx[None, :], d.shape)], axis=1)
            neg, pos = jax.lax.top_k(-cd, k)
            return (-neg, jnp.take_along_axis(cix, pos, axis=1),
                    t0 + jnp.int32(tile)), None

        best_d = jnp.full((m, k), jnp.inf, jnp.float32)
        best_i = jnp.full((m, k), -1, jnp.int32)
        (best_d, best_i, _), _ = jax.lax.scan(
            body, (best_d, best_i, jnp.int32(0)), (rc_t, rx_t))
        # [M, D·k] candidates on every device, then the final exact top-k
        dg = jax.lax.all_gather(best_d, data_axis, axis=1, tiled=True)
        ig = jax.lax.all_gather(best_i, data_axis, axis=1, tiled=True)
        neg2, pos2 = jax.lax.top_k(-dg, k)
        return -neg2, jnp.take_along_axis(ig, pos2, axis=1)

    # the outputs are replicated (every device holds the same merged top-k
    # after the all_gather), but shard_map cannot infer that statically
    in_specs = (P(), P(), P(data_axis, None), P(data_axis, None), P(), P(), P())
    wrapped = _shard_map_norep(step, mesh, in_specs, (P(), P()))
    return jax.jit(wrapped)


def sharded_lr_step(mesh: Mesh, data_axis: str = "data"):
    """Data-parallel logistic-regression step: per-device partial gradient
    (the reference's per-mapper Σ x·(y−σ(wᵀx)) accumulation,
    regress/LogisticRegressionJob.java:169-176) + ``psum`` (its single
    reducer), then the weight update — replicated weights out.

    Returns a jitted fn(w [D], x [N, D] data-sharded, y [N] data-sharded,
    n_total, lr, l2) → new w.
    """

    def step(w, x, y, n_total, lr, l2):
        p = jax.nn.sigmoid(x @ w)
        partial_g = x.T @ (y - p)                 # local combiner output
        grad = jax.lax.psum(partial_g, data_axis) / n_total - l2 * w
        return w + lr * grad

    wrapped = _shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(data_axis, None), P(data_axis), P(), P(), P()),
        out_specs=P(),
    )
    return jax.jit(wrapped)


def sharded_mi_step(mesh: Mesh, num_classes: int, num_bins: int,
                    data_axis: str = "data", model_axis: str = "model"):
    """2-D sharded mutual-information count step — the high-cardinality
    joint-distribution layout (SURVEY.md §7 "hard parts": feature-pair×class
    one-hots are O(F²·V²·C)).

    Batch shards over ``data`` (the reference's record sharding across MI
    mappers, explore/MutualInformation.java:136-214); the [P, B, B, C]
    pair-class tensor shards its *pair axis* over ``model`` (the reference's
    key-space partitioning of (distrType, ordinals…) shuffle keys), so each
    device holds only P/model_parallel of the largest tensor while the
    ``psum`` over ``data`` plays the combiner+shuffle. The [F, B, C]
    feature-class tensor and [C] class counts are cheap and come back
    replicated.

    Returns a jitted fn(codes [N, F] data-sharded, labels [N] data-sharded,
    ci [P] model-sharded, cj [P] model-sharded) →
    (pair_class [P, B, B, C] pair-axis model-sharded,
     feature_class [F, B, C] replicated, class_counts [C] replicated).
    """

    def step(codes, labels, ci, cj):
        _check_chunk(codes)            # per-shard f32 exact-accumulation cap
        oh_c = _onehot(labels, num_classes)            # [n_loc, C]
        # local slice of the pair list: gather both columns per local pair,
        # then the SAME two-operand joint (bin_j, class) kernel the
        # single-device path uses (ops/agg.py::pair_class_counts — 2.3× the
        # three-operand einsum on-chip, drop-invalid labels preserved)
        pabc = pair_class_counts(jnp.take(codes, ci, axis=1),
                                 jnp.take(codes, cj, axis=1),
                                 labels, num_classes, num_bins)
        fbc = jnp.einsum("nfb,nc->fbc", _onehot(codes, num_bins), oh_c,
                         precision="highest").astype(jnp.int32)
        cc = jnp.sum(oh_c, axis=0).astype(jnp.int32)
        return (jax.lax.psum(pabc, data_axis),
                jax.lax.psum(fbc, data_axis),
                jax.lax.psum(cc, data_axis))

    in_specs = (P(data_axis, None), P(data_axis),
                P(model_axis), P(model_axis))
    # fbc/cc are replicated across model by construction but shard_map
    # cannot infer it
    wrapped = _shard_map_norep(step, mesh, in_specs,
                               (P(model_axis, None, None, None), P(), P()))
    return jax.jit(wrapped)


def quantized_allreduce_sum(x: jax.Array, axis_name: str) -> jax.Array:
    """EQuARX-style bandwidth-reduced all-reduce-sum (arXiv 2506.17615)
    for count-tensor partials inside a ``shard_map``.

    Each device block-quantizes its partial to int8 with one f32 scale per
    trailing-axis row (``s = max(|row|, 127) / 127`` — never below 1, so
    partials whose cells all fit int8 quantize EXACTLY with scale 1), then
    ONE ``all_gather`` moves the int8 payload + scales (≈4× fewer bytes on
    the wire than an int32/f32 ring psum) and each device dequantizes and
    sums locally in f32.

    Exact whenever every per-device partial cell is ≤ 127 in magnitude —
    true for gram partials of chunks smaller than 127·D rows per cell —
    and bounded by scale/2 per device otherwise, which is why this rides
    behind ``shard.allreduce.quantized`` (default off) with the exact
    psum as the byte-identity oracle."""
    qmax = 127.0
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True), qmax) / qmax
    q = jnp.round(xf / s).astype(jnp.int8)
    qg = jax.lax.all_gather(q, axis_name)            # [D, ...] int8
    sg = jax.lax.all_gather(s, axis_name)            # [D, ..., 1] f32
    return jnp.sum(qg.astype(jnp.float32) * sg, axis=0)


@functools.lru_cache(maxsize=32)
def sharded_scan_step(mesh: Mesh, num_bins: int, num_classes: int,
                      data_axis: str = "data", interpret: bool = False,
                      block_cols=None, quantized: bool = False,
                      moments: bool = True,
                      proc_axis: Optional[str] = None):
    """THE ShardGraft SharedScan dispatch (round 12): per-device Pallas
    co-occurrence gram + class counts + class moments of ONE data-sharded
    chunk, all-reduced over the mesh's data axis inside the compiled
    program — the reference's combiner (per-device partials) + shuffle
    (psum) for every table the scan's consumers collectively read, in one
    dispatch per chunk exactly like the single-chip fast path.

    Returns a jitted fn(codes [N, F] data-sharded, labels [N], cont
    [N, Fc]) → (G, cc [C] int32, cnt [C] f32, s1 [C, Fc] f32, s2 [C, Fc]
    f32), all replicated — or just (G, cc) under ``moments=False``
    (count-only consumer sets).  G's layout is the single-device kernel's
    (``pallas_hist.plan``/``w_index``), so ``counts_from_cooc`` reads it
    out unchanged and the fold is byte-identical to the 1-chip gram;
    per-device moment partials are exact f32 sums, so the psum'd moments
    match the single-chip fold bit-for-bit whenever those partials are
    exactly representable (integer-grid values — the scope the stream
    panes already document).

    ``interpret=True`` runs the kernel through the Pallas interpreter —
    how the host-mesh tier-1 byte-identity tests attest the collective
    wiring without Mosaic hardware.  ``quantized=True`` routes the gram
    all-reduce (the dominant payload) through
    :func:`quantized_allreduce_sum`; class counts and moments stay on the
    exact psum either way.

    CrossGraft (``proc_axis`` set): the GLOBAL form over a (proc × data)
    hybrid mesh — the batch axis sharded over BOTH axes, the gram
    reduced HIERARCHICALLY inside the same fused dispatch: ``psum`` over
    ``data`` first (the within-host ICI leg, always exact — the cheap
    hop carries full precision), then over ``proc`` (the cross-host DCN
    leg; under ``quantized`` THIS leg rides the EQuARX-style int8
    collective, because DCN — not ICI — is where the bytes hurt, arXiv
    2506.17615).  The DrJAX mapreduce decomposition (arXiv 2403.07128):
    per-host map + hierarchical reduce, one compiled program.  Counts
    and moments psum over both axes exactly.

    Memoized on the full signature (``Mesh`` is hashable): every
    ``ChunkFolder`` construction — one per ``SharedScan.run`` — reuses the
    SAME jitted program, so a warm pass warms all later runs in the
    process instead of each run paying a fresh trace+compile."""
    from avenir_tpu.ops import pallas_hist

    batch_axes = (data_axis if proc_axis is None
                  else (proc_axis, data_axis))

    def step(codes, labels, cont):
        _check_chunk(codes)        # per-shard f32 exact-accumulation cap
        g = pallas_hist.cooc_counts.__wrapped__(
            codes, labels, num_bins, num_classes, interpret=interpret,
            block_cols=block_cols)
        if proc_axis is None:
            if quantized:
                g = jnp.round(quantized_allreduce_sum(
                    g, data_axis)).astype(jnp.int32)
            else:
                g = jax.lax.psum(g, data_axis)
        else:
            # hierarchical: exact within-host psum, then the cross-host
            # leg — quantized only here, where the wire is DCN
            g = jax.lax.psum(g, data_axis)
            if quantized:
                g = jnp.round(quantized_allreduce_sum(
                    g, proc_axis)).astype(jnp.int32)
            else:
                g = jax.lax.psum(g, proc_axis)
        oh_c = _onehot(labels, num_classes)                    # [n_loc, C]
        cnt = jnp.sum(oh_c, axis=0)                            # exact f32
        cc = jax.lax.psum(cnt.astype(jnp.int32), batch_axes)
        if not moments:
            # count-only consumer sets skip the moment einsums + psums
            # entirely (the single-chip kernel path makes the same cut)
            return g, cc
        s1 = jnp.einsum("nc,nf->cf", oh_c, cont, precision="highest")
        s2 = jnp.einsum("nc,nf->cf", oh_c, cont * cont,
                        precision="highest")
        return (g, cc,
                jax.lax.psum(cnt, batch_axes),
                jax.lax.psum(s1, batch_axes),
                jax.lax.psum(s2, batch_axes))

    # norep: pallas_call outputs don't carry varying-mesh-axis metadata
    wrapped = _shard_map_norep(
        step, mesh,
        (P(batch_axes, None), P(batch_axes), P(batch_axes, None)),
        (P(),) * (5 if moments else 2))
    return jax.jit(wrapped)


def sharded_cooc_step(mesh: Mesh, num_bins: int, num_classes: int,
                      interpret: bool = False, block_cols=None):
    """Data-sharded MXU co-occurrence count step (the round-3 count kernel
    under explicit SPMD): each device runs the Pallas XᵀX kernel
    (ops/pallas_hist.py) over its local rows — the per-device partial is
    the reference's combiner — and ONE ``psum`` over ``data`` plays the
    shuffle. G's layout (``pallas_hist.plan``/``w_index`` — fmaj for most
    shapes, jmaj fallback) is identical to the single-device kernel, so
    ``pallas_hist.counts_from_cooc`` reads the result out unchanged.

    ``interpret=True`` runs the kernel through the Pallas interpreter —
    how the CPU-mesh dryrun/tests attest the collective wiring without
    Mosaic hardware; on a TPU mesh leave it False."""
    from avenir_tpu.ops import pallas_hist

    def step(codes, labels):
        g = pallas_hist.cooc_counts.__wrapped__(
            codes, labels, num_bins, num_classes, interpret=interpret,
            block_cols=block_cols)
        return jax.lax.psum(g, "data")

    # norep: pallas_call outputs don't carry varying-mesh-axis metadata, so
    # the replication check cannot validate them
    wrapped = _shard_map_norep(step, mesh,
                               (P("data", None), P("data")), P())
    return jax.jit(wrapped)
