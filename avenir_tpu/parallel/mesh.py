"""Device mesh + sharding helpers — the rebuild's cluster runtime.

Where the reference scales by scheduling mapper/reducer JVMs over a Hadoop
cluster (HDFS-block data parallelism + the MR shuffle as transport), this
framework scales by laying arrays out over a `jax.sharding.Mesh` and letting
XLA insert collectives over ICI (psum/all-gather), per the standard JAX SPMD
recipe. Two axes:

- ``data``  — batch/record axis: every estimator shards its record stream
  here (the analog of records-across-mappers).
- ``model`` — bin/feature axis for the large count tensors (feature-pair ×
  class contingency tensors can reach O(F²·B²·C); sharding their feature axis
  is the analog of the reference's key-space partitioners).

Count-neutral padding: all count kernels in :mod:`avenir_tpu.ops.agg` encode
via ``one_hot``, which maps index −1 to an all-zero row. Padding a batch with
−1 codes/labels therefore changes no statistic, which is how ragged final
chunks meet XLA's static-shape + even-sharding requirements.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axis_names: Tuple[str, ...] = ("data",),
    shape: Optional[Tuple[int, ...]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh over available devices.

    Default: 1-D ``data`` mesh over all devices. For 2-D requests without an
    explicit shape, puts as many devices as possible on ``data`` and the rest
    on trailing axes (factor 2 per extra axis when divisible).
    """
    devs = np.array(devices if devices is not None else jax.devices())
    n = devs.size
    if shape is None:
        if len(axis_names) == 1:
            shape = (n,)
        else:
            trailing = []
            rem = n
            for _ in axis_names[1:]:
                f = 2 if rem % 2 == 0 and rem >= 2 else 1
                trailing.append(f)
                rem //= f
            shape = (rem, *trailing)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    return Mesh(devs.reshape(shape), axis_names)


def data_sharding(mesh: Mesh, rank: int, data_axis: str = "data") -> NamedSharding:
    """NamedSharding that shards axis 0 over ``data`` and replicates the rest."""
    return NamedSharding(mesh, P(data_axis, *([None] * (rank - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_batch(n_target: int, *arrays: np.ndarray, fill: int = -1):
    """Pad axis 0 of each array up to ``n_target`` rows.

    Thin alias of :func:`avenir_tpu.core.encoding.pad_rows` — the ONE
    ballast-fill home (integer arrays pad with ``fill``, default −1 →
    count-neutral under one-hot; float arrays pad with 0).  Kept here so
    mesh-side callers don't reach into ``core`` for an array utility."""
    from avenir_tpu.core.encoding import pad_rows

    return pad_rows(n_target, *arrays, fill=fill)


def padded_size(n: int, num_shards: int) -> int:
    return ((n + num_shards - 1) // num_shards) * num_shards


def shard_pad_target(n: int, num_shards: int) -> int:
    """Row target for a ShardGraft-staged chunk: the next power of two ≥ n,
    rounded up to a multiple of ``num_shards`` (every device gets an equal
    slice, and at least one row).  For a fixed shard count the target set is
    finite — one value per pow-2 bucket — so a steady chunk stream with a
    ragged tail compiles a bounded shape set instead of one program per
    tail size (the stream-pane pow-2 discipline applied to mesh staging)."""
    if n < 1:
        raise ValueError(f"cannot stage an empty chunk (n={n})")
    t = 1
    while t < n:
        t *= 2
    return padded_size(t, num_shards)


def device_put_sharded_batch(mesh: Mesh, *arrays, data_axis: str = "data"):
    """Pad axis 0 to a multiple of the data-axis size and device_put with the
    batch axis sharded over ``data``."""
    nshard = mesh.shape[data_axis]
    n = next(a.shape[0] for a in arrays if a is not None)
    padded = pad_batch(padded_size(n, nshard), *arrays)
    if len(arrays) == 1:
        padded = [padded]
    out = []
    for a in padded:
        if a is None:
            out.append(None)
        else:
            out.append(jax.device_put(a, data_sharding(mesh, a.ndim, data_axis)))
    return out if len(out) > 1 else out[0]


# ---------------------------------------------------------------------------
# multi-host (DCN) support
# ---------------------------------------------------------------------------

# the last successful coordinator join of THIS process (CrossGraft): the
# tracer is usually not configured yet when init_distributed runs (the
# join must precede any jax work, configuration follows), so the join
# facts are recorded here and announced into the journal later by the
# seams that know the journal exists (ShardSpec.announce / the launcher)
_LAST_JOIN: Optional[dict] = None


def last_join() -> Optional[dict]:
    """The recorded ``fleet.join`` payload of this process's coordinator
    join, or None when the process never joined (single-process run)."""
    return _LAST_JOIN


def journal_fleet_join(coordinator: str, nprocs: int, attempts: int,
                       wall_ms: float) -> None:
    """Journal one golden-schema'd ``fleet.join`` event (the worker's
    cluster-join record: coordinator address, fleet size, how many join
    attempts it took, and the join wall time) — proc/host identity rides
    the GraftFleet stamp every record carries.  At most once per journal
    per coordinator: the join-time emission (usually a no-op — tracing
    is rarely configured that early) and the later ``announce`` replay
    share the dedupe key."""
    from avenir_tpu.telemetry import spans as tel

    tel.tracer().event_once("fleet.join", str(coordinator),
                            coordinator=coordinator,
                            nprocs=int(nprocs), attempts=int(attempts),
                            wall_ms=round(float(wall_ms), 3))


def _enable_cpu_collectives() -> None:
    """Arm the CPU backend's cross-process collective transport (gloo)
    BEFORE the backend is created.  Without it every cross-process
    computation on a multi-process CPU runtime dies with XLA's
    'Multiprocess computations aren't implemented on the CPU backend' —
    the root cause of the long-standing multiprocess-env tier-1 failures
    this round retired.  No-op off-CPU and on jax builds without the
    option; harmless when already set."""
    import os as _os

    platforms = (_os.environ.get("JAX_PLATFORMS", "")
                 or str(jax.config.jax_platforms or ""))
    if platforms.split(",")[0].strip().lower() not in ("cpu", ""):
        return
    try:
        if getattr(jax.config, "jax_cpu_collectives_implementation",
                   None) in (None, "", "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:                              # pragma: no cover
        pass                    # older jax: option absent; TPU paths unaffected


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout_s: Optional[float] = None,
                     attempts: Optional[int] = None) -> int:
    """Join a multi-host run (the analog of the reference's cluster join —
    its JobTracker/Storm nimbus handshake, SURVEY.md §5 'distributed
    communication backend').

    Wraps :func:`jax.distributed.initialize`: on TPU pods the arguments are
    discovered from the environment, elsewhere pass the coordinator
    explicitly. Idempotent; returns this host's process index. Single-host
    runs skip initialization entirely.

    Hardened (CrossGraft): the join is BOUNDED.  A non-zero rank first
    PROBES the coordinator's TCP endpoint under the ``utils/retry``
    decorrelated-jitter backoff (so N workers re-arriving spread out
    instead of thundering in lockstep) for up to ``timeout_s`` (default
    300 s, ``AVENIR_JOIN_TIMEOUT_SEC``); an unreachable/refused address
    raises a typed :class:`~avenir_tpu.launch.LaunchError` NAMING the
    coordinator — the probe exists because jax's own client ABORTS the
    process (LOG(FATAL) on RegisterTask deadline) rather than raising,
    so the typed error must fire before jax ever connects.  The
    initialize itself then carries ``initialization_timeout`` and
    retries up to ``attempts`` times (default 3,
    ``AVENIR_JOIN_ATTEMPTS``) on transient service errors.  The CPU
    gloo collective transport is armed before the backend exists
    (:func:`_enable_cpu_collectives` — without it every cross-process
    CPU computation dies), and the join is recorded for the journal
    (:func:`last_join` → ``fleet.join``, emitted immediately too when
    tracing is already on).
    """
    import os as _os
    import time as _time

    # Probe the distributed-client state WITHOUT touching the backend:
    # jax.process_count() would itself initialize a single-process backend,
    # after which jax.distributed.initialize always fails — the join must
    # come first.
    try:
        from jax._src import distributed as _dist
        already = getattr(_dist.global_state, "client", None) is not None
    except Exception:
        already = False
    if already:
        return jax.process_index()          # already joined
    env = _os.environ
    if coordinator_address is None and num_processes is None:
        if not any(k in env for k in
                   ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                    "MEGASCALE_COORDINATOR_ADDRESS",
                    "AVENIR_COORDINATOR_ADDRESS")):
            return 0                        # single host, nothing to join
        coordinator_address = (
            coordinator_address or env.get("AVENIR_COORDINATOR_ADDRESS"))
        if env.get("AVENIR_NUM_PROCESSES"):
            num_processes = int(env["AVENIR_NUM_PROCESSES"])
        if env.get("AVENIR_PROCESS_ID"):
            process_id = int(env["AVENIR_PROCESS_ID"])
    _enable_cpu_collectives()
    if attempts is None:
        attempts = int(env.get("AVENIR_JOIN_ATTEMPTS", "3"))
    if timeout_s is None:
        timeout_s = float(env.get("AVENIR_JOIN_TIMEOUT_SEC", "300"))
    from avenir_tpu.utils.retry import RetryPolicy

    policy = RetryPolicy(max_attempts=max(int(attempts), 1), backoff_s=0.5)
    t0 = _time.monotonic()
    if process_id not in (None, 0) and coordinator_address:
        # rank 0 BINDS the address (nothing to probe); every other rank
        # waits for it to become reachable within the bounded window
        _wait_for_coordinator(str(coordinator_address), float(timeout_s))
    last_err: Optional[BaseException] = None
    sleep_s = 0.0
    for attempt in range(1, policy.max_attempts + 1):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                initialization_timeout=max(int(timeout_s), 1))
            _record_join(coordinator_address, attempt,
                         (_time.monotonic() - t0) * 1e3)
            return jax.process_index()
        except RuntimeError as e:
            if "before" in str(e) and "initialize" in str(e):
                # backend already initialized: a single-host run that
                # touched a device before calling in, or a repeat call in
                # an already-joined process (e.g. if the private-state
                # probe above broke on a JAX upgrade).  process_index()
                # reports the truth — never assume rank 0.
                return jax.process_index()
            last_err = e
        except ValueError:
            raise                          # malformed arguments: fail fast
        except Exception as e:             # timeout / connect failure
            last_err = e
        try:                               # clear any half-joined state
            jax.distributed.shutdown()
        except Exception:
            pass
        if attempt < policy.max_attempts:
            sleep_s = policy.next_backoff(sleep_s)
            _time.sleep(sleep_s)
    from avenir_tpu.launch import LaunchError

    raise LaunchError(
        f"fleet join failed: coordinator {coordinator_address!r} "
        f"(process {process_id} of {num_processes}) did not accept the "
        f"join within {timeout_s:g}s on any of {policy.max_attempts} "
        f"attempt(s) — check the coordinator address/port and that "
        f"process 0 is up: {last_err!r}") from last_err


def _wait_for_coordinator(address: str, timeout_s: float) -> None:
    """Bounded, jittered wait for the coordinator's TCP endpoint.

    Retries a plain socket connect under the decorrelated-jitter backoff
    (``utils/retry.RetryPolicy.next_backoff`` — base 0.2 s) until the
    endpoint accepts or ``timeout_s`` expires, then raises the typed
    :class:`~avenir_tpu.launch.LaunchError` naming the address.  This
    runs BEFORE ``jax.distributed.initialize`` because jax's client
    terminates the process outright (abort, not an exception) when its
    RegisterTask RPC times out — the pre-flight probe is the only place
    a bad coordinator address can fail typed."""
    import socket as _socket
    import time as _time

    from avenir_tpu.utils.retry import RetryPolicy

    host, _, port_s = address.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        from avenir_tpu.launch import LaunchError

        raise LaunchError(
            f"coordinator address {address!r} is not host:port")
    policy = RetryPolicy(max_attempts=1, backoff_s=0.2, backoff_cap_s=2.0)
    deadline = _time.monotonic() + max(float(timeout_s), 0.1)
    sleep_s = 0.0
    last: Optional[BaseException] = None
    while True:
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            from avenir_tpu.launch import LaunchError

            raise LaunchError(
                f"fleet join failed: coordinator {address!r} was not "
                f"reachable within {timeout_s:g}s — check the address/"
                f"port and that process 0 (the coordinator) is up: "
                f"{last!r}") from last
        try:
            sock = _socket.create_connection(
                (host or "localhost", port),
                timeout=min(2.0, max(remaining, 0.1)))
            sock.close()
            return
        except OSError as e:
            last = e
        sleep_s = min(policy.next_backoff(sleep_s),
                      max(deadline - _time.monotonic(), 0.0))
        _time.sleep(sleep_s)


def _record_join(coordinator, attempts: int, wall_ms: float) -> None:
    """Record (and, when tracing is already configured, journal) this
    process's successful coordinator join."""
    global _LAST_JOIN
    _LAST_JOIN = {"coordinator": str(coordinator or "env-discovered"),
                  "nprocs": int(jax.process_count()),
                  "attempts": int(attempts),
                  "wall_ms": round(float(wall_ms), 3)}
    journal_fleet_join(**_LAST_JOIN)       # no-op until tracing is on


def make_hybrid_mesh(
    axis_names: Tuple[str, ...] = ("data", "model"),
    ici_shape: Optional[Tuple[int, ...]] = None,
    dcn_shape: Optional[Tuple[int, ...]] = None,
) -> Mesh:
    """Mesh whose leading axis spans hosts over DCN and whose trailing axes
    stay within a slice on ICI.

    The framework's aggregation patterns are all counts/moments reduced with
    psum, so the natural layout is: record (``data``) axis across DCN —
    cross-host traffic is one small count-tensor all-reduce per chunk — and
    the ``model`` (bin/feature) axis inside the slice where all-gathers ride
    ICI. Falls back to :func:`make_mesh` in single-slice runs so callers can
    use it unconditionally.
    """
    num_slices = max(getattr(jax.devices()[0], "num_slices", 1),
                     jax.process_count() if jax.process_count() > 1 else 1)
    if num_slices <= 1:
        shape = None
        if ici_shape is not None:
            shape = tuple(ici_shape)
            if len(shape) < len(axis_names):
                shape = (len(axis_names) - len(shape)) * (1,) + shape
        return make_mesh(axis_names, shape=shape)
    n_local = len(jax.devices()) // num_slices
    if dcn_shape is None:
        dcn_shape = (num_slices,) + (1,) * (len(axis_names) - 1)
    if ici_shape is None:
        ici_shape = (1,) * (len(axis_names) - 1) + (n_local,)
    distinct_slices = {getattr(d, "slice_index", None) for d in jax.devices()}
    if None not in distinct_slices and len(distinct_slices) == num_slices:
        from jax.experimental import mesh_utils
        devs = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=jax.devices())
    else:
        # no slice topology on this backend (multi-process CPU run: the
        # DCN boundary IS the process boundary) — group devices by owning
        # process, then lay out (dcn..., ici...) and merge axis-wise
        devs_sorted = sorted(jax.devices(),
                             key=lambda d: (d.process_index, d.id))
        arr = np.array(devs_sorted, dtype=object).reshape(
            tuple(dcn_shape) + tuple(ici_shape))
        k = len(dcn_shape)
        perm = []
        for i in range(k):
            perm.extend([i, k + i])
        arr = arr.transpose(perm).reshape(
            tuple(d * i for d, i in zip(dcn_shape, ici_shape)))
        devs = arr
    return Mesh(devs, axis_names)


def process_local_batch(mesh: Mesh, array: np.ndarray, data_axis: str = "data"):
    """Multi-host data loading: build a globally-sharded array from each
    process's local rows (every process passes ITS shard of the batch; the
    result behaves like the concatenation sharded over ``data``).

    Single-process meshes reduce to :func:`device_put_sharded_batch`. This is
    the analog of per-host HDFS-block locality in the reference's mapper
    scheduling.
    """
    if jax.process_count() == 1:
        return device_put_sharded_batch(mesh, array, data_axis=data_axis)
    sharding = data_sharding(mesh, array.ndim, data_axis)
    return jax.make_array_from_process_local_data(sharding, array)


def maybe_shard_batch(mesh, *arrays, data_axis: str = "data"):
    """Shard the batch axis over ``mesh`` when it is a real >1-device data
    mesh, else plain ``jnp.asarray`` — the single dispatch policy shared by
    every estimator's ``mesh=`` parameter (NaiveBayes, MutualInformation).
    Single-process only, like :func:`device_put_sharded_batch`; multi-host
    callers build arrays with ``make_array_from_process_local_data``.
    Always returns a list matching ``arrays``."""
    def placed(a) -> bool:
        # staged already (e.g. by the DeviceFeeder prefetch path, which runs
        # this same sharding on its worker thread): transferring again would
        # serialize exactly the copy the feeder overlapped. A bare jax.Array
        # only counts as placed when no >1-device mesh is requested OR it
        # already carries this mesh's batch sharding — a single-device array
        # must still be resharded, not silently run unsharded.
        if a is None:
            return True
        if not isinstance(a, jax.Array):
            return False
        if mesh is None or mesh.shape.get(data_axis, 1) <= 1:
            return True
        sh = a.sharding
        return (isinstance(sh, NamedSharding) and sh.mesh == mesh and
                len(sh.spec) > 0 and sh.spec[0] == data_axis)

    if all(placed(a) for a in arrays):
        return list(arrays)
    if mesh is not None and mesh.shape.get(data_axis, 1) > 1:
        arrays = tuple(None if a is None else np.asarray(a) for a in arrays)
        out = device_put_sharded_batch(mesh, *arrays, data_axis=data_axis)
        return out if len(arrays) > 1 else [out]
    return [None if a is None else jnp.asarray(a) for a in arrays]


def all_process_sum_state(state: dict) -> dict:
    """Deterministic across-process sum of an accumulator state tree —
    the job layer's final "reduce" when streaming chunks are partitioned
    over processes (the multi-host analog of Hadoop's single reducer over
    per-mapper partials, e.g. BayesianDistribution.java:203-328).

    A collective every process must enter, but key sets MAY differ (a
    process that owned no chunks contributes nothing; a missing key counts
    as zero) — everything is packed into ONE payload per process (a
    length gather + one byte gather, so the collective sequence is
    identical everywhere and the merge costs two cross-host round trips
    total, not one per key).  Raw bytes are used because
    ``process_allgather`` would silently downcast int64/float64 under the
    default x64-off config.  Per-key sums run on host in ascending
    process order — the fixed order keeps float accumulation
    deterministic, and integer counts are exact in any order, so
    distributed output files stay reproducible.

    Keys prefixed ``min:`` / ``max:`` merge by elementwise minimum /
    maximum instead of summing (order-free and exact for any dtype) —
    the analog of a Hadoop reducer folding MIN/MAX aggregates; used for
    extrema stats and for broadcasting a dimension only some processes
    know (``max:`` over 0/D)."""
    if jax.process_count() == 1:
        return {k: np.asarray(v) for k, v in state.items()}
    import json as _json
    import time as _time

    from jax.experimental import multihost_utils

    arrays = {k: np.ascontiguousarray(np.asarray(state[k]))
              for k in sorted(state)}
    header = _json.dumps(
        [[k, a.dtype.str, list(a.shape)] for k, a in arrays.items()]).encode()
    payload = header + b"\0" + b"".join(a.tobytes() for a in arrays.values())
    # int32 explicitly: process_allgather silently downcasts int64 under
    # x64-off (the very reason the payload rides as raw bytes), so an
    # int64 length gather would truncate >2^31-byte payloads silently —
    # assert instead.
    if len(payload) >= 2 ** 31:
        raise ValueError(
            f"accumulator payload {len(payload)} bytes exceeds the int32 "
            "length-gather limit; shard the state across keys/jobs")
    # GraftFleet (round 15): the gather below is where a straggling PEER
    # surfaces on this process — every process enters it, so the wall a
    # fast process spends here is mostly waiting for the slowest one.
    # Journal it as a collective.wait event (per-process shards make the
    # asymmetry readable in the merged fleet view: the straggler's wait
    # is short, everyone else's is long).  Telemetry wall clock only —
    # never enters the collective payload, so process divergence is
    # impossible by construction.
    t0 = _time.perf_counter()   # graftlint: disable=GL001
    lens = np.asarray(multihost_utils.process_allgather(
        np.array([len(payload)], np.int32))).reshape(-1)
    buf = np.zeros(int(lens.max()), np.uint8)
    buf[:len(payload)] = np.frombuffer(payload, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    wait_ms = (_time.perf_counter() - t0) * 1e3   # graftlint: disable=GL001
    from avenir_tpu.telemetry import spans as _tel

    _tracer = _tel.tracer()
    if _tracer.enabled:
        _tracer.event("collective.wait", site="all_process_sum_state",
                      wall_ms=round(wait_ms, 3), bytes=len(payload),
                      procs=int(gathered.shape[0]))
    out: dict = {}
    for p in range(gathered.shape[0]):
        raw = gathered[p, :int(lens[p])].tobytes()
        head, _, body = raw.partition(b"\0")
        off = 0
        for key, dt, shape in _json.loads(head.decode()):
            dtype = np.dtype(dt)
            nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
            arr = np.frombuffer(body[off:off + nbytes],
                                dtype=dtype).reshape(shape)
            off += nbytes
            if key in out:
                if out[key].shape != arr.shape:
                    raise ValueError(
                        f"process {p} contributed {key!r} with shape "
                        f"{arr.shape}, expected {out[key].shape} — schema "
                        f"mismatch across processes")
                if key.startswith("min:"):
                    out[key] = np.minimum(out[key], arr)
                elif key.startswith("max:"):
                    out[key] = np.maximum(out[key], arr)
                else:
                    out[key] = out[key] + arr
            else:
                out[key] = arr.copy()
    return out
