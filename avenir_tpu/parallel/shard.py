"""ShardGraft — the mesh-sharded SharedScan execution policy (round 12).

``parallel/mesh.py`` knows how to lay arrays out over a mesh and
``parallel/collectives.py`` knows how to psum partials across it; this
module is the POLICY seam that turns a ``shard.*`` config family into a
concrete sharded execution plan for the SharedScan hot loop:

- ``shard.devices``           — how many local devices the 1-D data mesh
  spans (``all`` or an integer; unset/0 = off → today's single-chip path,
  byte-for-byte: no new dispatches, no resharding, no new keys);
- ``shard.data.axis``         — the mesh axis name (default ``data``);
- ``shard.allreduce.quantized`` — route the gram all-reduce through the
  EQuARX-style int8 block-quantized collective
  (``collectives.quantized_allreduce_sum``; default off — the exact psum
  path remains the byte-identity oracle).

The plan a :class:`ShardSpec` encodes (DrJAX-style mapreduce discipline,
arXiv 2403.07128: placed batches in, ``psum``-reduced replicated totals
out):

1. the chunk feeder ballast-pads each chunk to its pow-2 shard target
   (``mesh.shard_pad_target`` — label −1 rows, the drop-invalid contract,
   so padding changes no statistic while the compiled-shape set stays
   finite) and stages it round-robin over the ``data`` axis;
2. ``ChunkFolder`` folds the staged chunk through ONE
   ``collectives.sharded_scan_step`` dispatch — per-device Pallas gram +
   class counts + class moments, all-reduced in-kernel;
3. the 64-bit host accumulators key the gram under a MESH-QUALIFIED
   ``g_key`` (:meth:`ShardSpec.g_suffix`), so state written under a
   different device count / axis name fails loudly at read-out instead of
   folding stale counts (the GL002 discipline applied to topology).

Single-process only, like ``Job.auto_mesh``: multi-host runs partition
chunks per process and merge through ``all_process_sum_state`` — the two
composability seams are documented in docs/architecture.md (ShardGraft).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from avenir_tpu.core.config import ConfigError


@dataclass(frozen=True)
class ShardSpec:
    """A resolved ShardGraft plan: the mesh, its data axis, and the
    collective flavor.  Built once per run (``from_conf``) and threaded
    through ``SharedScan``/``ChunkFolder``/``WindowedScan`` and the chunk
    feeder so every seam stages and folds under the SAME topology."""

    mesh: object                      # jax.sharding.Mesh (1-D data mesh)
    data_axis: str = "data"
    quantized: bool = False
    # GraftFleet straggler attribution (round 15; parallel/skew.py —
    # active only under profile.on): sampled per-device wall probe around
    # the fused fold, flagging chunks whose max/min per-device time
    # exceeds the threshold.  The fault.* pair injects a synthetic
    # straggler publish-side (test/bench knob, the stream.fault.*
    # discipline).
    skew_threshold: float = 1.5
    skew_sample: int = 1
    skew_fault_device: int = -1
    skew_fault_ms: float = 0.0

    @staticmethod
    def requested(conf) -> bool:
        """Is a ``shard.*`` topology configured?  One predicate for every
        caller that must agree with :meth:`from_conf`'s off-set (the
        driver's singleton-fuse decision, span attrs) — cheap, no jax
        import; resolution/validation stays with ``from_conf``."""
        return conf.get("shard.devices") not in (None, "", "0")

    @classmethod
    def from_conf(cls, conf) -> Optional["ShardSpec"]:
        """The ``shard.*`` config family → a spec, or None when unset
        (today's single-chip path, exactly).  Refuses impossible requests
        loudly: more devices than attached, a multi-process run (chunk
        ownership is per-process there — ``all_process_sum_state`` is the
        cross-host reduce), or a non-positive count."""
        if not cls.requested(conf):
            return None
        raw = conf.get("shard.devices")
        import jax

        if jax.process_count() > 1:
            raise ConfigError(
                "shard.devices is single-process (it places globally-"
                "addressed arrays); multi-host runs partition chunks per "
                "process and merge via all_process_sum_state instead")
        avail = jax.devices()
        try:
            n = len(avail) if str(raw).strip().lower() == "all" else int(raw)
        except ValueError:
            raise ConfigError(
                f"shard.devices={raw!r} must be an integer or 'all'")
        if n < 1:
            raise ConfigError(f"shard.devices={raw!r} must be >= 1 or 'all'")
        if n > len(avail):
            raise ConfigError(
                f"shard.devices={n} but only {len(avail)} device(s) "
                f"attached ({avail[0].platform})")
        axis = conf.get("shard.data.axis", "data")
        from avenir_tpu.parallel.mesh import make_mesh

        return cls(mesh=make_mesh((axis,), shape=(n,), devices=avail[:n]),
                   data_axis=axis,
                   quantized=conf.get_bool("shard.allreduce.quantized",
                                           False),
                   skew_threshold=conf.get_float("shard.skew.threshold",
                                                 1.5),
                   skew_sample=conf.get_int("shard.skew.sample", 1),
                   skew_fault_device=conf.get_int("shard.skew.fault.device",
                                                  -1),
                   skew_fault_ms=conf.get_float("shard.skew.fault.ms", 0.0))

    # -- identity -------------------------------------------------------------
    @property
    def num_devices(self) -> int:
        return int(self.mesh.shape[self.data_axis])

    @property
    def g_suffix(self) -> str:
        """Mesh-shape qualifier appended to the gram accumulator key: a
        resharded run (different device count or axis name) reads a
        DIFFERENT key, and ``ChunkFolder.tables`` raises on the orphaned
        one — stale topology state can never be silently summed."""
        return f":mesh:{self.data_axis}{self.num_devices}"

    def device_kind(self) -> str:
        d = next(iter(np.asarray(self.mesh.devices).flat))
        return getattr(d, "device_kind", "") or d.platform

    # -- staging --------------------------------------------------------------
    def pad_target(self, n: int) -> int:
        from avenir_tpu.parallel.mesh import shard_pad_target

        return shard_pad_target(n, self.num_devices)

    def stage(self, ds):
        """Ballast-pad an encoded chunk to its pow-2 shard target and place
        it sharded over the data axis — the feeder-side half of the plan
        (``runtime/feeder.sharded_pair_stage`` runs this on the prefetch
        worker thread so the padded upload overlaps compute).  Idempotent:
        an already-staged chunk (jax arrays carrying this mesh's batch
        sharding) passes through untouched.  Row ids are kept as-is —
        un-padded host metadata, exactly like the unsharded prefetch
        stage — and ``valid_rows`` records the true pre-ballast count so
        row accounting downstream never counts pad."""
        import jax

        from avenir_tpu.core.encoding import EncodedDataset

        valid = ds.valid_rows
        if valid is None and not isinstance(ds.codes, jax.Array):
            valid = ds.num_rows
        codes, labels, cont = self.shard_batch(ds.codes, ds.labels, ds.cont)
        return EncodedDataset(
            codes=codes, cont=cont, labels=labels, ids=ds.ids,
            n_bins=ds.n_bins, class_values=ds.class_values,
            binned_ordinals=ds.binned_ordinals,
            cont_ordinals=ds.cont_ordinals, valid_rows=valid)

    def shard_batch(self, codes, labels, cont):
        """Array-level staging (the fold-side entry): ballast-pad host
        arrays to the shard target, then place over the data axis; arrays
        already carrying this mesh's batch sharding pass through."""
        import jax

        from avenir_tpu.parallel.mesh import maybe_shard_batch, pad_batch

        if not isinstance(codes, jax.Array):
            n = codes.shape[0]
            codes, labels, cont = pad_batch(self.pad_target(n), codes,
                                            labels, cont)
        return maybe_shard_batch(self.mesh, codes, labels, cont,
                                 data_axis=self.data_axis)

    # -- telemetry ------------------------------------------------------------
    def announce(self, tracer=None) -> dict:
        """Journal the run's hardware identity (``shard.topology``: device
        kind, mesh shape, axis names) so any bench/journal artifact is
        self-describing about what it ran on; returns the payload for
        callers embedding it in their own artifacts."""
        topo = {
            "devices": self.num_devices,
            "device_kind": self.device_kind(),
            "mesh": {k: int(v) for k, v in self.mesh.shape.items()},
            "axes": list(self.mesh.axis_names),
        }
        if tracer is None:
            from avenir_tpu.telemetry import spans as tel

            tracer = tel.tracer()
        # once per journal per topology: several seams announce (the
        # driver's fused scan, the streaming job) and a run's journal must
        # carry ONE hardware identity — a run mixing topologies (distinct
        # shard.* stage props) still journals each distinct one
        tracer.event_once("shard.topology", self.g_suffix, **topo)
        return topo
