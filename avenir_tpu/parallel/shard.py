"""ShardGraft — the mesh-sharded SharedScan execution policy (round 12).

``parallel/mesh.py`` knows how to lay arrays out over a mesh and
``parallel/collectives.py`` knows how to psum partials across it; this
module is the POLICY seam that turns a ``shard.*`` config family into a
concrete sharded execution plan for the SharedScan hot loop:

- ``shard.devices``           — how many local devices the 1-D data mesh
  spans (``all`` or an integer; unset/0 = off → today's single-chip path,
  byte-for-byte: no new dispatches, no resharding, no new keys);
- ``shard.data.axis``         — the mesh axis name (default ``data``);
- ``shard.allreduce.quantized`` — route the gram all-reduce through the
  EQuARX-style int8 block-quantized collective
  (``collectives.quantized_allreduce_sum``; default off — the exact psum
  path remains the byte-identity oracle).

The plan a :class:`ShardSpec` encodes (DrJAX-style mapreduce discipline,
arXiv 2403.07128: placed batches in, ``psum``-reduced replicated totals
out):

1. the chunk feeder ballast-pads each chunk to its pow-2 shard target
   (``mesh.shard_pad_target`` — label −1 rows, the drop-invalid contract,
   so padding changes no statistic while the compiled-shape set stays
   finite) and stages it round-robin over the ``data`` axis;
2. ``ChunkFolder`` folds the staged chunk through ONE
   ``collectives.sharded_scan_step`` dispatch — per-device Pallas gram +
   class counts + class moments, all-reduced in-kernel;
3. the 64-bit host accumulators key the gram under a MESH-QUALIFIED
   ``g_key`` (:meth:`ShardSpec.g_suffix`), so state written under a
   different device count / axis name fails loudly at read-out instead of
   folding stale counts (the GL002 discipline applied to topology).

CrossGraft (this round) lifts the old single-process refusal: under
``jax.process_count() > 1`` the SAME ``shard.*`` family resolves to a
GLOBAL hybrid mesh — a leading process axis (``shard.proc.axis``,
default ``proc``) across the DCN/process boundary × ``shard.devices``
local devices per process on ICI.  Chunks enter per-process (each
process uploads only ITS contiguous row block of the padded chunk via
``jax.make_array_from_process_local_data`` — the ``process_local_batch``
recipe under the 2-D layout), the fused dispatch psums the gram within a
host over ``data`` and then across hosts over ``proc`` (exact psum; the
EQuARX-style int8 hop rides the CROSS-HOST leg only, where DCN — not
ICI — is the bottleneck, arXiv 2506.17615), and the ``g:`` qualifier
gains the process topology (``:mesh:proc2xdata4``) so stale-topology
folds still refuse loudly.  Finalize stays on the data-free
constructors, so the N-process × M-device fold is byte-identical to the
1-chip oracle by construction (tests/crossgraft_worker.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from avenir_tpu.core.config import ConfigError


@dataclass(frozen=True)
class ShardSpec:
    """A resolved ShardGraft plan: the mesh, its data axis, and the
    collective flavor.  Built once per run (``from_conf``) and threaded
    through ``SharedScan``/``ChunkFolder``/``WindowedScan`` and the chunk
    feeder so every seam stages and folds under the SAME topology."""

    mesh: object                      # jax.sharding.Mesh (1-D data mesh,
    #                                   or (proc, data) global hybrid mesh)
    data_axis: str = "data"
    quantized: bool = False
    # CrossGraft (this round): >1 means the mesh is the GLOBAL hybrid
    # mesh — a leading process axis across the DCN/process boundary, the
    # data axis within each host on ICI.  1 = the round-12 local plan,
    # byte-for-byte (no proc axis anywhere in mesh, key, or dispatch).
    proc_axis: str = "proc"
    num_procs: int = 1
    proc_index: int = 0
    # GraftFleet straggler attribution (round 15; parallel/skew.py —
    # active only under profile.on): sampled per-device wall probe around
    # the fused fold, flagging chunks whose max/min per-device time
    # exceeds the threshold.  The fault.* pair injects a synthetic
    # straggler publish-side (test/bench knob, the stream.fault.*
    # discipline).
    skew_threshold: float = 1.5
    skew_sample: int = 1
    skew_fault_device: int = -1
    skew_fault_ms: float = 0.0

    @staticmethod
    def requested(conf) -> bool:
        """Is a ``shard.*`` topology configured?  One predicate for every
        caller that must agree with :meth:`from_conf`'s off-set (the
        driver's singleton-fuse decision, span attrs) — cheap, no jax
        import; resolution/validation stays with ``from_conf``."""
        return conf.get("shard.devices") not in (None, "", "0")

    @classmethod
    def from_conf(cls, conf) -> Optional["ShardSpec"]:
        """The ``shard.*`` config family → a spec, or None when unset
        (today's single-chip path, exactly).  In a multi-process run
        (CrossGraft) ``shard.devices`` counts PER-PROCESS devices and the
        spec resolves to the global (proc × data) hybrid mesh.  Refuses
        genuinely impossible requests loudly: more devices than any
        process has locally attached, a process axis named like the data
        axis, or a non-positive/unparsable count."""
        if not cls.requested(conf):
            return None
        raw = conf.get("shard.devices")
        import jax

        nprocs = jax.process_count()
        avail = jax.local_devices() if nprocs > 1 else jax.devices()
        try:
            n = len(avail) if str(raw).strip().lower() == "all" else int(raw)
        except ValueError:
            raise ConfigError(
                f"shard.devices={raw!r} must be an integer or 'all'")
        if n < 1:
            raise ConfigError(f"shard.devices={raw!r} must be >= 1 or 'all'")
        if n > len(avail):
            raise ConfigError(
                f"shard.devices={n} but only {len(avail)} "
                + ("locally-attached " if nprocs > 1 else "")
                + f"device(s) "
                + (f"on process {jax.process_index()} " if nprocs > 1
                   else "")
                + f"attached ({avail[0].platform})"
                + (" — in a multi-process run shard.devices counts "
                   "per-process devices" if nprocs > 1 else ""))
        axis = conf.get("shard.data.axis", "data")
        quantized = conf.get_bool("shard.allreduce.quantized", False)
        skew = dict(
            skew_threshold=conf.get_float("shard.skew.threshold", 1.5),
            skew_sample=conf.get_int("shard.skew.sample", 1),
            skew_fault_device=conf.get_int("shard.skew.fault.device", -1),
            skew_fault_ms=conf.get_float("shard.skew.fault.ms", 0.0))
        if nprocs > 1:
            proc_axis = conf.get("shard.proc.axis", "proc")
            if proc_axis == axis:
                raise ConfigError(
                    f"shard.proc.axis={proc_axis!r} collides with "
                    f"shard.data.axis — the global mesh needs two distinct "
                    f"axis names")
            return cls(mesh=cls._global_mesh(proc_axis, axis, n),
                       data_axis=axis, quantized=quantized,
                       proc_axis=proc_axis, num_procs=nprocs,
                       proc_index=jax.process_index(), **skew)
        from avenir_tpu.parallel.mesh import make_mesh

        return cls(mesh=make_mesh((axis,), shape=(n,), devices=avail[:n]),
                   data_axis=axis, quantized=quantized, **skew)

    @staticmethod
    def _global_mesh(proc_axis: str, data_axis: str, n: int):
        """The (nprocs × n) global hybrid mesh: leading axis spans
        processes (the DCN boundary), trailing axis the first ``n``
        devices OF EACH process (ICI) — the ``make_hybrid_mesh`` layout,
        built explicitly so a run may use fewer than all local devices.
        Every process constructs the identical mesh (devices sorted by
        (process, id)), which SPMD dispatch requires."""
        import jax
        from jax.sharding import Mesh

        by_proc: dict = {}
        for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
            by_proc.setdefault(d.process_index, []).append(d)
        nprocs = jax.process_count()
        short = min(len(v) for v in by_proc.values())
        if n > short:
            raise ConfigError(
                f"shard.devices={n} but the smallest process has only "
                f"{short} device(s) — the global mesh needs n devices on "
                f"EVERY process")
        arr = np.array([by_proc[p][:n] for p in sorted(by_proc)],
                       dtype=object)
        assert arr.shape == (nprocs, n)      # one row per process
        return Mesh(arr, (proc_axis, data_axis))

    # -- identity -------------------------------------------------------------
    @property
    def num_devices(self) -> int:
        """Data-axis width: per-process device count on a global mesh."""
        return int(self.mesh.shape[self.data_axis])

    @property
    def total_devices(self) -> int:
        """Every device the plan folds over, fleet-wide."""
        return self.num_procs * self.num_devices

    @property
    def is_global(self) -> bool:
        """Does the plan span processes (CrossGraft hybrid mesh)?"""
        return self.num_procs > 1

    @property
    def g_suffix(self) -> str:
        """Mesh-shape qualifier appended to the gram accumulator key: a
        resharded run (different device count, process count, or axis
        name) reads a DIFFERENT key, and ``ChunkFolder.tables`` raises on
        the orphaned one — stale topology state can never be silently
        summed.  A global plan's qualifier carries the PROCESS topology
        too (``:mesh:proc2xdata4``), so a 2-proc fold resumed on 1 proc
        crosses the same loud gate (checkpoint/reshard redistributes it
        under ``shard.reshard.on.restore``)."""
        if self.is_global:
            return (f":mesh:{self.proc_axis}{self.num_procs}"
                    f"x{self.data_axis}{self.num_devices}")
        return f":mesh:{self.data_axis}{self.num_devices}"

    def device_kind(self) -> str:
        d = next(iter(np.asarray(self.mesh.devices).flat))
        return getattr(d, "device_kind", "") or d.platform

    # -- staging --------------------------------------------------------------
    def pad_target(self, n: int) -> int:
        from avenir_tpu.parallel.mesh import shard_pad_target

        return shard_pad_target(n, self.total_devices)

    def stage(self, ds):
        """Ballast-pad an encoded chunk to its pow-2 shard target and place
        it sharded over the data axis — the feeder-side half of the plan
        (``runtime/feeder.sharded_pair_stage`` runs this on the prefetch
        worker thread so the padded upload overlaps compute).  Idempotent:
        an already-staged chunk (jax arrays carrying this mesh's batch
        sharding) passes through untouched.  Row ids are kept as-is —
        un-padded host metadata, exactly like the unsharded prefetch
        stage — and ``valid_rows`` records the true pre-ballast count so
        row accounting downstream never counts pad."""
        import jax

        from avenir_tpu.core.encoding import EncodedDataset

        valid = ds.valid_rows
        if valid is None and not isinstance(ds.codes, jax.Array):
            valid = ds.num_rows
        codes, labels, cont = self.shard_batch(ds.codes, ds.labels, ds.cont)
        return EncodedDataset(
            codes=codes, cont=cont, labels=labels, ids=ds.ids,
            n_bins=ds.n_bins, class_values=ds.class_values,
            binned_ordinals=ds.binned_ordinals,
            cont_ordinals=ds.cont_ordinals, valid_rows=valid)

    def shard_batch(self, codes, labels, cont):
        """Array-level staging (the fold-side entry): ballast-pad host
        arrays to the shard target, then place over the data axis; arrays
        already carrying this mesh's batch sharding pass through.

        Global plans stage PER PROCESS: the pad target covers the whole
        fleet (pow-2 rounded to a ``nprocs × n`` multiple — identical on
        every process by construction), each process slices ITS
        contiguous row block of the padded chunk, and
        ``jax.make_array_from_process_local_data`` assembles the
        globally-sharded batch without moving a byte cross-host — the
        ``process_local_batch`` recipe under the (proc, data) layout."""
        import jax

        from avenir_tpu.parallel.mesh import maybe_shard_batch, pad_batch

        if not self.is_global:
            if not isinstance(codes, jax.Array):
                n = codes.shape[0]
                codes, labels, cont = pad_batch(self.pad_target(n), codes,
                                                labels, cont)
            return maybe_shard_batch(self.mesh, codes, labels, cont,
                                     data_axis=self.data_axis)
        if isinstance(codes, jax.Array):
            # staged already (the sharded prefetch path ran this on its
            # worker thread); a foreign placement cannot be resharded
            # cross-process, so refuse instead of silently mislaying
            from jax.sharding import NamedSharding

            sh = codes.sharding
            if not (isinstance(sh, NamedSharding) and sh.mesh == self.mesh):
                raise ConfigError(
                    "chunk arrays are device-placed under a different "
                    "mesh than this global shard plan — stage host arrays "
                    "through ShardSpec.stage/shard_batch instead")
            return [codes, labels, cont]
        from jax.sharding import NamedSharding, PartitionSpec as P

        target = self.pad_target(codes.shape[0])
        codes, labels, cont = pad_batch(target, codes, labels, cont)
        per = target // self.num_procs
        lo = self.proc_index * per
        axes = (self.proc_axis, self.data_axis)
        out = []
        for a in (codes, labels, cont):
            if a is None:
                out.append(None)
                continue
            spec = P(axes, *([None] * (a.ndim - 1)))
            out.append(jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, spec),
                np.ascontiguousarray(a[lo:lo + per])))
        return out

    # -- telemetry ------------------------------------------------------------
    def announce(self, tracer=None) -> dict:
        """Journal the run's hardware identity (``shard.topology``: device
        kind, mesh shape, axis names, process count) so any bench/journal
        artifact is self-describing about what it ran on; returns the
        payload for callers embedding it in their own artifacts.  On a
        global plan ``devices`` counts the WHOLE fleet and the mesh/axes
        carry the process axis — the per-run topology record the
        acceptance gate reads.  A multi-process worker also announces its
        coordinator join here (``fleet.join``, recorded by
        ``init_distributed`` before any journal existed)."""
        topo = {
            "devices": self.total_devices,
            "device_kind": self.device_kind(),
            "mesh": {k: int(v) for k, v in self.mesh.shape.items()},
            "axes": list(self.mesh.axis_names),
            "procs": self.num_procs,
        }
        if tracer is None:
            from avenir_tpu.telemetry import spans as tel

            tracer = tel.tracer()
        # once per journal per topology: several seams announce (the
        # driver's fused scan, the streaming job) and a run's journal must
        # carry ONE hardware identity — a run mixing topologies (distinct
        # shard.* stage props) still journals each distinct one
        tracer.event_once("shard.topology", self.g_suffix, **topo)
        from avenir_tpu.parallel import mesh as pmesh

        join = pmesh.last_join()
        if join is not None:
            pmesh.journal_fleet_join(**join)
        return topo
