"""GraftFleet straggler/skew attribution — per-device wall sampling for
the mesh-sharded SharedScan (round 15).

The fused ``collectives.sharded_scan_step`` dispatch hides per-device
behavior by construction: its outputs are psum'd, so every device's copy
becomes ready only after the SLOWEST device has contributed — host-side
timing of the fused program can say "this chunk was slow" but never
"device 3 made it slow".  Multi-host TPU practice treats exactly that
attribution as table stakes for scaling claims (pjit/TPUv4 scaling
discipline, arXiv 2204.06514): a fleet with one throttled or contended
chip otherwise reads as a uniformly slow fleet.

This module measures the PRE-collective per-device time with a sampled
probe dispatch:

- :func:`skew_probe_step` compiles the same per-device Pallas gram the
  fused step runs (same kernel, same per-device rows) but with NO
  collective and the output left **sharded** over the data axis — so
  device *d*'s output shard becomes ready exactly when device *d*
  finishes its local chunk work;
- :class:`DeviceSkewProbe` dispatches it every ``shard.skew.sample``-th
  chunk (behind ``profile.on`` — off means the fold pays one attribute
  check and the probe program is never even built), blocks on every
  device's shard from its own thread (``block_until_ready`` releases the
  GIL, so each thread observes its device's true completion), and
  publishes:

  - a ``Shard::skew.pct`` gauge counter (latest max/min ratio × 100) and
    a ``shard.skew.ratio`` journal gauge,
  - one golden-schema'd ``shard.skew`` journal event per sampled chunk
    carrying the full per-device ms distribution, ``flagged`` when the
    max/min ratio exceeds ``shard.skew.threshold`` (plus a
    ``Shard::skew.flagged`` counter — the straggler alarm),
  - rendered post-hoc by ``python -m avenir_tpu.telemetry skew
    <journal>`` (per-device distribution, slowest device highlighted).

Honesty note: the probe is an EXTRA dispatch of the gram kernel — its
absolute ms is the per-device chunk-compute time in isolation, not the
in-situ time inside the fused program (which overlaps the collective).
Skew RATIOS are what it attributes; that is the straggler signal.  The
cost is one additional gram per sampled chunk, which is why it rides
``profile.on`` + a sampling stride, never ambient.

``shard.skew.fault.device`` / ``shard.skew.fault.ms`` inject a synthetic
straggler AFTER measurement (publish-side, the ``stream.fault.*``
discipline) so the flag → journal → CLI path is testable on a host mesh
where every virtual device runs the same silicon.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import List, Optional


@functools.lru_cache(maxsize=32)
def skew_probe_step(mesh, num_bins: int, num_classes: int,
                    data_axis: str = "data", interpret: bool = False,
                    block_cols=None, proc_axis=None):
    """The per-device timing probe: each device runs the SAME local gram
    pass as ``sharded_scan_step`` (identical kernel + shapes, so its wall
    is representative) reduced to one scalar per device, with NO
    cross-device collective and the [D] output sharded over the data
    axis — shard *d* is ready exactly when device *d* is done.  Memoized
    like the fused step, so repeated folds reuse the compiled probe.

    CrossGraft: on a global (proc × data) mesh pass ``proc_axis`` — the
    batch and the [D] output shard over BOTH axes, and each process
    observes its ADDRESSABLE shards (its own devices); cross-process
    attribution composes in the merged fleet journal, where every
    process's ``shard.skew`` events carry its proc stamp."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from avenir_tpu.ops import pallas_hist
    from avenir_tpu.parallel.collectives import _shard_map_norep

    axes = data_axis if proc_axis is None else (proc_axis, data_axis)

    def step(codes, labels):
        g = pallas_hist.cooc_counts.__wrapped__(
            codes, labels, num_bins, num_classes, interpret=interpret,
            block_cols=block_cols)
        # int32 checksum: the value is discarded, only readiness is read
        return jnp.sum(g, dtype=jnp.int32).reshape(1)

    wrapped = _shard_map_norep(step, mesh,
                               (P(axes, None), P(axes)),
                               P(axes))
    return jax.jit(wrapped)


def publish_skew(device_ms: List[float], chunk: int, threshold: float,
                 device_labels: List[str], counters=None,
                 fault_device: int = -1, fault_ms: float = 0.0) -> dict:
    """Publish one probe's per-device distribution: gauge + counters +
    the golden-schema'd ``shard.skew`` journal event (``flagged`` when
    max/min exceeds ``threshold``).  Factored out of the probe so the
    fault-injection knobs and the golden-schema test exercise the REAL
    emission path without a mesh."""
    from avenir_tpu.telemetry import spans as tel

    device_ms = [float(ms) for ms in device_ms]
    if fault_ms > 0 and 0 <= fault_device < len(device_ms):
        # synthetic straggler (test/bench knob): injected after the real
        # measurement so the publish/flag path is attestable on a host
        # mesh of identical virtual devices
        device_ms[fault_device] += float(fault_ms)
    floor = 1e-6
    mx = max(device_ms)
    mn = max(min(device_ms), floor)
    ratio = mx / mn
    slowest = int(device_ms.index(mx))
    flagged = ratio > threshold
    if counters is not None:
        counters.set("Shard", "skew.pct", int(round(ratio * 100)))
        if flagged:
            counters.increment("Shard", "skew.flagged")
    tracer = tel.tracer()
    tracer.gauge("shard.skew.ratio", round(ratio, 4))
    tracer.event(
        "shard.skew", chunk=int(chunk),
        device_ms=[round(ms, 3) for ms in device_ms],
        max_ms=round(mx, 3), min_ms=round(min(device_ms), 3),
        ratio=round(ratio, 4), threshold=float(threshold),
        slowest=(device_labels[slowest]
                 if slowest < len(device_labels) else str(slowest)),
        flagged=bool(flagged))
    return {"device_ms": device_ms, "ratio": ratio, "slowest": slowest,
            "flagged": flagged}


class DeviceSkewProbe:
    """Sampled per-device wall probe around the sharded SharedScan fold.

    Constructed by ``ChunkFolder`` only when a shard topology is active
    AND ``profile.on`` is set (the off state never builds the probe or
    its compiled program).  ``maybe_probe`` runs every
    ``shard.skew.sample``-th call."""

    def __init__(self, spec, num_bins: int, num_classes: int,
                 interpret: bool = False, counters=None):
        self.spec = spec
        self.counters = counters
        self.threshold = float(spec.skew_threshold)
        self.sample_every = max(int(spec.skew_sample), 1)
        self.step = skew_probe_step(
            spec.mesh, num_bins, num_classes, data_axis=spec.data_axis,
            interpret=interpret,
            proc_axis=spec.proc_axis if spec.is_global else None)
        self._n = 0

    def maybe_probe(self, codes, labels) -> Optional[dict]:
        """Probe this chunk when its index lands on the sampling stride;
        returns the published skew record or None.  ``codes``/``labels``
        are the ALREADY-STAGED sharded operands of the fused dispatch —
        each device times its own rows, the real per-device load."""
        n = self._n
        self._n += 1
        if n % self.sample_every:
            return None
        out = self.step(codes, labels)
        t0 = time.perf_counter()
        shards = list(out.addressable_shards)
        # label each timing with the shard's OWN device — never assume
        # addressable_shards order matches the mesh's device order
        labels_now = [
            f"{getattr(sh.device, 'platform', 'dev')}:"
            f"{getattr(sh.device, 'id', i)}"
            for i, sh in enumerate(shards)]
        times = [0.0] * len(shards)
        errors: List[BaseException] = []

        def wait(i: int, data) -> None:
            # block_until_ready releases the GIL: each thread observes
            # ITS device's completion independently — sequential blocking
            # would mask any straggler ordered before a fast device
            try:
                data.block_until_ready()
                times[i] = (time.perf_counter() - t0) * 1e3
            except Exception as e:        # noqa: BLE001
                # a failed device must not report 0 ms (it would read as
                # the FASTEST shard) — surface it after the join barrier
                errors.append(e)

        threads = [threading.Thread(target=wait, args=(i, sh.data),
                                    daemon=True)
                   for i, sh in enumerate(shards)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return publish_skew(times, chunk=n, threshold=self.threshold,
                            device_labels=labels_now,
                            counters=self.counters,
                            fault_device=self.spec.skew_fault_device,
                            fault_ms=self.spec.skew_fault_ms)
