from avenir_tpu.pipeline.driver import Pipeline, Stage, decision_tree_pipeline, knn_pipeline
from avenir_tpu.pipeline.plan import PipelinePlan, plan_pipeline
from avenir_tpu.pipeline.streaming import (
    InProcQueue,
    QueueActionWriter,
    QueueRewardReader,
    QueueEventSource,
    ReinforcementLearnerServer,
)

__all__ = [
    "InProcQueue",
    "Pipeline",
    "PipelinePlan",
    "plan_pipeline",
    "QueueActionWriter",
    "QueueRewardReader",
    "QueueEventSource",
    "ReinforcementLearnerServer",
    "Stage",
    "decision_tree_pipeline",
    "knn_pipeline",
]
