from avenir_tpu.pipeline.streaming import (
    InProcQueue,
    QueueActionWriter,
    QueueRewardReader,
    QueueEventSource,
    ReinforcementLearnerServer,
)

__all__ = [
    "InProcQueue",
    "QueueActionWriter",
    "QueueRewardReader",
    "QueueEventSource",
    "ReinforcementLearnerServer",
]
