"""CLI — the conf-declared pipeline DAG as a runnable verb.

::

    python -m avenir_tpu.pipeline plan <conf> [-Dkey=value ...]
    python -m avenir_tpu.pipeline plan explain <conf> [-Dkey=value ...]
    python -m avenir_tpu.pipeline run <conf> [-Dkey=value ...] [--resume]

``plan`` (and its ``plan explain`` alias) loads the DAG declared by the
``pipeline.*`` properties (``Pipeline.from_conf``), lowers it through the
PlanGraft planner, and prints the fused plan tree — per-node cost
estimates and which rewrites (fuse / share-gram / prune / encode-once /
pack) fired — without executing anything.  ``run`` executes the pipeline;
``plan.on=true`` (conf or ``-D``) routes it through the planned program.
``-D`` overrides and ``conf.path``-free property files follow the main
``python -m avenir_tpu`` CLI's conventions.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Tuple

USAGE = (
    "usage: python -m avenir_tpu.pipeline plan [explain] <conf> "
    "[-Dkey=value ...]\n"
    "       python -m avenir_tpu.pipeline run <conf> [-Dkey=value ...] "
    "[--resume]")


def parse_args(argv: List[str]) -> Tuple[str, str, Dict[str, str], bool]:
    """(verb, conf path, -D overrides, resume) from the argument list."""
    if not argv or argv[0] not in ("plan", "run"):
        raise SystemExit(USAGE)
    verb = argv[0]
    rest = argv[1:]
    if verb == "plan" and rest and rest[0] == "explain":
        rest = rest[1:]        # ``plan explain`` — same rendering
    overrides: Dict[str, str] = {}
    positional: List[str] = []
    resume = False
    for arg in rest:
        if arg == "--resume":
            resume = True
        elif arg.startswith("-D"):
            body = arg[2:]
            if "=" not in body:
                raise SystemExit(f"bad -D option (need -Dkey=value): {arg!r}")
            k, v = body.split("=", 1)
            overrides[k.strip()] = v.strip()
        else:
            positional.append(arg)
    if len(positional) != 1:
        raise SystemExit(USAGE)
    return verb, positional[0], overrides, resume


def main(argv: List[str]) -> int:
    import os

    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        # the image's sitecustomize pins the jax_platforms *config* to the
        # TPU tunnel, which beats the env var — honor an explicit CPU request
        import jax

        jax.config.update("jax_platforms", "cpu")
    verb, conf_path, overrides, resume = parse_args(argv)
    from avenir_tpu.core.config import JobConfig

    conf = JobConfig.from_file(conf_path)
    for k, v in overrides.items():
        conf.set(k, v)
    from avenir_tpu.pipeline.driver import Pipeline

    pipeline = Pipeline.from_conf(conf)
    if verb == "plan":
        from avenir_tpu.pipeline import plan as plan_mod

        pl = plan_mod.plan_pipeline(pipeline, resume=resume)
        print(pl.explain())
        return 0
    counters = pipeline.run(resume=resume)
    for name in counters:
        print(f"stage {name}")
        for group, vals in sorted(counters[name].as_dict().items()):
            print(f"  {group}")
            for k, v in sorted(vals.items()):
                print(f"\t{k}={v}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
