"""In-process pipeline driver — the L4 layer the reference leaves to shell
scripts and humans.

The reference's multi-stage pipelines are bash verbs staging files through
HDFS (resource/knn.sh:16-137) or runbook steps a human executes
(resource/price_optimize_tutorial.txt:73-78). Here a :class:`Pipeline` is an
ordered DAG of named stages over a shared artifact workspace: each stage is a
job (from avenir_tpu.jobs) bound to input/output artifact names, and the
driver resolves artifact paths, runs stages in dependency order, and collects
per-stage counters. :func:`knn_pipeline` reproduces knn.sh end-to-end in one
process.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.utils.metrics import Counters


@dataclass
class Stage:
    """One pipeline step: a registered job name (or a callable with the job
    ``run`` signature), the artifact it reads, the artifact it writes, and
    per-stage property overrides."""

    name: str
    job: str | Callable[[JobConfig, str, str], Counters]
    input: str
    output: str
    props: Dict[str, str] = field(default_factory=dict)
    # artifacts this stage consumes via config paths (dependency edges only)
    uses: Sequence[str] = ()

    def run(self, conf: JobConfig, in_path: str, out_path: str) -> Counters:
        # resolved at call time: a module-level jobs import would close the
        # import cycle jobs/__init__ → stream → pipeline → driver → jobs
        # (any avenir_tpu.stream-first import would crash at startup)
        from avenir_tpu.jobs import get_job

        runner = get_job(self.job).run if isinstance(self.job, str) else self.job
        return runner(conf, in_path, out_path)


class Pipeline:
    """Artifact-addressed stage runner.

    Artifacts are named paths in a workspace directory; ``bind`` points a
    name at an existing external path (the input dataset, a schema file).
    ``run`` executes stages in order, skipping any whose output artifact
    already exists when ``resume=True`` — the free checkpoint/resume the
    reference got from durable HDFS staging dirs, kept deliberately.
    """

    def __init__(self, workspace: str, conf: JobConfig,
                 stages: Optional[List[Stage]] = None):
        self.workspace = workspace
        self.conf = conf
        self.stages: List[Stage] = list(stages or [])
        self.bindings: Dict[str, str] = {}
        self.counters: Dict[str, Counters] = {}
        os.makedirs(workspace, exist_ok=True)

    @classmethod
    def from_conf(cls, conf: JobConfig,
                  workspace: Optional[str] = None) -> "Pipeline":
        """The conf-declared pipeline DAG — what the shell runbooks staged
        by hand, as properties the planner (``pipeline/plan.py``) and the
        ``python -m avenir_tpu.pipeline`` CLI can load whole:

        - ``pipeline.workspace`` — artifact directory (or pass it here);
        - ``pipeline.stages`` — stage names, comma-separated, in order;
        - ``pipeline.stage.<name>.job`` / ``.input`` / ``.output`` /
          ``.uses`` (comma list) / ``.prop.<key>`` (per-stage override,
          ``@artifact`` references resolve like :class:`Stage` props);
        - ``pipeline.bind.<artifact>`` — external path bindings."""
        names = conf.get_list("pipeline.stages")
        if not names:
            raise ConfigError(
                "pipeline.stages must list the stage names in execution "
                "order (see docs/jobs.md, 'Conf-declared pipelines')")
        ws = workspace or conf.get("pipeline.workspace") or "pipeline_ws"
        p = cls(ws, conf)
        bind_pref = "pipeline.bind."
        for key in sorted(conf.props):
            if key.startswith(bind_pref):
                p.bind(key[len(bind_pref):], conf.props[key])
        for name in names:
            pref = f"pipeline.stage.{name}."
            job = conf.get(pref + "job")
            inp = conf.get(pref + "input")
            out = conf.get(pref + "output")
            if not (job and inp and out):
                raise ConfigError(
                    f"stage {name!r} needs {pref}job, {pref}input and "
                    f"{pref}output")
            prop_pref = pref + "prop."
            props = {k[len(prop_pref):]: v for k, v in conf.props.items()
                     if k.startswith(prop_pref)}
            p.add(Stage(name, job, inp, out, props=props,
                        uses=tuple(conf.get_list(pref + "uses") or ())))
        return p

    def add(self, stage: Stage) -> "Pipeline":
        self.stages.append(stage)
        return self

    def bind(self, artifact: str, path: str) -> "Pipeline":
        self.bindings[artifact] = path
        return self

    def path(self, artifact: str) -> str:
        if artifact in self.bindings:
            return self.bindings[artifact]
        return os.path.join(self.workspace, artifact)

    def _deps(self, stage: Stage) -> List[str]:
        """Artifacts a stage consumes: its input, declared ``uses``, and any
        ``@artifact`` references in its property overrides."""
        deps = [stage.input] + list(stage.uses)
        deps += [v[1:] for v in stage.props.values()
                 if isinstance(v, str) and v.startswith("@")]
        return deps

    def _stage_conf(self, stage: Stage) -> JobConfig:
        conf = JobConfig(dict(self.conf.props), prefix=self.conf.prefix)
        for k, v in stage.props.items():
            # per-stage overrides may reference artifacts as @name
            if isinstance(v, str) and v.startswith("@"):
                v = self.path(v[1:])
            conf.set(k, v)
        return conf

    def _scan_group(self, todo: List[Stage], i: int, resume: bool):
        """Maximal run of consecutive stages starting at ``todo[i]`` that
        one SharedScan can serve: every stage a fusable count job over the
        SAME input artifact, none consuming another group member's output,
        none already satisfied under ``resume``, and all stage confs
        compatible (same schema/delimiter/stream keys — see
        ``pipeline/scan.py``).  Returns ``(stages, confs, fuse)`` — fuse
        True when the group (even a singleton, under a shard.* topology)
        should run through the one SharedScan; the confs are reused by the
        caller so a stage conf is only ever built once."""
        from avenir_tpu.pipeline import scan

        first = todo[i]
        in_path = self.path(first.input)
        group: List[Stage] = []
        confs: List[JobConfig] = []
        outputs: set = set()
        for s in todo[i:]:
            if self.path(s.input) != in_path:
                break
            if resume and os.path.exists(self.path(s.output)):
                break
            if any(a in outputs for a in self._deps(s)):
                break          # consumes an output of an earlier group member
            conf = self._stage_conf(s)
            if not scan.stage_fusable(s.job, conf):
                break
            group.append(s)
            confs.append(conf)
            outputs.add(s.output)
        # a SINGLETON count stage still routes through the one SharedScan
        # when a shard.* topology is configured: the mesh-sharded fold
        # lives only there, and a shard.devices request silently running
        # the single-chip standalone path would contradict the journal
        from avenir_tpu.parallel.shard import ShardSpec

        if group and scan.stages_compatible(confs) and (
                len(group) > 1 or ShardSpec.requested(confs[0])):
            return group, confs, True
        return [first], confs[:1], False

    def _xla_trace(self, name: str, tracer):
        """Per-stage XProf/XLA capture (round 14, off by default): with
        ``trace.xla.dir`` set, each executed stage (or fused group) runs
        under ``utils/profiling.trace`` into its own subdirectory —
        ``<trace.xla.dir>/<stage name>`` — viewable in TensorBoard/XProf,
        and the capture path is journaled (``xla.trace``) so the run's
        timeline names its own device traces.  Unset: a null context, no
        jax.profiler import on the path."""
        xla_dir = self.conf.get("trace.xla.dir")
        if not xla_dir:
            return contextlib.nullcontext()
        from avenir_tpu.utils import profiling

        path = os.path.join(xla_dir, name)
        tracer.event("xla.trace", stage=name, dir=path)
        return profiling.trace(path)

    def rollup(self) -> Counters:
        """Run-level counter rollup: the SUM of every stage's counters
        (``merge_add`` — overwrite-merge would keep only the last stage's
        count for any name two stages share, e.g. ``Records::Processed``)."""
        total = Counters()
        for stage_counters in self.counters.values():
            total.merge_add(stage_counters)
        return total

    def run(self, only: Optional[Sequence[str]] = None,
            resume: bool = False) -> Dict[str, Counters]:
        if only is None:
            todo = list(self.stages)
        else:
            # transitive closure over artifact edges: a requested stage pulls
            # in the producers of every artifact it consumes
            producers = {s.output: s for s in self.stages}
            needed = {name: True for name in only}
            frontier = [s for s in self.stages if s.name in needed]
            while frontier:
                stage = frontier.pop()
                for art in self._deps(stage):
                    prod = producers.get(art)
                    if prod is not None and prod.name not in needed:
                        needed[prod.name] = True
                        frontier.append(prod)
            todo = [s for s in self.stages if s.name in needed]
        from avenir_tpu import tenancy
        from avenir_tpu.telemetry import spans as tel

        tracer = tel.configure(self.conf)
        # GraftPool (round 18): arm the device arbiter from tenant.*
        # contracts (no-op without them) and run the whole pipeline AS
        # this conf's tenant — every stage span, counter snapshot and
        # chunk-fold dispatch slot below carries/obeys the tenant
        tenancy.configure(self.conf)
        tenant = self.conf.get("tenant.id")
        # ElasticGraft (round 16): resolve the elastic-restore policy once
        # at run start — shard.reshard.on.restore=true lets the restore
        # seams (WindowCheckpointer / StreamCheckpointer) redistribute a
        # snapshot written under a different mesh topology onto this
        # run's; default OFF keeps the loud refusal.  Resolved here so an
        # unparsable value fails before any stage runs, and the journal's
        # root span records the policy the run restored under.
        run_attrs = {"workspace": self.workspace, "stages": len(todo),
                     "resume": bool(resume)}
        if tenant:
            run_attrs["tenant"] = tenant
        if self.conf.get_bool("shard.reshard.on.restore", False):
            run_attrs["reshard.on.restore"] = True
        with tel.label_scope(tenant=tenant), \
                tracer.span("pipeline.run", attrs=run_attrs):
            # ShardGraft (round 12) / CrossGraft (this round): resolve
            # the shard.* topology once at run start so a genuinely
            # impossible request (more devices than any process has
            # attached, colliding axis names) fails HERE, before any
            # stage runs; a multi-process runtime resolves to the global
            # (proc × data) hybrid mesh instead of refusing.
            # The journal's shard.topology event is emitted by the seams
            # that actually fold sharded (run_fused_stages, the streaming
            # job) — announce() dedupes per journal — so the artifact
            # never claims parallelism that did not execute
            from avenir_tpu.parallel.shard import ShardSpec

            ShardSpec.from_conf(self.conf)
            if self.conf.get_bool("plan.on", False):
                # PlanGraft: lower the declared DAG into plan units (non-
                # adjacent fusion, share-gram, dead-column pruning, AOT-
                # costed pack selection) and execute the plan — byte-
                # identical artifacts to the staged loop below, which
                # remains the default and the oracle (tests/test_plan.py)
                from avenir_tpu.pipeline import plan as plan_mod

                pl = plan_mod.plan_pipeline(self, todo, resume=resume)
                plan_mod.journal_plan(pl.summary(), tracer)
                plan_mod.run_plan(self, pl, tracer)
            else:
                self._run_stages(todo, resume, tracer)
            tracer.counters("pipeline", self.rollup())
        # fused-scan samples never pass through Job.run — flush them here
        # so the run journal's program totals are complete at pipeline end
        from avenir_tpu.telemetry import profile as _profile

        _profile.profiler().flush()
        return self.counters

    def _mark_skipped(self, stage: Stage, tracer) -> None:
        """A resume-satisfied stage must still appear in the run report
        (and the journal): an absent entry is indistinguishable from a
        stage the DAG never declared.  Mark IN PLACE when the stage
        already has counters (a partial run resumed on the same Pipeline
        object) — replacing them would throw away the real counts the
        earlier execution collected."""
        marked = self.counters.setdefault(stage.name, Counters())
        marked.set("Pipeline", "skipped", 1)
        tracer.event("stage.skipped", stage=stage.name,
                     output=self.path(stage.output))

    def _run_single(self, stage: Stage, conf: JobConfig, tracer) -> None:
        """One stage on its own job path — the staged loop's per-stage
        body, shared with the planner's fallback units."""
        out = self.path(stage.output)
        attrs = {"job": (stage.job if isinstance(stage.job, str)
                         else getattr(stage.job, "__name__", "callable")),
                 "output": out}
        from avenir_tpu.parallel.shard import ShardSpec

        if ShardSpec.requested(conf):
            # shard.* covers only the SharedScan fold (fused count
            # stages, streaming); this stage runs its normal path —
            # say so in the trace instead of implying parallelism
            attrs["sharded"] = stage.job == "StreamAnalytics"
        with tracer.span(f"stage.{stage.name}", attrs=attrs), \
                self._xla_trace(stage.name, tracer):
            self.counters[stage.name] = stage.run(
                conf, self.path(stage.input), out)
            tracer.counters(stage.name, self.counters[stage.name])

    def _run_fused(self, group: List[Stage], gconfs: List[JobConfig],
                   tracer, extra_attrs: Optional[dict] = None,
                   **fused_kwargs) -> None:
        """A stage group through ONE SharedScan — the staged loop's fused
        branch, shared with the planner's scan units (which pass the
        plan-node span attrs plus prune/pack/encode-cache decisions
        through ``fused_kwargs``)."""
        from avenir_tpu.pipeline import scan

        attrs = {"stages": [s.name for s in group],
                 "input": self.path(group[0].input)}
        if extra_attrs:
            attrs.update(extra_attrs)
        with tracer.span("scan.fused", attrs=attrs) as sp, \
                self._xla_trace(group[0].name, tracer):
            fused = scan.run_fused_stages(
                [(s.name, s.job, self.path(s.input),
                  self.path(s.output), conf)
                 for s, conf in zip(group, gconfs)], **fused_kwargs)
            self.counters.update(fused)
            first = fused[group[0].name]
            sp.set("chunks", first.get("SharedScan", "Chunks"))
            sp.set("rows", first.get("Records", "Processed"))
            for s in group:
                tracer.counters(s.name, fused[s.name])

    def _run_stages(self, todo: List[Stage], resume: bool, tracer) -> None:
        i = 0
        while i < len(todo):
            stage = todo[i]
            if resume and os.path.exists(self.path(stage.output)):
                self._mark_skipped(stage, tracer)
                i += 1
                continue
            # stage fusion (round 7): consecutive count jobs reading the
            # same artifact with a compatible schema collapse into ONE
            # SharedScan — one parse+encode+gram pass serving every stage
            # (scan.fuse=false opts a stage or the whole pipeline out)
            group, gconfs, fuse = self._scan_group(todo, i, resume)
            if fuse:
                self._run_fused(group, gconfs, tracer)
                i += len(group)
                continue
            conf = gconfs[0] if gconfs else self._stage_conf(stage)
            self._run_single(stage, conf, tracer)
            i += 1


def knn_pipeline(workspace: str, conf: JobConfig, train_path: str,
                 test_path: str, class_cond: bool = False) -> Pipeline:
    """resource/knn.sh as a DAG: [bayesianDistr → bayesianPredictor →] the
    in-memory kNN classifier (which fuses computeDistance / joinFeatureDistr /
    knnClassifier into one device pass)."""
    p = Pipeline(workspace, conf)
    p.bind("train", train_path)
    p.bind("test", test_path)
    if class_cond:
        p.add(Stage("bayesianDistr", "BayesianDistribution", "train", "bayes_model"))
        p.add(Stage("knnClassifier", "NearestNeighbor", "test", "predictions",
                    props={"training.data.path": "@train",
                           "class.condition.weighted": "true",
                           "bayesian.model.file.path": "@bayes_model"},
                    uses=("bayes_model",)))
    else:
        p.add(Stage("knnClassifier", "NearestNeighbor", "test", "predictions",
                    props={"training.data.path": "@train"}))
    return p


def decision_tree_pipeline(workspace: str, conf: JobConfig,
                           data_path: str) -> Pipeline:
    """The SplitGenerator/DataPartitioner runbook as one stage (the in-memory
    frontier loop) plus the per-level artifacts for parity inspection."""
    p = Pipeline(workspace, conf)
    p.bind("data", data_path)
    p.add(Stage("splitGenerator", "ClassPartitionGenerator", "data", "splits"))
    p.add(Stage("treeBuilder", "DecisionTreeBuilder", "data", "tree"))
    return p
