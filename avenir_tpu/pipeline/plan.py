"""PlanGraft — compile the conf-declared pipeline DAG into one device program.

The driver executes a pipeline as a Python loop over stages with host hops
between them; round 7's SharedScan fuses only *consecutive* count stages,
and PackGraft (round 16) packs tables only within such a group.  This
module treats the declared DAG as a query plan instead: :func:`plan_pipeline`
lowers a whole train→select→score pipeline into an ordered list of plan
units, where every fusable count stage over the same artifact — adjacent or
not — rides ONE scan unit (one parse+encode+gram pass), and four rewrites
fire per unit:

- **fuse** — non-adjacent fusable stages over the same input collapse into
  one scan unit (the driver's ``_scan_group`` stops at the first
  non-fusable stage; the planner hoists past it when dependency-safe);
- **share-gram** — a stage whose ``uses`` edge names another member's
  output joins the same unit and reads the SAME gram (the edge is
  ordering-only: fusable consumers are constructed from conf+schema, never
  from a data artifact, and outputs are written at finalize in declared
  order).  A ``@artifact`` property reference is a *value* dependency and
  keeps the stage staged;
- **prune** — dead binned columns (columns no member's output depends on)
  are dropped from the fold; correlation statistics slice each pair to its
  true ``n_bins`` support, so the narrower gram reproduces the same output
  bytes;
- **pack** — the PackGraft packed-vs-einsum choice is made at *plan* time:
  both candidates are compiled ahead of time over a peeked sample chunk
  (the PR-9 CompiledProgramRegistry's ``profile.aot_cost`` records their
  estimates) and ONE measured dispatch of each picks the faster program,
  instead of the runtime width heuristic alone;

plus **encode-once**: scan units reading the same artifact under the same
encode keys share one whole-input ``EncodedDataset`` through an encode
cache (``scan.run_fused_stages``'s ``encode_cache`` seam).

Checkpointed / multi-process / text-mode / opted-out stages fall back to
staged execution (:class:`StageUnit`) with the refusal reason surfaced in
``plan explain``, exactly as ``_scan_group`` fusion refuses them today;
resume-satisfied stages are pruned from the plan (:class:`SkipUnit`) and
journaled per stage without clobbering a partial run's counters.

Byte-identity to the staged path is the oracle (tests/test_plan.py): a
planned run's artifacts are bit-for-bit the staged run's, for every
rewrite, on both the kernel and einsum routings.

``python -m avenir_tpu.pipeline plan <conf>`` prints :meth:`PipelinePlan.
explain` — the fused plan tree with per-node cost estimates and which
rewrites fired.  ``plan.on=true`` routes ``Pipeline.run`` through
:func:`run_plan` (default off).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from avenir_tpu.core.config import JobConfig
from avenir_tpu.pipeline.driver import Pipeline, Stage

REWRITES = ("fuse", "share-gram", "prune", "encode-once", "pack")


@dataclass
class SkipUnit:
    """A resume-satisfied stage: pruned from the plan, journaled as
    ``stage.skipped`` at execution without touching its counters."""

    stage: Stage


@dataclass
class StageUnit:
    """A stage the planner keeps on the staged path, and why."""

    stage: Stage
    conf: JobConfig
    reason: str


@dataclass
class ScanUnit:
    """One planned SharedScan serving one or more stages."""

    stages: List[Stage]
    confs: List[JobConfig]
    input: str                              # artifact name
    in_path: str
    rewrites: List[str] = field(default_factory=list)
    keep: Optional[List[int]] = None        # pruned binned positions
    pruned_from: int = 0                    # full binned width
    pack_on: Optional[bool] = None          # None = runtime heuristic
    pack_max_width: Optional[int] = None
    pack_source: str = ""                   # "measured" | "aot" | "model" | ""
    cost: Optional[dict] = None             # AOT estimate over the sample
    cost_rows: int = 0                      # sample rows the estimate covers
    wall_ms: Optional[float] = None         # measured sample-chunk dispatch
    program: str = ""                       # predicted routing tag
    staged_scans: int = 1                   # scans the staged path would pay


class PipelinePlan:
    """The ordered unit list :func:`plan_pipeline` produced, with the
    explain rendering and the ``plan.compiled`` journal summary."""

    def __init__(self, pipeline: Pipeline, units: List[object],
                 resume: bool):
        self.pipeline = pipeline
        self.units = units
        self.resume = resume

    @property
    def scan_units(self) -> List[ScanUnit]:
        return [u for u in self.units if isinstance(u, ScanUnit)]

    def summary(self) -> dict:
        """The ``plan.compiled`` event payload: unit/stage shape, which
        rewrites fired anywhere, and the summed cost estimate (null when
        the backend degraded to shapes-only)."""
        scans = self.scan_units
        stages = sum(len(u.stages) for u in scans) + sum(
            1 for u in self.units if not isinstance(u, ScanUnit))
        rewrites = sorted({r for u in scans for r in u.rewrites})

        def total(key: str) -> Optional[float]:
            vals = [u.cost.get(key) for u in scans if u.cost]
            vals = [v for v in vals if v is not None]
            return float(sum(vals)) if vals else None

        ranks = {"measured": 3, "aot": 2, "model": 1}
        best = max((ranks.get(u.pack_source, 0) for u in scans), default=0)
        source = {3: "measured", 2: "aot", 1: "model", 0: "none"}[best]
        return {"units": len(self.units), "stages": stages,
                "fused": sum(len(u.stages) for u in scans),
                "rewrites": rewrites, "source": source,
                "est_flops": total("flops"),
                "est_bytes": total("bytes_accessed")}

    def explain(self) -> str:
        """The fused plan tree: one node per unit, member stages beneath,
        per-node cost estimates and the rewrites that fired."""
        lines = []
        scans = self.scan_units
        lines.append(
            f"PlanGraft: {sum(len(u.stages) for u in scans) + sum(1 for u in self.units if not isinstance(u, ScanUnit))}"
            f" stage(s) -> {len(self.units)} unit(s)"
            + (" [resume]" if self.resume else ""))
        last = len(self.units) - 1
        for k, unit in enumerate(self.units):
            head = "`-" if k == last else "|-"
            bar = "  " if k == last else "| "
            if isinstance(unit, SkipUnit):
                lines.append(f"{head} skip {unit.stage.name}: output exists"
                             f" (resume)")
                continue
            if isinstance(unit, StageUnit):
                job = (unit.stage.job if isinstance(unit.stage.job, str)
                       else getattr(unit.stage.job, "__name__", "callable"))
                lines.append(f"{head} stage {unit.stage.name}: job={job} -- "
                             f"{unit.reason}")
                continue
            lines.append(
                f"{head} scan unit: input={unit.input} serves "
                f"{len(unit.stages)} stage(s) in 1 scan"
                + (f" (staged path ~ {unit.staged_scans} scans)"
                   if len(unit.stages) > 1 else ""))
            if unit.rewrites:
                lines.append(f"{bar}   rewrites: "
                             + ", ".join(unit.rewrites))
            if unit.keep is not None:
                lines.append(f"{bar}   prune: {unit.pruned_from} -> "
                             f"{len(unit.keep)} binned columns")
            detail = f"{bar}   program: {unit.program or '?'}"
            if unit.cost is not None:
                detail += " -- est " + _fmt_cost(unit.cost, unit.cost_rows)
                if unit.wall_ms is not None:
                    detail += f", predicted {unit.wall_ms:.2f} ms/chunk"
                detail += f" ({unit.pack_source or 'aot'})"
            elif unit.pack_source:
                detail += f" -- est unavailable ({unit.pack_source})"
            lines.append(detail)
            for m, s in enumerate(unit.stages):
                sub = "`-" if m == len(unit.stages) - 1 else "|-"
                lines.append(f"{bar}   {sub} {s.name} ({s.job}) -> "
                             f"{s.output}")
        return "\n".join(lines)


def _fmt_cost(cost: dict, rows: int) -> str:
    parts = []
    if cost.get("flops") is not None:
        parts.append(f"{cost['flops'] / 1e6:.3f} MFLOP")
    if cost.get("bytes_accessed") is not None:
        parts.append(f"{cost['bytes_accessed'] / 1e6:.3f} MB")
    body = " / ".join(parts) if parts else "n/a"
    return f"{body} per {rows}-row sample chunk"


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------

def _join_shares(pipeline: Pipeline, cand: Stage, producers: Dict[str, Stage],
                 taken: set, member_names: set, member_outs: set,
                 stages: List[Stage], i: int, j: int, in_path: str
                 ) -> Optional[List[str]]:
    """Can ``cand`` (position ``j``) join the unit anchored at ``i``?
    Returns the member outputs it reaches via ``uses`` (share-gram edges),
    or None when joining would reorder a real dependency:

    - a ``@artifact`` property naming a member output is a *value*
      dependency — the stage reads the file's contents, which do not exist
      until the unit finalizes;
    - any dependency produced by a stage not yet scheduled (it would run
      AFTER this unit) refuses the hoist;
    - an unclaimed stage between the anchor and the candidate that rewrites
      the shared input (or the candidate's own output) would observe a
      different file under the hoisted order."""
    shares: List[str] = []
    prop_arts = [v[1:] for v in cand.props.values()
                 if isinstance(v, str) and v.startswith("@")]
    for art in prop_arts:
        if art in member_outs:
            return None
        prod = producers.get(art)
        if prod is not None and prod.name not in taken \
                and prod.name not in member_names:
            return None
    for art in cand.uses:
        if art in member_outs:
            shares.append(art)
            continue
        prod = producers.get(art)
        if prod is not None and prod.name not in taken \
                and prod.name not in member_names:
            return None
    for k in range(i + 1, j):
        mid = stages[k]
        if mid.name in taken or mid.name in member_names:
            continue
        if pipeline.path(mid.output) == in_path \
                or mid.output == cand.output:
            return None
    return shares


def _peek_sample(conf: JobConfig, in_path: str, rows: int):
    """``(EncodedDataset, estimated total rows)`` from the head of
    ``in_path`` — shape-true metadata for cost estimation, plus a
    bytes-per-row extrapolation of the file's row count (the wall model
    evaluates candidates at the ACTUAL chunk size, not the sample's).
    None when the input does not exist yet (an artifact a prior stage
    will produce) or cannot be parsed; the plan then records
    model-derived estimates only."""
    from avenir_tpu.jobs.base import Job

    if rows <= 0 or not in_path or not os.path.isfile(in_path):
        return None
    enc = Job.encoder_for(conf)
    delim = conf.field_delim_regex
    parsed: List[List[str]] = []
    consumed = 0
    try:
        with open(in_path, "r", errors="replace") as fh:
            for line in fh:
                consumed += len(line)
                line = line.rstrip("\n")
                if not line.strip():
                    continue
                parsed.append(re.split(delim, line))
                if len(parsed) >= rows:
                    break
    except OSError:
        return None
    ncols = enc.max_ordinal()
    parsed = [r for r in parsed if len(r) > ncols]
    if not parsed:
        return None
    est_rows = max(
        int(os.path.getsize(in_path) * len(parsed) / max(consumed, 1)),
        len(parsed))
    width = min(len(r) for r in parsed)
    try:
        ds = enc.fit_transform(
            np.asarray([r[:width] for r in parsed], dtype=object))
    except Exception:
        return None
    return ds, est_rows


def _sum_costs(parts: List[Optional[dict]]) -> Optional[dict]:
    if not parts or any(p is None for p in parts):
        return None
    out: dict = {}
    for key in ("flops", "bytes_accessed", "output_bytes", "temp_bytes"):
        vals = [p.get(key) for p in parts]
        out[key] = (None if any(v is None for v in vals)
                    else float(sum(vals)))
    return out


def _score(cost: Optional[dict]) -> Optional[float]:
    """One comparable scalar per candidate program: compute plus traffic
    (a crude roofline sum — both terms cost wall time; either alone can
    be zero on a backend that reports only the other)."""
    if cost is None:
        return None
    flops, by = cost.get("flops"), cost.get("bytes_accessed")
    if flops is None and by is None:
        return None
    return float(flops or 0.0) + float(by or 0.0)


# AOT estimates are pure in (program, operand shapes) — memoized process-
# wide so re-planning the same pipeline (a resumed run, the benchmark's
# best-of passes) pays XLA's lower+compile once, like the jit cache
_AOT_CACHE: Dict[tuple, Optional[dict]] = {}


def _shape_sig(args, kwargs) -> tuple:
    sig = []
    for a in args:
        if hasattr(a, "shape"):
            sig.append((tuple(a.shape), str(a.dtype)))
        else:
            sig.append(repr(a))
    return (tuple(sig), tuple(sorted((kwargs or {}).items())))


def _cached_aot(tag: str, lowerable, args=(), kwargs=None
                ) -> Optional[dict]:
    from avenir_tpu.telemetry import profile as _profile

    key = (tag, _shape_sig(args, kwargs))
    if key not in _AOT_CACHE:
        _AOT_CACHE[key] = _profile.aot_cost(lowerable, args, kwargs)
    return _AOT_CACHE[key]


# Measured sample-chunk walls, same key discipline.  The AOT *cost model*
# cannot rank packed-vs-einsum on real hardware: the packed gram is one
# dense matmul (huge nominal flops, near-peak execution) while the einsum
# family is many scatter-shaped dispatches (tiny nominal flops, dispatch-
# and memory-bound) — flops+bytes anti-correlates with wall between the
# two styles.  So the selection dispatches each ahead-of-time-compiled
# candidate ONCE over the peeked sample and compares measured wall; the
# AOT estimates still ride the plan (journal + explain) as the portable
# cost record.
_WALL_CACHE: Dict[tuple, Optional[float]] = {}


def _measured_ms(tag: str, fn, args, kwargs=None) -> Optional[float]:
    import time

    import jax

    key = (tag, _shape_sig(args, kwargs))
    if key in _WALL_CACHE:
        return _WALL_CACHE[key]
    kw = kwargs or {}
    try:
        jax.block_until_ready(fn(*args, **kw))      # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            # the sync IS the measurement: this is a plan-time timing
            # probe, so each dispatch must drain before the clock reads
            jax.block_until_ready(fn(*args, **kw))  # graftlint: disable=GL005
            best = min(best, time.perf_counter() - t0)
        _WALL_CACHE[key] = best * 1000.0
    except Exception:
        _WALL_CACHE[key] = None
    return _WALL_CACHE[key]


def _einsum_wall_ms(folder, ds) -> Optional[float]:
    """Measured wall of the per-table einsum family over the sample —
    the same component programs :func:`_einsum_cost` lowers."""
    from avenir_tpu.ops import agg

    walls = [_measured_ms("class_counts", agg.class_counts, (ds.labels,),
                          {"num_classes": folder.c})]
    if folder.needs_counts:
        walls.append(_measured_ms(
            "feature_class_counts",
            agg.feature_class_counts, (ds.codes, ds.labels),
            {"num_classes": folder.c, "num_bins": folder.b}))
        npairs = len(folder.pair_index)
        if npairs:
            sl = folder.pair_index[:min(folder.pair_chunk, npairs)]
            one = _measured_ms(
                "pair_class_counts", agg.pair_class_counts,
                (ds.codes[:, sl[:, 0]], ds.codes[:, sl[:, 1]], ds.labels),
                {"num_classes": folder.c, "num_bins": folder.b})
            walls.append(None if one is None else one * (npairs / len(sl)))
    if folder.needs_moments:
        walls.append(_measured_ms("class_moments", agg.class_moments,
                                  (ds.cont, ds.labels),
                                  {"num_classes": folder.c}))
    if any(w is None for w in walls):
        return None
    return float(sum(walls))


def _probe_wall_ms(folder, ds) -> Optional[float]:
    probe = folder.cost_probe(ds)
    if probe is None:
        return None
    return _measured_ms(folder.program_tag, probe[0], probe[1])


def _einsum_cost(folder, ds) -> Optional[dict]:
    """The summed AOT estimate of the per-table einsum family one chunk
    dispatches — class counts + [F, B, C] + the pair-chunk series (one
    representative slice lowered, scaled to the union) + moments."""
    from avenir_tpu.ops import agg

    parts = [_cached_aot("class_counts", agg.class_counts, (ds.labels,),
                         {"num_classes": folder.c})]
    if folder.needs_counts:
        parts.append(_cached_aot(
            "feature_class_counts",
            agg.feature_class_counts, (ds.codes, ds.labels),
            {"num_classes": folder.c, "num_bins": folder.b}))
        npairs = len(folder.pair_index)
        if npairs:
            sl = folder.pair_index[:min(folder.pair_chunk, npairs)]
            one = _cached_aot(
                "pair_class_counts", agg.pair_class_counts,
                (ds.codes[:, sl[:, 0]], ds.codes[:, sl[:, 1]], ds.labels),
                {"num_classes": folder.c, "num_bins": folder.b})
            if one is None:
                return None
            scale = npairs / len(sl)
            one = {k: (v * scale if isinstance(v, (int, float)) else v)
                   for k, v in one.items()}
            parts.append(one)
    if folder.needs_moments:
        parts.append(_cached_aot("class_moments", agg.class_moments,
                                 (ds.cont, ds.labels),
                                 {"num_classes": folder.c}))
    return _sum_costs(parts)


def _probe_cost(folder, ds, site: str) -> Optional[dict]:
    """AOT cost of a single-dispatch routing via the folder's own cost
    probe, registered with the CompiledProgramRegistry when profiling is
    on (the plan's candidates become ``program.compiled`` records)."""
    from avenir_tpu.telemetry import profile as _profile
    from avenir_tpu.telemetry import spans as tel

    probe = folder.cost_probe(ds)
    if probe is None:
        return None
    prof = _profile.profiler()
    if prof.enabled:
        key = tel.CompileKeyMonitor.shape_key(ds.codes, ds.labels, ds.cont
                                              ) + (folder.program_tag,)
        prof.observe(key, site=site, lowerable=probe[0], args=probe[1])
    return _cached_aot(folder.program_tag, probe[0], probe[1])


def _estimate(unit: ScanUnit, schema, enc, peek) -> None:
    """Fill the unit's predicted routing + cost, and make the PackGraft
    selection at plan time: compile the packed gram and the einsum family
    ahead of time over the peeked sample, measure one dispatch of each,
    and choose the faster program (the AOT estimates ride the plan as the
    portable cost record; the raw flops+bytes score is only the fallback
    ranking — see ``_WALL_CACHE``).  Falls back to the runtime width
    heuristic (``pack_on=None``, source "model") when neither measurement
    nor AOT analysis is available, or no sample exists."""
    from avenir_tpu.jobs.base import Job
    from avenir_tpu.parallel.shard import ShardSpec
    from avenir_tpu.pipeline import scan

    conf = unit.confs[0]
    if ShardSpec.requested(conf):
        unit.program = "shard"
        return
    mesh = Job.auto_mesh(conf)
    if peek is None:
        unit.program = "sharded" if mesh is not None else unit.program
        unit.pack_source = "model"
        return
    sample, est_rows = peek
    chunk_rows = conf.get_int("stream.chunk.rows", 0) or est_rows
    view = (sample if unit.keep is None
            else scan.pruned_view(sample, np.asarray(unit.keep, np.int64)))
    consumers = [scan.stage_consumer(s.name, s.job, c, "", schema, enc,
                                     keep=unit.keep)[0]
                 for s, c in zip(unit.stages, unit.confs)]
    pmw = conf.get_int("scan.pack.max.width", 0) or None
    base = scan.ChunkFolder(consumers, view, pack_on=False,
                            pack_max_width=pmw)
    unit.cost_rows = view.num_rows
    if mesh is not None:
        # auto data-parallel mesh: the per-device program is the same
        # einsum family (pack requires a single device) — estimate the
        # per-chunk work, leave the pack question to nobody
        unit.program = "sharded"
        unit.cost = _einsum_cost(base, view)
        unit.pack_source = "aot" if unit.cost is not None else "model"
        return
    if base.step != "einsum":
        # kernel / moments-only: a single program with no pack question
        unit.cost = _probe_cost(base, view, "plan.candidate")
        unit.program = base.program_tag or "moments"
        unit.pack_source = "aot" if unit.cost is not None else "model"
        return
    packed = None
    if conf.get_bool("scan.pack.on", True):
        packed = scan.ChunkFolder(consumers, view, pack_on=True,
                                  pack_max_width=pmw)
        if packed.step != "packed":
            packed = None           # the pack planner found no viable pack
    cost_e = _einsum_cost(base, view)
    cost_p = (_probe_cost(packed, view, "plan.candidate")
              if packed is not None else None)
    if packed is not None:
        # primary selection: measured dispatches at two sample sizes fit
        # a per-candidate wall(N) = a + b*N line, evaluated at the run's
        # ACTUAL chunk size — the packed gram trades a large fixed
        # dispatch (b*W^2 work per row is tiny, the intercept is not)
        # against the einsum family's many small dispatches, so the
        # ranking flips with N and a sample-sized comparison misleads
        n = view.num_rows
        n_small = max(min(n // 8, n - 1), 1)
        small = view.slice(0, n_small) if n_small < n else None

        def predicted(wall_fn, folder):
            w1 = wall_fn(folder, view)
            if w1 is None:
                return None
            if small is None or chunk_rows <= n:
                return w1
            w0 = wall_fn(folder, small)
            if w0 is None:
                return w1 * chunk_rows / n
            b = (w1 - w0) / (n - n_small)
            a = max(w1 - b * n, 0.0)
            return a + max(b, 0.0) * chunk_rows

        wall_e = predicted(_einsum_wall_ms, base)
        wall_p = predicted(_probe_wall_ms, packed)
        if wall_e is not None and wall_p is not None:
            choose_packed = wall_p <= wall_e
            unit.pack_source = "measured"
            unit.pack_on = choose_packed
            unit.cost = cost_p if choose_packed else cost_e
            unit.wall_ms = wall_p if choose_packed else wall_e
            unit.program = (packed.program_tag if choose_packed
                            else base.program_tag)
            if choose_packed:
                unit.rewrites.append("pack")
            return
    if packed is None:
        # no pack candidate (opt-out, or no viable pack plan) — the
        # einsum family is the program; record its estimate
        unit.pack_source = "aot" if cost_e is not None else "model"
        unit.cost = cost_e
        unit.program = base.program_tag
        return
    se, sp = _score(cost_e), _score(cost_p)
    if se is not None and sp is not None:
        choose_packed = sp <= se
        unit.pack_source = "aot"
        unit.pack_on = choose_packed
        unit.cost = cost_p if choose_packed else cost_e
        unit.program = (packed.program_tag if choose_packed
                        else base.program_tag)
        if choose_packed:
            unit.rewrites.append("pack")
        return
    # AOT degraded to shapes-only — defer to the runtime width heuristic,
    # which packs exactly when pack_tables found a plan
    unit.pack_source = "model"
    unit.pack_on = None
    unit.cost = cost_p if cost_p is not None else cost_e
    unit.program = (packed.program_tag if packed is not None
                    else base.program_tag)
    if packed is not None:
        unit.rewrites.append("pack")


def plan_pipeline(pipeline: Pipeline,
                  todo: Optional[Sequence[Stage]] = None,
                  resume: bool = False) -> PipelinePlan:
    """Lower a pipeline's declared stage DAG into an ordered unit list.

    Greedy over declared order: each unclaimed fusable stage anchors a
    scan unit and pulls in every later dependency-safe fusable stage over
    the same input artifact (``_join_shares``); non-fusable stages become
    staged fallbacks with their refusal reason; under ``resume``,
    satisfied stages become skip units.  Per scan unit the planner then
    computes the dead-column set, the encode-once cache key, and the
    AOT-costed pack selection over a peeked sample chunk
    (``plan.peek.rows``, default 512)."""
    from avenir_tpu.jobs.base import Job
    from avenir_tpu.parallel.shard import ShardSpec
    from avenir_tpu.pipeline import scan

    stages = list(todo) if todo is not None else list(pipeline.stages)
    confs = {s.name: pipeline._stage_conf(s) for s in stages}
    producers = {s.output: s for s in stages}
    pos = {s.name: k for k, s in enumerate(stages)}
    units: List[object] = []
    taken: set = set()
    encode_seen: set = set()
    samples: Dict[str, object] = {}
    for i, s in enumerate(stages):
        if s.name in taken:
            continue
        conf = confs[s.name]
        if resume and os.path.exists(pipeline.path(s.output)):
            units.append(SkipUnit(stage=s))
            taken.add(s.name)
            continue
        reason = scan.fuse_refusal(s.job, conf)
        if reason is not None:
            units.append(StageUnit(stage=s, conf=conf, reason=reason))
            taken.add(s.name)
            continue
        in_path = pipeline.path(s.input)
        members, mconfs = [s], [conf]
        member_names, member_outs = {s.name}, {s.output}
        shares: List[str] = []
        for j in range(i + 1, len(stages)):
            c = stages[j]
            if c.name in taken or c.name in member_names:
                continue
            if resume and os.path.exists(pipeline.path(c.output)):
                continue           # becomes a SkipUnit at its own slot
            if pipeline.path(c.input) != in_path:
                continue
            cconf = confs[c.name]
            if scan.fuse_refusal(c.job, cconf) is not None:
                continue
            if not scan.stages_compatible([mconfs[0], cconf]):
                continue
            share = _join_shares(pipeline, c, producers, taken,
                                 member_names, member_outs, stages, i, j,
                                 in_path)
            if share is None:
                continue
            members.append(c)
            mconfs.append(cconf)
            member_names.add(c.name)
            member_outs.add(c.output)
            shares.extend(share)
        if not scan.stages_compatible(mconfs[:1]):
            # schema unloadable or no class attribute — the SharedScan
            # cannot serve even a singleton; keep the staged job path
            units.append(StageUnit(stage=s, conf=conf,
                                   reason="scan-incompatible conf "
                                          "(schema/class attribute)"))
            taken.add(s.name)
            continue
        unit = ScanUnit(stages=members, confs=mconfs, input=s.input,
                        in_path=in_path)
        if len(members) > 1:
            unit.rewrites.append("fuse")
        if shares:
            unit.rewrites.append("share-gram")
        # dead-column pruning: the union of binned columns any member's
        # output depends on; None (NB/MI — every column) blocks the rewrite
        schema = Job.load_schema(mconfs[0])
        enc = Job.encoder_for(mconfs[0])
        f = len(enc.binned_fields)
        needed: Optional[set] = set()
        for m, mc in zip(members, mconfs):
            cons, _w = scan.stage_consumer(m.name, m.job, mc, "", schema,
                                           enc)
            cols = scan.consumer_columns(cons, f)
            if cols is None:
                needed = None
                break
            needed |= cols
        if needed is not None and needed and len(needed) < f:
            unit.keep = sorted(needed)
            unit.pruned_from = f
            unit.rewrites.append("prune")
        # a singleton with no prune win and no shard topology runs its
        # standalone job byte-identically — keep the staged path (same
        # rule as the driver's _scan_group singleton gate)
        if len(members) == 1 and unit.keep is None \
                and not ShardSpec.requested(conf):
            units.append(StageUnit(stage=s, conf=conf,
                                   reason="singleton scan -- staged path "
                                          "is identical"))
            taken.add(s.name)
            continue
        mconf = mconfs[0]
        if not mconf.get("stream.chunk.rows") \
                and not ShardSpec.requested(mconf):
            ekey = ((in_path,)
                    + tuple(mconf.get(k) for k in scan._ENCODE_KEYS))
            if ekey in encode_seen:
                unit.rewrites.append("encode-once")
            encode_seen.add(ekey)
        ps = sorted(pos[m.name] for m in members)
        unit.staged_scans = 1 + sum(1 for a, b in zip(ps, ps[1:])
                                    if b != a + 1)
        if in_path not in samples:
            samples[in_path] = _peek_sample(
                mconf, in_path, mconf.get_int("plan.peek.rows", 2048))
        _estimate(unit, schema, enc, samples[in_path])
        units.append(unit)
        taken.update(member_names)
    return PipelinePlan(pipeline, units, resume)


# ---------------------------------------------------------------------------
# execution + journal
# ---------------------------------------------------------------------------

def journal_plan(summary: dict, tracer=None) -> None:
    """One golden-schema'd ``plan.compiled`` event per planned run — the
    journal's record of what the planner decided before anything executed
    (tests/test_telemetry.py pins the exact key set)."""
    from avenir_tpu.telemetry import spans as tel

    (tracer or tel.tracer()).event("plan.compiled", **summary)


def run_plan(pipeline: Pipeline, plan: PipelinePlan, tracer) -> None:
    """Execute a plan in unit order: skip units journal ``stage.skipped``
    (counters marked in place), staged fallbacks run the normal per-stage
    path, and scan units run through ``scan.run_fused_stages`` with the
    plan's prune/pack decisions — sharing one encode cache across units
    (encode-once) and carrying the plan-node attrs on each fused span."""
    cache: dict = {}
    for k, unit in enumerate(plan.units):
        if isinstance(unit, SkipUnit):
            pipeline._mark_skipped(unit.stage, tracer)
        elif isinstance(unit, StageUnit):
            pipeline._run_single(unit.stage, unit.conf, tracer)
        else:
            extra = {"planned": True, "unit": k,
                     "rewrites": list(unit.rewrites)}
            if unit.program:
                extra["plan.program"] = unit.program
            pipeline._run_fused(
                unit.stages, unit.confs, tracer, extra_attrs=extra,
                prune=unit.keep, pack_on=unit.pack_on,
                pack_max_width=unit.pack_max_width, encode_cache=cache)
