"""Minimal RESP (REdis Serialization Protocol) client — stdlib sockets only.

The reference's streaming stack talks to Redis through Jedis
(reinforce/RedisSpout.java:70-74, RedisActionWriter.java:46-49,
RedisRewardReader.java:72-86: ``rpop`` events, ``lpush`` actions, reward-list
reads). This image has no ``redis`` package, and the framework must not grow
dependencies for one transport — RESP is a ~100-line protocol, so the client
is implemented directly. Covers RESP2 reply types (simple string, error,
integer, bulk string, array), which is everything the list commands use.
"""

from __future__ import annotations

import socket
from typing import List, Optional, Union


class RespError(RuntimeError):
    """Server-reported error reply (RESP ``-ERR ...``)."""


class RespClient:
    """One blocking connection; thread-compat like Jedis (one per thread)."""

    def __init__(self, host: str = "localhost", port: int = 6379,
                 db: int = 0, timeout: float = 5.0):
        self._addr = (host, port)
        self._timeout = timeout
        self._db = db
        self.reconnects = 0              # transport faults absorbed so far
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""
        if db:
            self._exchange(("SELECT", db))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _reconnect(self) -> None:
        self.close()
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)
        self._buf = b""
        self.reconnects += 1
        if self._db:
            self._exchange(("SELECT", self._db))

    # -- protocol ------------------------------------------------------------
    def _exchange(self, args):
        parts = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            parts.append(b"$%d\r\n%s\r\n" % (len(b), b))
        self._sock.sendall(b"".join(parts))
        return self._read_reply()

    def command(self, *args: Union[str, bytes, int, float],
                retry: bool = True):
        """Send one command as a RESP array of bulk strings; return the
        decoded reply (str | int | None | list, recursively).

        Survives ONE transient transport fault per call (server restart,
        idle-connection reap): any ``ConnectionError`` — ``BrokenPipeError``
        / ``ConnectionResetError`` on send, or the clean-close error the
        reply reader raises — triggers a reconnect and a single resend.
        Caveat the caller owns: if the fault hit AFTER the server executed
        the command (reply lost in flight), the resend makes delivery
        at-least-once — the same trade Jedis' reconnect-on-retry makes.
        Pass ``retry=False`` for writes where a duplicate is worse than a
        surfaced fault (e.g. non-idempotent LPUSH into an exactly-once
        pipeline)."""
        try:
            return self._exchange(args)
        except ConnectionError:
            if not retry:
                raise
            self._reconnect()
            return self._exchange(args)

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:          # payload + trailing CRLF
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RespError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return None if n == -1 else self._read_exact(n).decode()
        if t == b"*":
            n = int(rest)
            return None if n == -1 else [self._read_reply() for _ in range(n)]
        raise RespError(f"unknown RESP reply type {line!r}")

    # -- the command surface the streaming stack uses ------------------------
    def ping(self) -> bool:
        return self.command("PING") == "PONG"

    def lpush(self, key: str, value: str) -> int:
        return self.command("LPUSH", key, value)

    def rpop(self, key: str) -> Optional[str]:
        return self.command("RPOP", key)

    def rpop_count(self, key: str, count: int) -> Optional[List[str]]:
        """Batched ``RPOP key count`` (redis ≥ 6.2); RespError if unsupported."""
        return self.command("RPOP", key, count)

    def llen(self, key: str) -> int:
        return self.command("LLEN", key)

    def lindex(self, key: str, index: int) -> Optional[str]:
        return self.command("LINDEX", key, index)

    def delete(self, key: str) -> int:
        return self.command("DEL", key)


class RedisListQueue:
    """The push/pop queue surface (same as InProcQueue) over one Redis list:
    ``push`` = LPUSH, ``pop`` = RPOP — the exact verbs of the reference's
    spout/writer pair, so simulators written against either side match."""

    def __init__(self, name: str, client: Optional[RespClient] = None,
                 host: str = "localhost", port: int = 6379, db: int = 0):
        self.name = name
        self.client = client or RespClient(host, port, db=db)
        self._batch_pop = True          # downgraded on first unsupported RPOP count

    def push(self, msg: str) -> None:
        self.client.lpush(self.name, msg)

    def pop(self) -> Optional[str]:
        return self.client.rpop(self.name)

    def drain(self) -> List[str]:
        """Empty the list. Batched (one round-trip per 128 messages) on
        redis ≥ 6.2; falls back to one RPOP per message on older servers —
        this sits on the serving loop's per-event path."""
        out: List[str] = []
        while self._batch_pop:
            try:
                batch = self.client.rpop_count(self.name, 128)
            except RespError:
                self._batch_pop = False
                break
            if batch is None:
                return out
            out.extend(batch)
            if len(batch) < 128:
                return out
        while True:
            msg = self.pop()
            if msg is None:
                return out
            out.append(msg)

    def __len__(self) -> int:
        return self.client.llen(self.name)
