"""SharedScan engine — one encode+gram pass serving every contingency-table job.

The reference runs one MapReduce Tool per statistic: BayesianDistribution,
MutualInformation and CategoricalCorrelation are separate jobs that each
rescan the same HDFS dataset.  The port inherited that shape — each
estimator's ``fit`` re-parsed, re-encoded, re-uploaded and re-aggregated the
same chunks, so a churn/readmission pipeline paid K scans for one scan's
worth of information.  This module collapses the K scans into one:

- ONE chunk stream (native parse → encode → ``DeviceFeeder`` staging, once,
  via the jobs' existing ``encoded_data_source``);
- ONE device pass per chunk: the fused int8-MXU co-occurrence gram G
  (``ops/pallas_hist``), with the class-conditional continuous moments of
  the same resident chunk folded into the SAME dispatch
  (``pallas_hist.gram_moments``) when any consumer wants them;
- 64-bit host accumulation keyed by the existing layout-qualified
  ``g_key`` scheme, exactly like the standalone fast paths;
- at end of stream, each registered consumer is finalized from the shared
  tables through the models' ``from_counts`` constructors — NB's [F, B, C]
  table is G's diagonal block, MI's pair tensors are
  ``counts_from_cooc``, Cramér/heterogeneity contingency tables are the
  class-summed pair read-out (or the [F, B, C] block against the class),
  and Fisher/NumericalAttrStats statistics reduce from the fused moments.

Consumers are byte-identical to running each estimator's own ``fit`` over
the same chunks (tests/test_scan.py), on both the kernel and the einsum
fallback paths.

Row-validity contract: rows whose label is out of range drop out of EVERY
table (the NB/MI drop-invalid contract).  A *standalone* pair-mode Cramér
run counts such rows (its one-class gram ignores labels), so fused
semantics match the standalone jobs only for fully-labeled streams — which
is what the fusable jobs already require.

``pipeline/driver.py`` fuses consecutive pipeline stages that read the same
artifact with a compatible schema into one SharedScan stage
(``scan.fuse=false`` opts a stage — or a whole pipeline — out).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from avenir_tpu.core.encoding import EncodedDataset, peek_chunks
from avenir_tpu.ops import agg
from avenir_tpu.utils.metrics import Counters


class ScanError(ValueError):
    """A SharedScan configuration the engine cannot serve."""


class ScanTables:
    """The shared per-stream totals every consumer finalizes from."""

    def __init__(self, meta: EncodedDataset, rows: int,
                 class_counts: np.ndarray,
                 fbc: Optional[np.ndarray],
                 pair_index: np.ndarray,
                 pcc: Optional[np.ndarray],
                 moments: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]):
        self.meta = meta                      # first-chunk shape metadata
        self.rows = rows
        self.class_counts = class_counts      # [C] int64
        self.fbc = fbc                        # [F, B, C] int64 or None
        self.pair_index = pair_index          # [P, 2] all i<j binned pairs
        self.pcc = pcc                        # [P, B, B, C] int64 or None
        self.moments = moments                # (cnt [C], s1 [C,Fc], s2) or None

    def pair_pos(self) -> Dict[Tuple[int, int], int]:
        return {(int(i), int(j)): k
                for k, (i, j) in enumerate(self.pair_index)}


class ScanConsumer:
    """Base consumer: declare what the scan must compute, finalize from
    the shared tables.  ``name`` keys the result in :meth:`SharedScan.run`'s
    output dict (pipeline stages use their stage name)."""

    needs_bin = False          # the [F, B, C] class-conditional table
    needs_pairs = False        # the [P, B, B, C] pair-class tensors
    needs_moments = False      # continuous (count, Σx, Σx²) class moments

    def __init__(self, name: str = ""):
        self.name = name or type(self).__name__

    def required_pairs(self, num_binned: int) -> List[Tuple[int, int]]:
        """The (i, j) i<j feature pairs this consumer reads.  The engine
        aggregates only the UNION across consumers — a correlation stage
        restricted to a few attribute pairs must not drag the all-pairs
        [P, B, B, C] tensor through the einsum fallback."""
        return []

    def finalize(self, tables: ScanTables):
        raise NotImplementedError


class NaiveBayesConsumer(ScanConsumer):
    """NB class-conditional counts are G's [F, B, C] diagonal block; the
    Gaussian moments ride the fused moment op.  Finalizes through
    ``naive_bayes.model_from_counts`` — byte-identical to ``NaiveBayes.fit``."""

    needs_bin = True
    needs_moments = True

    def __init__(self, laplace: float = 1.0, name: str = ""):
        super().__init__(name)
        self.laplace = laplace

    def finalize(self, t: ScanTables):
        from avenir_tpu.models import naive_bayes as nb

        mom = t.moments
        return nb.model_from_counts(
            class_values=list(t.meta.class_values),
            n_bins=np.asarray(t.meta.n_bins, np.int64),
            bin_counts=t.fbc,
            class_counts=t.class_counts,
            cont_count=mom[0] if mom is not None else None,
            cont_sum=mom[1] if mom is not None else None,
            cont_sumsq=mom[2] if mom is not None else None,
            laplace=self.laplace,
        )


class MutualInfoConsumer(ScanConsumer):
    """All seven MI distribution families from the shared [F, B, C] and
    [P, B, B, C] tensors — ``mutual_info.result_from_counts``."""

    needs_bin = True
    needs_pairs = True

    def __init__(self, feature_names: Optional[Sequence[str]] = None,
                 name: str = ""):
        super().__init__(name)
        self.feature_names = feature_names

    def required_pairs(self, num_binned: int) -> List[Tuple[int, int]]:
        return [(i, j) for i in range(num_binned)
                for j in range(i + 1, num_binned)]

    def finalize(self, t: ScanTables):
        from avenir_tpu.models import mutual_info as mi

        meta = t.meta
        f, b, c = meta.num_binned, meta.max_bins, meta.num_classes
        names = (list(self.feature_names) if self.feature_names is not None
                 else [f"f{o}" for o in meta.binned_ordinals])
        fbc = t.fbc if t.fbc is not None else np.zeros((f, b, c), np.int64)
        pcc = t.pcc if t.pcc is not None else np.zeros((0, b, b, c), np.int64)
        return mi.result_from_counts(
            feature_names=names,
            class_values=list(meta.class_values),
            n_bins=meta.n_bins,
            class_counts=t.class_counts,
            feature_class_counts=fbc,
            pair_index=t.pair_index,
            pair_class_counts=pcc,
        )


class CorrelationConsumer(ScanConsumer):
    """Cramér / heterogeneity statistics from the shared gram: the
    against-class contingency stack is the [F, B, C] diagonal block, the
    feature-pair stack is the class-summed pair read-out —
    ``correlation.result_from_counts``.  Mirrors the attribute-selection
    contract of ``CategoricalCorrelation.fit``."""

    def __init__(self, algorithm: str = "cramerIndex",
                 src: Optional[Sequence[int]] = None,
                 dst: Optional[Sequence[int]] = None,
                 against_class: bool = False,
                 feature_names: Optional[Sequence[str]] = None,
                 name: str = ""):
        super().__init__(name)
        from avenir_tpu.models.correlation import STATS
        if algorithm not in STATS:
            raise ValueError(
                f"unknown algorithm {algorithm!r}; known: {sorted(STATS)}")
        self.algorithm = algorithm
        self.src = src
        self.dst = dst
        self.against_class = against_class
        self.feature_names = feature_names
        self.needs_bin = against_class
        self.needs_pairs = not against_class

    def _pair_list(self, f: int) -> List[Tuple[int, int]]:
        """The fit contract's (src × dst, i < j) pair selection — the ONE
        construction shared by required_pairs and finalize."""
        src_idx = list(self.src) if self.src is not None else list(range(f))
        dst_idx = list(self.dst) if self.dst is not None else list(range(f))
        return [(i, j) for i in src_idx for j in dst_idx if i < j]

    def required_pairs(self, num_binned: int) -> List[Tuple[int, int]]:
        return [] if self.against_class else self._pair_list(num_binned)

    def finalize(self, t: ScanTables):
        from avenir_tpu.models import correlation as corr

        meta = t.meta
        f, b, c = meta.num_binned, meta.max_bins, meta.num_classes
        names = (list(self.feature_names) if self.feature_names is not None
                 else [f"f{o}" for o in meta.binned_ordinals])
        if self.against_class:
            src_idx = list(self.src) if self.src is not None else list(range(f))
            pairs = [(i, -1) for i in src_idx]
            pair_names = [(names[i], "class") for i in src_idx]
            b_dst = max(b, c)
            cont = np.zeros((len(pairs), b_dst, b_dst),
                            t.fbc.dtype if t.fbc is not None else np.int64)
            if t.fbc is not None:
                cont[:, :b, :c] = t.fbc[src_idx]
        else:
            pairs = self._pair_list(f)
            pair_names = [(names[i], names[j]) for i, j in pairs]
            pos = t.pair_pos()
            if pairs:
                sel = np.array([pos[p] for p in pairs], np.int64)
                cont = t.pcc[sel].sum(axis=-1)           # [P, B, B] int64
            else:
                cont = np.zeros((0, b, b), np.int64)
        return corr.result_from_counts(self.algorithm, pairs, pair_names,
                                       cont, meta.n_bins, meta.num_classes)


class FisherConsumer(ScanConsumer):
    """Univariate Fisher discriminant from the fused continuous moments —
    ``fisher.model_from_moments`` over the same ``class_moments`` sums the
    standalone fit accumulates."""

    needs_moments = True

    def finalize(self, t: ScanTables):
        from avenir_tpu.models import fisher

        if t.moments is None:
            raise ScanError("Fisher consumer requires continuous features")
        cnt, s1, s2 = t.moments
        return fisher.model_from_moments(list(t.meta.class_values),
                                         cnt, s1, s2)


class MomentsConsumer(ScanConsumer):
    """Raw per-class (count, Σx, Σx²) totals of the continuous block — the
    NumericalAttrStats-shaped statistics of the scanned stream, served from
    the same fused moment op without another pass."""

    needs_moments = True

    def finalize(self, t: ScanTables):
        if t.moments is None:
            raise ScanError("Moments consumer requires continuous features")
        return t.moments


class ChunkFolder:
    """One SharedScan chunk pass, factored out for external accumulation.

    Captures the fit-static routing ONCE from the consumer set and the
    stream's shape metadata — the count-path selection (kernel fast path,
    sharded-kernel mesh path, the PackGraft packed gram where the pack
    planner decides one wide dispatch beats the per-table einsums, or the
    einsum fallback: the standalone paths' routing plus the pack tier),
    the layout-qualified gram key,
    the union of required pairs, and the moments flag — then folds any
    number of chunks into *caller-owned* :class:`~avenir_tpu.ops.agg.Accumulator`
    objects.  :class:`SharedScan` folds the whole stream into one
    accumulator; ``stream/windows.py`` folds each pane into its own and
    merges panes per window — windowed results are byte-identical to a
    batch scan over the same rows *because both paths run exactly this
    fold*, not a parallel implementation.
    """

    def __init__(self, consumers: Sequence[ScanConsumer],
                 meta: EncodedDataset, mesh=None, pair_chunk: int = 256,
                 shard=None, counters: Optional[Counters] = None,
                 pack_on: bool = True,
                 pack_max_width: Optional[int] = None):
        from avenir_tpu.ops import pallas_hist

        if not consumers:
            raise ScanError("no consumers registered")
        self.consumers = list(consumers)
        self.meta = meta
        self.shard = shard                # parallel/shard.ShardSpec or None
        self.mesh = shard.mesh if shard is not None else mesh
        self.pair_chunk = pair_chunk
        self.counters = counters          # optional Shard telemetry home
        f, b, c = meta.num_binned, meta.max_bins, meta.num_classes
        self.f, self.b, self.c = f, b, c
        self.needs_counts = any(x.needs_bin or x.needs_pairs
                                for x in self.consumers) and f > 0 and b > 0
        self.needs_moments = any(x.needs_moments
                                 for x in self.consumers) and meta.num_cont > 0
        # union of the pairs any consumer reads, in sorted (i, j) order —
        # for an MI consumer that IS the all-i<j row-major index; a
        # correlation stage restricted to a few pairs aggregates only those
        union = sorted({p for x in self.consumers
                        for p in x.required_pairs(f)})
        self.pair_index = (np.array(union, np.int32).reshape(-1, 2) if union
                           else np.zeros((0, 2), np.int32))
        # count-path routing: single source of truth with the standalone
        # fast paths (MutualInformation.fit / bench.py / e2e_pipeline).
        # An explicit ShardSpec (round 12) takes the fused shard_map+psum
        # dispatch whenever the kernel shape gates pass — interpret-mode
        # off TPU, so the host-mesh tests attest the same program — and
        # falls back to the sharded-einsum path (XLA auto-collectives over
        # the placed batch) for shapes the gram kernel cannot take.
        self.step = self._sharded = self._shard_step = None
        if self.needs_counts:
            if shard is not None and pallas_hist.applicable(f, b, c):
                from avenir_tpu.parallel import collectives
                self._shard_step = collectives.sharded_scan_step(
                    shard.mesh, b, c, data_axis=shard.data_axis,
                    interpret=not pallas_hist.mesh_on_tpu(shard.mesh),
                    quantized=shard.quantized,
                    moments=self.needs_moments,
                    # CrossGraft: a global plan reduces hierarchically —
                    # psum within the host, then the cross-process leg —
                    # inside the SAME fused dispatch
                    proc_axis=shard.proc_axis if shard.is_global else None)
                self.step = "shard"
            elif pallas_hist.use_kernel(f, b, c, mesh=self.mesh):
                self.step = "kernel"
            elif (pallas_hist.applicable(f, b, c)
                    and pallas_hist.mesh_on_tpu(self.mesh)):
                from avenir_tpu.parallel import collectives
                self._sharded = collectives.sharded_cooc_step(self.mesh, b, c)
                self.step = "sharded"
            else:
                self.step = "einsum"
        # PackGraft (round 16): where the per-table scatter einsums would
        # run, the pack planner may coalesce NB + MI pair tables +
        # against-class stacks into ONE wide block-diagonal gram dispatch
        # (pallas_hist.gram_counts — the exact einsum gram) so every table
        # rides the efficiency-vs-width curve.  Single-device only: the
        # packed fold is one unsharded program (the mesh paths carry their
        # own attested collectives).  Byte-identity is by construction —
        # tables() reads the same counts_from_cooc cells either way.
        self.pack = None
        if self.step == "einsum" and pack_on and self.mesh is None:
            pplan = pallas_hist.pack_tables(
                f, b, c, len(self.pair_index), max_width=pack_max_width)
            if pplan is not None:
                self.step = "packed"
                self.pack = pplan
        # mesh-qualified on the shard path: state folded under one topology
        # must never be silently summed under another (tables() raises on
        # an orphaned g: key — the GL002 discipline applied to mesh shape).
        # A packed fold writes the packed-provenance base — same G byte
        # layout as the kernel key, distinct base string, so adopt_state
        # can normalize between the two while foreign LAYOUTS still refuse.
        self.gk = (self.pack.g_key if self.step == "packed"
                   else pallas_hist.g_key(f, b, c) + (
                       shard.g_suffix if self.step == "shard" else ""))
        # logical all-reduce payload per fused shard dispatch (telemetry):
        # the gram (int8+scales when quantized, int32 psum otherwise) plus
        # the class-count/moment psums.  A global plan pays TWO legs —
        # the exact within-host psum plus the cross-process hop (int8
        # when quantized — only that leg rides the lossy collective), so
        # the counter reports the sum of both legs' logical payloads.
        if self.step == "shard":
            mode, _, wp = pallas_hist.plan(f, b, c)
            cells = (c * wp * wp) if mode in ("cls", "clsb") else (wp * wp)
            rows = cells // wp
            qbytes = cells + 4 * rows          # int8 payload + f32 scales
            counts = 4 * c * (2 + 2 * meta.num_cont
                              if self.needs_moments else 1)
            if shard.is_global:
                self._collective_bytes = (
                    4 * cells                          # ICI leg: exact psum
                    + (qbytes if shard.quantized else 4 * cells)  # DCN leg
                    + 2 * counts)
            else:
                gbytes = (qbytes if shard.quantized else 4 * cells)
                self._collective_bytes = gbytes + counts
        # GraftFleet straggler attribution (round 15): a sampled
        # per-device wall probe around the fused dispatch, built lazily
        # on the first profiled fold — off (profile.on unset) the fold
        # pays one attribute check and the probe program never compiles
        self._skew = None
        from avenir_tpu.telemetry import profile as _profile

        self._prof = _profile.profiler()

    @property
    def program_tag(self) -> Optional[str]:
        """Routing label for telemetry program registration.  Packed
        routings carry the composite pack signature so GraftProf/roofline
        attributes MFU to THIS packed shape, not a generic step name —
        and so a pack-width change registers a distinct program."""
        if self.step == "packed":
            return f"packed:{self.pack.signature}"
        return self.step

    def cost_probe(self, ds: EncodedDataset):
        """(lowerable, args) for this folder's per-chunk device program —
        the GraftProf AOT cost hook.  The single-dispatch routings are
        probeable (kernel, and the packed gram whose ONE program IS the
        chunk pass — a packed chunk must never degrade to
        ``source:"shapes"``); the per-table einsum fallback and the
        shard_map path dispatch several programs per chunk, so they
        register shapes-only rather than publishing a misleading
        single-program cost."""
        from avenir_tpu.ops import pallas_hist

        if self.step == "kernel":
            if self.needs_moments:
                return (pallas_hist.gram_moments,
                        (ds.codes, ds.labels, ds.cont, self.b, self.c))
            return (pallas_hist.cooc_counts,
                    (ds.codes, ds.labels, self.b, self.c))
        if self.step == "packed":
            if self.needs_moments:
                return (pallas_hist.gram_counts_moments,
                        (ds.codes, ds.labels, ds.cont, self.b, self.c))
            return (pallas_hist.gram_counts,
                    (ds.codes, ds.labels, self.b, self.c))
        return None

    def fold(self, ds: EncodedDataset, acc: agg.Accumulator) -> None:
        """One chunk's device pass + 64-bit host accumulation into ``acc``.

        GraftPool (round 18): the fold acquires a tenant dispatch slot
        first — batch SharedScan chunks AND stream panes both pass here,
        so ONE arbiter hook fair-queues both against every other tenant
        on the device pool.  Un-tenanted runs get the shared null context
        (one attribute check); a tenant past its queue share raises the
        typed TenantShedError to its OWN workload, never a neighbor's.

        GraftBox: the fold is a watchdog-guarded seam — a chunk pass
        that wedges (stuck collective, dead device) past
        ``blackbox.watchdog.sec`` journals ``hang.detected`` and captures
        a forensics bundle (the guard is one attribute check when off)."""
        from avenir_tpu import tenancy
        from avenir_tpu.telemetry import blackbox

        with blackbox.watchdog_guard("fold"), tenancy.pool().slot():
            self._fold(ds, acc)

    def _fold(self, ds: EncodedDataset, acc: agg.Accumulator) -> None:
        from avenir_tpu.ops import pallas_hist
        from avenir_tpu.parallel.mesh import maybe_shard_batch

        if self.shard is not None:
            codes, labels, cont = self.shard.shard_batch(
                ds.codes, ds.labels, ds.cont)
        else:
            codes, labels, cont = maybe_shard_batch(
                self.mesh, ds.codes, ds.labels, ds.cont)
        if self.step == "shard":
            # ONE fused dispatch: per-device gram + class counts (+ class
            # moments when any consumer reads them), psum'd in-kernel over
            # the data axis — class counts ride the collective instead of
            # a second dispatch
            if self.needs_moments:
                g, cc, cnt, s1, s2 = self._shard_step(codes, labels, cont)
            else:
                g, cc = self._shard_step(codes, labels, cont)
            acc.add("class", cc)
            acc.add(self.gk, g)
            if self.needs_moments:
                acc.add("cont_count", cnt)
                acc.add("cont_sum", s1)
                acc.add("cont_sumsq", s2)
            if self.counters is not None:
                # staged rows include ballast; true row counts live with
                # the stream cursor (Records::Processed), so the Shard
                # group reports only what this seam measures exactly
                self.counters.increment("Shard", "chunks")
                self.counters.increment("Shard", "collective.bytes",
                                        self._collective_bytes)
            if self._prof.enabled:
                # per-device skew probe (after the host accumulation has
                # drained the device — the probe times each chip's chunk
                # work in isolation); stream panes inherit it through
                # this same fold, zero stream-side code
                if self._skew is None:
                    from avenir_tpu.parallel.skew import DeviceSkewProbe

                    self._skew = DeviceSkewProbe(
                        self.shard, self.b, self.c,
                        interpret=not pallas_hist.mesh_on_tpu(
                            self.shard.mesh),
                        counters=self.counters)
                self._skew.maybe_probe(codes, labels)
            return
        acc.add("class", agg.class_counts(labels, self.c))
        moments_done = False
        if self.step == "kernel":
            if self.needs_moments:
                # one fused dispatch: gram + moments of the resident chunk
                g, cnt, s1, s2 = pallas_hist.gram_moments(
                    codes, labels, cont, self.b, self.c)
                acc.add(self.gk, g)
                acc.add("cont_count", cnt)
                acc.add("cont_sum", s1)
                acc.add("cont_sumsq", s2)
                moments_done = True
            else:
                acc.add(self.gk, pallas_hist.cooc_counts(
                    codes, labels, self.b, self.c))
        elif self.step == "packed":
            # ONE wide block-diagonal gram dispatch standing in for the
            # per-table einsum family below — same fused-moments shape as
            # the kernel branch, exact by construction (gram_counts is
            # bit-identical to the kernel's G)
            if self.needs_moments:
                g, cnt, s1, s2 = pallas_hist.gram_counts_moments(
                    codes, labels, cont, self.b, self.c)
                acc.add(self.gk, g)
                acc.add("cont_count", cnt)
                acc.add("cont_sum", s1)
                acc.add("cont_sumsq", s2)
                moments_done = True
            else:
                acc.add(self.gk, pallas_hist.gram_counts(
                    codes, labels, self.b, self.c))
        elif self.step == "sharded":
            acc.add(self.gk, self._sharded(codes, labels))
        elif self.step == "einsum":
            acc.add("fc", agg.feature_class_counts(codes, labels,
                                                   self.c, self.b))
            for s in range(0, len(self.pair_index), self.pair_chunk):
                sl = self.pair_index[s:s + self.pair_chunk]
                # SharedScan accumulators live only for one fused scan,
                # and windowed pane accumulators carry the conf-derived
                # run fingerprint in their snapshot envelope
                # (stream/windows.py), so no restore path exists for a
                # stale key to corrupt; keys mirror
                # models/mutual_info.py's gated family
                # graftlint: disable=GL002
                acc.add(f"pcc{s}", agg.pair_class_counts(
                    codes[:, sl[:, 0]], codes[:, sl[:, 1]], labels,
                    self.c, self.b))
        if self.needs_moments and not moments_done:
            cnt, s1, s2 = agg.class_moments(cont, labels, self.c)
            acc.add("cont_count", cnt)
            acc.add("cont_sum", s1)
            acc.add("cont_sumsq", s2)

    @property
    def g_suffix(self) -> str:
        """The mesh qualifier this folder's gram key carries ("" off the
        fused shard path) — what a pane snapshot records as its writing
        topology and the elastic restore compares against."""
        return self.shard.g_suffix if self.step == "shard" else ""

    def state_matches_routing(self, state: Dict[str, Any]) -> bool:
        """Does a persisted accumulator-state mapping use THIS folder's
        key family?  False means folding it with fresh panes would mix
        key families — the restore seam must adopt (or refuse) it first.
        Catches more than a mesh-suffix comparison: a kernel↔einsum
        ROUTING crossing at the same topology (a snapshot moved between
        a TPU host and a CPU host) re-keys too, in BOTH directions —
        gram state landing on the einsum routing, and einsum ``fc``
        counts landing on a gram routing (where ``tables()``'s
        gram-first read-out would silently ignore them) — and previously
        slipped through to a silent partial fold."""
        gram = [k for k in state
                if isinstance(k, str) and k.startswith("g:")]
        if self.step == "einsum":
            return not gram
        return "fc" not in state and all(k == self.gk for k in gram)

    def adopt_state(self, state: Dict[str, Any]) -> Tuple[Dict[str, Any],
                                                          List[str]]:
        """Redistribute one persisted accumulator-state mapping onto THIS
        folder's routing — the "refuse OR reshard, never silently fold"
        half of the foreign-key discipline (``tables()`` keeps the
        refusal; restore seams call this first, under the
        ``shard.reshard.on.restore`` gate).  Returns ``(state,
        rekeyed_keys)`` — unchanged state comes back as-is.

        Exact by construction: 64-bit host totals are mesh-shape-
        invariant, so re-keying ``:mesh:<axis><n>`` qualifiers moves the
        SAME bytes under the new topology's key (checkpoint/reshard.py).
        Packed↔unpacked is a PROVENANCE crossing, not a layout one — the
        packed base ``g:packed:<mode>:...`` stores byte-for-byte the same
        G as the kernel base for the same (F, B, C), so the base string
        is normalized to this folder's own (kill-packed → resume-unpacked
        and the reverse both redistribute exactly).  Demotion onto the
        chunked-einsum routing converts either gram base through
        ``counts_from_cooc`` — the identical read-out ``tables()`` itself
        runs.  Genuinely non-portable state raises
        :class:`~avenir_tpu.checkpoint.reshard.ReshardError`: a foreign
        base LAYOUT (the schema changed), mixed-topology or
        mixed-provenance state, or einsum-chunked counts promoted onto a
        gram routing (pairs outside the persisted union were never
        aggregated)."""
        from avenir_tpu.checkpoint import reshard
        from avenir_tpu.ops import pallas_hist

        reshard.state_suffix(state)         # refuse mixed-topology state
        base_gk = pallas_hist.g_key(self.f, self.b, self.c)
        accepted = {base_gk,
                    pallas_hist.packed_g_key(self.f, self.b, self.c)}
        gram_keys = [k for k in state
                     if isinstance(k, str) and k.startswith("g:")]
        for key in gram_keys:
            base, _ = reshard.split_mesh_key(key)
            if base not in accepted:
                raise reshard.ReshardError(
                    f"gram state {key!r} has base layout {base!r} but "
                    f"this fold's is {base_gk!r} — the kernel layout "
                    f"(schema shape F/B/C) changed; no redistribution "
                    f"can reconcile different layouts")
        if len(gram_keys) > 1:
            raise reshard.ReshardError(
                f"state holds gram counts under {sorted(gram_keys)} — "
                f"mixed kernel/packed provenance in one mapping means "
                f"the same rows were split across two accumulators; "
                f"redistribution cannot prove they partition the stream")
        if gram_keys and "fc" in state:
            raise reshard.ReshardError(
                f"state holds both gram {gram_keys[0]!r} and einsum 'fc' "
                f"counts — mixed-routing state cannot be redistributed")
        if self.step == "einsum":
            if not gram_keys:
                return state, []            # same chunked-einsum routing
            # demote: one gram → the einsum family ("fc" + per-chunk
            # "pcc<off>"), via the exact read-out tables() runs
            (key,) = gram_keys              # bounded above: one topology
            out = {k: v for k, v in state.items() if k != key}
            fbc, pcc = pallas_hist.counts_from_cooc(
                np.asarray(state[key]), self.f, self.b, self.c,
                self.pair_index[:, 0], self.pair_index[:, 1])
            out["fc"] = fbc
            for s in range(0, len(self.pair_index), self.pair_chunk):
                # keys mirror fold()'s gated family — graftlint: disable=GL002
                out[f"pcc{s}"] = pcc[s:s + self.pair_chunk]
            return out, [key]
        if "fc" in state and not gram_keys:
            raise reshard.ReshardError(
                "state was folded under the chunked-einsum routing "
                "('fc'/'pcc<off>' keys) but this fold reads the fused "
                "gram — pair counts outside the persisted union were "
                "never aggregated, so promotion is impossible; restore "
                "on an einsum-routed topology or start clean")
        # provenance normalization: at most ONE gram key survives the
        # checks above (one topology, one base) — rename its base to this
        # routing's own (packed↔kernel store identical G bytes for one
        # (F, B, C)), then let reshard move the mesh suffix
        renamed: List[str] = []
        own_base = self.pack.g_key if self.step == "packed" else base_gk
        if gram_keys:
            (key,) = gram_keys
            base, suffix = reshard.split_mesh_key(key)
            if base != own_base:
                state = {(own_base + suffix if k == key else k): v
                         for k, v in state.items()}
                renamed = [key]
        out, moved = reshard.rekey_state(state, self.g_suffix)
        return out, renamed + moved

    def tables(self, acc: agg.Accumulator, rows: int) -> ScanTables:
        """The shared per-stream totals from an accumulator this folder
        filled.  Tolerates an EMPTY accumulator (a window whose panes held
        zero rows): every table the consumers need comes back all-zero, so
        empty windows finalize deterministically instead of raising."""
        from avenir_tpu.ops import pallas_hist

        f, b, c = self.f, self.b, self.c
        if self.needs_counts:
            # refuse FOREIGN gram keys even when our own is also present:
            # a mixed accumulator (panes restored under one topology, new
            # folds under another) would silently drop the foreign counts
            # from fbc/pcc while class totals still include their rows
            foreign = [k for k in acc.names()
                       if k.startswith("g:") and k != self.gk]
            if foreign:
                raise ScanError(
                    f"accumulator holds gram state under {foreign} but "
                    f"this fold reads {self.gk!r} — the kernel layout or "
                    f"mesh topology (shard.devices / shard.data.axis) "
                    f"changed since that state was written; a resharded "
                    f"run must either redistribute the snapshot through "
                    f"checkpoint/reshard (shard.reshard.on.restore=true "
                    f"on the restore path) or start from a clean "
                    f"accumulator, never fold stale counts")
        fbc = pcc = None
        if self.needs_counts and self.gk in acc:
            fbc, pcc = pallas_hist.counts_from_cooc(
                acc.get(self.gk), f, b, c,
                self.pair_index[:, 0], self.pair_index[:, 1])
        elif self.needs_counts:
            fbc = (acc.get("fc") if "fc" in acc
                   else np.zeros((f, b, c), np.int64))
            pcc = (np.concatenate(
                [acc.get(f"pcc{s}") if f"pcc{s}" in acc
                 else np.zeros((min(self.pair_chunk,
                                    len(self.pair_index) - s), b, b, c),
                               np.int64)
                 for s in range(0, len(self.pair_index), self.pair_chunk)])
                if len(self.pair_index) else np.zeros((0, b, b, c), np.int64))
        moments = None
        if self.needs_moments:
            fc = self.meta.num_cont
            moments = ((acc.get("cont_count"), acc.get("cont_sum"),
                        acc.get("cont_sumsq")) if "cont_count" in acc
                       else (np.zeros(c, np.float64),
                             np.zeros((c, fc), np.float64),
                             np.zeros((c, fc), np.float64)))
        return ScanTables(
            meta=self.meta, rows=rows,
            class_counts=(acc.get("class") if "class" in acc
                          else np.zeros(c, np.int64)),
            fbc=fbc, pair_index=self.pair_index, pcc=pcc, moments=moments)

    def finalize(self, acc: agg.Accumulator, rows: int) -> Dict[str, Any]:
        """``{consumer.name: result}`` from an accumulator this folder
        filled — the end-of-stream (or end-of-window) read-out."""
        tables = self.tables(acc, rows)
        return {cons.name: cons.finalize(tables) for cons in self.consumers}


class SharedScan:
    """Consumer registry + one-pass dispatch over an encoded chunk stream.

    ``run(data)`` streams the chunks ONCE.  Per chunk it computes only what
    the registered consumers collectively need — the co-occurrence gram
    (kernel fast path, sharded-kernel mesh path, or the einsum fallback —
    the SAME three-way routing as ``MutualInformation.fit``) and/or the
    continuous class moments, fused into one dispatch on the kernel path —
    and accumulates 64-bit host totals.  Returns ``{consumer.name: result}``.
    The per-chunk pass itself lives in :class:`ChunkFolder` so windowed
    streaming consumers (``stream/windows.py``) fold the exact same code.
    """

    def __init__(self, mesh=None, pair_chunk: int = 256, shard=None,
                 counters: Optional[Counters] = None, pack_on: bool = True,
                 pack_max_width: Optional[int] = None):
        self.mesh = mesh
        self.pair_chunk = pair_chunk
        self.shard = shard                # parallel/shard.ShardSpec or None
        self.counters = counters
        self.pack_on = pack_on            # scan.pack.on
        self.pack_max_width = pack_max_width   # scan.pack.max.width
        self.chunks_seen = 0              # set by run(); fused stages report it
        self.count_path = None            # routing tag of the last run()
        self._consumers: List[ScanConsumer] = []

    def register(self, consumer: ScanConsumer) -> ScanConsumer:
        if any(c.name == consumer.name for c in self._consumers):
            raise ScanError(f"duplicate consumer name {consumer.name!r}")
        self._consumers.append(consumer)
        return consumer

    @property
    def consumers(self) -> List[ScanConsumer]:
        return list(self._consumers)

    def run(self, data: Union[EncodedDataset, Iterable[EncodedDataset]]
            ) -> Dict[str, Any]:
        if not self._consumers:
            raise ScanError("no consumers registered")
        meta, chunks = peek_chunks(data)
        if meta.labels is None:
            raise ScanError(
                "SharedScan requires labels: every shared table is "
                "class-conditioned (see the row-validity contract)")
        folder = ChunkFolder(self._consumers, meta, mesh=self.mesh,
                             pair_chunk=self.pair_chunk, shard=self.shard,
                             counters=self.counters, pack_on=self.pack_on,
                             pack_max_width=self.pack_max_width)
        from avenir_tpu.telemetry import profile as _profile
        from avenir_tpu.telemetry import spans as tel

        tracer = tel.tracer()
        prof = _profile.profiler()
        acc = agg.Accumulator()
        rows = 0
        self.chunks_seen = 0
        self.count_path = folder.program_tag or "moments"
        attrs = {"consumers": [x.name for x in self._consumers],
                 "path": folder.program_tag or "moments"}
        if self.shard is not None:
            attrs["shard.devices"] = self.shard.num_devices
            attrs["shard.axis"] = self.shard.data_axis
            if self.shard.is_global:
                attrs["shard.procs"] = self.shard.num_procs
        with tracer.span("scan", attrs=attrs) as scan_span:
            for ds in chunks:
                # a pre-staged chunk (sharded prefetch) arrives ballast-
                # padded; valid_rows is its true count — never count pad
                true_rows = (ds.valid_rows if ds.valid_rows is not None
                             else ds.num_rows)
                chunk_attrs = {"chunk": self.chunks_seen, "rows": true_rows}
                pkey = None
                if prof.enabled:
                    # GraftProf: the fold program — registered with AOT
                    # cost where the routing is single-dispatch, sampled
                    # per chunk so the profile table knows this seam
                    # packed programs register under the composite
                    # (shape, pack-signature) key — the roofline table
                    # attributes MFU to the packed dispatch itself
                    pkey = tel.CompileKeyMonitor.shape_key(
                        ds.codes, ds.labels, ds.cont) + (
                        folder.program_tag or "moments",)
                    probe = folder.cost_probe(ds)
                    chunk_attrs["program"] = prof.observe(
                        pkey, site="scan.chunk",
                        lowerable=probe[0] if probe else None,
                        args=probe[1] if probe else ())
                with tracer.span("scan.chunk", attrs=chunk_attrs):
                    # host accumulation inside fetches every device result,
                    # so the chunk span's close is naturally synced.
                    # Recompile accounting lives with the chunk SOURCE
                    # (jobs' _chunk_telemetry) — a second monitor here
                    # would double-count the same stream
                    t0 = time.perf_counter()
                    folder.fold(ds, acc)
                    if pkey is not None:
                        prof.sample(pkey, "scan.chunk",
                                    time.perf_counter() - t0)
                if prof.enabled:
                    prof.sample_device_memory("scan")
                rows += true_rows
                self.chunks_seen += 1
            scan_span.set("chunks", self.chunks_seen)
            scan_span.set("rows", rows)
        return folder.finalize(acc, rows)


# ---------------------------------------------------------------------------
# driver-level stage fusion — the jobs the SharedScan can stand in for
# ---------------------------------------------------------------------------

FUSABLE_JOBS = ("BayesianDistribution", "MutualInformation",
                "CramerCorrelation", "HeterogeneityReductionCorrelation")

# conf keys that must agree across fused stages: they shape the shared
# encode (schema, delimiters) and the shared stream (chunking, prefetch,
# device-mesh policy — incl. the ShardGraft topology, which decides the
# staging pad targets and the fused dispatch the one scan compiles)
_COMPAT_KEYS = ("feature.schema.file.path", "field.delim.regex",
                "field.delim", "stream.chunk.rows", "stream.prefetch.depth",
                "data.parallel.auto", "shard.devices", "shard.data.axis",
                "shard.allreduce.quantized", "shard.proc.axis",
                "scan.pack.on", "scan.pack.max.width")


def fuse_refusal(job, conf) -> Optional[str]:
    """Why this (job name, stage conf) cannot ride a SharedScan — or None
    when it can.  Conservative: anything the fused path does not reproduce
    byte-for-byte — per-stage opt-out, text-mode NB, per-job stream
    checkpointing — keeps the stage on its own scan.  Multi-process runs
    fuse ONLY under an explicit ``shard.*`` topology (CrossGraft: the
    global fold row-partitions each chunk across processes inside the
    dispatch); without one, the per-job round-robin chunk ownership +
    ``all_process_sum_state`` path remains the multi-process contract.

    The ONE gate shared by the driver's consecutive-stage fusion
    (``stage_fusable``) and the PlanGraft planner (``pipeline/plan.py``),
    which surfaces the reason string in ``plan explain`` fallback nodes."""
    if not isinstance(job, str) or job not in FUSABLE_JOBS:
        return "not a fusable count job"
    if not conf.get_bool("scan.fuse", True):
        return "scan.fuse=false opt-out"
    if conf.get("stream.checkpoint.dir"):
        # per-job durability is not composed with fusion
        return "checkpointed stream (stream.checkpoint.dir)"
    if job == "BayesianDistribution" and not conf.get_bool("tabular.input", True):
        return "text-mode NB (tabular.input=false)"
    if not conf.get("feature.schema.file.path"):
        return "no schema (feature.schema.file.path unset)"
    import jax

    from avenir_tpu.parallel.shard import ShardSpec
    try:
        if jax.process_count() > 1 and not ShardSpec.requested(conf):
            # round-robin chunk ownership is per-job
            return "multi-process without a shard.* topology"
    except Exception:                              # pragma: no cover
        return "process topology unavailable"
    return None


def stage_fusable(job, conf) -> bool:
    """Can this (job name, stage conf) ride a SharedScan?  See
    :func:`fuse_refusal` for the reasons a stage stays on its own scan."""
    return fuse_refusal(job, conf) is None


def stages_compatible(confs) -> bool:
    """Do these stage confs describe ONE scan?  Encoding/stream keys must
    agree, and the shared schema must declare a class attribute (every
    shared table is class-conditioned)."""
    first = confs[0]
    for conf in confs[1:]:
        if any(conf.get(k) != first.get(k) for k in _COMPAT_KEYS):
            return False
    try:
        from avenir_tpu.core.schema import FeatureSchema
        schema = FeatureSchema.from_file(first.get("feature.schema.file.path"))
    except Exception:
        return False
    return schema.class_field is not None


def stage_consumer(name, job, conf, out_path, schema, enc,
                   counters: Optional[Counters] = None,
                   keep: Optional[Sequence[int]] = None):
    """``(consumer, writer)`` for one fusable stage — the ONE construction
    shared by :func:`run_fused_stages` and the PlanGraft planner
    (``pipeline/plan.py``), which builds consumers data-free to compute
    pair unions, prunable columns and AOT cost estimates before any row
    is read.  ``keep`` (the sorted binned positions the planner's
    dead-column rewrite retains) remaps a correlation stage's attribute
    selection into the pruned space; the all-column consumers (NB, MI)
    refuse it.  The writer publishes the finalized result byte-identically
    to the standalone job; ``counters`` receives NB's model-row count."""
    from avenir_tpu.jobs import get_job
    from avenir_tpu.jobs.base import write_output
    from avenir_tpu.jobs.explore import correlation_plan, mi_output_lines
    from avenir_tpu.models import naive_bayes as nb

    if job == "BayesianDistribution":
        if keep is not None:
            raise ScanError("NB reads every binned column; cannot prune")
        consumer = NaiveBayesConsumer(
            laplace=conf.get_float("laplace.smoothing", 1.0), name=name)

        def write_nb(model):
            lines = nb.model_to_lines(model, enc, delim=conf.field_delim)
            write_output(out_path, lines)
            if counters is not None:
                counters.set("Model", "Rows", len(lines))

        return consumer, write_nb
    if job == "MutualInformation":
        if keep is not None:
            raise ScanError("MI aggregates every pair; cannot prune")
        names_ = [schema.field_by_ordinal(fld.ordinal).name
                  for fld in enc.binned_fields]
        consumer = MutualInfoConsumer(feature_names=names_, name=name)

        def write_mi(result):
            write_output(out_path, mi_output_lines(conf, result, names_))

        return consumer, write_mi
    # CramerCorrelation / HeterogeneityReductionCorrelation
    src_idx, dst_idx, against_class, names_ = correlation_plan(
        conf, schema, enc)
    if keep is not None:
        # remap the full-space attribute selection into the pruned space;
        # a None selection means "every column", which the planner only
        # prunes to itself — so both restricted lists are present here
        pos = {int(c): k for k, c in enumerate(keep)}
        src_idx = None if src_idx is None else [pos[i] for i in src_idx]
        dst_idx = None if dst_idx is None else [pos[i] for i in dst_idx]
        names_ = [names_[int(c)] for c in keep]
    algorithm = get_job(job)._algorithm(conf)
    consumer = CorrelationConsumer(
        algorithm=algorithm, src=src_idx, dst=dst_idx,
        against_class=against_class, feature_names=names_, name=name)

    def write_corr(result):
        write_output(out_path, result.to_lines(delim=conf.field_delim))

    return consumer, write_corr


def consumer_columns(consumer, num_binned: int) -> Optional[set]:
    """The binned columns a consumer reads, or None for "all" — drives the
    planner's dead-column rewrite.  NB's model and MI's all-pairs tensors
    cover every column; a correlation stage restricted to explicit
    source/dest attributes touches only their union (the statistic slices
    each pair to its true ``n_bins`` support, so folding a narrower codes
    block reproduces the same output bytes)."""
    if not isinstance(consumer, CorrelationConsumer):
        return None
    if consumer.against_class:
        return None if consumer.src is None else set(int(i)
                                                     for i in consumer.src)
    if consumer.src is None or consumer.dst is None:
        return None
    cols: set = set()
    for i, j in consumer._pair_list(num_binned):
        cols.add(int(i))
        cols.add(int(j))
    return cols


# conf keys that shape the encoded bytes of a whole-input read — the
# planner's encode-once cache key (streaming/shard staging is per-unit)
_ENCODE_KEYS = ("feature.schema.file.path", "field.delim.regex",
                "field.delim")


def pruned_view(ds: EncodedDataset, keep: np.ndarray) -> EncodedDataset:
    """The dead-column rewrite applied to one chunk: the kept binned
    columns' codes/cardinalities/ordinals, everything else untouched.
    A host-side gather per chunk — the device fold then runs on the
    narrower gram."""
    return EncodedDataset(
        codes=ds.codes[:, keep], cont=ds.cont, labels=ds.labels, ids=ds.ids,
        n_bins=np.asarray(ds.n_bins)[keep],
        class_values=ds.class_values,
        binned_ordinals=[ds.binned_ordinals[int(k)] for k in keep],
        cont_ordinals=ds.cont_ordinals, valid_rows=ds.valid_rows)


def run_fused_stages(stages, prune: Optional[Sequence[int]] = None,
                     pack_on: Optional[bool] = None,
                     pack_max_width: Optional[int] = None,
                     encode_cache: Optional[dict] = None
                     ) -> Dict[str, Counters]:
    """Execute a group of fusable pipeline stages as ONE SharedScan.

    ``stages``: list of ``(name, job, input_path, output_path, conf)`` with
    a common input and compatible confs (the driver checks both).  Builds
    one chunk source through the jobs' existing ``encoded_data_source``
    (native parse → encode → DeviceFeeder staging, once), registers one
    consumer per stage, runs the scan, and writes each stage's output
    byte-identically to its standalone job.  Returns per-stage Counters;
    each carries a ``SharedScan`` counter group attesting the fusion.

    The PlanGraft planner (``pipeline/plan.py``) drives the same seam with
    its plan-time decisions: ``prune`` folds only the listed binned
    columns (consumers remapped into the pruned space — byte-identical by
    the true-support contract), ``pack_on``/``pack_max_width`` override
    the runtime pack heuristic with the planner's AOT-costed choice (the
    conf's ``scan.pack.on=false`` opt-out still wins), and
    ``encode_cache`` lets a whole-input encode be reused by every scan
    unit reading the same artifact under the same encode keys."""
    from avenir_tpu.jobs.base import Job

    first_conf = stages[0][4]
    in_path = stages[0][2]
    job_obj = Job()
    schema = Job.load_schema(first_conf)
    # ShardGraft (round 12): an explicit shard.* topology supersedes the
    # implicit auto-mesh — one spec decides the staging pad targets, the
    # fused shard_map dispatch, and the mesh-qualified accumulator keys
    from avenir_tpu.parallel.shard import ShardSpec

    spec = ShardSpec.from_conf(first_conf)
    mesh = spec.mesh if spec is not None else Job.auto_mesh(first_conf)
    counters = {name: Counters() for name, *_ in stages}
    # the first stage's Counters carries the stream-side telemetry
    # (Telemetry::recompiles via _chunk_telemetry, the Shard counter
    # group) — one scan, one accounting home
    if spec is not None:
        spec.announce()       # deduped per journal — one event per run
    ckey = None
    if (encode_cache is not None and spec is None
            and not first_conf.get("stream.chunk.rows")):
        ckey = (in_path,) + tuple(first_conf.get(k) for k in _ENCODE_KEYS)
    if ckey is not None and ckey in encode_cache:
        enc, data = encode_cache[ckey]
        rows_fn = (lambda d=data: d.num_rows)
    else:
        enc, data, rows_fn = job_obj.encoded_data_source(
            first_conf, in_path, counters[stages[0][0]], mesh=mesh,
            shard=spec)
        if ckey is not None and isinstance(data, EncodedDataset):
            encode_cache[ckey] = (enc, data)
    keep = None
    if prune is not None:
        keep = np.asarray(sorted(int(c) for c in prune), np.int64)
        if keep.size == len(enc.binned_fields):
            keep = None            # nothing dead — fold the full width
    engine = SharedScan(
        mesh=mesh, shard=spec, counters=counters[stages[0][0]],
        pack_on=(first_conf.get_bool("scan.pack.on", True) if pack_on is None
                 else pack_on and first_conf.get_bool("scan.pack.on", True)),
        pack_max_width=(first_conf.get_int("scan.pack.max.width", 0) or None
                        if pack_max_width is None else pack_max_width))
    writers = {}
    for name, job, _inp, out_path, conf in stages:
        consumer, writers[name] = stage_consumer(
            name, job, conf, out_path, schema, enc,
            counters=counters[name],
            keep=None if keep is None else [int(k) for k in keep])
        engine.register(consumer)
    scan_data = data
    if keep is not None:
        scan_data = (pruned_view(data, keep)
                     if isinstance(data, EncodedDataset)
                     else (pruned_view(ds, keep) for ds in data))
    results = engine.run(scan_data)
    rows = rows_fn()
    for name, _job, _inp, _out, _conf in stages:
        # CrossGraft: under a global plan every process finalizes the
        # SAME replicated totals — the single-writer output protocol
        # (process 0 writes the part file, like the streaming jobs)
        if Job.is_output_writer():
            writers[name](results[name])
        counters[name].set("Records", "Processed", rows)
        counters[name].set("SharedScan", "FusedStages", len(stages))
        counters[name].set("SharedScan", "Scans", 1)
        counters[name].set("SharedScan", "Chunks", engine.chunks_seen)
        if keep is not None:
            counters[name].set("SharedScan", "PrunedCols",
                               len(enc.binned_fields) - int(keep.size))
    return counters
