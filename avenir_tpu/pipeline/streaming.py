"""Streaming serving loop — the Storm/Redis topology replacement.

Capability parity with the reference's real-time path
(``reinforce/ReinforcementLearnerTopology.java`` builds RedisSpout →
shuffle → learner bolt :42-85; ``RedisSpout.java`` rpop's
``(eventID, roundNum)`` events :86-100; ``ReinforcementLearnerBolt.java``
drains the reward queue into ``learner.setReward`` then calls
``learner.nextActions(round)`` and writes to the action queue :93-125;
pluggable queue I/O via ``ActionWriter`` / ``RewardReader`` interfaces with
Redis impls — lpush actions ``RedisActionWriter.java:46-49``, lindex walk of
the reward list ``RedisRewardReader.java:72-86``).

Re-design: the topology collapses into an in-process event loop around the
learner — the queue abstraction survives (in-proc deques for tests and
embedding; Redis transports over the in-tree stdlib RESP client,
``pipeline/resp.py``, for drop-in use against the reference's own
simulators — no external redis package). Learner state is checkpointable
between events (the reference loses bolt state on restart, SURVEY.md §3.5).
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Callable, Iterable, List, Optional, Protocol, Tuple

from avenir_tpu.models.online_rl import ReinforcementLearner
from avenir_tpu.utils.metrics import Counters, LatencyTracker, serving_stats


# ---------------------------------------------------------------------------
# queue transports
# ---------------------------------------------------------------------------

class QueueFullError(RuntimeError):
    """Typed backpressure: a push against a bounded queue at its depth cap.

    The in-proc analog of the scoring plane's ShedError — load is rejected
    at the door with a type the producer can catch (drop, block, or shed
    upstream), instead of the queue growing without bound until the process
    OOMs mid-stream."""


class InProcQueue:
    """Deque-backed FIFO with the push/pop surface the Redis impls use.

    Bounded: ``depth`` (``stream.queue.depth``, default 65536) caps the
    backlog; a push past the cap raises :class:`QueueFullError`.
    ``depth=0`` disables the cap — only for tests that model an external
    broker's durability, never for a production in-proc hop."""

    DEFAULT_DEPTH = 65536

    def __init__(self, depth: int = DEFAULT_DEPTH):
        self._q = deque()
        self.depth = max(int(depth), 0)

    def push(self, msg: str) -> None:
        # len+appendleft is not atomic across threads, so a concurrent
        # producer pair can land at depth+1 — the cap bounds GROWTH (its
        # job), it is not an exact high-water mark
        if self.depth and len(self._q) >= self.depth:
            raise QueueFullError(
                f"in-proc queue at depth cap {self.depth} — consumer is "
                f"not keeping up; shed, block, or raise stream.queue.depth")
        self._q.appendleft(msg)

    def push_all(self, msgs: Iterable[str]) -> None:
        """All-or-nothing batch push: either every message is enqueued or
        none is (:class:`QueueFullError`).  Same growth-bound (not exact
        high-water) concurrency caveat as :meth:`push`."""
        batch = list(msgs)
        if self.depth and len(self._q) + len(batch) > self.depth:
            raise QueueFullError(
                f"in-proc queue cannot take {len(batch)} messages within "
                f"depth cap {self.depth} — consumer is not keeping up; "
                f"shed, block, or raise stream.queue.depth")
        for m in batch:
            self._q.appendleft(m)

    def pop(self) -> Optional[str]:
        return self._q.pop() if self._q else None

    def drain(self) -> List[str]:
        # pop-loop, not snapshot+clear: a concurrent push landing between a
        # snapshot and the clear would be silently lost (deque.pop/append
        # are individually atomic, so this drains every element exactly
        # once even with a producer on another thread)
        out: List[str] = []
        while True:
            try:
                out.append(self._q.pop())
            except IndexError:
                return out

    def __len__(self) -> int:
        return len(self._q)


class EventSource(Protocol):
    def next_event(self) -> Optional[Tuple[str, int]]: ...


class RewardReader(Protocol):
    def read_rewards(self) -> List[Tuple[str, float]]: ...


class ActionWriter(Protocol):
    def write(self, event_id: str, actions: List[str]) -> None: ...


class QueueEventSource:
    """Events are ``eventID,roundNum`` lines (RedisSpout.java:86-100)."""

    def __init__(self, queue: InProcQueue, delim: str = ","):
        self.queue = queue
        self.delim = delim

    def next_event(self) -> Optional[Tuple[str, int]]:
        msg = self.queue.pop()
        if msg is None:
            return None
        event_id, _, round_num = msg.partition(self.delim)
        return event_id, int(round_num)


class QueueRewardReader:
    """Rewards are ``action,reward`` lines."""

    def __init__(self, queue: InProcQueue, delim: str = ","):
        self.queue = queue
        self.delim = delim

    def read_rewards(self) -> List[Tuple[str, float]]:
        out = []
        for msg in self.queue.drain():
            action, _, reward = msg.partition(self.delim)
            out.append((action, float(reward)))
        return out


class QueueActionWriter:
    """Actions are written as ``eventID,action`` (RedisActionWriter.java:46-49)."""

    def __init__(self, queue: InProcQueue, delim: str = ","):
        self.queue = queue
        self.delim = delim

    def write(self, event_id: str, actions: List[str]) -> None:
        msgs = [f"{event_id}{self.delim}{a}" for a in actions]
        push_all = getattr(self.queue, "push_all", None)
        if push_all is not None:
            # all-or-nothing on bounded queues: the serving loop's shed
            # path treats QueueFullError as "this event's actions dropped",
            # so a multi-action selection must never publish a partial set
            push_all(msgs)
        else:
            # uncapped broker transports (Redis LPUSH) never shed
            for m in msgs:
                self.queue.push(m)


# Redis transports — the reference's spout/reader/writer contract
# (RedisSpout.java rpop events; RedisActionWriter.java lpush actions;
# RedisRewardReader.java reward-list reads) over the in-tree stdlib RESP
# client (pipeline/resp.py) — no external redis package needed. Rewards are
# consumed destructively (rpop drain), matching the serving loop's
# read-once semantics; the reference's non-destructive lindex walk with a
# running offset is equivalent for a single reader.

def _redis_queue(queue, host, port, db):
    from avenir_tpu.pipeline.resp import RedisListQueue
    return RedisListQueue(queue, host=host, port=port, db=db)


class RedisEventSource(QueueEventSource):
    def __init__(self, host="localhost", port=6379, db=0, queue="eventQueue", delim=","):
        super().__init__(_redis_queue(queue, host, port, db), delim=delim)


class RedisRewardReader(QueueRewardReader):
    def __init__(self, host="localhost", port=6379, db=0, queue="rewardQueue", delim=","):
        super().__init__(_redis_queue(queue, host, port, db), delim=delim)


class RedisActionWriter(QueueActionWriter):
    def __init__(self, host="localhost", port=6379, db=0, queue="actionQueue", delim=","):
        super().__init__(_redis_queue(queue, host, port, db), delim=delim)


# ---------------------------------------------------------------------------
# the serving loop (the bolt, minus Storm)
# ---------------------------------------------------------------------------

class ReinforcementLearnerServer:
    """Per event: drain rewards → update learner → emit next actions
    (ReinforcementLearnerBolt.java:93-125).

    Observability rides the SAME schema as the scoring plane
    (``serving/batcher.py``): a ``Serving.<model_name>`` counter group plus
    a :class:`LatencyTracker`, published through :meth:`stats` — so the two
    online paths (RL loop, ServeGraft) report through one shape and
    BASELINE.md's serving rows compare like for like.  The RL loop
    dispatches one event at a time, so its whole size histogram lands in
    ``bucket.1``.  Pass shared ``counters``/``latency`` objects to
    aggregate several servers (e.g. a fleet's per-group learners) into one
    report.
    """

    def __init__(
        self,
        learner: ReinforcementLearner,
        events: EventSource,
        rewards: RewardReader,
        actions: ActionWriter,
        log_interval: int = 0,
        on_log: Optional[Callable[[int], None]] = None,
        counters: Optional[Counters] = None,
        latency: Optional[LatencyTracker] = None,
        model_name: str = "rl",
    ):
        self.learner = learner
        self.events = events
        self.rewards = rewards
        self.actions = actions
        self.log_interval = log_interval
        self.on_log = on_log
        self.processed = 0
        self.model_name = model_name
        self.counters = counters if counters is not None else Counters()
        self.latency = latency if latency is not None else LatencyTracker()

    def handle(self, event_id: str, round_num: int) -> None:
        """The per-event body (drain rewards → update → emit actions) —
        shared by :meth:`process_one` and the ShardedServingFleet workers."""
        t0 = time.monotonic()
        for action, reward in self.rewards.read_rewards():
            self.learner.set_reward(action, reward)
        selected = self.learner.next_actions(round_num)
        try:
            self.actions.write(event_id, selected)
        except QueueFullError:
            # bounded action queue + lagging consumer: SHED this event's
            # actions (counted) and keep serving — the deployed
            # ``replay.failed.message=false`` drop semantics; the learner
            # update above already happened, and dying mid-serve (or
            # growing the queue without bound, the pre-round-11 behavior)
            # are both strictly worse
            self.counters.increment(f"Serving.{self.model_name}", "shed")
        self.processed += 1
        self.latency.record(time.monotonic() - t0)
        group = f"Serving.{self.model_name}"
        self.counters.increment(group, "requests")
        self.counters.increment(group, "batches")
        self.counters.increment(group, "bucket.1")
        if self.log_interval and self.on_log and self.processed % self.log_interval == 0:
            self.on_log(self.processed)

    def stats(self) -> dict:
        """The scoring plane's stats schema (utils/metrics.serving_stats)."""
        return serving_stats(self.counters, {self.model_name: self.latency})

    def process_one(self) -> bool:
        """Handle one event; False when the event queue is empty."""
        ev = self.events.next_event()
        if ev is None:
            return False
        self.handle(*ev)
        return True

    def run(self, max_events: Optional[int] = None) -> int:
        n = 0
        while max_events is None or n < max_events:
            if not self.process_one():
                break
            n += 1
        return n

    # -- learner-state checkpointing ----------------------------------------
    def checkpoint(self) -> str:
        return json.dumps(self.learner.get_state())

    def restore(self, blob: str) -> None:
        self.learner.set_state(json.loads(blob))


# ---------------------------------------------------------------------------
# parallel serving — the Storm executor-scaling analog
# ---------------------------------------------------------------------------

class ShardedServingFleet:
    """Multi-worker event dispatch with per-group learner state — the
    capacity analog of Storm's topology scaling
    (ReinforcementLearnerTopology.java:42-85: ``num.bolt.threads`` bolt
    executors fed by a shuffle, ``num.workers`` JVMs, ``max.spout.pending``
    backpressure).

    Events carry a group key (the reference reaches the same effect with
    one topology per engagement group); ``hash(group) % num_workers`` pins
    every group to one worker — Storm's fieldsGrouping — so each learner
    updates single-threaded (no lock on the hot path) while distinct groups
    process concurrently. Each worker owns the servers for its groups,
    created on first event via ``server_factory(group)``. A bounded
    per-worker queue (``max_pending``) applies backpressure to the
    dispatcher exactly like ``max.spout.pending`` caps in-flight tuples.

    ``dispatch`` blocks when the target worker's queue is full; ``close``
    drains and joins the workers. Results (event_id → actions) flow through
    each server's own ActionWriter, so any transport (in-proc, Redis)
    works unchanged.
    """

    def __init__(self, server_factory: Callable[[str], "ReinforcementLearnerServer"],
                 num_workers: int = 2, max_pending: int = 128):
        import queue as _qmod
        import threading

        self.server_factory = server_factory
        self.num_workers = max(num_workers, 1)
        self._queues = [_qmod.Queue(maxsize=max(max_pending, 1))
                        for _ in range(self.num_workers)]
        self._servers: List[dict] = [{} for _ in range(self.num_workers)]
        self._errors: List[BaseException] = []
        self._closed = False
        self._threads = []
        for w in range(self.num_workers):
            t = threading.Thread(target=self._work, args=(w,), daemon=True)
            t.start()
            self._threads.append(t)

    @property
    def processed(self) -> int:
        """Events handled across all workers — summed from the per-server
        counters each worker owns alone, so the hot path stays lock-free."""
        return sum(srv.processed for servers in self._servers
                   for srv in servers.values())

    def _work(self, w: int) -> None:
        q = self._queues[w]
        servers = self._servers[w]
        while True:
            item = q.get()
            if item is None:
                return
            group, event_id, round_num = item
            try:
                srv = servers.get(group)
                if srv is None:
                    srv = servers[group] = self.server_factory(group)
                srv.handle(event_id, round_num)
            except BaseException as e:       # surfaced on close()
                self._errors.append(e)

    def dispatch(self, group: str, event_id: str, round_num: int) -> None:
        """Route one event to its group's worker (blocks on backpressure)."""
        if self._closed:
            # a dispatch after close() would silently enqueue to a dead
            # worker and, once the bounded queue fills, block forever
            raise RuntimeError("dispatch() after close()")
        self._queues[hash(group) % self.num_workers].put(
            (group, event_id, round_num))

    def close(self) -> None:
        """Flush queues, stop workers, re-raise the first worker error."""
        self._closed = True
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join()
        if self._errors:
            raise self._errors[0]

    def checkpoints(self) -> dict:
        """group → learner-state JSON for every group across workers (call
        after close(), or accept in-flight staleness)."""
        out = {}
        for servers in self._servers:
            for group, srv in servers.items():
                out[group] = srv.checkpoint()
        return out


# ---------------------------------------------------------------------------
# process-backed serving — the Storm num.workers (multi-JVM) analog
# ---------------------------------------------------------------------------

class _ForwardingActionWriter:
    """Tees a server's action writes to the parent's result queue (the
    caller-provided transport still runs in the worker — a Redis writer's
    effects are globally visible; an in-proc queue's are not, which is why
    the parent needs the forwarded copy)."""

    def __init__(self, inner, group: str, out_q):
        self.inner = inner
        self.group = group
        self.out_q = out_q

    def write(self, event_id: str, actions: List[str]) -> None:
        self.inner.write(event_id, actions)
        self.out_q.put(("act", self.group, event_id, list(actions)))


def _fleet_worker(worker_id: int, server_factory, in_q, out_q) -> None:
    servers: dict = {}
    while True:
        item = in_q.get()
        if item is None:
            out_q.put(("ckpt", worker_id,
                       [(g, srv.checkpoint()) for g, srv in servers.items()]))
            return
        group, event_id, round_num = item
        try:
            srv = servers.get(group)
            if srv is None:
                srv = servers[group] = server_factory(group)
                srv.actions = _ForwardingActionWriter(srv.actions, group,
                                                      out_q)
            srv.handle(event_id, round_num)
        except BaseException as e:     # surfaced on close()
            out_q.put(("err", worker_id, repr(e)))


class ProcessServingFleet:
    """Multi-PROCESS event dispatch with per-group learner state — the
    capacity analog of Storm's ``num.workers`` (one JVM per worker,
    ReinforcementLearnerTopology.java:42-85), where
    :class:`ShardedServingFleet` mirrors ``num.bolt.threads`` (executors
    inside one JVM).

    Same contract as the thread fleet: ``hash(group) % num_workers`` pins
    each group to one worker (fieldsGrouping — learners update
    single-threaded), bounded per-worker queues apply ``max.spout.pending``
    backpressure, ``close()`` drains and re-raises the first worker error.
    Because workers are processes, CPU-bound learner updates scale past the
    GIL on multi-core hosts (thread workers cannot — BASELINE.md serving
    notes; on the 1-core dev rig both measure flat).

    Process-boundary additions:
    - action writes are forwarded to the parent (``actions()`` after
      ``close()`` — per-group streams in dispatch order); the factory's own
      transport still runs in the worker, so Redis-backed writers behave
      exactly as in the thread fleet;
    - learner state is collected at shutdown (``checkpoints()``), matching
      the thread fleet's post-close semantics;
    - ``server_factory`` is transferred via fork at worker start, so it may
      be a closure; workers are started eagerly in ``__init__`` — create
      the fleet BEFORE initializing any accelerator runtime (forking a
      process that holds a TPU client is undefined behavior; the serving
      learners are numpy-only by design).
    """

    def __init__(self, server_factory: Callable[[str], ReinforcementLearnerServer],
                 num_workers: int = 2, max_pending: int = 128,
                 mp_context: str = "fork"):
        import multiprocessing as mp

        ctx = mp.get_context(mp_context)
        self.num_workers = max(num_workers, 1)
        self._in_qs = [ctx.Queue(maxsize=max(max_pending, 1))
                       for _ in range(self.num_workers)]
        self._out_q = ctx.Queue()
        self._procs = [
            ctx.Process(target=_fleet_worker,
                        args=(w, server_factory, self._in_qs[w], self._out_q),
                        daemon=True)
            for w in range(self.num_workers)
        ]
        for p in self._procs:
            p.start()
        self._closed = False
        self._actions: List[Tuple[str, str, List[str]]] = []
        self._checkpoints: dict = {}
        self._errors: List[str] = []
        self.dispatched = 0

    def dispatch(self, group: str, event_id: str, round_num: int) -> None:
        """Route one event to its group's worker (blocks on backpressure)."""
        import queue as _qmod

        if self._closed:
            raise RuntimeError("dispatch() after close()")
        w = hash(group) % self.num_workers
        while True:
            try:
                self._in_qs[w].put((group, event_id, round_num), timeout=1.0)
                break
            except _qmod.Full:
                # backpressure against a DEAD worker would block forever
                if not self._procs[w].is_alive():
                    raise RuntimeError(
                        f"serving worker {w} died (exitcode "
                        f"{self._procs[w].exitcode}); queue full")
        self.dispatched += 1

    def _drain_out(self, expect_ckpts: int, deadline: float = 60.0) -> None:
        import queue as _qmod
        import time as _time

        remaining = expect_ckpts
        t_end = _time.monotonic() + deadline
        empty_after_dead = 0
        while remaining:
            try:
                kind, *rest = self._out_q.get(timeout=1.0)
            except _qmod.Empty:
                # a worker killed without sending its ckpt (OOM, segfault in
                # native code) must not hang close() on a get() that can
                # never be satisfied
                dead = sum(1 for p in self._procs if not p.is_alive())
                if dead >= remaining:
                    # a just-exited worker's queue feeder thread may still be
                    # flushing its ckpt/act payload into the pipe, so require
                    # several consecutive empty polls before declaring the
                    # handshake lost (each get() above already waited 1 s)
                    empty_after_dead += 1
                    if empty_after_dead >= 3:
                        self._errors.append(
                            f"{dead} serving worker(s) died without shutdown "
                            f"handshake (exitcodes "
                            f"{[p.exitcode for p in self._procs]})")
                        return
                else:
                    # live-but-wedged worker (hung in handle()): bound the
                    # IDLE time so close() terminates it instead of hanging —
                    # t_end resets on every received message, so a fleet
                    # draining a deep backlog slowly but steadily never trips
                    if _time.monotonic() > t_end:
                        self._errors.append(
                            f"{remaining} serving worker(s) idle without "
                            f"shutdown handshake for {deadline:.0f}s "
                            f"(wedged in handle()?); terminating")
                        return
                continue
            empty_after_dead = 0
            t_end = _time.monotonic() + deadline
            if kind == "act":
                group, event_id, actions = rest
                self._actions.append((group, event_id, actions))
            elif kind == "err":
                self._errors.append(rest[1])
            elif kind == "ckpt":
                for group, blob in rest[1]:
                    self._checkpoints[group] = blob
                remaining -= 1

    def close(self) -> None:
        """Flush queues, stop workers, re-raise the first worker error."""
        import queue as _qmod

        if self._closed:
            return
        self._closed = True
        import time as _time
        for w, q in enumerate(self._in_qs):
            t_end = _time.monotonic() + 30.0     # per-worker budget
            while True:
                try:
                    q.put(None, timeout=1.0)
                    break
                except _qmod.Full:
                    if not self._procs[w].is_alive():
                        break          # dead worker: nothing to hand-shake
                    if _time.monotonic() > t_end:
                        # wedged worker holding a full queue: give up on the
                        # sentinel, let the drain deadline + terminate below
                        # reclaim it (DeviceFeeder.close bounds the same way)
                        self._errors.append(
                            f"serving worker {w} input queue still full at "
                            f"close deadline; skipping shutdown sentinel")
                        break
        self._drain_out(expect_ckpts=self.num_workers)
        for p in self._procs:
            p.join(timeout=30.0)
            if p.is_alive():           # wedged worker: don't hang close()
                p.terminate()
        if self._errors:
            raise RuntimeError(f"serving worker failed: {self._errors[0]}")

    def actions(self) -> List[Tuple[str, str, List[str]]]:
        """(group, event_id, actions) in per-group dispatch order (call
        after close())."""
        return list(self._actions)

    def checkpoints(self) -> dict:
        """group → learner-state JSON collected at worker shutdown (call
        after close())."""
        return dict(self._checkpoints)


# ---------------------------------------------------------------------------
# supervision — the Storm worker-restart analog
# ---------------------------------------------------------------------------

class ServerSupervisor:
    """Failure detection + elastic restart for the serving loop.

    Storm restarts a crashed bolt worker but the reference's learner state is
    per-bolt-instance in-memory and unreplicated, so a restart loses it
    (SURVEY.md §3.5); replay of the in-flight message is governed by
    ``replay.failed.message`` (the spout's fail hook is stubbed empty,
    RedisSpout.java:103-106). Here the supervisor owns both halves properly:

    - learner state is checkpointed every ``checkpoint_interval`` events and
      restored into a fresh learner on restart (no state loss);
    - a persistent crash loop is detected and surfaced after
      ``max_restarts`` crashes *within one unstable window*: sustained
      progress (``restart_reset_after`` consecutive events since the last
      crash) resets the budget, so sporadic transient faults spread over a
      long-lived loop never masquerade as a crash loop (elastic recovery,
      not infinite flapping);
    - the failed event itself is dropped, matching the deployed
      ``replay.failed.message=false`` semantics — queue transports hand an
      event over exactly once, so replay would need producer cooperation.

    ``server_factory`` builds a fresh server (learner + queue bindings);
    the supervisor restores the last checkpoint into it before resuming.
    """

    def __init__(self, server_factory: Callable[[], ReinforcementLearnerServer],
                 checkpoint_interval: int = 64, max_restarts: int = 3,
                 restart_reset_after: int = 1000):
        self.server_factory = server_factory
        self.checkpoint_interval = max(checkpoint_interval, 1)
        self.max_restarts = max_restarts
        self.restart_reset_after = max(restart_reset_after, 1)
        self.restarts = 0
        self.events_processed = 0
        self.last_checkpoint: Optional[str] = None
        self._server: Optional[ReinforcementLearnerServer] = None
        self._events_since_crash = 0

    @property
    def server(self) -> ReinforcementLearnerServer:
        if self._server is None:
            self._server = self.server_factory()
            if self.last_checkpoint is not None:
                self._server.restore(self.last_checkpoint)
                from avenir_tpu.telemetry import spans as tel

                tel.tracer().event("checkpoint.restore", scope="rl",
                                   events=self.events_processed)
        return self._server

    def run(self, max_events: Optional[int] = None) -> int:
        """Drive the serving loop to queue exhaustion (or ``max_events``),
        restarting from the last checkpoint on crashes. Returns events
        processed across all incarnations; raises the last error once
        ``max_restarts`` is exceeded (crash-loop detection)."""
        done = 0
        while max_events is None or done < max_events:
            srv = self.server
            try:
                if not srv.process_one():
                    break
                done += 1
                self.events_processed += 1
                self._events_since_crash += 1
                if self._events_since_crash >= self.restart_reset_after:
                    self.restarts = 0      # stable again: refill the budget
                if self.events_processed % self.checkpoint_interval == 0:
                    self.last_checkpoint = srv.checkpoint()
                    from avenir_tpu.telemetry import spans as tel

                    tel.tracer().event("checkpoint.save", scope="rl",
                                       events=self.events_processed)
            except Exception as exc:
                self.restarts += 1
                self._events_since_crash = 0
                self._server = None        # next access builds + restores
                from avenir_tpu.telemetry import spans as tel

                tel.tracer().event("server.restart", scope="rl",
                                   restarts=self.restarts,
                                   error=type(exc).__name__)
                if self.restarts > self.max_restarts:
                    raise
        # final checkpoint so a subsequent supervisor resumes precisely
        if self._server is not None:
            self.last_checkpoint = self._server.checkpoint()
        return done
