"""Streaming serving loop — the Storm/Redis topology replacement.

Capability parity with the reference's real-time path
(``reinforce/ReinforcementLearnerTopology.java`` builds RedisSpout →
shuffle → learner bolt :42-85; ``RedisSpout.java`` rpop's
``(eventID, roundNum)`` events :86-100; ``ReinforcementLearnerBolt.java``
drains the reward queue into ``learner.setReward`` then calls
``learner.nextActions(round)`` and writes to the action queue :93-125;
pluggable queue I/O via ``ActionWriter`` / ``RewardReader`` interfaces with
Redis impls — lpush actions ``RedisActionWriter.java:46-49``, lindex walk of
the reward list ``RedisRewardReader.java:72-86``).

Re-design: the topology collapses into an in-process event loop around the
learner — the queue abstraction survives (in-proc deques for tests and
embedding; Redis transports over the in-tree stdlib RESP client,
``pipeline/resp.py``, for drop-in use against the reference's own
simulators — no external redis package). Learner state is checkpointable
between events (the reference loses bolt state on restart, SURVEY.md §3.5).
"""

from __future__ import annotations

import json
from collections import deque
from typing import Callable, Iterable, List, Optional, Protocol, Tuple

from avenir_tpu.models.online_rl import ReinforcementLearner


# ---------------------------------------------------------------------------
# queue transports
# ---------------------------------------------------------------------------

class InProcQueue:
    """Deque-backed FIFO with the push/pop surface the Redis impls use."""

    def __init__(self):
        self._q = deque()

    def push(self, msg: str) -> None:
        self._q.appendleft(msg)

    def pop(self) -> Optional[str]:
        return self._q.pop() if self._q else None

    def drain(self) -> List[str]:
        out = list(reversed(self._q))
        self._q.clear()
        return out

    def __len__(self) -> int:
        return len(self._q)


class EventSource(Protocol):
    def next_event(self) -> Optional[Tuple[str, int]]: ...


class RewardReader(Protocol):
    def read_rewards(self) -> List[Tuple[str, float]]: ...


class ActionWriter(Protocol):
    def write(self, event_id: str, actions: List[str]) -> None: ...


class QueueEventSource:
    """Events are ``eventID,roundNum`` lines (RedisSpout.java:86-100)."""

    def __init__(self, queue: InProcQueue, delim: str = ","):
        self.queue = queue
        self.delim = delim

    def next_event(self) -> Optional[Tuple[str, int]]:
        msg = self.queue.pop()
        if msg is None:
            return None
        event_id, _, round_num = msg.partition(self.delim)
        return event_id, int(round_num)


class QueueRewardReader:
    """Rewards are ``action,reward`` lines."""

    def __init__(self, queue: InProcQueue, delim: str = ","):
        self.queue = queue
        self.delim = delim

    def read_rewards(self) -> List[Tuple[str, float]]:
        out = []
        for msg in self.queue.drain():
            action, _, reward = msg.partition(self.delim)
            out.append((action, float(reward)))
        return out


class QueueActionWriter:
    """Actions are written as ``eventID,action`` (RedisActionWriter.java:46-49)."""

    def __init__(self, queue: InProcQueue, delim: str = ","):
        self.queue = queue
        self.delim = delim

    def write(self, event_id: str, actions: List[str]) -> None:
        for a in actions:
            self.queue.push(f"{event_id}{self.delim}{a}")


# Redis transports — the reference's spout/reader/writer contract
# (RedisSpout.java rpop events; RedisActionWriter.java lpush actions;
# RedisRewardReader.java reward-list reads) over the in-tree stdlib RESP
# client (pipeline/resp.py) — no external redis package needed. Rewards are
# consumed destructively (rpop drain), matching the serving loop's
# read-once semantics; the reference's non-destructive lindex walk with a
# running offset is equivalent for a single reader.

def _redis_queue(queue, host, port, db):
    from avenir_tpu.pipeline.resp import RedisListQueue
    return RedisListQueue(queue, host=host, port=port, db=db)


class RedisEventSource(QueueEventSource):
    def __init__(self, host="localhost", port=6379, db=0, queue="eventQueue", delim=","):
        super().__init__(_redis_queue(queue, host, port, db), delim=delim)


class RedisRewardReader(QueueRewardReader):
    def __init__(self, host="localhost", port=6379, db=0, queue="rewardQueue", delim=","):
        super().__init__(_redis_queue(queue, host, port, db), delim=delim)


class RedisActionWriter(QueueActionWriter):
    def __init__(self, host="localhost", port=6379, db=0, queue="actionQueue", delim=","):
        super().__init__(_redis_queue(queue, host, port, db), delim=delim)


# ---------------------------------------------------------------------------
# the serving loop (the bolt, minus Storm)
# ---------------------------------------------------------------------------

class ReinforcementLearnerServer:
    """Per event: drain rewards → update learner → emit next actions
    (ReinforcementLearnerBolt.java:93-125)."""

    def __init__(
        self,
        learner: ReinforcementLearner,
        events: EventSource,
        rewards: RewardReader,
        actions: ActionWriter,
        log_interval: int = 0,
        on_log: Optional[Callable[[int], None]] = None,
    ):
        self.learner = learner
        self.events = events
        self.rewards = rewards
        self.actions = actions
        self.log_interval = log_interval
        self.on_log = on_log
        self.processed = 0

    def process_one(self) -> bool:
        """Handle one event; False when the event queue is empty."""
        ev = self.events.next_event()
        if ev is None:
            return False
        event_id, round_num = ev
        for action, reward in self.rewards.read_rewards():
            self.learner.set_reward(action, reward)
        selected = self.learner.next_actions(round_num)
        self.actions.write(event_id, selected)
        self.processed += 1
        if self.log_interval and self.on_log and self.processed % self.log_interval == 0:
            self.on_log(self.processed)
        return True

    def run(self, max_events: Optional[int] = None) -> int:
        n = 0
        while max_events is None or n < max_events:
            if not self.process_one():
                break
            n += 1
        return n

    # -- learner-state checkpointing ----------------------------------------
    def checkpoint(self) -> str:
        return json.dumps(self.learner.get_state())

    def restore(self, blob: str) -> None:
        self.learner.set_state(json.loads(blob))
