"""Runtime: native C++ data plane + device feeder."""

from avenir_tpu.runtime.feeder import DeviceFeeder, prefetch_encoded

__all__ = ["DeviceFeeder", "prefetch_encoded"]
