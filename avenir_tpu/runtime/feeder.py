"""Device feeder — overlapped host→TPU transfer for chunked datasets.

The reference overlaps I/O with compute for free (mapper JVMs stream HDFS
blocks while reducers shuffle). On TPU the analog is double-buffering: a
background thread parses/encodes the next CSV chunk and stages it on device
while the current chunk is being consumed by the compiled step, keeping the
MXU fed instead of alternating parse → transfer → compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional, TypeVar

import jax

T = TypeVar("T")

_SENTINEL = object()


class DeviceFeeder:
    """Prefetching iterator: pulls from ``source`` on a worker thread,
    applies ``stage`` (default: ``jax.device_put`` of array leaves), and
    hands off through a bounded queue (``depth`` buffers in flight)."""

    def __init__(self, source: Iterable[T], depth: int = 2,
                 stage: Optional[Callable[[T], T]] = None,
                 device: Optional[jax.Device] = None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._stage = stage or (lambda item: self._default_stage(item, device))
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._produce, args=(iter(source),), daemon=True)
        self._thread.start()

    @staticmethod
    def _default_stage(item, device):
        def put(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return jax.device_put(x, device)
            return x
        return jax.tree_util.tree_map(put, item)

    def _produce(self, it: Iterator[T]) -> None:
        try:
            for item in it:
                self._q.put(self._stage(item))
        except BaseException as e:     # propagate to the consumer
            self._err = e
        finally:
            self._q.put(_SENTINEL)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is _SENTINEL:
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


def prefetch_encoded(path: str, encoder, ncols: int, delim: str = ",",
                     chunk_bytes: int = 64 << 20, with_labels: bool = True,
                     depth: int = 2,
                     device: Optional[jax.Device] = None) -> DeviceFeeder:
    """Native-parse a CSV file in chunks and prefetch each EncodedDataset's
    arrays to device. Falls back to the Python encoder when the native
    library is unavailable."""
    from avenir_tpu.runtime import native

    if native.is_available():
        source = native.iter_encoded_native(
            path, encoder, ncols, delim=delim, chunk_bytes=chunk_bytes,
            with_labels=with_labels)
    else:
        # rough rows-per-chunk from the byte budget (assume ~64B/row floor)
        source = encoder.iter_encoded(
            path, chunk_rows=max(chunk_bytes // 64, 1), delim=delim,
            with_labels=with_labels)

    def stage(ds):
        import jax.numpy as jnp
        staged = type(ds)(
            codes=jax.device_put(jnp.asarray(ds.codes), device),
            cont=jax.device_put(jnp.asarray(ds.cont), device),
            labels=(jax.device_put(jnp.asarray(ds.labels), device)
                    if ds.labels is not None else None),
            ids=ds.ids, n_bins=ds.n_bins, class_values=ds.class_values,
            binned_ordinals=ds.binned_ordinals, cont_ordinals=ds.cont_ordinals)
        return staged

    return DeviceFeeder(source, depth=depth, stage=stage, device=device)
