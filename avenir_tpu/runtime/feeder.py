"""Device feeder — overlapped host→TPU transfer for chunked datasets.

The reference overlaps I/O with compute for free (mapper JVMs stream HDFS
blocks while reducers shuffle). On TPU the analog is double-buffering: a
background thread parses/encodes the next CSV chunk and stages it on device
while the current chunk is being consumed by the compiled step, keeping the
MXU fed instead of alternating parse → transfer → compute.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
import weakref
from typing import Callable, Iterable, Iterator, Optional, TypeVar

import jax

T = TypeVar("T")

_SENTINEL = object()


def _put_guarded(q: "queue.Queue", stop: threading.Event, item) -> bool:
    """Bounded put that gives up when the consumer is gone."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _produce(it: Iterator, stage: Callable, q: "queue.Queue",
             stop: threading.Event, err_box: dict) -> None:
    try:
        for item in it:
            if stop.is_set():
                return
            if not _put_guarded(q, stop, stage(item)):
                return
    except BaseException as e:         # propagate to the consumer
        err_box["err"] = e
    finally:
        _put_guarded(q, stop, _SENTINEL)


class DeviceFeeder:
    """Prefetching iterator: pulls from ``source`` on a worker thread,
    applies ``stage`` (default: ``jax.device_put`` of array leaves), and
    hands off through a bounded queue (``depth`` buffers in flight).

    Abandoning the iterator mid-stream (consumer raised, GC'd the feeder, or
    called :meth:`close`) unblocks and stops the worker — staged device
    buffers are dropped rather than pinned for the life of the process."""

    def __init__(self, source: Iterable[T], depth: int = 2,
                 stage: Optional[Callable[[T], T]] = None,
                 device: Optional[jax.Device] = None):
        source, stage = self._traced_pipeline(source, stage, device)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._err_box: dict = {}
        self._stop = threading.Event()
        self._done = False
        # the worker must NOT hold a reference to self (a bound-method
        # target would keep the feeder alive for as long as the thread
        # runs, so the GC finalizer below could never fire); it closes over
        # only the queue, the stop event, and the error box
        self._thread = threading.Thread(
            target=_produce,
            args=(iter(source),
                  stage or (lambda item, _d=device:
                            DeviceFeeder._default_stage(item, _d)),
                  self._q, self._stop, self._err_box),
            daemon=True)
        # unblock the worker when the consumer drops the feeder without
        # exhausting it (fit raised mid-stream); the finalizer must not
        # reference self or it would keep the feeder alive forever
        self._finalizer = weakref.finalize(self, self._stop.set)
        self._thread.start()

    @staticmethod
    def _traced_pipeline(source, stage, device):
        """Wrap (source, stage) with a retroactive ``feeder.stage`` span
        per item when tracing is on.  The worker thread never holds the
        consumer's contextvar, so the parent is captured HERE — on the
        constructing thread, inside whatever job/stage span is current.

        Honest wall times (the spans.py contract): the span covers the
        SOURCE PULL (where the lazy chunk readers actually parse+encode)
        plus the stage call, and the staged arrays are host-synced before
        the close — ``device_put`` dispatch is async, so an unsynced span
        would time the enqueue, not the upload, and the slowest-path
        marker would point at the wrong seam.  Pull and stage run
        sequentially on the one worker thread, so the shared time box is
        race-free.  With tracing off this returns the inputs untouched —
        no wrapper frame on the hot path.

        GraftProf (round 14): under ``profile.on`` the staged chunk is
        also a device-memory sampling boundary (the upload is where HBM
        grows) — wrapped even when tracing is off, so a profile-only run
        still gauges staging."""
        from avenir_tpu.telemetry import profile as _profile
        from avenir_tpu.telemetry import spans as tel

        tracer = tel.tracer()
        prof = _profile.profiler()
        if not tracer.enabled and not prof.enabled:
            return source, stage
        inner = stage or (lambda item, _d=device:
                          DeviceFeeder._default_stage(item, _d))
        if not tracer.enabled:
            def profiled_stage(item):
                out = inner(item)
                prof.sample_device_memory("feeder")
                return out

            return source, profiled_stage
        parent = tracer.current()
        box = {"t0": None, "chunk": itertools.count()}

        def timed_source():
            it = iter(source)
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                box["t0"] = t0
                yield item

        def traced_stage(item):
            out = inner(item)
            DeviceFeeder._sync_staged(out)
            t0 = box["t0"] if box["t0"] is not None else time.perf_counter()
            box["t0"] = None
            tracer.emit_span("feeder.stage", time.perf_counter() - t0,
                             parent=parent,
                             attrs={"chunk": next(box["chunk"])})
            if prof.enabled:
                prof.sample_device_memory("feeder")
            return out

        return timed_source(), traced_stage

    @staticmethod
    def _sync_staged(out) -> None:
        """Host-sync a staged item's device arrays (EncodedDataset-shaped
        objects, tuples of them, or plain array pytrees)."""
        from avenir_tpu.utils.profiling import device_sync

        for item in (out if isinstance(out, (tuple, list)) else (out,)):
            if hasattr(item, "codes"):          # EncodedDataset-shaped
                device_sync([x for x in (item.codes, item.labels, item.cont)
                             if x is not None])
            else:
                device_sync(item)

    @staticmethod
    def _default_stage(item, device):
        def put(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return jax.device_put(x, device)
            return x
        return jax.tree_util.tree_map(put, item)

    def close(self) -> None:
        """Stop the worker and drop any staged-but-unconsumed buffers."""
        self._stop.set()
        self._done = True
        self._drain()
        # a put blocked past its stop check can still land one item after
        # the first drain; once the worker has exited nothing else can be
        # enqueued, so join-then-drain makes the drop reliable. If the
        # worker outlives the timeout (e.g. stage() wedged in a device
        # transfer), keep drain-polling — bounded at 60 s so a truly hung
        # transport cannot wedge close() — then give up loudly: the worker
        # is a daemon thread, so at worst one staged buffer stays pinned
        # until process exit.
        self._thread.join(timeout=10.0)
        self._drain()
        deadline = time.monotonic() + 60.0
        while self._thread.is_alive() and time.monotonic() < deadline:
            self._thread.join(timeout=1.0)
            self._drain()
        if self._thread.is_alive():
            import logging
            logging.getLogger("avenir_tpu").warning(
                "DeviceFeeder worker still alive 60s after close(); "
                "up to one staged buffer may stay pinned until exit")

    def _drain(self) -> None:
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        if item is _SENTINEL:
            self._done = True
            self._thread.join()
            err = self._err_box.pop("err", None)
            if err is not None:
                raise err
            raise StopIteration
        return item


def sharded_pair_stage(shard):
    """DeviceFeeder stage for ShardGraft chunk streams: ballast-pad each
    encoded chunk to its pow-2 shard target (label −1 rows — the
    drop-invalid contract, so the pad changes no statistic while the
    compiled-shape set stays finite) and ``device_put`` it sharded over the
    mesh's data axis — chunks land round-robin across the chips as the
    worker thread pulls them, so the padded upload overlaps the compiled
    fold exactly like the single-device prefetch path.  Items are the
    ``(EncodedDataset, cursor)`` pairs ``iter_encoded_retrying`` emits."""
    def stage(item):
        ds, cur = item
        return shard.stage(ds), cur

    return stage


def prefetch_encoded(path: str, encoder, ncols: int, delim: str = ",",
                     chunk_bytes: int = 64 << 20, with_labels: bool = True,
                     depth: int = 2,
                     device: Optional[jax.Device] = None) -> DeviceFeeder:
    """Native-parse a CSV file in chunks and prefetch each EncodedDataset's
    arrays to device. Falls back to the Python encoder when the native
    library is unavailable."""
    from avenir_tpu.runtime import native

    if native.is_available():
        source = native.iter_encoded_native(
            path, encoder, ncols, delim=delim, chunk_bytes=chunk_bytes,
            with_labels=with_labels)
    else:
        # rough rows-per-chunk from the byte budget (assume ~64B/row floor)
        source = encoder.iter_encoded(
            path, chunk_rows=max(chunk_bytes // 64, 1), delim=delim,
            with_labels=with_labels)

    def stage(ds):
        import jax.numpy as jnp
        staged = type(ds)(
            codes=jax.device_put(jnp.asarray(ds.codes), device),
            cont=jax.device_put(jnp.asarray(ds.cont), device),
            labels=(jax.device_put(jnp.asarray(ds.labels), device)
                    if ds.labels is not None else None),
            ids=ds.ids, n_bins=ds.n_bins, class_values=ds.class_values,
            binned_ordinals=ds.binned_ordinals, cont_ordinals=ds.cont_ordinals)
        return staged

    return DeviceFeeder(source, depth=depth, stage=stage, device=device)
