"""ctypes bridge to the C++ data plane (runtime/native/csv_encode.cpp).

Compiles the shared library on first use (g++, cached next to the source;
rebuilt when the source is newer) and exposes :func:`encode_bytes` — CSV
bytes → :class:`EncodedDataset` with semantics identical to
``DatasetEncoder.transform``. All callers must treat this as an optional fast
path: :func:`is_available` gates it, and ``DatasetEncoder`` stays the
portable reference implementation.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "native", "csv_encode.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _lib_path() -> str:
    """Where the compiled library lives: next to the source when that
    directory is writable (repo checkouts — keeps the prebuilt .so in
    place), else a per-user cache dir (pip installs into read-only
    site-packages must not silently lose the native fast path). The cache
    filename embeds a hash of the source so a package upgrade can never be
    served a stale-ABI build (mtime comparison is unreliable there —
    wheel extraction preserves archive timestamps)."""
    pkg_dir = os.path.join(os.path.dirname(__file__), "native")
    pkg_lib = os.path.join(pkg_dir, "libavenir_native.so")
    if os.path.exists(pkg_lib) and \
            os.path.getmtime(pkg_lib) >= os.path.getmtime(_SRC):
        return pkg_lib                 # shipped/prebuilt and current
    if os.access(pkg_dir, os.W_OK):
        return pkg_lib
    import hashlib
    with open(_SRC, "rb") as fh:
        tag = hashlib.sha1(fh.read()).hexdigest()[:12]
    cache = os.path.join(os.path.expanduser("~"), ".cache", "avenir_tpu",
                         "native")
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, f"libavenir_native-{tag}.so")


try:
    _LIB: Optional[str] = _lib_path()
except OSError as e:                   # e.g. unwritable/absent HOME: the
    _LIB = None                        # native path is OPTIONAL — degrade,
    _build_error = str(e)              # never crash the import

_ERRORS = {
    -1: "ragged CSV record",
    -2: "unparseable numeric field",
    -3: "unknown class label",
    -4: "row buffer overflow",
}

KIND_CATEGORICAL, KIND_BINNED_NUMERIC, KIND_CONTINUOUS, KIND_LABEL, KIND_ID = \
    0, 1, 2, 3, 4


def _build() -> Optional[ctypes.CDLL]:
    global _build_error
    if _LIB is None:                   # no writable location for the build
        return None
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return ctypes.CDLL(_LIB)
    # two processes importing concurrently must not both write the .so:
    # serialize builders on a lock, compile to a temp path, publish with an
    # atomic rename, and re-check under the lock (the loser just loads)
    from avenir_tpu.utils.locking import FileLock, LockHeldError

    try:
        with FileLock(_LIB, timeout_s=150.0):
            if os.path.exists(_LIB) and \
                    os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
                return ctypes.CDLL(_LIB)
            tmp = _LIB + ".build"
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                     "-std=c++17", "-o", tmp, _SRC],
                    check=True, capture_output=True, text=True, timeout=120)
                os.replace(tmp, _LIB)
            except BaseException:
                try:
                    os.unlink(tmp)     # no partial artifact on failure
                except OSError:
                    pass
                raise
    except LockHeldError as e:
        _build_error = str(e)
        return None
    except (OSError, subprocess.SubprocessError) as e:
        _build_error = getattr(e, "stderr", None) or str(e)
        return None
    return ctypes.CDLL(_LIB)


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    with _lock:
        if _lib is None and _build_error is None:
            lib = _build()
            if lib is not None:
                i32p = ctypes.POINTER(ctypes.c_int32)
                lib.avenir_csv_encode.restype = ctypes.c_long
                lib.avenir_csv_encode.argtypes = [
                    ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_int32,
                    i32p, i32p,
                    ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
                    i32p, ctypes.c_int32, ctypes.c_char_p,
                    i32p, ctypes.c_long,
                    ctypes.POINTER(ctypes.c_float), ctypes.c_long,
                    i32p,
                    ctypes.POINTER(ctypes.c_int64), i32p,
                    ctypes.c_long, ctypes.POINTER(ctypes.c_long),
                ]
                lib.avenir_csv_encode_mt.restype = ctypes.c_long
                lib.avenir_csv_encode_mt.argtypes = \
                    lib.avenir_csv_encode.argtypes + [ctypes.c_int32]
                lib.avenir_csv_count_rows.restype = ctypes.c_long
                lib.avenir_csv_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_long]
                lib.avenir_gather_ids_u32.restype = ctypes.c_int32
                lib.avenir_gather_ids_u32.argtypes = [
                    ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                    i32p, ctypes.c_long, ctypes.POINTER(ctypes.c_uint32),
                    ctypes.c_int32,
                ]
                _lib = lib
        return _lib


def is_available() -> bool:
    return _get_lib() is not None


def build_error() -> Optional[str]:
    _get_lib()
    return _build_error


def _specs_from_encoder(encoder, with_labels: bool = True) -> tuple:
    """Flatten a fitted DatasetEncoder into the parallel spec arrays."""
    kinds: List[int] = []
    ordinals: List[int] = []
    widths: List[float] = []
    offsets: List[int] = []
    nbins: List[int] = []
    vocab_parts: List[bytes] = []
    for f in encoder.binned_fields:
        ordinals.append(f.ordinal)
        if f.is_categorical:
            kinds.append(KIND_CATEGORICAL)
            widths.append(0.0)
            offsets.append(0)
            nbins.append(encoder.n_bins[f.ordinal])
            vocab = sorted(encoder.vocab[f.ordinal].items(), key=lambda kv: kv[1])
            vocab_parts.append(
                b"".join(v.encode() + b"\x1f" for v, _ in vocab) + b"\x1e")
        else:
            kinds.append(KIND_BINNED_NUMERIC)
            widths.append(float(f.bucket_width))
            offsets.append(int(encoder.bin_offset[f.ordinal]))
            nbins.append(encoder.n_bins[f.ordinal])
    for f in encoder.cont_fields:
        kinds.append(KIND_CONTINUOUS)
        ordinals.append(f.ordinal)
        widths.append(0.0)
        offsets.append(0)
        nbins.append(0)
    if with_labels and encoder.class_field is not None and encoder.class_values:
        kinds.append(KIND_LABEL)
        ordinals.append(encoder.class_field.ordinal)
        widths.append(0.0)
        offsets.append(0)
        nbins.append(len(encoder.class_values))
        vocab_parts.append(
            b"".join(v.encode() + b"\x1f" for v in encoder.class_values) + b"\x1e")
    if encoder.id_field is not None:
        kinds.append(KIND_ID)
        ordinals.append(encoder.id_field.ordinal)
        widths.append(0.0)
        offsets.append(0)
        nbins.append(0)
    return (np.asarray(kinds, np.int32), np.asarray(ordinals, np.int32),
            np.asarray(widths, np.float64), np.asarray(offsets, np.int64),
            np.asarray(nbins, np.int32), b"".join(vocab_parts))


def encode_bytes(data: bytes, encoder, ncols: int, delim: str = ",",
                 with_labels: bool = True, nthreads: Optional[int] = None):
    """CSV bytes → EncodedDataset via the native kernel.

    ``encoder`` must be a fitted DatasetEncoder; raises ValueError on data
    errors (same conditions as the Python path) and RuntimeError if the
    native library is unavailable. Buffers over 1 MiB are parsed by
    ``nthreads`` worker threads (default: up to 8 or the CPU count) with
    output identical to the single-threaded path.
    """
    from avenir_tpu.core.encoding import EncodedDataset

    lib = _get_lib()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    kinds, ordinals, widths, offsets, nbins, vocab_blob = \
        _specs_from_encoder(encoder, with_labels=with_labels)
    n_binned = len(encoder.binned_fields)
    n_cont = len(encoder.cont_fields)
    max_rows = lib.avenir_csv_count_rows(data, len(data))
    codes = np.zeros((max_rows, max(n_binned, 1)), np.int32)
    cont = np.zeros((max_rows, max(n_cont, 1)), np.float32)
    has_labels = with_labels and encoder.class_field is not None and \
        bool(encoder.class_values)
    labels = np.zeros(max_rows, np.int32) if has_labels else None
    has_ids = encoder.id_field is not None
    id_off = np.zeros(max_rows, np.int64) if has_ids else None
    id_len = np.zeros(max_rows, np.int32) if has_ids else None
    err_row = ctypes.c_long(0)
    if nthreads is None:
        nthreads = min(os.cpu_count() or 1, 8)
    rows = lib.avenir_csv_encode_mt(
        data, len(data), ctypes.c_char(delim.encode()), ncols,
        kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ordinals.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        widths.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        nbins.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(kinds), vocab_blob,
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        max(n_binned, 1),
        cont.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        max(n_cont, 1),
        (labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
         if labels is not None else None),
        (id_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
         if id_off is not None else None),
        (id_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
         if id_len is not None else None),
        max_rows, ctypes.byref(err_row), nthreads)
    if rows < 0:
        raise ValueError(
            f"{_ERRORS.get(rows, 'parse error')} at row {err_row.value}")
    ids = None
    if has_ids and rows:
        # id extraction: native gather of the id byte ranges, widened to
        # UCS4, directly into U-dtype memory (null-padded; numpy drops
        # trailing nulls). One pass, no numpy temporaries, no astype — the
        # numpy gather + astype('U') pair this replaces dominated encode
        # time. U-dtype (not object): no per-row PyObject creation;
        # elements compare equal to str.
        off = id_off[:rows]
        ln = id_len[:rows]
        maxlen = max(int(ln.max()), 1)
        chars = np.empty((rows, maxlen), np.uint32)  # gather fills every slot
        ascii_ok = lib.avenir_gather_ids_u32(
            data, off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ln.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            rows, chars.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            maxlen)
        if ascii_ok:
            ids = chars.view(f"<U{maxlen}")[:, 0]
        else:                            # non-ASCII ids: slow exact path
            ids = np.array([data[off[i]:off[i] + ln[i]].decode()
                            for i in range(rows)], dtype=object)
    return EncodedDataset(
        codes=codes[:rows, :n_binned] if n_binned else np.zeros((rows, 0), np.int32),
        cont=cont[:rows, :n_cont] if n_cont else np.zeros((rows, 0), np.float32),
        labels=labels[:rows] if labels is not None else None,
        ids=ids,
        n_bins=np.array([encoder.n_bins[f.ordinal] for f in encoder.binned_fields],
                        np.int32),
        class_values=list(encoder.class_values),
        binned_ordinals=[f.ordinal for f in encoder.binned_fields],
        cont_ordinals=[f.ordinal for f in encoder.cont_fields],
    )


def iter_encoded_native(path: str, encoder, ncols: int, delim: str = ",",
                        chunk_bytes: int = 64 << 20, with_labels: bool = True):
    """Stream a CSV file through the native encoder in newline-aligned byte
    chunks — the TPU infeed producer."""
    with open(path, "rb") as fh:
        carry = b""
        while True:
            block = fh.read(chunk_bytes)
            if not block:
                if carry.strip():
                    yield encode_bytes(carry, encoder, ncols, delim, with_labels)
                return
            block = carry + block
            cut = block.rfind(b"\n")
            if cut < 0:
                carry = block
                continue
            carry = block[cut + 1:]
            yield encode_bytes(block[:cut + 1], encoder, ncols, delim, with_labels)
